"""Scheduling queue (layer L3, SURVEY.md §1).

[K8S] kube-scheduler queue semantics: an active heap ordered by QueueSort
(priority desc, then FIFO), a backoff queue with exponential per-pod backoff
(1s → 10s), and an unschedulable set that is flushed back to active when a
cluster event might make pods schedulable. Time here is the simulator's
virtual clock.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

INITIAL_BACKOFF = 1.0
MAX_BACKOFF = 10.0


@dataclass
class _Entry:
    pod: int
    priority: int
    seq: int

    def sort_key(self) -> Tuple[int, int]:
        return (-self.priority, self.seq)


class SchedulingQueue:
    def __init__(self):
        self._heap: List[Tuple[Tuple[int, int], _Entry]] = []
        self._backoff: List[Tuple[float, Tuple[int, int], _Entry]] = []
        self._unschedulable: Dict[int, _Entry] = {}
        self._attempts: Dict[int, int] = {}
        self._fail_time: Dict[int, float] = {}
        self._seq = 0

    def push(self, pod: int, priority: int) -> None:
        e = _Entry(pod, priority, self._seq)
        self._seq += 1
        heapq.heappush(self._heap, (e.sort_key(), e))

    def pop(self) -> Optional[int]:
        if not self._heap:
            return None
        return heapq.heappop(self._heap)[1].pod

    def requeue_backoff(self, pod: int, priority: int, now: float) -> None:
        """Pod failed a scheduling attempt for a transient reason — retry
        after exponential backoff. The exponent is capped: the delay
        saturates at MAX_BACKOFF by n=4, and an uncapped 2**n overflows
        float for pods that fail thousands of times in a long trace."""
        n = self._attempts.get(pod, 0)
        self._attempts[pod] = n + 1
        delay = min(INITIAL_BACKOFF * (2 ** min(n, 8)), MAX_BACKOFF)
        e = _Entry(pod, priority, self._seq)
        self._seq += 1
        heapq.heappush(self._backoff, (now + delay, e.sort_key(), e))

    def mark_unschedulable(self, pod: int, priority: int, now: Optional[float] = None) -> None:
        """Record a failed scheduling attempt. With ``now``, the failure
        time and attempt count feed the backoff computed at flush time
        ([K8S]: pods moved out of unschedulableQ go through backoffQ until
        their per-pod backoff expires)."""
        e = _Entry(pod, priority, self._seq)
        self._seq += 1
        self._unschedulable[pod] = e
        if now is not None:
            self._attempts[pod] = self._attempts.get(pod, 0) + 1
            self._fail_time[pod] = now

    def _backoff_expiry(self, pod: int) -> float:
        if pod not in self._fail_time:
            # No recorded failed attempt (e.g. parked without an attempt) —
            # no backoff to serve: eligible for active immediately.
            return float("-inf")
        n = min(max(self._attempts.get(pod, 1) - 1, 0), 8)
        delay = min(INITIAL_BACKOFF * (2**n), MAX_BACKOFF)
        return self._fail_time[pod] + delay

    def flush_unschedulable(self, now: Optional[float] = None) -> None:
        """A cluster event occurred (binding freed resources, node change) —
        move unschedulable pods back toward active ([K8S]
        MoveAllToActiveOrBackoffQueue). With ``now``, pods whose backoff has
        not yet expired land in the backoff queue instead of active."""
        for e in self._unschedulable.values():
            if now is not None:
                exp = self._backoff_expiry(e.pod)
                if exp > now:
                    heapq.heappush(self._backoff, (exp, e.sort_key(), e))
                    continue
            heapq.heappush(self._heap, (e.sort_key(), e))
        self._unschedulable.clear()

    def flush_backoff(self, now: float) -> None:
        while self._backoff and self._backoff[0][0] <= now:
            _, _, e = heapq.heappop(self._backoff)
            heapq.heappush(self._heap, (e.sort_key(), e))

    def next_backoff_time(self) -> Optional[float]:
        return self._backoff[0][0] if self._backoff else None

    def __len__(self) -> int:
        return len(self._heap)

    @property
    def num_unschedulable(self) -> int:
        return len(self._unschedulable)

    @property
    def num_backoff(self) -> int:
        return len(self._backoff)
