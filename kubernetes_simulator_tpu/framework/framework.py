"""SchedulerFramework — layer L1 (SURVEY.md §1, §3.3).

Runs one pod through the [K8S] extension-point order:

    PreFilter → Filter → (PostFilter: preemption) → PreScore → Score →
    NormalizeScore → weighted sum → select → Reserve → Permit → Bind

Filter and Score are the extension points [BASELINE] names explicitly; the
rest follow upstream framework ordering. The CPU path evaluates each
extension point vectorized over all nodes (the `(nodes × pending_pods)`
tensorization, host edition); the JAX strategy swaps the whole cycle for a
fused device program selected through the strategy registry (L6).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import SchedState, bind, unbind
from ..plugins.builtin import (
    DEFAULT_WEIGHTS,
    Plugin,
    SchedulingContext,
    make_plugins,
)


@dataclass
class ScheduleResult:
    node: int  # PAD = unschedulable
    reason: str = ""
    victims: Tuple[int, ...] = ()  # preempted pods (PostFilter)
    # Per-plugin first-reject node counts (kube "0/N nodes available"
    # breakdown) — populated only on a fully-failed attempt when the caller
    # passed ``want_reasons=True``; always sums to num_nodes then.
    reasons: Optional[Dict[str, int]] = None


@dataclass
class FrameworkConfig:
    plugins: Optional[List[dict]] = None  # [{"name":..., "args": {...}}]
    weights: Optional[Dict[str, float]] = None  # Score weights by plugin name
    enable_preemption: bool = True
    profile: bool = False  # per-extension-point latency accounting

    def with_policy(
        self,
        weights: Dict[str, float],
        fit_strategy: Optional[str] = None,
    ) -> "FrameworkConfig":
        """A copy of this config with the given Score weights merged in
        and (optionally) the NodeResourcesFit scoring strategy replaced —
        how the policy tuner (round 9, sim.tuner) re-materializes a
        searched policy vector as an ordinary scheduler config for the
        CPU-oracle re-evaluation. Plugin entries other than
        NodeResourcesFit are carried unchanged."""
        merged = dict(self.weights or {})
        merged.update(weights)
        plugins = self.plugins
        if fit_strategy is not None:
            entries = (
                [dict(e) for e in plugins]
                if plugins is not None
                else [{"name": n} for n in DEFAULT_WEIGHTS]
            )
            found = False
            for e in entries:
                if e.get("name") == "NodeResourcesFit":
                    e["args"] = {**e.get("args", {}), "strategy": fit_strategy}
                    found = True
            if not found:
                entries.append(
                    {"name": "NodeResourcesFit",
                     "args": {"strategy": fit_strategy}}
                )
            plugins = entries
        return FrameworkConfig(
            plugins=plugins,
            weights=merged,
            enable_preemption=self.enable_preemption,
            profile=self.profile,
        )


class SchedulerFramework:
    def __init__(self, ec: EncodedCluster, pods: EncodedPods, config: Optional[FrameworkConfig] = None):
        self.config = config or FrameworkConfig()
        self.ctx = SchedulingContext.build(ec, pods)
        self.plugins: List[Plugin] = make_plugins(self.ctx, self.config.plugins)
        weights = dict(DEFAULT_WEIGHTS)
        weights.update(self.config.weights or {})
        self.weights = weights
        self.ec = ec
        self.pods = pods
        # Any required anti-affinity anywhere in the trace ⇒ symmetric
        # checks make every pod's feasibility state-dependent (preemption
        # fast path gate).
        self._trace_has_anti = bool((pods.anti_req >= 0).any())
        # Per-extension-point latency accounting (SURVEY.md §5 tracing).
        self.plugin_time: Dict[str, float] = {}

    # -- Filter + Score over all nodes -------------------------------------

    def feasible_mask(
        self, st: SchedState, p: int, reject_counts: Optional[Dict[str, int]] = None
    ) -> np.ndarray:
        """Filter chain over all nodes. ``reject_counts`` (telemetry
        opt-in) is filled with per-plugin FIRST-reject node counts —
        each rejected node charged to the earliest plugin in Filter order
        that rejected it. The short-circuit break is attribution-lossless:
        once the mask is empty no later plugin can newly reject anything."""
        import time as _time

        from ..ops import cpu as C

        mask = np.ones(self.ec.num_nodes, dtype=bool)
        for pl in self.plugins:
            t0 = _time.perf_counter() if self.config.profile else 0.0
            if reject_counts is not None:
                reject_counts.setdefault(pl.name, 0)
            m = pl.filter(self.ctx, st, p)
            if self.config.profile:
                key = f"Filter/{pl.name}"
                self.plugin_time[key] = self.plugin_time.get(key, 0.0) + _time.perf_counter() - t0
            if m is not None:
                if reject_counts is not None:
                    newly, mask = C.first_reject_update(mask, m)
                    reject_counts[pl.name] += newly
                else:
                    mask &= m
                if not mask.any():
                    break
        return mask

    def score_nodes(self, st: SchedState, p: int, feasible: np.ndarray) -> np.ndarray:
        import time as _time

        total = np.zeros(self.ec.num_nodes, dtype=np.float32)
        for pl in self.plugins:
            w = self.weights.get(pl.name, 1.0)
            if w == 0:
                continue
            t0 = _time.perf_counter() if self.config.profile else 0.0
            raw = pl.score(self.ctx, st, p)
            if raw is not None:
                total += w * pl.normalize(raw, feasible)
            if self.config.profile:
                key = f"Score/{pl.name}"
                self.plugin_time[key] = self.plugin_time.get(key, 0.0) + _time.perf_counter() - t0
        return total

    def schedule_one(
        self,
        st: SchedState,
        p: int,
        allow_preemption: bool = True,
        want_reasons: bool = False,
    ) -> ScheduleResult:
        """One scheduling cycle (SURVEY.md §3.3). Does NOT bind — the caller
        (runtime) owns Reserve/Permit/Bind so gang commit stays transactional.

        ``allow_preemption=False`` skips PostFilter: the runtime disables it
        for gang members because a speculative reserve must be cheaply
        revertible, and evicting victims for a reservation that later rolls
        back cannot be undone.

        ``want_reasons=True`` (telemetry ``series``+ only) attaches the
        per-plugin first-reject breakdown to a fully-failed result. A
        result rescued by PostFilter preemption carries no reasons — in
        kube terms the pod nominated a node, it is not unschedulable."""
        rc: Optional[Dict[str, int]] = {} if want_reasons else None
        feasible = self.feasible_mask(st, p, reject_counts=rc)
        if not feasible.any():
            if self.config.enable_preemption and allow_preemption:
                res = self._post_filter_preempt(st, p)
                if res is not None:
                    return res
            return ScheduleResult(PAD, "Unschedulable", reasons=rc)
        scores = self.score_nodes(st, p, feasible)
        masked = np.where(feasible, scores, -np.inf)
        # Deterministic lowest-index tie-break (SURVEY.md §7 hard part #6).
        return ScheduleResult(int(np.argmax(masked)))

    # -- PostFilter: preemption ([K8S] defaultpreemption) -------------------

    def _post_filter_preempt(self, st: SchedState, p: int) -> Optional[ScheduleResult]:
        """Find a node where evicting the fewest, lowest-priority pods with
        priority < pod's makes it fit. Victims are chosen lowest-priority
        first; candidate nodes ranked by (fewest victims, lowest max victim
        priority). Gang members are never chosen as victims (their group
        would be left partial)."""
        pods, ec = self.pods, self.ec
        prio = int(pods.priority[p])
        bound_nodes = st.bound  # [P]
        candidates: List[Tuple[int, int, int, List[int]]] = []
        placed = np.nonzero(bound_nodes >= 0)[0]
        lower = placed[(pods.priority[placed] < prio) & (pods.group_id[placed] == PAD)]
        if lower.size == 0:
            return None
        # State-INDEPENDENT filters (taints, node affinity) cannot change
        # under evictions: evaluate once, skip nodes they reject, and use
        # a node-local O(R) resource check inside the victim loop — the
        # full mask is only recomputed to CONFIRM a fit (affinity/spread
        # filters can also unblock from evictions, so a failed confirm
        # keeps evicting). Replaces the O(nodes × victims × full-mask)
        # recomputation that was pathological at 5k+ nodes.
        static_mask = np.ones(ec.num_nodes, dtype=bool)
        for pl in self.plugins:
            if pl.name in ("NodeResourcesFit", "InterPodAffinity", "PodTopologySpread"):
                continue
            m = pl.filter(self.ctx, st, p)
            if m is not None:
                static_mask &= m
        req = pods.requests[p]
        names = {pl.name for pl in self.plugins}
        has_fit = "NodeResourcesFit" in names
        # When no state-DEPENDENT filter can reject node n for this pod
        # (no required interpod terms on p, no anti-affinity anywhere in
        # the trace to check symmetrically, no DoNotSchedule spread rows),
        # feasibility at n is exactly static_mask[n] ∧ resource fit — the
        # full-mask confirm is skipped entirely (the common, fit-bound
        # preemption shape).
        state_free = not (
            (
                "InterPodAffinity" in names
                and (
                    pods.aff_req[p, 0] >= 0
                    or pods.anti_req[p, 0] >= 0
                    or self._trace_has_anti
                )
            )
            or (
                "PodTopologySpread" in names
                and bool(((pods.spread_g[p] >= 0) & pods.spread_dns[p]).any())
            )
        )
        # Group victims by node once (sorted by priority asc then pod index
        # — the greedy eviction order) instead of re-scanning per node.
        order_all = np.lexsort((lower, pods.priority[lower], bound_nodes[lower]))
        sorted_lower = lower[order_all]
        node_of = bound_nodes[sorted_lower]
        cand_nodes = np.unique(node_of)
        seg_lo = np.searchsorted(node_of, cand_nodes, side="left")
        seg_hi = np.searchsorted(node_of, cand_nodes, side="right")
        for ci_n, n in enumerate(cand_nodes):
            n = int(n)
            if not static_mask[n]:
                continue
            order = sorted_lower[seg_lo[ci_n] : seg_hi[ci_n]]
            victims: List[int] = []
            fits = False
            if has_fit and state_free:
                # Vectorized: smallest k with all resources fitting after
                # evicting order[:k+1] — no state copies at all.
                cum = np.cumsum(pods.requests[order], axis=0)  # [K, R]
                fit_k = np.all(
                    st.used[n] + req - cum <= ec.allocatable[n] + 1e-6, axis=1
                )
                hit = np.nonzero(fit_k)[0]
                if hit.size:
                    fits = True
                    victims = [int(v) for v in order[: hit[0] + 1]]
            else:
                # Greedily evict lowest-priority victims until the pod fits.
                trial = st.copy()
                for v in order:
                    unbind(ec, pods, trial, int(v))
                    victims.append(int(v))
                    if has_fit and not bool(
                        np.all(trial.used[n] + req <= ec.allocatable[n] + 1e-6)
                    ):
                        continue
                    if state_free or self._fits_after(trial, p, n):
                        fits = True
                        break
            if not fits:
                continue
            max_vprio = int(pods.priority[victims].max()) if victims else -(2**31)
            candidates.append((len(victims), max_vprio, n, victims))
        if not candidates:
            return None
        candidates.sort(key=lambda c: (c[0], c[1], c[2]))
        nvict, _, n, victims = candidates[0]
        return ScheduleResult(n, "Preempted", tuple(victims))

    def _fits_after(self, st: SchedState, p: int, n: int) -> bool:
        mask = self.feasible_mask(st, p)
        return bool(mask[n])
