"""Pluggable scheduler-strategy registry (layer L6, SURVEY.md §1).

[BASELINE] requires alternate scheduling backends to be selected through a
registry, with the CPU plugin path as the default and the `jax` backend as
an opt-in strategy. A strategy factory receives the encoded cluster +
workload and the framework config and returns a replay engine exposing
``replay(...)`` (see :mod:`..sim.runtime` for the contract).
"""

from __future__ import annotations

from typing import Callable, Dict

_STRATEGIES: Dict[str, Callable] = {}


def register_strategy(name: str):
    def deco(factory: Callable) -> Callable:
        if name in _STRATEGIES:
            raise ValueError(f"strategy {name!r} already registered")
        _STRATEGIES[name] = factory
        return factory

    return deco


def get_strategy(name: str) -> Callable:
    if name not in _STRATEGIES:
        # Import built-in strategies lazily so `cpu` works without jax
        # installed and `jax` only pays its import cost when selected.
        if name == "cpu":
            from ..sim import runtime  # noqa: F401  (registers "cpu")
        elif name == "jax":
            from ..sim import jax_runtime  # noqa: F401  (registers "jax")
    try:
        return _STRATEGIES[name]
    except KeyError:
        raise KeyError(
            f"unknown strategy {name!r}; registered: {sorted(_STRATEGIES)}"
        ) from None


def available_strategies():
    return sorted(_STRATEGIES)
