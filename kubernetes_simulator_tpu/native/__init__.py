"""Native runtime layer — ctypes bindings to the C++ host-side components
(``native/*.cpp``): gang-aware wave packing and columnar trace IO.

The shared library is built lazily with ``g++ -O3`` into
``native/_build/`` the first time it is needed and cached by source mtime.
Every entry point has a pure-Python fallback (the original implementations)
so the framework still runs where no toolchain exists; parity between the
two is pinned by tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional

import numpy as np

_REPO = Path(__file__).resolve().parent.parent.parent
_SRC = _REPO / "native"
_BUILD = _SRC / "_build"
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SOURCES = ("wavepack.cpp", "traceio.cpp", "borg2019.cpp")


def _build_lib() -> Optional[Path]:
    so = _BUILD / "libksim.so"
    srcs = [_SRC / s for s in _SOURCES]
    if not all(s.exists() for s in srcs):
        return None
    if so.exists() and so.stat().st_mtime >= max(s.stat().st_mtime for s in srcs):
        return so
    _BUILD.mkdir(parents=True, exist_ok=True)
    # Compile to a process-private path and os.replace into place, so a
    # concurrent process never dlopens a partially written .so.
    tmp = _BUILD / f"libksim.{os.getpid()}.so"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", str(tmp)] + [
        str(s) for s in srcs
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
    except (OSError, subprocess.SubprocessError):
        tmp.unlink(missing_ok=True)
        return None
    return so


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        if os.environ.get("KSIM_NO_NATIVE"):
            return None
        so = _build_lib()
        if so is not None:
            try:
                lib = ctypes.CDLL(str(so))
            except OSError:
                return None
            lib.ksim_pack_waves.restype = ctypes.c_int64
            lib.ksim_pack_waves.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ]
            lib.ksim_trace_count.restype = ctypes.c_int64
            lib.ksim_trace_count.argtypes = [ctypes.c_char_p]
            lib.ksim_trace_parse.restype = ctypes.c_int64
            lib.ksim_trace_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ]
            lib.ksim_trace_write.restype = ctypes.c_int64
            lib.ksim_trace_write.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ]
            lib.ksim_borg2019_count.restype = ctypes.c_int64
            lib.ksim_borg2019_count.argtypes = [ctypes.c_char_p]
            lib.ksim_borg2019_parse.restype = ctypes.c_int64
            lib.ksim_borg2019_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ]
            _LIB = lib
    return _LIB


def available() -> bool:
    return _lib() is not None


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def pack_waves_native(
    order: np.ndarray, group_of: np.ndarray, wave_width: int
) -> Optional[np.ndarray]:
    """[num_waves, W] i32 wave table (PAD=-1), or None if the native lib is
    unavailable. Raises ValueError when a gang exceeds the wave width (same
    contract as the Python packer)."""
    lib = _lib()
    if lib is None:
        return None
    order = np.ascontiguousarray(order, dtype=np.int32)
    group_of = np.ascontiguousarray(group_of, dtype=np.int32)
    n = order.shape[0]
    out = np.empty((max(n, 1), wave_width), dtype=np.int32)
    waves = lib.ksim_pack_waves(
        _i32p(order), n, _i32p(group_of), group_of.shape[0], wave_width, _i32p(out)
    )
    if waves < 0:
        raise ValueError(f"gang exceeds wave width {wave_width}")
    return out[:waves].copy()


def read_trace_csv(path: str | os.PathLike) -> Optional[dict]:
    """Columnar task-event trace → dict of numpy arrays, or None if the
    native lib is unavailable (callers fall back to numpy loadtxt)."""
    lib = _lib()
    if lib is None:
        return None
    p = str(path).encode()
    n = lib.ksim_trace_count(p)
    if n < 0:
        raise FileNotFoundError(path)
    cols = {
        "arrival": np.empty(n, np.float64),
        "cpu": np.empty(n, np.float32),
        "mem": np.empty(n, np.float32),
        "priority": np.empty(n, np.int32),
        "group_id": np.empty(n, np.int64),  # real Borg collection ids > 2^31
        "app_id": np.empty(n, np.int64),
        "tolerates": np.empty(n, np.int32),
        "duration": np.empty(n, np.float32),
    }
    got = lib.ksim_trace_parse(
        p, n,
        cols["arrival"].ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        cols["cpu"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        cols["mem"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _i32p(cols["priority"]), _i64p(cols["group_id"]), _i64p(cols["app_id"]),
        _i32p(cols["tolerates"]),
        cols["duration"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if got < 0:
        raise ValueError(f"malformed trace file: {path}")
    return {k: v[:got] for k, v in cols.items()}


def read_borg2019_events(path: str | os.PathLike) -> Optional[dict]:
    """Borg-2019 schema CSV (instance_events / collection_events) → raw
    per-event columnar arrays (time_us, etype, cid, iidx, prio, alloc,
    cpu, mem), or None when the native lib is unavailable OR the file
    needs the tolerant csv.DictReader fallback (quoted fields, missing
    required columns). Sentinels: prio/alloc −1 = field absent."""
    lib = _lib()
    if lib is None:
        return None
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    # Streaming newline count (an upper bound on data rows — blanks and
    # the header over-allocate slightly; parse() returns the real count).
    # Avoids the C side slurping the whole file twice at the
    # billions-of-rows scale this exists for.
    n = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(1 << 24)
            if not buf:
                break
            n += buf.count(b"\n")
    n += 1  # file may lack a trailing newline
    p = str(path).encode()
    cols = {
        "time_us": np.empty(n, np.float64),
        "etype": np.empty(n, np.int32),
        "cid": np.empty(n, np.int64),
        "iidx": np.empty(n, np.int64),
        "prio": np.empty(n, np.int32),
        "alloc": np.empty(n, np.int64),
        "cpu": np.empty(n, np.float32),
        "mem": np.empty(n, np.float32),
    }
    got = lib.ksim_borg2019_parse(
        p, n,
        cols["time_us"].ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        _i32p(cols["etype"]), _i64p(cols["cid"]), _i64p(cols["iidx"]),
        _i32p(cols["prio"]), _i64p(cols["alloc"]),
        cols["cpu"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        cols["mem"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if got < 0:
        return None  # unsupported shape → csv.DictReader fallback
    return {k: v[:got] for k, v in cols.items()}


def write_trace_csv(path: str | os.PathLike, cols: dict) -> bool:
    """Write a columnar trace; False if the native lib is unavailable."""
    lib = _lib()
    if lib is None:
        return False
    n = len(cols["arrival"])
    arrs = {
        "arrival": np.ascontiguousarray(cols["arrival"], np.float64),
        "cpu": np.ascontiguousarray(cols["cpu"], np.float32),
        "mem": np.ascontiguousarray(cols["mem"], np.float32),
        "priority": np.ascontiguousarray(cols["priority"], np.int32),
        "group_id": np.ascontiguousarray(cols["group_id"], np.int64),
        "app_id": np.ascontiguousarray(cols["app_id"], np.int64),
        "tolerates": np.ascontiguousarray(cols["tolerates"], np.int32),
        "duration": np.ascontiguousarray(cols["duration"], np.float32),
    }
    got = lib.ksim_trace_write(
        str(path).encode(), n,
        arrs["arrival"].ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        arrs["cpu"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        arrs["mem"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        _i32p(arrs["priority"]), _i64p(arrs["group_id"]), _i64p(arrs["app_id"]),
        _i32p(arrs["tolerates"]),
        arrs["duration"].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if got != n:
        raise IOError(f"short trace write to {path}: {got}/{n}")
    return True
