"""Persistent XLA compilation cache for the heavy entry points.

The north-star chunk program costs ~4 minutes of XLA compile per shape
(judge-measured 233.5 s warmup vs 53.9 s steady-state in round 3); a
process restart with the SAME shapes should pay seconds, not minutes.
``enable()`` points JAX's persistent compilation cache at a stable
directory so compiled executables survive across processes — every
config change still compiles once, but only once per machine.

Opt-out with ``KSIM_COMPILE_CACHE=0``; override the directory with
``KSIM_COMPILE_CACHE_DIR``. Entries below 1 s of compile time are not
persisted (the cache is for the chunk programs, not every tiny jit).

CPU backend (round 6): the cache is OFF by default. jax 0.4.x's
thunk-runtime CPU executables do not survive the persistent-cache
round-trip — warm-cache replays of the chunk programs returned
nondeterministic placements (the preemption program most visibly),
out-of-bounds node ids and occasional segfaults, while every cold
compile of the same program was correct. Until the upstream
serialization is sound, correctness wins over warm-start time on CPU;
``KSIM_COMPILE_CACHE=1`` forces it back on for local experiments.

Concurrent DCN workers (round 11): N processes on one machine share the
cache directory, and jax 0.4.x's ``LRUCache.put`` writes entries with a
bare ``write_bytes`` — no lock when eviction is off (the default) — so a
reader can observe a half-written executable. ``enable()`` therefore
patches the put path to write a per-process temp file and ``os.replace``
it into place (atomic on POSIX): concurrent writers of the same
content-addressed key each land a complete file, last rename wins with
identical bytes. Ordering stays as documented: ``enable()`` must run
BEFORE ``jax.distributed.initialize`` (parallel.dcn.maybe_init_from_env
does this by construction; pinned by tests/test_dcn_units.py).
"""

from __future__ import annotations

import os
from pathlib import Path

_DEFAULT_DIR = "~/.cache/ksim_tpu_xla"
_configured_dir: str | None = None
_atomic_patched = False


def patch_atomic_writes() -> bool:
    """Replace ``jax._src.lru_cache.LRUCache.put``'s unlocked
    ``write_bytes`` with temp-then-``os.replace`` so concurrent DCN
    workers sharing one cache directory never expose partial entries.
    Returns True when the patch is in place (idempotent); False when the
    jax internals moved (the cache then stays stock — slower under
    contention, never broken worse than upstream)."""
    global _atomic_patched
    if _atomic_patched:
        return True
    try:
        import time

        from jax._src import lru_cache as _lru

        suffix_c = _lru._CACHE_SUFFIX
        suffix_a = _lru._ATIME_SUFFIX
        orig_put = _lru.LRUCache.put

        def _atomic_put(self, key, val):
            if getattr(self, "eviction_enabled", False):
                # The eviction path serializes through a file lock
                # upstream — keep it.
                return orig_put(self, key, val)
            if not key:
                raise ValueError("key cannot be empty")
            cache_path = self.path / f"{key}{suffix_c}"
            if cache_path.exists():
                return
            tmp = self.path / f"{key}.tmp.{os.getpid()}"
            tmp.write_bytes(val)
            os.replace(str(tmp), str(cache_path))
            (self.path / f"{key}{suffix_a}").write_bytes(
                time.time_ns().to_bytes(8, "little")
            )

        _lru.LRUCache.put = _atomic_put
    except Exception:  # noqa: BLE001 — never fatal
        return False
    _atomic_patched = True
    return True


def enable(cache_dir: str | None = None) -> str | None:
    """Idempotently enable the persistent compilation cache. Returns the
    cache directory JAX is actually configured with, or None when
    disabled/unavailable. A repeat call with a different ``cache_dir``
    returns the originally-configured path (JAX keeps using it), never
    the ignored new one."""
    global _configured_dir
    raw = os.environ.get("KSIM_COMPILE_CACHE")
    if raw in ("", "0"):
        return None
    if raw != "1":
        # Default: refuse on the CPU backend (see module docstring — the
        # deserialized thunk-runtime executables are unsound). "1" set
        # explicitly overrides for local experiments. The platform check
        # must NOT initialize the backend (enable() runs before
        # jax.distributed.initialize in the DCN workers), so it reads
        # config/env and probes for a TPU plugin instead of asking the
        # runtime.
        try:
            import importlib.util

            import jax

            plats = (
                os.environ.get("JAX_PLATFORMS")
                or getattr(jax.config, "jax_platforms", None)
                or ""
            )
            first = plats.split(",")[0].strip().lower()
            if first in ("", "cpu"):
                if first == "cpu" or importlib.util.find_spec("libtpu") is None:
                    return None
        except Exception:  # noqa: BLE001 — never fatal
            return None
    path = Path(
        cache_dir
        or os.environ.get("KSIM_COMPILE_CACHE_DIR", _DEFAULT_DIR)
    ).expanduser()
    if _configured_dir is not None:
        return _configured_dir
    try:
        path.mkdir(parents=True, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", str(path))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        # Persist regardless of entry size (the default gates on bytes).
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 — a broken cache must never be fatal
        return None
    patch_atomic_writes()
    _configured_dir = str(path)
    return _configured_dir
