"""Kubernetes resource-quantity parsing.

Semantics follow the upstream ``resource.Quantity`` grammar
(apimachinery/pkg/api/resource): decimal SI suffixes (k, M, G, T, P, E),
binary suffixes (Ki, Mi, Gi, Ti, Pi, Ei), and the milli suffix ``m``.

Provenance: [K8S] upstream semantics; the reference mount was empty this
session (see SURVEY.md §0), so no reference file:line citations exist.
"""

from __future__ import annotations

_BINARY = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL = {
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}


def parse_quantity(value) -> float:
    """Parse a k8s quantity (``"100m"``, ``"2"``, ``"4Gi"``, 0.5) to a float.

    CPU quantities come back in cores (``"100m"`` -> 0.1); memory/storage in
    bytes (``"1Ki"`` -> 1024.0). Plain ints/floats pass through unchanged.
    """
    if isinstance(value, (int, float)):
        return float(value)
    if not isinstance(value, str):
        raise TypeError(f"cannot parse quantity of type {type(value)!r}")
    s = value.strip()
    if not s:
        raise ValueError("empty quantity")
    for suf, mult in _BINARY.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    for suf, mult in _DECIMAL.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult
    return float(s)


def format_quantity(value: float, binary: bool = False) -> str:
    """Best-effort inverse of :func:`parse_quantity` for logs and dumps."""
    if binary:
        for suf in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
            mult = _BINARY[suf]
            if value >= mult and value % mult == 0:
                return f"{int(value // mult)}{suf}"
    if value == int(value):
        return str(int(value))
    milli = value * 1000
    if milli == int(milli):
        return f"{int(milli)}m"
    return repr(value)
