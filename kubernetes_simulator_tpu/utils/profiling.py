"""Profiling hooks (SURVEY.md §5 tracing/profiling).

- ``device_trace(dir)``: jax.profiler trace (TensorBoard/Perfetto) around a
  replay.
- ``profiling_active()`` / ``annotate(name)``: the round-12 device-profiler
  hook contract — ``KSIM_PROFILE_DIR`` (set directly or via the
  ``--profile`` flags on bench.py / scripts/northstar.py) arms
  ``jax.profiler.TraceAnnotation`` markers on the telemetry PHASE_NAMES
  phases and chunk dispatch, so fused-program device time is attributable
  in XLA traces. Off by default; annotations never change results (pinned
  in tests/test_telemetry.py).
- ``live_buffer_stats()``: live-buffer / memory watermark gauge.
- ``timed(fn)``: block-until-ready wall-clock timing harness.
- ``cost_analysis(jitted, *args)``: XLA cost analysis of a compiled step.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable, Optional


def profile_dir() -> Optional[str]:
    """The device-profiler sink (``KSIM_PROFILE_DIR``), or None when
    profiling is off."""
    return os.environ.get("KSIM_PROFILE_DIR") or None


def profiling_active() -> bool:
    """True when profiler hooks should annotate. One env-dict lookup — the
    replay engines consult this per replay (not per chunk) to build their
    tick functions."""
    return bool(profile_dir())


def annotate(name: str):
    """``jax.profiler.TraceAnnotation(name)`` when profiling is active,
    else a no-op context. Annotations outside a live ``jax.profiler.trace``
    are harmless, so callers gate on :func:`profiling_active` only to skip
    the object construction on hot paths."""
    if not profiling_active():
        return contextlib.nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)


def live_buffer_stats(collect: bool = True) -> dict:
    """Live-buffer / memory watermark gauge (round 12): the count and
    total bytes of ``jax.live_arrays()`` — the same counter machinery as
    tests/test_donation.py's leak pin — plus the backend's
    ``peak_bytes_in_use`` watermark where it reports one (TPU/GPU; CPU
    devices return nothing and the key is simply absent). ``collect``
    runs ``gc.collect()`` first so the count reflects reachable buffers,
    not garbage awaiting a cycle — skip it on hot paths."""
    try:
        import jax

        if collect:
            import gc

            gc.collect()
        arrs = jax.live_arrays()
        out: dict = {
            "count": len(arrs),
            "bytes": int(
                sum(int(getattr(a, "nbytes", 0) or 0) for a in arrs)
            ),
        }
    except Exception:
        return {}
    try:
        ms = jax.local_devices()[0].memory_stats()
        if ms and "peak_bytes_in_use" in ms:
            out["peak_bytes_in_use"] = int(ms["peak_bytes_in_use"])
    except Exception:
        pass
    return out


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]):
    import jax

    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def timed(fn: Callable, *args, **kw):
    """(result, seconds) with device completion awaited."""
    import jax

    t0 = time.perf_counter()
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def cost_analysis(jitted: Callable, *args) -> dict:
    """FLOP/byte estimates for one compiled step (flattened keys only)."""
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return {k: v for k, v in (ca or {}).items() if isinstance(v, (int, float))}
