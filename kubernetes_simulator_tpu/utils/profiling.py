"""Profiling hooks (SURVEY.md §5 tracing/profiling).

- ``device_trace(dir)``: jax.profiler trace (TensorBoard/Perfetto) around a
  replay.
- ``timed(fn)``: block-until-ready wall-clock timing harness.
- ``cost_analysis(jitted, *args)``: XLA cost analysis of a compiled step
  (the ``--profile`` flag's payload).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Optional


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]):
    import jax

    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def timed(fn: Callable, *args, **kw):
    """(result, seconds) with device completion awaited."""
    import jax

    t0 = time.perf_counter()
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def cost_analysis(jitted: Callable, *args) -> dict:
    """FLOP/byte estimates for one compiled step (flattened keys only)."""
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return {k: v for k, v in (ca or {}).items() if isinstance(v, (int, float))}
