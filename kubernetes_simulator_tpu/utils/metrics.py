"""Metrics & observability (layer L7; SURVEY.md §5).

Structured JSONL results (per-run and per-scenario rows), plain-text
progress logging, and a BASELINE.md-compatible table emitter. The headline
metric is pod-placements/sec ([BASELINE])."""

from __future__ import annotations

import hashlib
import json
import logging
import os
import sys
import time
from typing import IO, Dict, Iterable, Optional

import numpy as np


def deterministic_jsonl() -> bool:
    """``KSIM_DETERMINISTIC_JSONL=1`` zeroes every wall-clock-derived
    JSONL field (``ts``, ``wall_clock_s``, ``placements_per_sec``) while
    keeping the fields PRESENT as numbers, so v2-schema rows stay valid.
    This is what makes the round-11 DCN parity bar byte-for-byte testable:
    a 2-process replay and its single-process oracle differ only in
    timing, never in results — with timing zeroed, the JSONL files must
    be identical down to the byte (tests/test_dcn.py)."""
    return os.environ.get("KSIM_DETERMINISTIC_JSONL", "") == "1"

log = logging.getLogger("k8sim")
if not log.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s"))
    log.addHandler(_h)
    log.setLevel(logging.INFO)

# JSONL row schema version. Bump on any breaking change to the row shape;
# scripts/check_metrics_schema.py validates emitted files against it.
#   v1 — rows carried only "ts" + payload (implicit, unversioned).
#   v2 — every row stamped with "schema" plus writer context
#        (seed / engine / config_hash from the CLI).
#   v3 — tuner rows (sim.tuner): "run_type" required, "ts" optional —
#        trajectory files are bit-deterministic for a fixed seed + config,
#        so no wall-clock fields. Non-tuner rows stay v2.
#   v4 — utilization economics (round 13): replay rows may carry a
#        "fragmentation" dict (stranded / frag_index / packing gauges);
#        whatif-scenario rows may carry stranded_cpu / frag_index_cpu /
#        packing_efficiency (None on paths without host mirrors). All new
#        fields are virtual-time-deterministic — KSIM_DETERMINISTIC_JSONL
#        needs no new scrubs.
#   v5 — flight recorder (round 16): a new "flight" row kind
#        (sim.flight.FlightRecorder) with a relaxed base — flight streams
#        are engine-internal, so rows carry ts/schema/kind but no CLI
#        context (seed/engine/config_hash). Non-flight rows keep the v4
#        rules; v1–v4 files validate byte-unchanged.
#        KSIM_DETERMINISTIC_JSONL zeroes every wall-clock-derived flight
#        field (sim.flight.FLIGHT_WALL_FIELDS) so fixed-seed recorder
#        streams are byte-stable.
#   v6 — fleet black box (round 21): rows may carry the causal trace
#        identity fields "trace"/"span"/"parent"/"link" (parallel.trace
#        — pure functions of protocol state, never scrubbed), flight
#        streams may carry "fleet" event rows (dcn fleet events
#        flattened by the recorder), and a new "postmortem" row kind
#        (scripts/fleet_postmortem.py audit summary: events ingested,
#        links resolved, invariant verdicts, audit wall). Non-flight
#        rows keep the v4 rules; v1–v5 files validate byte-unchanged.
#   v7 — simulator-as-a-service (round 22, sim.service): three new row
#        kinds on the serving plane — "query" (admission: tenant /
#        query id / family / queue depth), "query-result" (per-tenant
#        demux of a coalesced batch: slot, occupancy, warm flag, batch
#        latency, eviction cost + fragmentation benefit vs the baseline
#        slot) and "query-error" (a malformed serve line, structured —
#        the service keeps serving). Flight streams gain a "query"
#        event (queue depth, batch occupancy, cold-vs-warm latency).
#        KSIM_DETERMINISTIC_JSONL zeroes the new wall-derived fields
#        ("latency_s" / "queue_wait_s"). v1–v6 files validate
#        byte-unchanged.
SCHEMA_VERSION = 7
TUNE_SCHEMA_VERSION = 3


def config_hash(cfg_dict: dict) -> str:
    """Short stable hash of a config mapping (canonical-JSON sha256).
    Stamped on every JSONL row so runs are attributable to the exact
    config that produced them."""
    blob = json.dumps(cfg_dict, sort_keys=True, separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


# -- utilization economics (round 13) ------------------------------------
#
# Every engine (CPU event engine, device boundary mirror, plain device
# path after D2H) funnels its end-of-replay and per-sample utilization /
# fragmentation arithmetic through the three helpers below. One shared
# float64 code path is what makes the CPU↔device bit-parity bar hold BY
# CONSTRUCTION: both engines hand over the same committed state, so the
# gauges cannot drift through reimplementation.

_UTIL_RESOURCES = ("cpu", "memory")


def utilization_means(used, allocatable, rindex) -> Dict[str, float]:
    """Mean per-node utilization fraction per resource name.

    ``used``/``allocatable`` are [N, R]; ``rindex`` maps resource name →
    column. Nodes with zero allocatable (drained / chaos node_down before
    restore) count as 0 utilization, matching the historical inline loops
    this replaces."""
    used = np.asarray(used, dtype=np.float64)
    alloc_all = np.asarray(allocatable, dtype=np.float64)
    util: Dict[str, float] = {}
    for rname in _UTIL_RESOURCES:
        ri = rindex.get(rname)
        if ri is not None:
            alloc = alloc_all[:, ri]
            with np.errstate(invalid="ignore", divide="ignore"):
                u = np.where(alloc > 0, used[:, ri] / np.where(alloc > 0, alloc, 1), 0)
            util[rname] = float(u.mean())
    return util


def series_gauges(used, allocatable, rindex) -> Dict[str, float]:
    """Per-sample utilization gauges for the telemetry series (round 13).

    Keys: ``util_cpu`` (mean per-node CPU utilization), ``util_mem``
    (only when the vocab has a memory column — series keys must stay
    consistent within one run), and ``frag_cpu`` (CPU fragmentation
    index: 1 − largest free block / total free; 0 when nothing is free).
    Called at every event-loop sample on the CPU engine and at every
    chunk boundary on the device path — same helper, bit-parity by
    construction."""
    means = utilization_means(used, allocatable, rindex)
    out = {"util_cpu": means.get("cpu", 0.0)}
    if "memory" in means:
        out["util_mem"] = means["memory"]
    ci = rindex.get("cpu")
    frag = 0.0
    if ci is not None:
        alloc = np.asarray(allocatable, dtype=np.float64)[:, ci]
        u = np.asarray(used, dtype=np.float64)[:, ci]
        free = np.maximum(alloc - u, 0.0)
        total_free = float(free.sum())
        if total_free > 0.0:
            frag = 1.0 - float(free.max()) / total_free
    out["frag_cpu"] = frag
    return out


def fragmentation_gauges(allocatable, used, pending_requests, rindex) -> dict:
    """End-of-replay fragmentation / packing gauges (round 13).

    - ``stranded[r]``: free capacity on nodes that cannot fit the largest
      still-pending pod (largest by CPU request, memory tie-break, lowest
      pod index last) — the classic stranded-capacity gauge. 0 when no
      pod is pending. The fit test is vector-wise over ALL resource
      columns, so a node is only "usable" if the whole pod fits.
    - ``frag_index[r]``: 1 − largest free block / total free (0 when the
      cluster is fully packed or fully empty).
    - ``packing_efficiency``: ideal node count (sum-of-usage lower bound,
      per-resource ceiling against the largest node) / nodes actually
      touched. 1.0 when nothing is placed.

    Pure float64 numpy on host state — both engines call it with the
    restored allocatable and their committed ``used``/pending sets, so
    the outputs are bit-identical CPU ↔ device."""
    alloc = np.asarray(allocatable, dtype=np.float64)
    used = np.asarray(used, dtype=np.float64)
    req = np.asarray(pending_requests, dtype=np.float64)
    if req.ndim == 1:
        req = req.reshape(0, alloc.shape[1]) if req.size == 0 else req.reshape(1, -1)
    free = np.maximum(alloc - used, 0.0)
    names = [r for r in _UTIL_RESOURCES if rindex.get(r) is not None]

    stranded: Dict[str, float] = {r: 0.0 for r in names}
    stranded_frac: Dict[str, float] = {r: 0.0 for r in names}
    npend = int(req.shape[0])
    if npend:
        n = npend
        ci, mi = rindex.get("cpu"), rindex.get("memory")
        key_cpu = req[:, ci] if ci is not None else np.zeros(n)
        key_mem = req[:, mi] if mi is not None else np.zeros(n)
        # lexsort: last key is primary — biggest CPU, then biggest memory,
        # then lowest index, so the "largest pending pod" is deterministic.
        big = req[int(np.lexsort((np.arange(n), -key_mem, -key_cpu))[0])]
        # The scheduler's own fit arithmetic decides "cannot fit" (local
        # import: ops pulls the model stack, metrics must stay light).
        from ..ops.cpu import pending_fit_mask

        fits = pending_fit_mask(used, alloc, big)
        for r in names:
            ri = rindex[r]
            stranded[r] = float(free[~fits, ri].sum())
            total = float(alloc[:, ri].sum())
            stranded_frac[r] = stranded[r] / total if total > 0 else 0.0

    frag_index: Dict[str, float] = {}
    for r in names:
        ri = rindex[r]
        total_free = float(free[:, ri].sum())
        frag_index[r] = (
            1.0 - float(free[:, ri].max()) / total_free if total_free > 0 else 0.0
        )

    nodes_active = int(np.any(used > 0, axis=1).sum())
    nodes_ideal = 0
    for r in names:
        ri = rindex[r]
        cap = float(alloc[:, ri].max()) if alloc.shape[0] else 0.0
        total_used = float(used[:, ri].sum())
        if cap > 0 and total_used > 0:
            nodes_ideal = max(nodes_ideal, int(np.ceil(total_used / cap)))
    packing = float(nodes_ideal) / nodes_active if nodes_active else 1.0
    return {
        "stranded": stranded,
        "stranded_frac": stranded_frac,
        "frag_index": frag_index,
        "packing_efficiency": packing,
        "nodes_active": nodes_active,
        "nodes_ideal": nodes_ideal,
        "pending": npend,
    }


def round_fragmentation(frag: Optional[dict]) -> Optional[dict]:
    """JSONL/summary-friendly copy of a fragmentation_gauges() dict with
    floats rounded to 6 places (virtual-time-deterministic, so no
    KSIM_DETERMINISTIC_JSONL scrub is needed)."""
    if frag is None:
        return None
    out: dict = {}
    for k, v in frag.items():
        if isinstance(v, dict):
            out[k] = {kk: round(float(vv), 6) for kk, vv in v.items()}
        elif isinstance(v, float):
            out[k] = round(v, 6)
        else:
            out[k] = v
    return out


class JsonlWriter:
    """Append-mode JSONL sink (stdout when ``path`` is None). Usable as a
    context manager — the CLI wraps whole commands in ``with`` so the file
    is closed (rows flushed) even when the run raises. Every row is
    stamped with ``ts``, ``schema`` and the writer's ``context`` (seed /
    engine / config hash); explicit row keys win over context keys."""

    def __init__(self, path: Optional[str] = None, context: Optional[dict] = None):
        self.path = path
        self.context = dict(context or {})
        self._f: Optional[IO] = open(path, "a") if path else None
        self._proc: Optional[dict] = None  # lazy DCN process stamp

    def _process_stamp(self) -> dict:
        """``process_id``/``process_count`` under DCN (round 12): rows from
        a fleet are attributable to the worker that wrote them. Empty in
        single-process runs — v1–v3 rows are byte-unchanged there, and the
        DCN parity bar strips exactly these two keys before comparing
        against the single-process oracle (tests/dcn_case_worker.py)."""
        if self._proc is None:
            try:
                from ..parallel import dcn

                nproc, pid = dcn.process_info()
                self._proc = (
                    {"process_id": int(pid), "process_count": int(nproc)}
                    if nproc > 1
                    else {}
                )
            except Exception:
                self._proc = {}
        return self._proc

    def write(self, row: dict, stamp_ts: bool = True) -> None:
        # stamp_ts=False drops the wall-clock stamp — the policy tuner's
        # trajectory rows must be byte-identical across same-seed runs.
        stamp = (
            {"ts": 0.0 if deterministic_jsonl() else time.time()}
            if stamp_ts
            else {}
        )
        row = {
            **stamp,
            "schema": SCHEMA_VERSION,
            **self._process_stamp(),
            **self.context,
            **row,
        }
        line = json.dumps(row)
        if self._f:
            self._f.write(line + "\n")
            self._f.flush()
        else:
            print(line)

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    def __enter__(self) -> "JsonlWriter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _scrub_timing(row: dict) -> dict:
    """Zero wall-clock-derived fields under KSIM_DETERMINISTIC_JSONL
    (fields stay present as numbers — schema v2 requires them)."""
    if deterministic_jsonl():
        for k in (
            "wall_clock_s", "placements_per_sec", "latency_s",
            "queue_wait_s",
        ):
            if k in row:
                row[k] = 0.0
    return row


def replay_row(kind: str, res, extra: Optional[dict] = None) -> dict:
    row = {"kind": kind, **res.summary()} if hasattr(res, "summary") else {"kind": kind}
    if extra:
        row.update(extra)
    return _scrub_timing(row)


def whatif_rows(res, extra: Optional[dict] = None) -> Iterable[dict]:
    base = extra or {}
    yield _scrub_timing({
        "kind": "whatif-aggregate",
        "scenarios": int(res.placed.shape[0]),
        "total_placed": res.total_placed,
        "wall_clock_s": round(res.wall_clock_s, 4),
        "placements_per_sec": round(res.placements_per_sec, 1),
        "completions_on": bool(res.completions_on),
        "engine": res.engine,
        **base,
    })
    pre = getattr(res, "preemptions", None)
    drop = getattr(res, "retry_dropped", None)
    evi = getattr(res, "evictions", None)
    lat50 = getattr(res, "latency_p50", None)
    str_cpu = getattr(res, "stranded_cpu", None)
    for s in range(res.placed.shape[0]):
        row = {
            "kind": "whatif-scenario",
            "scenario": s,
            "placed": int(res.placed[s]),
            "unschedulable": int(res.unschedulable[s]),
            "utilization_cpu": (
                round(float(res.utilization_cpu[s]), 4) if res.utilization_cpu is not None else None
            ),
            **base,
        }
        if pre is not None:
            # kube batches: drops mean placements lost to buffer
            # capacity, not infeasibility.
            row["preemptions"] = int(pre[s])
            row["retry_dropped"] = int(drop[s])
        if evi is not None:
            # chaos disruption — distinct from scheduler-initiated
            # preemption above.
            row["evictions"] = int(evi[s])
            row["evict_rescheduled"] = int(res.evict_rescheduled[s])
            row["evict_stranded"] = int(res.evict_stranded[s])
            row["evict_latency_mean"] = round(
                float(res.evict_latency_mean[s]), 4
            )
        if lat50 is not None:
            # Telemetry layer: per-scenario first-bind latency quantiles
            # (virtual seconds); None when the scenario bound nothing.
            import math

            for key, arr in (
                ("latency_p50", lat50),
                ("latency_p90", res.latency_p90),
                ("latency_p99", res.latency_p99),
            ):
                v = float(arr[s])
                row[key] = None if math.isnan(v) else round(v, 6)
        if str_cpu is not None:
            # Fragmentation economics (schema v4, kube what-if paths with
            # host mirrors); virtual-time-deterministic by construction.
            row["stranded_cpu"] = round(float(str_cpu[s]), 6)
            row["frag_index_cpu"] = round(float(res.frag_index_cpu[s]), 6)
            row["packing_efficiency"] = round(
                float(res.packing_efficiency[s]), 6
            )
        yield row


def baseline_table(rows: Iterable[dict]) -> str:
    """Markdown table in the BASELINE.md format."""
    out = ["| Metric | Value | Hardware | Source |", "|---|---|---|---|"]
    for r in rows:
        out.append(
            f"| {r.get('metric', r.get('kind'))} | {r.get('value', r.get('placements_per_sec'))} "
            f"| {r.get('hardware', '-')} | {r.get('source', 'this run')} |"
        )
    return "\n".join(out)
