"""Metrics & observability (layer L7; SURVEY.md §5).

Structured JSONL results (per-run and per-scenario rows), plain-text
progress logging, and a BASELINE.md-compatible table emitter. The headline
metric is pod-placements/sec ([BASELINE])."""

from __future__ import annotations

import hashlib
import json
import logging
import os
import sys
import time
from typing import IO, Iterable, Optional


def deterministic_jsonl() -> bool:
    """``KSIM_DETERMINISTIC_JSONL=1`` zeroes every wall-clock-derived
    JSONL field (``ts``, ``wall_clock_s``, ``placements_per_sec``) while
    keeping the fields PRESENT as numbers, so v2-schema rows stay valid.
    This is what makes the round-11 DCN parity bar byte-for-byte testable:
    a 2-process replay and its single-process oracle differ only in
    timing, never in results — with timing zeroed, the JSONL files must
    be identical down to the byte (tests/test_dcn.py)."""
    return os.environ.get("KSIM_DETERMINISTIC_JSONL", "") == "1"

log = logging.getLogger("k8sim")
if not log.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s"))
    log.addHandler(_h)
    log.setLevel(logging.INFO)

# JSONL row schema version. Bump on any breaking change to the row shape;
# scripts/check_metrics_schema.py validates emitted files against it.
#   v1 — rows carried only "ts" + payload (implicit, unversioned).
#   v2 — every row stamped with "schema" plus writer context
#        (seed / engine / config_hash from the CLI).
#   v3 — tuner rows (sim.tuner): "run_type" required, "ts" optional —
#        trajectory files are bit-deterministic for a fixed seed + config,
#        so no wall-clock fields. Non-tuner rows stay v2.
SCHEMA_VERSION = 2
TUNE_SCHEMA_VERSION = 3


def config_hash(cfg_dict: dict) -> str:
    """Short stable hash of a config mapping (canonical-JSON sha256).
    Stamped on every JSONL row so runs are attributable to the exact
    config that produced them."""
    blob = json.dumps(cfg_dict, sort_keys=True, separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


class JsonlWriter:
    """Append-mode JSONL sink (stdout when ``path`` is None). Usable as a
    context manager — the CLI wraps whole commands in ``with`` so the file
    is closed (rows flushed) even when the run raises. Every row is
    stamped with ``ts``, ``schema`` and the writer's ``context`` (seed /
    engine / config hash); explicit row keys win over context keys."""

    def __init__(self, path: Optional[str] = None, context: Optional[dict] = None):
        self.path = path
        self.context = dict(context or {})
        self._f: Optional[IO] = open(path, "a") if path else None
        self._proc: Optional[dict] = None  # lazy DCN process stamp

    def _process_stamp(self) -> dict:
        """``process_id``/``process_count`` under DCN (round 12): rows from
        a fleet are attributable to the worker that wrote them. Empty in
        single-process runs — v1–v3 rows are byte-unchanged there, and the
        DCN parity bar strips exactly these two keys before comparing
        against the single-process oracle (tests/dcn_case_worker.py)."""
        if self._proc is None:
            try:
                from ..parallel import dcn

                nproc, pid = dcn.process_info()
                self._proc = (
                    {"process_id": int(pid), "process_count": int(nproc)}
                    if nproc > 1
                    else {}
                )
            except Exception:
                self._proc = {}
        return self._proc

    def write(self, row: dict, stamp_ts: bool = True) -> None:
        # stamp_ts=False drops the wall-clock stamp — the policy tuner's
        # trajectory rows must be byte-identical across same-seed runs.
        stamp = (
            {"ts": 0.0 if deterministic_jsonl() else time.time()}
            if stamp_ts
            else {}
        )
        row = {
            **stamp,
            "schema": SCHEMA_VERSION,
            **self._process_stamp(),
            **self.context,
            **row,
        }
        line = json.dumps(row)
        if self._f:
            self._f.write(line + "\n")
            self._f.flush()
        else:
            print(line)

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None

    def __enter__(self) -> "JsonlWriter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _scrub_timing(row: dict) -> dict:
    """Zero wall-clock-derived fields under KSIM_DETERMINISTIC_JSONL
    (fields stay present as numbers — schema v2 requires them)."""
    if deterministic_jsonl():
        for k in ("wall_clock_s", "placements_per_sec"):
            if k in row:
                row[k] = 0.0
    return row


def replay_row(kind: str, res, extra: Optional[dict] = None) -> dict:
    row = {"kind": kind, **res.summary()} if hasattr(res, "summary") else {"kind": kind}
    if extra:
        row.update(extra)
    return _scrub_timing(row)


def whatif_rows(res, extra: Optional[dict] = None) -> Iterable[dict]:
    base = extra or {}
    yield _scrub_timing({
        "kind": "whatif-aggregate",
        "scenarios": int(res.placed.shape[0]),
        "total_placed": res.total_placed,
        "wall_clock_s": round(res.wall_clock_s, 4),
        "placements_per_sec": round(res.placements_per_sec, 1),
        "completions_on": bool(res.completions_on),
        "engine": res.engine,
        **base,
    })
    pre = getattr(res, "preemptions", None)
    drop = getattr(res, "retry_dropped", None)
    evi = getattr(res, "evictions", None)
    lat50 = getattr(res, "latency_p50", None)
    for s in range(res.placed.shape[0]):
        row = {
            "kind": "whatif-scenario",
            "scenario": s,
            "placed": int(res.placed[s]),
            "unschedulable": int(res.unschedulable[s]),
            "utilization_cpu": (
                round(float(res.utilization_cpu[s]), 4) if res.utilization_cpu is not None else None
            ),
            **base,
        }
        if pre is not None:
            # kube batches: drops mean placements lost to buffer
            # capacity, not infeasibility.
            row["preemptions"] = int(pre[s])
            row["retry_dropped"] = int(drop[s])
        if evi is not None:
            # chaos disruption — distinct from scheduler-initiated
            # preemption above.
            row["evictions"] = int(evi[s])
            row["evict_rescheduled"] = int(res.evict_rescheduled[s])
            row["evict_stranded"] = int(res.evict_stranded[s])
            row["evict_latency_mean"] = round(
                float(res.evict_latency_mean[s]), 4
            )
        if lat50 is not None:
            # Telemetry layer: per-scenario first-bind latency quantiles
            # (virtual seconds); None when the scenario bound nothing.
            import math

            for key, arr in (
                ("latency_p50", lat50),
                ("latency_p90", res.latency_p90),
                ("latency_p99", res.latency_p99),
            ):
                v = float(arr[s])
                row[key] = None if math.isnan(v) else round(v, 6)
        yield row


def baseline_table(rows: Iterable[dict]) -> str:
    """Markdown table in the BASELINE.md format."""
    out = ["| Metric | Value | Hardware | Source |", "|---|---|---|---|"]
    for r in rows:
        out.append(
            f"| {r.get('metric', r.get('kind'))} | {r.get('value', r.get('placements_per_sec'))} "
            f"| {r.get('hardware', '-')} | {r.get('source', 'this run')} |"
        )
    return "\n".join(out)
