"""YAML configuration (SURVEY.md §5 config/flag system).

One schema with the upstream ``KubeSchedulerConfiguration`` vocabulary
(profiles → plugins → args, per-plugin Score weights) plus simulator
sections (cluster, workload, what-if, strategy). ``strategy`` selects the
backend through the L6 registry — ``cpu`` is the default path, ``jax`` the
TPU backend ([BASELINE] requirement).

Example::

    strategy: jax
    cluster:
      synthetic: {nodes: 5000, seed: 0, taintFraction: 0.1}
    workload:
      synthetic: {pods: 50000, seed: 0, affinity: true, spread: true,
                  tolerations: true, gangFraction: 0.02, gangSize: 4}
    profile:
      plugins:
        - name: NodeResourcesFit
          args: {strategy: LeastAllocated, resources: {cpu: 1, memory: 1}}
        - name: TaintToleration
        - name: NodeAffinity
        - name: InterPodAffinity
        - name: PodTopologySpread
      weights: {NodeResourcesFit: 1, TaintToleration: 3}
    whatIf:
      scenarios: 256
      seed: 0
      mesh: true
    output: results.jsonl
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml

from ..framework.framework import FrameworkConfig


@dataclass
class SyntheticClusterSpec:
    nodes: int = 100
    seed: int = 0
    taint_fraction: float = 0.0
    zones: int = 8
    extended_resources: Optional[Dict[str, Any]] = None


@dataclass
class SyntheticWorkloadSpec:
    pods: int = 1000
    seed: int = 0
    affinity: bool = False
    spread: bool = False
    tolerations: bool = False
    gang_fraction: float = 0.0
    gang_size: int = 4
    arrival_rate: float = 100.0
    duration_mean: Optional[float] = None
    num_apps: int = 20


@dataclass
class BorgWorkloadSpec:
    nodes: int = 10_000
    tasks: int = 1_000_000
    seed: int = 0
    gang_fraction: float = 0.08
    max_gang: int = 8
    num_apps: int = 48  # template/app vocabulary (clip bound for app_id)
    trace_path: Optional[str] = None  # external task-event CSV (sim.borg)
    # Real Borg-2019 schema ingest (sim.borg_etl): instance_events CSV
    # (required for the ETL path) + optional collection_events fallback.
    instance_events: Optional[str] = None
    collection_events: Optional[str] = None
    cpu_scale: float = 8.0
    mem_scale: float = 16.0 * 2**30


@dataclass
class WhatIfSpec:
    scenarios: int = 0
    seed: int = 0
    mesh: bool = False
    node_down_p: float = 0.02
    capacity_p: float = 0.3
    taint_p: float = 0.1
    # None = default-on completions (warn when unhonorable); True/False
    # are the explicit forms (sim.whatif.WhatIfEngine docstring).
    completions: object = None
    # Device-path unschedulable retry buffer width (0 = off).
    retry_buffer: int = 0


@dataclass
class ChaosSpec:
    """Seeded chaos campaign (``chaos:`` YAML section): MTBF/MTTR-style
    failure injection. ``cmd_run`` turns this into a single
    ``node_events`` timeline; ``cmd_whatif`` gives each scenario s > 0 its
    own ``seed + s`` timeline (scenario 0 stays the clean reference)."""

    enabled: bool = False
    seed: int = 0
    mtbf: float = 200.0
    mttr: float = 20.0
    node_fraction: float = 0.2
    horizon: Optional[float] = None  # None → workload makespan
    max_events: Optional[int] = None


@dataclass
class TuneSpec:
    """Policy-tuner section (``tune:`` YAML, round 9 — sim.tuner). Drives
    ``cmd_tune`` / ``Simulator.tune()``: a seeded search over the Score
    policy surface of the ``profile:`` scheduler against scenarios derived
    from the config's cluster/workload. ``objective`` maps metric name →
    weight (maximized; costs use negative weights). ``scenarios`` holds
    the train/held-out split sizes plus the perturbation sampler knobs;
    ``weight_bounds`` overrides the default search range for every weight
    column; ``output`` is the trajectory JSONL sink (falls back to the
    top-level ``output``)."""

    algo: str = "cem"
    population: int = 16
    rounds: int = 6
    seed: int = 0
    elite_frac: float = 0.25
    objective: Optional[Dict[str, float]] = None
    # Round 13: penalty constraints (list of {metric, max|min, penalty})
    # and the evaluator knob — "auto" (device sweep when the terms allow,
    # else the CPU event engine), "device", or "cpu".
    constraints: Optional[List[Dict[str, float]]] = None
    evaluator: str = "auto"
    train_scenarios: int = 4
    heldout_scenarios: int = 2
    scenario_seed: int = 0
    node_down_p: float = 0.02
    capacity_p: float = 0.3
    taint_p: float = 0.1
    weight_bounds: Optional[List[float]] = None
    tune_strategy: bool = True
    mesh: bool = False
    cpu_oracle: bool = True
    cpu_envelope: float = 1e-6
    output: Optional[str] = None


@dataclass
class DcnRecoverySpec:
    """Elastic fleet recovery (``dcn.recovery:`` YAML section, round 15 —
    parallel.dcn). Config-level spelling of the ``KSIM_DCN_RECOVER`` /
    ``KSIM_DCN_CKPT_EVERY`` / ``KSIM_DCN_MAX_CLAIMS`` env knobs: the CLI
    exports them (setdefault — an operator's explicit env wins) BEFORE
    ``jax.distributed`` bring-up, so the coordination-service failure
    detector is widened in the same run. ``checkpoint_every`` is the
    chunk cadence of compressed checkpoint publication (0 = off; a
    claimed block then re-executes from chunk 0); ``max_claims`` bounds
    the claim generations per dead block (a stale claimant's claim can
    be superseded that many times before the gather fails attributed)."""

    enable: bool = False
    checkpoint_every: int = 0
    max_claims: int = 2


@dataclass
class DcnWorkQueueSpec:
    """Work-stealing scenario-block queue (``dcn.workQueue:`` YAML
    section, round 18 — parallel.dcn). Config-level spelling of the
    ``KSIM_DCN_WORKQUEUE`` / ``KSIM_DCN_WQ_BLOCK`` /
    ``KSIM_DCN_SPECULATE`` / ``KSIM_DCN_STRAGGLER_S`` env knobs, exported
    by the CLI (setdefault) before ``jax.distributed`` bring-up.
    ``block_size`` is scenarios per lease (0 = auto: one block per worker
    — the static partition when nobody steals); ``speculate`` enables
    backup re-execution of straggling blocks (requires checkpoint
    publication via ``dcn.recovery.checkpointEvery`` to resume mid-block;
    validate_config refuses it without); ``straggler_s`` is the
    lease-renewal age past which a LIVE holder becomes
    speculation-eligible (0 = half the stall window)."""

    enable: bool = False
    block_size: int = 0
    speculate: bool = False
    straggler_s: float = 0.0


@dataclass
class DcnDurableSpec:
    """Durable ground (``dcn.durable:`` YAML section, round 20 —
    parallel.dcn). Config-level spelling of the ``KSIM_DCN_DURABLE_DIR``
    / ``KSIM_DCN_RESUME`` env knobs, exported by the CLI (setdefault)
    before ``jax.distributed`` bring-up. ``dir`` is the
    filesystem-backed durability journal the fleet mirrors its
    checkpoint blobs, work-queue results and done/lease ledger into
    (the writes ride the round-19 background publisher — the sync path
    gains no stall); ``resume: true`` seeds a fresh fleet's KV store
    from that journal on bring-up: completed blocks are adopted without
    re-execution, in-flight blocks resume from their newest complete
    durable cursor, and the end gather is byte-identical to an
    uninterrupted run. A bare string is shorthand for ``dir``.
    validate_config refuses a journal without a DCN fleet or without
    any checkpoint cadence — there would be nothing durable to mirror."""

    dir: Optional[str] = None
    resume: bool = False


@dataclass
class FlightRecorderSpec:
    """Flight recorder (``flightRecorder:`` YAML section, round 16 —
    sim.flight). ``path`` is the JSONL stream sink (suffixed per process
    under DCN); ``every`` is the chunk-row cadence (1 = every chunk
    boundary; page/checkpoint/fold events always emit). jax strategy
    only — the CPU engine has no chunk loop to record."""

    path: str = "flight.jsonl"
    every: int = 1


@dataclass
class FaultlineSpec:
    """Deterministic fleet fault injection (``faultline:`` YAML section,
    round 17 — parallel.faultline). Config-level spelling of the
    ``KSIM_FAULTLINE_*`` env knobs, exported by the CLI (setdefault)
    before ``jax.distributed`` bring-up. Rates are per-operation
    probabilities in [0, 1] drawn from seeded per-class streams; ``kill``
    is a SIGKILL schedule (``"1@run:0,*@recover:-1"`` — see
    ``faultline.parse_kill_schedule``). Off by default and only
    meaningful in multi-process (DCN) runs; enabling injection with
    ``dcn.recovery`` disabled is legal but warned — injected kills and
    give-ups then fail the fleet attributed instead of recovering."""

    enabled: bool = False
    seed: int = 0
    kv_error_rate: float = 0.0
    kv_delay_rate: float = 0.0
    kv_delay_s: float = 0.02
    torn_write_rate: float = 0.0
    stale_read_rate: float = 0.0
    kill: Optional[str] = None
    # Straggler schedule (round 18): "<pid>@<chunk>:<factor>" entries —
    # see faultline.parse_slow_schedule. Distinct from kill: the process
    # stays alive, each heartbeat just sleeps `factor` seconds.
    slow: Optional[str] = None


@dataclass
class OverlapSpec:
    """Overlap plane (``overlap:`` YAML section, round 19). Config-level
    spelling of the three stall-hiding gates — each defaults ON in the
    engines; a field left None inherits the engine/env default, an
    explicit false exports the opt-out BEFORE ``jax.distributed``
    bring-up (setdefault — an operator's explicit env wins):

    * ``pagerThread`` → ``KSIM_PAGER_THREAD`` (sim.jax_runtime): run the
      pod-page encode/pack + device_put on a background worker. Requires
      ``pagedWaves: true`` when explicitly enabled.
    * ``backgroundPublisher`` → ``KSIM_DCN_CKPT_ASYNC`` (parallel.dcn):
      single-flight newest-wins checkpoint publication off the loop
      thread. Requires a checkpoint cadence (``dcn.recovery:
      checkpointEvery >= 1`` or a work queue) when explicitly enabled.
    * ``twoPhaseExchange`` → ``KSIM_TWO_PHASE_EXCHANGE`` (ops.tpu): slim
      two-phase selection exchange under ``nodeShards``.

    All three are bit-parity pinned (tests/test_overlap.py): placements,
    deterministic JSONL and checkpoint blobs are identical on vs off."""

    pager_thread: Optional[bool] = None
    background_publisher: Optional[bool] = None
    two_phase_exchange: Optional[bool] = None


@dataclass
class ServiceSpec:
    """Resident query service (``service:`` YAML section, round 22 —
    sim.service / the ``serve`` CLI subcommand). ``maxBatch`` is the
    number of query slots coalesced onto the scenario axis (the device
    batch is maxBatch + 1 — slot 0 is the clean baseline);
    ``batchDeadlineS`` is the admission-queue flush deadline;
    ``maxEngines`` caps the LRU engine pool (the
    ``KSIM_SERVICE_MAX_ENGINES`` env wins over this value);
    ``granularity`` is the default telemetry level of query results
    (queries may override per-request); ``retryBuffer`` sizes the kube
    boundary retry pass defrag drains evict through; ``input`` is an
    NDJSON query source (a file or named pipe; null = stdin). Results
    stream to the top-level ``output`` (null = stdout). Requires
    ``strategy: jax``, ``devicePreemption: kube`` and no
    ``nodeShards`` — validate_config refuses anything else."""

    max_batch: int = 3
    batch_deadline_s: float = 0.05
    max_engines: int = 4
    granularity: str = "summary"
    retry_buffer: int = 64
    input: Optional[str] = None


@dataclass
class TelemetrySpec:
    """Telemetry layer (``telemetry:`` YAML section, SURVEY.md §5).

    ``granularity`` is the collection knob (sim.telemetry docstring):
    off / summary (default; latency histogram + phase timers, zero
    device-program change) / series (+ rejection attribution and
    virtual-time depth series) / timeline (+ bind/preempt/evict/chaos
    events). ``timeline_out`` writes the simulated cluster timeline as a
    Chrome trace JSON (load in Perfetto) and implies ``timeline``."""

    granularity: str = "summary"
    timeline_out: Optional[str] = None


def _coerce_completions(v: object) -> Optional[bool]:
    """None stays None (default-on with warn); bool/int coerce to bool;
    everything else is a config error, not a truthy surprise."""
    if v is None:
        return None
    if isinstance(v, (bool, int)):
        return bool(v)
    raise ValueError(
        f"whatIf.completions: must be true or false, got {v!r}"
    )


@dataclass
class SimConfig:
    strategy: str = "cpu"
    cluster: SyntheticClusterSpec = field(default_factory=SyntheticClusterSpec)
    workload: Optional[SyntheticWorkloadSpec] = None
    borg: Optional[BorgWorkloadSpec] = None
    framework: FrameworkConfig = field(default_factory=FrameworkConfig)
    whatif: WhatIfSpec = field(default_factory=WhatIfSpec)
    tune: Optional[TuneSpec] = None
    chaos: Optional[ChaosSpec] = None
    dcn_recovery: Optional[DcnRecoverySpec] = None
    dcn_workqueue: Optional[DcnWorkQueueSpec] = None
    dcn_durable: Optional[DcnDurableSpec] = None
    faultline: Optional[FaultlineSpec] = None
    telemetry: TelemetrySpec = field(default_factory=TelemetrySpec)
    output: Optional[str] = None
    wave_width: int = 8
    chunk_waves: int = 1024
    # Device preemption (jax strategy / what-if): False, True/"tier" (the
    # in-scan tier approximation), or "kube" (exact minimal-victims
    # PostFilter at chunk boundaries; single-replay engine only — see
    # sim.greedy / sim.boundary docstrings).
    device_preemption: object = False
    # Big-scenario mode (round 14, jax strategy only): shard ONE scenario's
    # node planes over `nodeShards` local devices, and/or stream pod pages
    # host->device instead of whole-trace residency (`pagedWaves`).
    node_shards: int = 0
    paged_waves: bool = False
    # Flight recorder (round 16, jax strategy only): streaming JSONL
    # observability for long replays (sim.flight). None = off (the
    # default — the recorder is bit-parity pinned but still costs a
    # stream).
    flight_recorder: Optional[FlightRecorderSpec] = None
    # Overlap plane (round 19): the three stall-hiding gates. None = all
    # engine defaults (on).
    overlap: Optional[OverlapSpec] = None
    # Resident query service (round 22, `serve` subcommand only). None =
    # the config is not a service config.
    service: Optional[ServiceSpec] = None

    @classmethod
    def from_dict(cls, d: dict) -> "SimConfig":
        cfg = cls()
        cfg.strategy = d.get("strategy", "cpu")
        cl = d.get("cluster", {})
        syn = cl.get("synthetic", cl) or {}
        cfg.cluster = SyntheticClusterSpec(
            nodes=int(syn.get("nodes", 100)),
            seed=int(syn.get("seed", 0)),
            taint_fraction=float(syn.get("taintFraction", 0.0)),
            zones=int(syn.get("zones", 8)),
            extended_resources=syn.get("extendedResources"),
        )
        wl = d.get("workload", {})
        if "borg" in wl:
            b = wl["borg"]
            cfg.borg = BorgWorkloadSpec(
                nodes=int(b.get("nodes", 10_000)),
                tasks=int(b.get("tasks", 1_000_000)),
                seed=int(b.get("seed", 0)),
                gang_fraction=float(b.get("gangFraction", 0.08)),
                max_gang=int(b.get("maxGang", 8)),
                num_apps=int(b.get("numApps", 48)),
                trace_path=b.get("tracePath"),
                instance_events=b.get("instanceEvents"),
                collection_events=b.get("collectionEvents"),
                cpu_scale=float(b.get("cpuScale", 8.0)),
                mem_scale=float(b.get("memScale", 16.0 * 2**30)),
            )
        else:
            syn = wl.get("synthetic", wl) or {}
            cfg.workload = SyntheticWorkloadSpec(
                pods=int(syn.get("pods", 1000)),
                seed=int(syn.get("seed", 0)),
                affinity=bool(syn.get("affinity", False)),
                spread=bool(syn.get("spread", False)),
                tolerations=bool(syn.get("tolerations", False)),
                gang_fraction=float(syn.get("gangFraction", 0.0)),
                gang_size=int(syn.get("gangSize", 4)),
                arrival_rate=float(syn.get("arrivalRate", 100.0)),
                duration_mean=syn.get("durationMean"),
                num_apps=int(syn.get("numApps", 20)),
            )
        prof = d.get("profile", {})
        plugins = prof.get("plugins")
        cfg.framework = FrameworkConfig(
            plugins=plugins,
            weights=prof.get("weights"),
            enable_preemption=bool(prof.get("preemption", True)),
        )
        wi = d.get("whatIf", {})
        cfg.whatif = WhatIfSpec(
            scenarios=int(wi.get("scenarios", 0)),
            seed=int(wi.get("seed", 0)),
            mesh=bool(wi.get("mesh", False)),
            node_down_p=float(wi.get("nodeDownP", 0.02)),
            capacity_p=float(wi.get("capacityP", 0.3)),
            taint_p=float(wi.get("taintP", 0.1)),
            # int 0/1 coerce to real bools — the engine distinguishes
            # None/True/False by IDENTITY (explicit True must hard-error
            # when unhonorable; 0 must actually disable). Anything else
            # (e.g. the string "yes") raises HERE rather than silently
            # behaving as default-on in engines built without CLI
            # validate_config.
            completions=_coerce_completions(wi.get("completions")),
            retry_buffer=int(wi.get("retryBuffer", 0)),
        )
        tu = d.get("tune")
        if tu is not None:
            sc = tu.get("scenarios", {}) or {}
            wb = tu.get("weightBounds")
            cfg.tune = TuneSpec(
                algo=str(tu.get("algo", "cem")),
                population=int(tu.get("population", 16)),
                rounds=int(tu.get("rounds", 6)),
                seed=int(tu.get("seed", 0)),
                elite_frac=float(tu.get("eliteFrac", 0.25)),
                objective=tu.get("objective"),
                constraints=tu.get("constraints"),
                evaluator=str(tu.get("evaluator", "auto")),
                train_scenarios=int(sc.get("train", 4)),
                heldout_scenarios=int(sc.get("heldout", 2)),
                scenario_seed=int(sc.get("seed", 0)),
                node_down_p=float(sc.get("nodeDownP", 0.02)),
                capacity_p=float(sc.get("capacityP", 0.3)),
                taint_p=float(sc.get("taintP", 0.1)),
                weight_bounds=(
                    [float(wb[0]), float(wb[1])] if wb is not None else None
                ),
                tune_strategy=bool(tu.get("tuneStrategy", True)),
                mesh=bool(tu.get("mesh", False)),
                cpu_oracle=bool(tu.get("cpuOracle", True)),
                cpu_envelope=float(tu.get("cpuEnvelope", 1e-6)),
                output=tu.get("output"),
            )
        ch = d.get("chaos")
        if ch is not None:
            cfg.chaos = ChaosSpec(
                enabled=bool(ch.get("enabled", True)),
                seed=int(ch.get("seed", 0)),
                mtbf=float(ch.get("mtbf", 200.0)),
                mttr=float(ch.get("mttr", 20.0)),
                node_fraction=float(ch.get("nodeFraction", 0.2)),
                horizon=(
                    float(ch["horizon"]) if ch.get("horizon") is not None
                    else None
                ),
                max_events=(
                    int(ch["maxEvents"]) if ch.get("maxEvents") is not None
                    else None
                ),
            )
        dc = d.get("dcn")
        if dc is not None:
            rec = dc.get("recovery", dc) or {}
            cfg.dcn_recovery = DcnRecoverySpec(
                enable=bool(rec.get("enable", False)),
                checkpoint_every=int(rec.get("checkpointEvery", 0)),
                max_claims=int(rec.get("maxClaims", 2)),
            )
            wq = dc.get("workQueue")
            if wq is not None:
                cfg.dcn_workqueue = DcnWorkQueueSpec(
                    enable=bool(wq.get("enable", False)),
                    block_size=int(wq.get("blockSize", 0)),
                    speculate=bool(wq.get("speculate", False)),
                    straggler_s=float(wq.get("stragglerS", 0.0)),
                )
            du = dc.get("durable")
            if du is not None:
                if isinstance(du, str):
                    # Shorthand: `durable: /path` means `durable: {dir:
                    # /path}` — mirror-only, no resume.
                    du = {"dir": du}
                cfg.dcn_durable = DcnDurableSpec(
                    dir=du.get("dir"),
                    resume=bool(du.get("resume", False)),
                )
        fl = d.get("faultline")
        if fl is not None:
            cfg.faultline = FaultlineSpec(
                enabled=bool(fl.get("enabled", True)),
                seed=int(fl.get("seed", 0)),
                kv_error_rate=float(fl.get("kvErrorRate", 0.0)),
                kv_delay_rate=float(fl.get("kvDelayRate", 0.0)),
                kv_delay_s=float(fl.get("kvDelayS", 0.02)),
                torn_write_rate=float(fl.get("tornWriteRate", 0.0)),
                stale_read_rate=float(fl.get("staleReadRate", 0.0)),
                kill=fl.get("kill"),
                slow=fl.get("slow"),
            )
        tl = d.get("telemetry")
        if tl is not None:
            cfg.telemetry = TelemetrySpec(
                granularity=str(tl.get("granularity", "summary")),
                timeline_out=tl.get("timelineOut"),
            )
            if (
                cfg.telemetry.timeline_out
                and cfg.telemetry.granularity != "off"
            ):
                # A timeline sink needs timeline events collected.
                cfg.telemetry = TelemetrySpec(
                    granularity="timeline",
                    timeline_out=cfg.telemetry.timeline_out,
                )
        cfg.output = d.get("output")
        ww = d.get("waveWidth", 8)
        cfg.wave_width = ww if ww == "auto" else int(ww)
        cfg.chunk_waves = int(d.get("chunkWaves", 1024))
        # bool (legacy: true = tier) or the string "tier"/"kube".
        dp = d.get("devicePreemption", False)
        cfg.device_preemption = dp if isinstance(dp, str) else bool(dp)
        cfg.node_shards = int(d.get("nodeShards", 0))
        cfg.paged_waves = bool(d.get("pagedWaves", False))
        fr = d.get("flightRecorder")
        if fr is not None:
            if isinstance(fr, str):
                fr = {"path": fr}
            cfg.flight_recorder = FlightRecorderSpec(
                path=str(fr.get("path", "flight.jsonl")),
                every=int(fr.get("every", 1)),
            )
        ov = d.get("overlap")
        if ov is not None:

            def _tristate(key: str) -> Optional[bool]:
                v = ov.get(key)
                if v is None:
                    return None
                if isinstance(v, (bool, int)):
                    return bool(v)
                raise ValueError(
                    f"overlap.{key}: must be true or false, got {v!r}"
                )

            cfg.overlap = OverlapSpec(
                pager_thread=_tristate("pagerThread"),
                background_publisher=_tristate("backgroundPublisher"),
                two_phase_exchange=_tristate("twoPhaseExchange"),
            )
        sv = d.get("service")
        if sv is not None:
            if not isinstance(sv, dict):
                sv = {}
            cfg.service = ServiceSpec(
                max_batch=int(sv.get("maxBatch", 3)),
                batch_deadline_s=float(sv.get("batchDeadlineS", 0.05)),
                max_engines=int(sv.get("maxEngines", 4)),
                granularity=str(sv.get("granularity", "summary")),
                retry_buffer=int(sv.get("retryBuffer", 64)),
                input=sv.get("input"),
            )
        return cfg

    @classmethod
    def load(cls, path: str) -> "SimConfig":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})


def build_case(cfg: SimConfig):
    """Materialize (cluster, pods) from a SimConfig."""
    from ..sim.synthetic import make_cluster, make_workload

    ext = None
    if cfg.cluster.extended_resources:
        ext = {k: tuple(v) for k, v in cfg.cluster.extended_resources.items()}
    cluster = make_cluster(
        cfg.cluster.nodes,
        seed=cfg.cluster.seed,
        num_zones=cfg.cluster.zones,
        taint_fraction=cfg.cluster.taint_fraction,
        extended_resources=ext,
    )
    if cfg.borg is not None:
        from ..sim.borg import make_borg_trace

        cluster, pods = make_borg_trace(cfg.borg)
        return cluster, pods
    wl = cfg.workload or SyntheticWorkloadSpec()
    pods, _ = make_workload(
        wl.pods,
        seed=wl.seed,
        arrival_rate=wl.arrival_rate,
        duration_mean=wl.duration_mean,
        with_affinity=wl.affinity,
        with_spread=wl.spread,
        with_tolerations=wl.tolerations,
        num_apps=wl.num_apps,
        gang_fraction=wl.gang_fraction,
        gang_size=wl.gang_size,
    )
    from ..plugins.builtin import inject_default_spread

    inject_default_spread(pods, cfg.framework)
    return cluster, pods


def build_encoded_case(cfg: SimConfig):
    """(EncodedCluster, EncodedPods) for any SimConfig. Borg workloads use
    the vectorized template-expansion fast path (the object-model builder
    caps at 200k tasks), optionally ingesting an external task-event trace
    file (``workload.borg.tracePath``); everything else goes through
    build_case + encode.

    Note: the fast path samples the trace columns vectorized, so a seeded
    borg config yields a DIFFERENT (equally Borg-shaped) trace than the
    pre-CLI object-model generator did — determinism holds per generator,
    not across them."""
    from ..models.encode import encode

    if cfg.borg is not None:
        from ..plugins.builtin import resolved_default_constraints
        from ..sim.borg import BorgSpec, load_trace_csv, make_borg_encoded

        if resolved_default_constraints(cfg.framework):
            import warnings

            warnings.warn(
                "PodTopologySpread cluster-default constraints apply only to "
                "object-model workloads; the encoded Borg fast path ignores "
                "them (Borg tasks carry no controller labels to select on).",
                stacklevel=2,
            )
        spec = BorgSpec.from_spec(cfg.borg)
        if getattr(cfg.borg, "instance_events", None):
            from ..sim.borg_etl import load_borg2019

            ec, ep, _ = load_borg2019(
                cfg.borg.instance_events, spec,
                collection_events=cfg.borg.collection_events,
                cpu_scale=cfg.borg.cpu_scale,
                mem_scale=cfg.borg.mem_scale,
            )
        elif cfg.borg.trace_path:
            ec, ep, _ = load_trace_csv(cfg.borg.trace_path, spec)
        else:
            ec, ep, _ = make_borg_encoded(spec)
        return ec, ep
    cluster, pods = build_case(cfg)
    return encode(cluster, pods)
