"""Granularity-envelope guard for chunk-boundary completions (round 5,
VERDICT r4 next #2; SURVEY §4.3 determinism row).

The chunk-granular release semantics are a measured-faithful
approximation of exact-timestamp completions only while the chunk
arrival span stays ≲ the mean pod duration: releases then land at most
one boundary late. When durations are ≪ the span, every release batches
at a few boundaries, capacity placed early in a chunk stays invisible
for the whole chunk, and arrival-order greedy silently loses most
placements — measured 89% loss at duration/span ≈ 0.05 on a 100-node
shape (COVERAGE.md, test_divergence_pin.py docstring). The measured-safe
regime is ratio ≥ 0.67 (0.53% gap) with 0.00% at 1.33.

This module computes the ratio ON HOST before a completions-on run and,
below the safe regime, WARNS with the projected-loss reference and
auto-shrinks ``chunk_waves`` toward the duration scale — a pure fidelity
mitigation (smaller chunks converge on the CPU event engine's
semantics; the cost is more per-chunk dispatches, which the warning
states). When a retry buffer is already enabled but smaller than the
per-chunk failure burst, it is grown to cover one chunk (retry is a
semantics opt-in, so the guard never turns it ON by itself). Engines
pass ``granularity_guard=False`` to opt out.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass

import numpy as np

from ..models.encode import EncodedPods

# Below this duration/chunk-span ratio the guard fires (measured: 0.67
# → 0.53% gap is safe; 0.05 → 89% loss is the cliff).
SAFE_RATIO = 0.5
# The guard never shrinks chunks below this (dispatch-count sanity; a
# trace needing finer granularity than 8 waves/chunk is flagged as
# unhonorable instead).
MIN_CHUNK_WAVES = 8


@dataclass(frozen=True)
class GranularityAssessment:
    ratio: float  # mean finite duration / mean finite chunk span
    mean_duration: float
    mean_span: float
    chunk_waves: int  # recommended (== input when safe)
    retry_buffer: int  # recommended (== input when safe / retry off)
    honorable: bool  # False: even MIN_CHUNK_WAVES can't reach SAFE_RATIO


def assess(
    ep: EncodedPods,
    wave_idx: np.ndarray,
    chunk_waves: int,
    retry_buffer: int = 0,
) -> GranularityAssessment:
    """Pure computation — no warning, no mutation."""
    dur = ep.duration[np.isfinite(ep.duration)]
    first = wave_idx[:, 0]
    wt = np.where(first >= 0, ep.arrival[np.clip(first, 0, None)], np.inf)
    wt = wt[np.isfinite(wt)]
    if dur.size == 0 or wt.size < 2:
        return GranularityAssessment(
            np.inf, 0.0, 0.0, chunk_waves, retry_buffer, True
        )
    mean_dur = float(dur.mean())
    # Mean arrival span of one chunk of C waves, from the per-wave span
    # (robust to a trailing partial chunk and to C > num_waves: the span
    # of the chunks the run will actually have).
    total_span = float(wt[-1] - wt[0])
    num_waves = wt.size
    C_eff = min(chunk_waves, num_waves)
    mean_span = total_span * C_eff / max(num_waves - 1, 1)
    if mean_span <= 0:
        return GranularityAssessment(
            np.inf, mean_dur, mean_span, chunk_waves, retry_buffer, True
        )
    ratio = mean_dur / mean_span
    if ratio >= SAFE_RATIO:
        return GranularityAssessment(
            ratio, mean_dur, mean_span, chunk_waves, retry_buffer, True
        )
    # Shrink C so the new span ≈ mean duration (target ratio 1.0, i.e.
    # the 0.00%-gap regime, not merely the 0.5 threshold).
    span_per_wave = mean_span / C_eff
    want = int(mean_dur / span_per_wave) if span_per_wave > 0 else MIN_CHUNK_WAVES
    new_c = max(MIN_CHUNK_WAVES, want)
    honorable = new_c * span_per_wave * SAFE_RATIO <= mean_dur + 1e-12
    new_rb = retry_buffer
    if retry_buffer > 0:
        # Cover one (new) chunk's worth of failures.
        burst = new_c * wave_idx.shape[1]
        new_rb = max(retry_buffer, min(burst, 4096))
    return GranularityAssessment(
        ratio, mean_dur, mean_span, min(new_c, chunk_waves), new_rb, honorable
    )


def guard(
    ep: EncodedPods,
    wave_idx: np.ndarray,
    chunk_waves: int,
    retry_buffer: int = 0,
    enabled: bool = True,
    engine_name: str = "device engine",
) -> tuple:
    """Returns (chunk_waves, retry_buffer) to run with; warns when the
    trace is outside the measured-safe envelope."""
    if not enabled:
        return chunk_waves, retry_buffer
    a = assess(ep, wave_idx, chunk_waves, retry_buffer)
    changed = (
        a.chunk_waves != chunk_waves or a.retry_buffer != retry_buffer
    )
    if a.honorable and not changed:
        # In the safe regime (or already at the recommendation with the
        # target ratio reachable) — silent.
        return chunk_waves, retry_buffer
    if changed:
        fix = (
            f"auto-shrinking chunk_waves {chunk_waves} -> {a.chunk_waves}"
            + (
                f" and retry_buffer {retry_buffer} -> {a.retry_buffer}"
                if a.retry_buffer != retry_buffer
                else ""
            )
        )
    else:
        # Already at/below the floor but still outside the envelope —
        # nothing to shrink, but the user MUST hear about it (a silent
        # beyond-cliff run was the whole bug class this module guards).
        fix = (
            f"chunk_waves {chunk_waves} is already at the shrink floor "
            f"({MIN_CHUNK_WAVES}) — no finer chunking applied"
        )
    residual = (
        ""
        if a.honorable
        else (
            " Even at the floor the ratio stays below the safe regime — "
            "expect residual divergence; the CPU event engine (strategy: "
            "cpu) is the exact-timestamp reference for this trace."
        )
    )
    warnings.warn(
        f"{engine_name}: mean pod duration ({a.mean_duration:.3g}s) is "
        f"{a.ratio:.2f}x the chunk arrival span ({a.mean_span:.3g}s) — "
        f"below the measured-safe completions regime (>= {SAFE_RATIO}; "
        f"an 0.05x shape measured an 89% placement loss). {fix} (more, "
        f"smaller chunks: higher fidelity, more per-chunk dispatches)."
        + residual
        + " Pass granularity_guard=False to keep the requested chunking.",
        stacklevel=3,
    )
    return a.chunk_waves, a.retry_buffer
