"""Policy tuner (round 9): batched scheduler-policy search over the
scenario axis.

The what-if engine's scenario axis is the framework's data-parallel axis —
until now it only carried CLUSTER perturbations, so the simulator could
replay a scheduler but not improve one. This module makes the simulator an
optimizer: the per-scenario policy vector (ops.tpu.POLICY_COLS — one Score
weight per plugin plus the NodeResourcesFit strategy selector) is a TRACED
input to the compiled chunk program, so a whole candidate population
evaluates in one vmapped/mesh-sharded sweep with no per-candidate
recompiles, and a host-side seeded search loop (random search or the
cross-entropy method) walks the weight space against a configurable scalar
objective.

Layout: a population of P candidate vectors × S_t train scenarios flattens
onto the scenario axis as (candidate-major) [P·S_t] rows — candidate i
owns rows [i·S_t, (i+1)·S_t). Between rounds only the VECTOR VALUES change
(`WhatIfEngine.set_policies`), so the search runs against exactly one
compiled executable (pinned by tests/test_tuner.py via
``_chunk_fn._cache_size()``).

The winner is re-evaluated two ways: on a HELD-OUT scenario split (one
extra 2·S_h-row sweep, winner vs the config's default policy) and on the
CPU event engine (``greedy_replay`` per held-out scenario over the
perturbed host clusters — the bit-parity oracle the device engines anchor
to), whose objective must match the device objective within a pinned
envelope.

The full search trajectory streams as schema-v3 JSONL rows (``run_type:
"tune"``; see scripts/check_metrics_schema.py) and is bit-deterministic
for a fixed seed + config: rows carry no wall-clock fields (pass
``stamp_ts=False`` to JsonlWriter — the determinism satellite pins
byte-identical files across runs).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..framework.framework import FrameworkConfig
from ..ops import tpu as T
from ..plugins.builtin import (
    TUNABLE_FIT_STRATEGIES,
    tunable_parameters,
)
from ..utils.metrics import TUNE_SCHEMA_VERSION, log
from .whatif import Scenario, WhatIfEngine, uniform_scenarios

#: Objective terms every engine path provides. Terms outside this set need
#: specific what-if modes (latency quantiles / preemptions / evictions ride
#: the kube host mirrors) which the policy axis does not support yet — the
#: objective assembler raises an actionable error rather than scoring 0.
_ALWAYS_METRICS = ("placementRate", "unschedulable", "utilizationCpu")
_RESULT_METRICS = {
    "placementRate": None,  # computed from placed/unschedulable
    "unschedulable": "unschedulable",
    "utilizationCpu": "utilization_cpu",
    "preemptions": "preemptions",
    "retryDropped": "retry_dropped",
    "evictions": "evictions",
    "latencyP50": "latency_p50",
    "latencyP90": "latency_p90",
    "latencyP99": "latency_p99",
    # Utilization economics (round 13) — fragmentation gauges ride the
    # kube host mirrors on WhatIfResult and every ReplayResult; the
    # host (CPU event engine) evaluator provides them for any trace.
    "strandedCpu": "stranded_cpu",
    "fragIndexCpu": "frag_index_cpu",
    "packingEfficiency": "packing_efficiency",
}

#: Terms the CPU oracle (greedy_replay) can recompute exactly — the
#: envelope check is skipped (with a log note) for objectives outside it.
_ORACLE_METRICS = {
    "placementRate", "unschedulable", "utilizationCpu",
    "strandedCpu", "fragIndexCpu", "packingEfficiency",
}

DEFAULT_OBJECTIVE = {"placementRate": 1.0}


def _metric_series(res, key: str) -> np.ndarray:
    """Per-scenario [S] f64 series for one objective term, or raise with
    the engine mode the term needs."""
    if key == "placementRate":
        placed = np.asarray(res.placed, np.float64)
        unsched = np.asarray(res.unschedulable, np.float64)
        return placed / np.maximum(placed + unsched, 1.0)
    attr = _RESULT_METRICS[key]
    val = getattr(res, attr)
    if val is None:
        raise ValueError(
            f"objective term {key!r} is unavailable on this what-if path "
            "(latency quantiles / preemptions / evictions ride the kube "
            "host mirrors, which the policy axis does not support) — use "
            f"terms from {sorted(_ALWAYS_METRICS)}"
        )
    return np.asarray(val, np.float64)


def normalize_constraints(constraints) -> List[dict]:
    """Validate penalty-constraint specs (round 13). Each entry is
    ``{"metric": <term>, "max": x | "min": x, "penalty": p}`` — ``max``
    bounds the metric from above, ``min`` from below; ``penalty``
    (default 1.0, must be > 0) scales the hinge. Returns normalized
    copies (exactly one bound key, float values)."""
    out: List[dict] = []
    for i, c in enumerate(constraints or []):
        where = f"constraints[{i}]"
        if not isinstance(c, dict):
            raise ValueError(f"{where}: expected a mapping, got {c!r}")
        metric = c.get("metric")
        if metric not in _RESULT_METRICS:
            raise ValueError(
                f"{where}: unknown metric {metric!r} — known: "
                f"{sorted(_RESULT_METRICS)}"
            )
        has_max, has_min = "max" in c, "min" in c
        if has_max == has_min:
            raise ValueError(
                f"{where}: need exactly one of 'max' or 'min' (got "
                f"{sorted(set(c) & {'max', 'min'}) or 'neither'})"
            )
        penalty = float(c.get("penalty", 1.0))
        if not penalty > 0:
            raise ValueError(f"{where}: penalty must be > 0, got {penalty}")
        unknown = sorted(set(c) - {"metric", "max", "min", "penalty"})
        if unknown:
            raise ValueError(f"{where}: unknown key(s) {unknown}")
        norm = {"metric": metric, "penalty": penalty}
        norm["max" if has_max else "min"] = float(c["max" if has_max else "min"])
        out.append(norm)
    return out


def make_objective(
    weights: Optional[Dict[str, float]], constraints=None
) -> Tuple[Dict[str, float], List[dict], Callable]:
    """Validate an objective spec and return (weights, constraints, fn)
    where fn maps a WhatIfResult to a per-scenario [S] f64 objective
    (HIGHER IS BETTER — express costs with negative weights, e.g.
    ``{"placementRate": 1.0, "unschedulable": -0.01}``).

    ``constraints`` (round 13) turn the weighted sum into a penalty form:
    each violated bound subtracts ``penalty · relu(violation)`` — e.g.
    maximize ``utilizationCpu`` subject to ``latencyP99 <= 2.0``. A NaN
    constraint metric (a scenario that bound nothing has no latency
    quantiles) contributes zero violation."""
    w = dict(DEFAULT_OBJECTIVE if weights is None else weights)
    unknown = sorted(set(w) - set(_RESULT_METRICS))
    if unknown:
        raise ValueError(
            f"unknown objective term(s) {unknown} — known: "
            f"{sorted(_RESULT_METRICS)}"
        )
    if not w:
        raise ValueError("objective must contain at least one term")
    cons = normalize_constraints(constraints)

    def fn(res) -> np.ndarray:
        out = None
        for key, wt in w.items():
            term = float(wt) * _metric_series(res, key)
            out = term if out is None else out + term
        for c in cons:
            v = _metric_series(res, c["metric"])
            if "max" in c:
                viol = np.maximum(v - c["max"], 0.0)
            else:
                viol = np.maximum(c["min"] - v, 0.0)
            out = out - c["penalty"] * np.nan_to_num(viol, nan=0.0)
        return out

    return w, cons, fn


@dataclass(frozen=True)
class SearchSpace:
    """The searched dimensions, derived from the config's tunable-parameter
    surface (plugins.builtin.tunable_parameters). Weight columns of
    disabled plugins and an inert strategy selector are PINNED to their
    defaults — the device program statically dropped their rows, so
    searching them would only add noise dimensions."""

    lo: np.ndarray  # [5] per-weight-column lower bound
    hi: np.ndarray  # [5] upper bound
    defaults: np.ndarray  # [len(POLICY_COLS)] the config's own policy
    weight_mask: np.ndarray  # [5] bool — searched weight columns
    tune_strategy: bool  # search the fit_least selector?

    @classmethod
    def from_config(
        cls,
        config: Optional[FrameworkConfig],
        weight_bounds: Optional[Tuple[float, float]] = None,
        tune_strategy: bool = True,
    ) -> "SearchSpace":
        params = {p["name"]: p for p in tunable_parameters(config)}
        nW = len(T.POLICY_WEIGHT_COLS)
        lo = np.zeros(nW)
        hi = np.zeros(nW)
        mask = np.zeros(nW, bool)
        defaults = np.zeros(len(T.POLICY_COLS), np.float32)
        for i, name in enumerate(T.POLICY_WEIGHT_COLS):
            p = params[name]
            lo[i], hi[i] = p["lo"], p["hi"]
            if weight_bounds is not None:
                lo[i], hi[i] = weight_bounds
            mask[i] = p["enabled"]
            defaults[i] = p["default"]
        strat = params["NodeResourcesFit.strategy"]
        defaults[T.IDX_FIT_LEAST] = float(
            strat["default"] == "LeastAllocated"
        )
        if np.any(lo >= hi):
            raise ValueError(f"weight bounds must satisfy lo < hi, got {lo}..{hi}")
        return cls(
            lo=lo, hi=hi, defaults=defaults, weight_mask=mask,
            tune_strategy=bool(tune_strategy and strat["enabled"]),
        )

    def clip(self, vecs: np.ndarray) -> np.ndarray:
        """Project candidate vectors into the space: clip weights to
        bounds, binarize the selector, pin unsearched columns."""
        out = np.asarray(vecs, np.float32).copy()
        nW = len(T.POLICY_WEIGHT_COLS)
        out[:, :nW] = np.clip(out[:, :nW], self.lo, self.hi)
        out[:, ~np.concatenate([self.weight_mask, [self.tune_strategy]])] = (
            self.defaults[None, ~np.concatenate(
                [self.weight_mask, [self.tune_strategy]]
            )]
        )
        out[:, T.IDX_FIT_LEAST] = (out[:, T.IDX_FIT_LEAST] > 0.5).astype(
            np.float32
        )
        return out

    def describe(self, vec: np.ndarray) -> Dict[str, float]:
        """A policy vector as a {column: value} dict for JSONL/reporting
        (the selector reported as the strategy name)."""
        out = {
            name: round(float(vec[i]), 6)
            for i, name in enumerate(T.POLICY_WEIGHT_COLS)
        }
        out["fitStrategy"] = TUNABLE_FIT_STRATEGIES[
            int(vec[T.IDX_FIT_LEAST] > 0.5)
        ]
        return out


@dataclass
class TuneResult:
    best_policy: Dict[str, float]  # SearchSpace.describe of the winner
    best_vector: np.ndarray  # [len(POLICY_COLS)] f32
    train_objective: float
    heldout_objective: float
    default_heldout_objective: float
    rounds: int
    population: int
    evaluations: int  # candidate×train-scenario device evaluations
    wall_clock_s: float
    compile_count: Optional[int]  # chunk-program executables (pin: 1)
    cpu_objective: Optional[float] = None  # oracle mean over held-out
    cpu_envelope: Optional[float] = None  # |device − cpu|, None if skipped
    trajectory: List[dict] = field(default_factory=list)
    # Mesh provenance (round 10, no silent caps): the population the
    # caller ASKED for — ``population`` above is the fitted size after
    # parallel.mesh.fit_population rounded it up for mesh divisibility —
    # plus the device count the sweep actually ran on.
    population_requested: Optional[int] = None
    n_devices: int = 1
    mesh_shape: Optional[dict] = None  # {axis_name: size} or None
    # DCN provenance (round 11): processes that contributed candidate
    # blocks. The sweep engine gathers objectives exactly once per run()
    # (WhatIfEngine's end-of-replay gather), so every process scores the
    # identical full population and the search trajectory is
    # process-count-independent.
    process_count: int = 1
    # Constraint-aware objectives (round 13): the normalized penalty
    # constraints the search optimized under, and which evaluator scored
    # candidates — "device" (batched what-if sweep) or "cpu" (the CPU
    # event engine, required for latency/host-mirror terms).
    objective_constraints: List[dict] = field(default_factory=list)
    evaluator: str = "device"

    def improved(self) -> bool:
        return self.heldout_objective > self.default_heldout_objective


class PolicyTuner:
    """Seeded search over scheduler score policies against one trace.

    ``algo``: "cem" (cross-entropy method: Gaussian weight columns +
    Bernoulli strategy selector, elite refit with a std floor) or
    "random" (uniform in bounds). Both carry the incumbent best as
    candidate 0 of every round (round 0's incumbent is the config's own
    default policy, so the search can only match-or-beat the configured
    scheduler on the train split).
    """

    def __init__(
        self,
        ec,
        pods,
        config: Optional[FrameworkConfig] = None,
        *,
        algo: str = "cem",
        population: int = 16,
        rounds: int = 6,
        seed: int = 0,
        elite_frac: float = 0.25,
        objective: Optional[Dict[str, float]] = None,
        constraints: Optional[List[dict]] = None,
        evaluator: str = "auto",
        train_scenarios: int = 4,
        heldout_scenarios: int = 2,
        scenario_seed: int = 0,
        p_node_down: float = 0.02,
        p_capacity: float = 0.3,
        p_taint: float = 0.1,
        weight_bounds: Optional[Tuple[float, float]] = None,
        tune_strategy: bool = True,
        wave_width: int = 8,
        chunk_waves: int = 1024,
        completions: Optional[bool] = None,
        mesh=None,
        cpu_oracle: bool = True,
        cpu_envelope: float = 1e-6,
    ):
        if algo not in ("cem", "random"):
            raise ValueError(f"algo must be 'cem' or 'random', got {algo!r}")
        if rounds < 1 or population < 2:
            raise ValueError("need rounds >= 1 and population >= 2")
        if train_scenarios < 1 or heldout_scenarios < 1:
            raise ValueError(
                "need train_scenarios >= 1 and heldout_scenarios >= 1 "
                "(the acceptance check is on the held-out split)"
            )
        if not 0.0 < elite_frac <= 1.0:
            raise ValueError("elite_frac must be in (0, 1]")
        self.ec, self.pods, self.config = ec, pods, config
        self.algo = algo
        self.rounds = int(rounds)
        self.seed = int(seed)
        self.elite_frac = float(elite_frac)
        self.space = SearchSpace.from_config(
            config, weight_bounds=weight_bounds, tune_strategy=tune_strategy
        )
        (
            self.objective_weights,
            self.objective_constraints,
            self._objective,
        ) = make_objective(objective, constraints)
        # Evaluator selection (round 13). "device": the batched policy
        # sweep (one compiled executable, the round-9 fast path) —
        # restricted to _ALWAYS_METRICS because the policy axis has no
        # kube host mirrors. "cpu": score every candidate×scenario on the
        # CPU event engine, which carries EVERY metric (latency
        # quantiles, fragmentation gauges) exactly. "auto" picks device
        # when the terms allow it, else cpu.
        if evaluator not in ("auto", "device", "cpu"):
            raise ValueError(
                f"evaluator must be 'auto', 'device' or 'cpu', got "
                f"{evaluator!r}"
            )
        terms = set(self.objective_weights) | {
            c["metric"] for c in self.objective_constraints
        }
        needs_host = not terms <= set(_ALWAYS_METRICS)
        if evaluator == "device" and needs_host:
            raise ValueError(
                f"objective/constraint term(s) "
                f"{sorted(terms - set(_ALWAYS_METRICS))} ride the kube "
                "host mirrors, which the batched policy sweep does not "
                "support — use evaluator='cpu' (every candidate scored "
                "on the CPU event engine) or restrict terms to "
                f"{sorted(_ALWAYS_METRICS)}"
            )
        self.evaluator = "cpu" if (evaluator == "cpu" or needs_host) else "device"
        if self.evaluator == "cpu" and evaluator == "auto":
            log.info(
                "tune: objective terms %s need the host evaluator — "
                "scoring candidates on the CPU event engine",
                sorted(terms - set(_ALWAYS_METRICS)),
            )
        self.S_t = int(train_scenarios)
        self.S_h = int(heldout_scenarios)
        self.mesh = mesh
        from ..parallel.mesh import fit_population

        self.population_requested = int(population)
        self.population = fit_population(population, self.S_t, mesh)
        if self.population != population:
            log.info(
                "tune: population %d -> %d (flat population x train axis "
                "must divide over the mesh devices)",
                population, self.population,
            )
        # One scenario pool, split train/held-out: scenario 0 (the
        # unperturbed base) lands in TRAIN — the tuned policy must not
        # regress the nominal cluster; the held-out split is all-perturbed.
        pool = uniform_scenarios(
            ec, self.S_t + self.S_h, seed=scenario_seed,
            p_node_down=p_node_down, p_capacity=p_capacity, p_taint=p_taint,
        )
        self.train_split: List[Scenario] = list(pool[: self.S_t])
        self.heldout_split: List[Scenario] = list(pool[self.S_t :])
        self._engine_kw = dict(
            config=config, wave_width=wave_width, chunk_waves=chunk_waves,
            completions=completions, mesh=mesh,
        )
        self.cpu_oracle = bool(cpu_oracle)
        self.cpu_envelope = float(cpu_envelope)
        self._train_engine: Optional[WhatIfEngine] = None
        # Host-evaluator state: perturbed host clusters per split, and a
        # per-(split, vector) objective cache — the incumbent rides as
        # candidate 0 of EVERY round, so caching keeps the search loop
        # from re-replaying identical candidates.
        self._host_clusters: Dict[str, list] = {}
        self._host_cache: Dict[tuple, np.ndarray] = {}

    # -- population sampling ------------------------------------------------

    def _sample(self, rng, mean, std, theta) -> np.ndarray:
        P = self.population
        nW = len(T.POLICY_WEIGHT_COLS)
        vecs = np.tile(self.space.defaults, (P, 1)).astype(np.float32)
        if self.algo == "random":
            vecs[:, :nW] = rng.uniform(
                self.space.lo, self.space.hi, size=(P, nW)
            )
        else:
            vecs[:, :nW] = rng.normal(mean, std, size=(P, nW))
        if self.space.tune_strategy:
            p_least = 0.5 if self.algo == "random" else theta
            vecs[:, T.IDX_FIT_LEAST] = (
                rng.random(P) < p_least
            ).astype(np.float32)
        return self.space.clip(vecs)

    def _refit(self, elites, mean, std, theta):
        """CEM elite refit with a std floor (keeps exploration alive) —
        random search ignores the distribution state entirely."""
        if self.algo == "random":
            return mean, std, theta
        nW = len(T.POLICY_WEIGHT_COLS)
        floor = 0.05 * (self.space.hi - self.space.lo)
        mean = elites[:, :nW].astype(np.float64).mean(axis=0)
        std = np.maximum(elites[:, :nW].astype(np.float64).std(axis=0), floor)
        if self.space.tune_strategy:
            theta = float(
                np.clip(elites[:, T.IDX_FIT_LEAST].mean(), 0.05, 0.95)
            )
        return mean, std, theta

    # -- evaluation ---------------------------------------------------------

    def _flat_policies(self, cand: np.ndarray) -> np.ndarray:
        """[P, K] candidates → [P·S_t, K] candidate-major flat rows, the
        layout the train engine's scenario list was built with."""
        return np.repeat(cand, self.S_t, axis=0)

    def _policy_config(self, vec: np.ndarray) -> FrameworkConfig:
        """A candidate vector materialized as an ordinary FrameworkConfig
        (the host engines' policy carrier)."""
        desc = self.space.describe(vec)
        strategy = desc.pop("fitStrategy")
        base = self.config if self.config is not None else FrameworkConfig()
        return base.with_policy(
            desc, fit_strategy=strategy if self.space.tune_strategy else None
        )

    # -- host (CPU event engine) evaluator, round 13 -------------------------

    def _host_split_clusters(self, split_name: str) -> list:
        from .whatif import ScenarioSet

        clusters = self._host_clusters.get(split_name)
        if clusters is None:
            split = (
                self.train_split if split_name == "train"
                else self.heldout_split
            )
            clusters = ScenarioSet(
                self.ec, split, keep_host_stacks=True
            ).host_clusters(self.ec)
            self._host_clusters[split_name] = clusters
        return clusters

    def _host_row(self, ec_s, cfg: FrameworkConfig):
        """One scenario scored on the CPU event engine — the exact oracle:
        event-clock latencies, end-of-replay fragmentation gauges, every
        _RESULT_METRICS term present (len-1 arrays, WhatIfResult shape)."""
        from types import SimpleNamespace

        from .runtime import CpuReplayEngine

        r = CpuReplayEngine(ec_s, self.pods, cfg, telemetry="summary").replay()
        lat = r.telemetry.latency if r.telemetry is not None else None

        def q(k: str) -> np.ndarray:
            return np.array(
                [float(lat[k]) if lat else np.nan], np.float64
            )

        fr = r.fragmentation
        return SimpleNamespace(
            placed=np.array([float(r.placed)]),
            unschedulable=np.array([float(r.unschedulable)]),
            utilization_cpu=np.array([r.utilization.get("cpu", 0.0)]),
            preemptions=np.array([float(r.preemptions)]),
            retry_dropped=np.array([float(r.retry_dropped)]),
            evictions=np.array([float(r.evictions)]),
            latency_p50=q("p50"), latency_p90=q("p90"), latency_p99=q("p99"),
            stranded_cpu=np.array([fr["stranded"].get("cpu", 0.0)]),
            frag_index_cpu=np.array([fr["frag_index"].get("cpu", 0.0)]),
            packing_efficiency=np.array([fr["packing_efficiency"]]),
        )

    def _host_objective(self, vec: np.ndarray, split_name: str) -> np.ndarray:
        """Per-scenario objective of one candidate on one split, via the
        CPU event engine; cached by (split, vector bytes)."""
        key = (split_name, np.asarray(vec, np.float32).tobytes())
        hit = self._host_cache.get(key)
        if hit is not None:
            return hit
        cfg = self._policy_config(vec)
        rows = [
            self._host_row(ec_s, cfg)
            for ec_s in self._host_split_clusters(split_name)
        ]
        obj = np.concatenate([self._objective(r) for r in rows])
        self._host_cache[key] = obj
        return obj

    def _train_eval(self, cand: np.ndarray) -> np.ndarray:
        """Evaluate the whole population in ONE device sweep (host mode:
        one CPU event replay per candidate×scenario, cached); returns the
        [P] per-candidate objective (mean over its train scenarios)."""
        if self.evaluator == "cpu":
            return np.array([
                float(self._host_objective(cand[i], "train").mean())
                for i in range(self.population)
            ])
        flat = self._flat_policies(cand)
        if self._train_engine is None:
            self._train_engine = WhatIfEngine(
                self.ec, self.pods, self.train_split * self.population,
                policies=flat, **self._engine_kw,
            )
        else:
            self._train_engine.set_policies(flat)
        res = self._train_engine.run()
        per_scenario = self._objective(res)
        return per_scenario.reshape(self.population, self.S_t).mean(axis=1)

    def _heldout_eval(self, best_vec: np.ndarray):
        """One 2-policy sweep on the held-out split: winner vs the
        config's default policy. Returns (best_obj, default_obj,
        per-scenario winner objectives, engine)."""
        if self.evaluator == "cpu":
            best = self._host_objective(best_vec, "heldout")
            default = self._host_objective(self.space.defaults, "heldout")
            return float(best.mean()), float(default.mean()), best, None
        pol = np.concatenate([
            np.repeat(best_vec[None], self.S_h, axis=0),
            np.repeat(self.space.defaults[None], self.S_h, axis=0),
        ])
        eng = WhatIfEngine(
            self.ec, self.pods, self.heldout_split * 2,
            policies=pol, **self._engine_kw,
        )
        per_scenario = self._objective(eng.run())
        best = per_scenario[: self.S_h]
        default = per_scenario[self.S_h :]
        return float(best.mean()), float(default.mean()), best, eng

    def _oracle_eval(self, best_vec: np.ndarray, eng: WhatIfEngine):
        """Re-evaluate the winner on the CPU event engine per held-out
        scenario — the perturbed host clusters feed ``greedy_replay`` with
        the winning weights materialized as an ordinary FrameworkConfig."""
        from types import SimpleNamespace

        from .greedy import greedy_replay
        from .whatif import ScenarioSet

        if self.evaluator == "cpu":
            log.info(
                "tune: CPU-oracle check skipped — evaluation already ran "
                "on the CPU event engine"
            )
            return None
        terms = set(self.objective_weights) | {
            c["metric"] for c in self.objective_constraints
        }
        if not terms <= _ORACLE_METRICS:
            log.info(
                "tune: CPU-oracle check skipped — objective uses terms "
                "outside %s", sorted(_ORACLE_METRICS),
            )
            return None
        cfg = self._policy_config(best_vec)
        sset = ScenarioSet(self.ec, self.heldout_split, keep_host_stacks=True)
        chunk = eng.chunk_waves if eng.completions_on else None
        rows = []
        for ec_s in sset.host_clusters(self.ec):
            r = greedy_replay(
                ec_s, self.pods, cfg, wave_width=eng.wave_width,
                completions_chunk_waves=chunk,
            )
            placed, unsched = float(r.placed), float(r.unschedulable)
            fr = r.fragmentation or {}
            rows.append(SimpleNamespace(
                placed=np.array([placed]),
                unschedulable=np.array([unsched]),
                utilization_cpu=np.array([r.utilization.get("cpu", 0.0)]),
                preemptions=np.array([float(r.preemptions)]),
                retry_dropped=np.array([float(r.retry_dropped)]),
                evictions=np.array([float(r.evictions)]),
                latency_p50=None, latency_p90=None, latency_p99=None,
                stranded_cpu=np.array(
                    [fr.get("stranded", {}).get("cpu", 0.0)]
                ),
                frag_index_cpu=np.array(
                    [fr.get("frag_index", {}).get("cpu", 0.0)]
                ),
                packing_efficiency=np.array(
                    [fr.get("packing_efficiency", 1.0)]
                ),
            ))
        return np.concatenate([self._objective(r) for r in rows])

    # -- the search loop ----------------------------------------------------

    def run(self, writer=None) -> TuneResult:
        """Run the search. ``writer`` (utils.metrics.JsonlWriter) streams
        the trajectory; rows are written WITHOUT the wall-clock stamp so a
        fixed seed + config yields byte-identical files."""
        import time

        t0 = time.perf_counter()
        rng = np.random.default_rng(self.seed)
        nW = len(T.POLICY_WEIGHT_COLS)
        mean = self.space.defaults[:nW].astype(np.float64)
        std = (self.space.hi - self.space.lo) / 4.0
        theta = 0.5
        best_vec = self.space.clip(self.space.defaults[None])[0]
        best_obj = -math.inf
        trajectory: List[dict] = []

        def emit(row: dict) -> None:
            row = {"schema": TUNE_SCHEMA_VERSION, "run_type": "tune", **row}
            trajectory.append(row)
            if writer is not None:
                writer.write(row, stamp_ts=False)

        n_elite = max(1, int(math.ceil(self.elite_frac * self.population)))
        for rd in range(self.rounds):
            cand = self._sample(rng, mean, std, theta)
            # Elitism: the incumbent rides as candidate 0 (round 0's
            # incumbent is the config default) — the train best is
            # monotone and the default is always evaluated.
            cand[0] = best_vec
            objs = self._train_eval(cand)
            order = np.argsort(-objs, kind="stable")  # ties → lower index
            mean, std, theta = self._refit(
                cand[order[:n_elite]], mean, std, theta
            )
            if objs[order[0]] > best_obj:
                best_obj = float(objs[order[0]])
                best_vec = cand[order[0]].copy()
            for i in range(self.population):
                emit({
                    "kind": "tune-candidate", "round": rd, "candidate": i,
                    "policy": self.space.describe(cand[i]),
                    "objective": round(float(objs[i]), 9),
                    "split": "train",
                })
            emit({
                "kind": "tune-round", "round": rd,
                "best_objective": round(best_obj, 9),
                "round_best_objective": round(float(objs[order[0]]), 9),
                "mean_objective": round(float(objs.mean()), 9),
                "best_candidate": int(order[0]),
            })
            log.info(
                "tune: round %d/%d best=%.6f (incumbent %.6f)",
                rd + 1, self.rounds, float(objs[order[0]]), best_obj,
            )

        held_obj, held_default, held_rows, held_eng = self._heldout_eval(
            best_vec
        )
        cpu_obj = cpu_env = None
        if self.cpu_oracle:
            oracle_rows = self._oracle_eval(best_vec, held_eng)
            if oracle_rows is not None:
                cpu_obj = float(oracle_rows.mean())
                cpu_env = float(np.abs(oracle_rows - held_rows).max())
                if cpu_env > self.cpu_envelope:
                    log.warning(
                        "tune: CPU-oracle objective diverges from the "
                        "device objective by %.3g (> envelope %.3g)",
                        cpu_env, self.cpu_envelope,
                    )
        compile_count = None
        try:
            compile_count = int(self._train_engine._chunk_fn._cache_size())
        except Exception:  # jaxlib without _cache_size — report unknown
            pass
        emit({
            "kind": "tune-result",
            "best_policy": self.space.describe(best_vec),
            "train_objective": round(best_obj, 9),
            "heldout_objective": round(held_obj, 9),
            "default_heldout_objective": round(held_default, 9),
            "cpu_objective": (
                round(cpu_obj, 9) if cpu_obj is not None else None
            ),
            "cpu_envelope": (
                round(cpu_env, 12) if cpu_env is not None else None
            ),
            "rounds": self.rounds,
            "population": self.population,
            "evaluations": self.rounds * self.population * self.S_t,
            "objective_weights": {
                k: float(v) for k, v in self.objective_weights.items()
            },
            "objective_constraints": self.objective_constraints,
            "evaluator": self.evaluator,
            "algo": self.algo,
            "seed": self.seed,
        })
        return TuneResult(
            best_policy=self.space.describe(best_vec),
            best_vector=best_vec,
            train_objective=best_obj,
            heldout_objective=held_obj,
            default_heldout_objective=held_default,
            rounds=self.rounds,
            population=self.population,
            evaluations=self.rounds * self.population * self.S_t,
            wall_clock_s=time.perf_counter() - t0,
            compile_count=compile_count,
            cpu_objective=cpu_obj,
            cpu_envelope=cpu_env,
            trajectory=trajectory,
            population_requested=self.population_requested,
            n_devices=(
                int(self.mesh.devices.size) if self.mesh is not None else 1
            ),
            mesh_shape=(
                dict(zip(
                    self.mesh.axis_names,
                    (int(d) for d in self.mesh.devices.shape),
                ))
                if self.mesh is not None
                else None
            ),
            process_count=jax.process_count(),
            objective_constraints=self.objective_constraints,
            evaluator=self.evaluator,
        )
