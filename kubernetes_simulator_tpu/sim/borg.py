"""Borg-2019-like trace generation at config #4 scale (SURVEY.md §2 trace
driver; [BASELINE]: 10k nodes / 1M tasks, gang-scheduling predicates).

The real Google cluster trace ships as BigQuery tables (collection_events /
instance_events) that cannot be fetched from this environment (zero
egress), so this module generates a statistically Borg-shaped workload:

- heterogeneous machines (a few platform shapes, zone/rack topology)
- tasks with bucketed normalized cpu/memory requests (log-uniform-ish mix)
- priority tiers (free ≈ 0, batch ≈ 100, mid ≈ 200, prod ≈ 360,
  monitoring ≈ 450 — the 2019 trace's tiering)
- alloc sets → pod-groups (gangs) with contiguous members
- diurnal-bursty arrivals
- a slice of prod pods with zone topology-spread; batch pods tolerate a
  ``dedicated=batch`` taint on a fraction of machines

For 1M tasks, building Python Pod objects is too slow, so the generator
expands a few hundred *template pods* (run through the normal Encoder so
vocab/expr/count-group tables are exact) into vectorized EncodedPods
arrays — every per-pod row is a fancy-index of its template row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..models.core import (
    Cluster,
    LabelSelector,
    Pod,
    Toleration,
    TopologySpreadConstraint,
)
from ..models.encode import PAD, EncodedCluster, EncodedPods, Encoder
from .synthetic import make_cluster

PRIORITY_TIERS = np.array([0, 100, 200, 360, 450], dtype=np.int32)
TIER_PROBS = np.array([0.25, 0.35, 0.15, 0.2, 0.05])
CPU_BUCKETS = np.array([0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0], dtype=np.float32)
CPU_PROBS = np.array([0.2, 0.25, 0.2, 0.15, 0.1, 0.07, 0.03])
MEM_BUCKETS = (np.array([0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0], dtype=np.float32) * 2**30)
MEM_PROBS = np.array([0.15, 0.2, 0.25, 0.15, 0.12, 0.08, 0.05])


@dataclass
class BorgSpec:
    nodes: int = 10_000
    tasks: int = 1_000_000
    seed: int = 0
    gang_fraction: float = 0.08  # fraction of tasks that arrive in alloc sets
    max_gang: int = 8
    num_apps: int = 48  # apps with interpod/spread terms (bounds count groups)
    spread_app_fraction: float = 0.25
    toleration_fraction: float = 0.3
    mean_duration: float = 3600.0

    @classmethod
    def from_spec(cls, spec) -> "BorgSpec":
        """From any spec-like object (BorgSpec or
        utils.config.BorgWorkloadSpec) — the one conversion site."""
        if isinstance(spec, cls):
            return spec
        return cls(
            nodes=spec.nodes,
            tasks=spec.tasks,
            seed=spec.seed,
            gang_fraction=spec.gang_fraction,
            max_gang=spec.max_gang,
            num_apps=getattr(spec, "num_apps", 48),
        )


def _make_templates(spec: BorgSpec) -> List[Pod]:
    """One template per (app-term-class, cpu bucket, mem bucket, tier) cell
    actually used; kept small (~hundreds)."""
    out: List[Pod] = []
    for app in range(spec.num_apps):
        labels = {"app": f"borg-app-{app}"}
        spread = []
        if app < int(spec.num_apps * spec.spread_app_fraction):
            spread = [
                TopologySpreadConstraint(
                    max_skew=5,
                    topology_key="topology.kubernetes.io/zone",
                    when_unsatisfiable="ScheduleAnyway",
                    label_selector=LabelSelector.make({"app": f"borg-app-{app}"}),
                )
            ]
        for tol in (False, True):
            p = Pod(
                name=f"tmpl-{app}-{int(tol)}",
                labels=dict(labels),
                requests={"cpu": 1.0, "memory": 2**30},
                topology_spread=list(spread),
                tolerations=(
                    [Toleration(key="dedicated", operator="Equal", value="batch")] if tol else []
                ),
            )
            out.append(p)
    return out


def _sample_cols(spec: BorgSpec) -> dict:
    """Sample the per-task trace columns (the CSV/columnar schema shared
    with native.read_trace_csv): arrival, cpu, mem, priority, group_id,
    app_id, tolerates, duration."""
    rng = np.random.default_rng(spec.seed)
    P = spec.tasks
    app_probs = 1.0 / (np.arange(spec.num_apps) + 2.0)
    app_probs /= app_probs.sum()
    app = rng.choice(spec.num_apps, size=P, p=app_probs).astype(np.int32)
    tier = rng.choice(len(PRIORITY_TIERS), size=P, p=TIER_PROBS)
    tol = ((tier <= 1) & (rng.random(P) < spec.toleration_fraction)).astype(np.int32)

    cpu = rng.choice(CPU_BUCKETS, size=P, p=CPU_PROBS).astype(np.float32)
    mem = rng.choice(MEM_BUCKETS, size=P, p=MEM_PROBS).astype(np.float32)

    # Diurnal-bursty arrivals over a virtual day.
    base_rate = P / 86400.0
    phase = rng.random() * 86400
    gaps = rng.exponential(1.0 / base_rate, size=P)
    arrival = np.cumsum(gaps)
    arrival *= 1.0 + 0.5 * np.sin((arrival + phase) * (2 * np.pi / 86400.0))
    arrival = np.sort(arrival).astype(np.float64)

    # Alloc sets: contiguous gangs.
    group_id = np.full(P, PAD, dtype=np.int32)
    i = 0
    g = 0
    while i < P:
        if rng.random() < spec.gang_fraction / max(spec.max_gang / 2, 1):
            size = int(rng.integers(2, spec.max_gang + 1))
            size = min(size, P - i)
            group_id[i : i + size] = g
            g += 1
            i += size
        else:
            i += 1

    return {
        "arrival": arrival,
        "cpu": cpu,
        "mem": mem,
        "priority": PRIORITY_TIERS[tier].astype(np.int32),
        "group_id": group_id,
        "app_id": app,
        "tolerates": tol,
        "duration": rng.exponential(spec.mean_duration, size=P).astype(np.float32),
    }


def encoded_from_cols(spec: BorgSpec, cols: dict) -> Tuple[EncodedCluster, EncodedPods, dict]:
    """Columnar trace → (EncodedCluster, EncodedPods, meta) by expanding the
    app/toleration templates through the normal Encoder. The inverse of
    export_trace_csv; also the ingest path for external trace files."""
    cluster = make_cluster(spec.nodes, seed=spec.seed, taint_fraction=0.15)
    templates = _make_templates(spec)
    enc = Encoder()
    ec, tmpl_ep = enc.encode(cluster, templates)

    P = len(cols["arrival"])
    # Real Borg app/logical-collection ids are sparse 64-bit values far past
    # num_apps; remap to contiguous ids in first-appearance order (mirrors
    # the group_id remap below) so tasks spread across template classes
    # instead of all clipping into the top one. Apps past num_apps wrap.
    app_raw = np.asarray(cols["app_id"], np.int64)
    if app_raw.size and app_raw.max(initial=0) >= spec.num_apps:
        uniq_a, first_a, inv_a = np.unique(
            app_raw, return_index=True, return_inverse=True
        )
        rank_a = np.empty(len(uniq_a), dtype=np.int64)
        rank_a[np.argsort(first_a)] = np.arange(len(uniq_a), dtype=np.int64)
        app_raw = rank_a[inv_a] % spec.num_apps
    app = np.clip(app_raw, 0, spec.num_apps - 1)
    tol = np.asarray(cols["tolerates"], np.int64).clip(0, 1)
    tidx = app * 2 + tol

    requests = tmpl_ep.requests[tidx].copy()
    ci, mi, pi = enc.vocab._r["cpu"], enc.vocab._r["memory"], enc.vocab._r["pods"]
    requests[:, ci] = np.asarray(cols["cpu"], np.float32)
    requests[:, mi] = np.asarray(cols["mem"], np.float32)
    requests[:, pi] = 1.0

    arrival = np.asarray(cols["arrival"], np.float64)
    # int64 until after the remap: real Borg collection ids exceed 2^31.
    group_raw = np.asarray(cols["group_id"], np.int64)
    duration = np.asarray(cols["duration"], np.float32)

    # pg_min_member is indexed by gang id, so external traces with sparse
    # group ids (real Borg collection ids) are remapped to contiguous ids
    # in first-appearance order.
    mask = group_raw >= 0
    group_id = np.full(P, PAD, dtype=np.int32)
    if mask.any():
        uniq, first_idx, inv = np.unique(
            group_raw[mask], return_index=True, return_inverse=True
        )
        rank = np.empty(len(uniq), dtype=np.int32)
        rank[np.argsort(first_idx)] = np.arange(len(uniq), dtype=np.int32)
        group_id[mask] = rank[inv]
        gang_sizes = [int(c) for c in np.bincount(group_id[mask], minlength=len(uniq))]
    else:
        gang_sizes = []
    pg_min = np.array(gang_sizes or [1], dtype=np.int32)

    ep = EncodedPods(
        num_pods=P,
        names=[f"task-{j}" for j in range(P)],
        requests=requests,
        priority=np.asarray(cols["priority"], np.int32),
        arrival=arrival,
        duration=duration,
        ns=tmpl_ep.ns[tidx],
        bound_node=np.full(P, PAD, dtype=np.int32),
        tol_key=tmpl_ep.tol_key[tidx],
        tol_kv=tmpl_ep.tol_kv[tidx],
        tol_effect=tmpl_ep.tol_effect[tidx],
        na_req=tmpl_ep.na_req[tidx],
        na_has_req=tmpl_ep.na_has_req[tidx],
        na_pref=tmpl_ep.na_pref[tidx],
        na_pref_w=tmpl_ep.na_pref_w[tidx],
        aff_req=tmpl_ep.aff_req[tidx],
        anti_req=tmpl_ep.anti_req[tidx],
        pref_aff=tmpl_ep.pref_aff[tidx],
        pref_aff_w=tmpl_ep.pref_aff_w[tidx],
        spread_g=tmpl_ep.spread_g[tidx],
        spread_skew=tmpl_ep.spread_skew[tidx],
        spread_dns=tmpl_ep.spread_dns[tidx],
        pod_matches_group=tmpl_ep.pod_matches_group[tidx],
        group_id=group_id,
        pg_min_member=pg_min,
        pg_names=[f"alloc-set-{j}" for j in range(len(gang_sizes))] or ["none"],
    )
    meta = {
        "num_gangs": len(gang_sizes),
        "gang_pods": int((group_id >= 0).sum()),
        "num_groups": ec.num_groups,
        "makespan": float(arrival[-1]) if P else 0.0,
    }
    return ec, ep, meta


def make_borg_encoded(spec: BorgSpec) -> Tuple[EncodedCluster, EncodedPods, dict]:
    """Vectorized trace build → (EncodedCluster, EncodedPods, meta)."""
    return encoded_from_cols(spec, _sample_cols(spec))


def export_trace_csv(spec: BorgSpec, path) -> dict:
    """Sample a Borg-shaped trace and write it as a columnar task-event CSV
    (native C++ writer when available, numpy otherwise). Returns the cols."""
    from ..native import write_trace_csv

    cols = _sample_cols(spec)
    if not write_trace_csv(path, cols):
        header = "arrival_s,cpu,mem_bytes,priority,group_id,app_id,tolerates,duration_s"
        stacked = np.column_stack(
            [
                cols["arrival"], cols["cpu"], cols["mem"], cols["priority"],
                cols["group_id"], cols["app_id"], cols["tolerates"], cols["duration"],
            ]
        )
        np.savetxt(path, stacked, fmt="%.6f,%g,%g,%d,%d,%d,%d,%g", header=header, comments="")
    return cols


def load_trace_csv(path, spec: BorgSpec) -> Tuple[EncodedCluster, EncodedPods, dict]:
    """Ingest a task-event trace file (the replay driver's external-trace
    path). ``spec`` supplies the cluster shape and template vocabulary."""
    from ..native import read_trace_csv

    cols = read_trace_csv(path)
    if cols is None:  # pure-python fallback, same per-line rule as native
        def _data_lines(f):
            # Mirror traceio.cpp data_line(): skip blanks, '#' comments and
            # any non-numeric (header) line, wherever it appears.
            for line in f:
                s = line.lstrip()
                if s and s[0] != "#" and s[0] in "0123456789-+.":
                    yield s

        with open(path) as f:
            raw = np.genfromtxt(_data_lines(f), delimiter=",")
        raw = raw.reshape(-1, 8)
        cols = {
            "arrival": raw[:, 0].astype(np.float64),
            "cpu": raw[:, 1].astype(np.float32),
            "mem": raw[:, 2].astype(np.float32),
            "priority": raw[:, 3].astype(np.int32),
            "group_id": raw[:, 4].astype(np.int64),
            "app_id": raw[:, 5].astype(np.int64),
            "tolerates": raw[:, 6].astype(np.int32),
            "duration": raw[:, 7].astype(np.float32),
        }
    return encoded_from_cols(spec, cols)


def make_borg_trace(spec) -> Tuple[Cluster, List[Pod]]:
    """Object-model variant for SMALL task counts (CPU-engine tests).
    ``spec`` may be a BorgSpec or utils.config.BorgWorkloadSpec."""
    bspec = BorgSpec.from_spec(spec)
    if bspec.tasks > 200_000:
        raise ValueError("object-model borg trace capped at 200k tasks; use make_borg_encoded")
    rng = np.random.default_rng(bspec.seed)
    cluster = make_cluster(bspec.nodes, seed=bspec.seed, taint_fraction=0.15)
    templates = _make_templates(bspec)
    app_probs = 1.0 / (np.arange(bspec.num_apps) + 2.0)
    app_probs /= app_probs.sum()
    pods: List[Pod] = []
    t = 0.0
    g = 0
    i = 0
    while i < bspec.tasks:
        gang = rng.random() < bspec.gang_fraction / max(bspec.max_gang / 2, 1)
        size = int(rng.integers(2, bspec.max_gang + 1)) if gang else 1
        size = min(size, bspec.tasks - i)
        gname = f"alloc-set-{g}" if gang else None
        if gang:
            g += 1
        for _ in range(size):
            t += float(rng.exponential(86400.0 / bspec.tasks))
            app = int(rng.choice(bspec.num_apps, p=app_probs))
            tier = int(rng.choice(len(PRIORITY_TIERS), p=TIER_PROBS))
            tol = tier <= 1 and rng.random() < bspec.toleration_fraction
            tmpl = templates[app * 2 + int(tol)]
            pods.append(
                Pod(
                    name=f"task-{i}",
                    labels=dict(tmpl.labels),
                    requests={
                        "cpu": float(rng.choice(CPU_BUCKETS, p=CPU_PROBS)),
                        "memory": float(rng.choice(MEM_BUCKETS, p=MEM_PROBS)),
                    },
                    priority=int(PRIORITY_TIERS[tier]),
                    arrival_time=t,
                    duration=float(rng.exponential(bspec.mean_duration)),
                    tolerations=list(tmpl.tolerations),
                    topology_spread=list(tmpl.topology_spread),
                    pod_group=gname,
                )
            )
            i += 1
    return cluster, pods
