"""Simulator-as-a-service (round 22): resident engines serving batched
multi-tenant what-if queries.

Every entry point before this round was batch: build an engine, replay,
exit — each "what if" paid compile plus cold cluster state. This module
keeps the pieces RESIDENT between queries:

- **Engine pool** — one compiled executable per (query family,
  telemetry granularity) key, LRU-evicted under the
  ``KSIM_SERVICE_MAX_ENGINES`` cap. A pool hit swaps scenario VALUES
  against the compiled program via :meth:`WhatIfEngine.set_scenarios`
  (the round-5 ``set_policies`` trick applied to the cluster stacks),
  so a warm query recompiles NOTHING — compile count stays pinned at
  one per key for the whole session (tests/test_service.py, same
  ``_chunk_fn._cache_size()`` pin as the tuner's).
- **Incremental base state** — the service maintains a host mirror of
  committed usage (bind/release/evict deltas, per-node ordered bind
  lists summed in insertion order — deterministic f32) instead of
  rebuilding cluster state from the trace per query. The mirror enters
  every scenario as synthesized per-node-per-resource
  ``scale_capacity`` perturbations, the SAME :class:`ScenarioSet` code
  path a one-off run takes — which is what makes batched answers
  bit-identical to sequential oracles by construction.
- **Micro-batching admission queue** — queries from many tenants
  coalesce onto the scenario axis: scenario 0 is always the clean
  baseline (the benefit reference), slots 1..max_batch carry queries,
  unused slots are padded with baseline copies (per-scenario results
  are batch-composition independent — pinned round 15). The queue
  flushes on batch-full or a deadline (cooperative: checked at every
  submit/poll — the serve loop has no threads to race).

First query family: **defragmentation what-ifs** — drain-and-repack a
requested node set through the chaos eviction path (``node_down`` at
``drainAt``, optional ``node_up`` at ``recoverAt``), scored against
eviction cost (evictions, rescheduled, stranded, mean evict→re-bind
latency) AND the round-9/13 fragmentation economics (stranded CPU,
fragmentation index, packing efficiency) relative to the baseline slot
— one answer carries both the compaction benefit and its disruption
price.

Query grammar (one JSON object per line on the ``serve`` CLI)::

    {"op": "defrag", "tenant": "team-a", "id": "q1",
     "nodes": [3, "n7"], "drainAt": 5.0, "recoverAt": 12.0}

Results demux per tenant (:meth:`QueryService.poll`) and stream as
schema-v7 ``query`` / ``query-result`` rows; malformed input becomes a
``query-error`` row and the service keeps serving (the engine pool
never tears down on a bad line). Flight-recorder ``query`` rows carry
queue depth, batch occupancy and cold-vs-warm latency so the existing
observability stack sees the serving plane.
"""

from __future__ import annotations

import math
import os
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework.framework import FrameworkConfig
from ..models.encode import EncodedCluster, EncodedPods
from .runtime import NodeEvent
from .whatif import Perturbation, Scenario, WhatIfEngine

# Telemetry granularities a query may request; batches group by
# granularity so one flush can touch several pool engines.
_QUERY_FAMILIES = ("defrag",)


def max_engines_cap(default: int = 4) -> int:
    """Engine-pool cap: ``KSIM_SERVICE_MAX_ENGINES`` wins over the
    config/ctor value (operator env beats YAML, same rule as every
    other KSIM_* knob)."""
    v = os.environ.get("KSIM_SERVICE_MAX_ENGINES", "").strip()
    if v:
        try:
            return max(1, int(v))
        except ValueError:
            pass
    return max(1, int(default))


@dataclass
class DefragQuery:
    """One validated defragmentation what-if (parsed from the wire
    dict). ``nodes`` is sorted/deduped so the synthesized event
    timeline is deterministic regardless of request order."""

    tenant: str
    qid: str
    nodes: List[int]
    drain_at: float
    recover_at: Optional[float]
    granularity: Optional[str] = None  # None = service default
    submit_t: float = 0.0
    family: str = "defrag"


@dataclass
class ServiceStats:
    """Serving-plane counters (``QueryService.stats()`` returns the
    dict form; the bench's ``detail.service`` block is built from it)."""

    queries: int = 0
    batches: int = 0
    cold_builds: int = 0
    warm_hits: int = 0
    evicted_engines: int = 0
    errors: int = 0
    compile_counts: Dict[str, Optional[int]] = field(default_factory=dict)


class QueryService:
    """Resident what-if query service over one encoded (cluster, trace)
    pair. Single-threaded and cooperative by design — submit/poll/flush
    drive the admission queue; there is no background thread to race
    the host mirrors."""

    def __init__(
        self,
        ec: EncodedCluster,
        ep: EncodedPods,
        config: Optional[FrameworkConfig] = None,
        *,
        max_batch: int = 3,
        batch_deadline_s: float = 0.05,
        max_engines: int = 4,
        granularity: str = "summary",
        retry_buffer: int = 64,
        writer=None,
        flight=None,
        clock=None,
        **engine_kw,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if batch_deadline_s <= 0:
            raise ValueError(
                "batch_deadline_s must be > 0 (a zero deadline would "
                "flush every query alone and serve nothing batched)"
            )
        if retry_buffer < 1:
            raise ValueError(
                "retry_buffer must be >= 1 (defrag queries drain nodes "
                "through the kube boundary retry pass)"
            )
        self.ec = ec
        self.ep = ep
        self.config = config
        self.max_batch = int(max_batch)
        # Fixed batch shape: slot 0 = clean baseline, 1..max_batch =
        # queries (padded with baseline copies) — ONE compiled shape
        # per key regardless of instantaneous occupancy.
        self.S = self.max_batch + 1
        self.batch_deadline_s = float(batch_deadline_s)
        self.max_engines = max_engines_cap(max_engines)
        self.granularity = granularity
        self.retry_buffer = int(retry_buffer)
        self.engine_kw = dict(engine_kw)
        self.writer = writer
        self.flight = flight
        self._clock = clock or time.perf_counter
        self._pool: "OrderedDict[Tuple[str, str], WhatIfEngine]" = (
            OrderedDict()
        )
        self.stats_ = ServiceStats()
        # Host mirror of committed base state: per-node insertion-order
        # bind lists; used rows are recomputed lazily per dirty node by
        # summing the active binds IN ORDER (deterministic f32).
        self._alloc = np.asarray(ec.allocatable, dtype=np.float32)
        self._rindex = dict(ec.vocab._r)
        self._rname = {ri: name for name, ri in self._rindex.items()}
        self._node_index = {n: i for i, n in enumerate(ec.node_names)}
        self._binds: Dict[str, Tuple[int, np.ndarray]] = {}
        self._node_binds: Dict[int, List[str]] = {}
        self._used_rows: Dict[int, np.ndarray] = {}
        self._node_perts: Dict[int, List[Perturbation]] = {}
        self._dirty: set = set()
        # Admission queue + per-tenant result store.
        self._pending: List[DefragQuery] = []
        self._deadline: Optional[float] = None
        self._results: Dict[str, List[dict]] = {}
        self._inflight_ids: set = set()
        self._qseq = 0
        self._closed = False

    # -- base cluster state (incremental, never rebuilt from trace) ------

    def _req_vector(self, requests) -> np.ndarray:
        vec = np.zeros(self._alloc.shape[1], dtype=np.float32)
        if requests is None:
            return vec
        for name, amount in dict(requests).items():
            ri = self._rindex.get(name)
            if ri is None:
                raise ValueError(
                    f"unknown resource {name!r} (cluster vocabulary: "
                    f"{sorted(self._rindex)})"
                )
            vec[ri] = np.float32(amount)
        return vec

    def _node_id(self, node) -> int:
        if isinstance(node, str):
            ni = self._node_index.get(node)
            if ni is None:
                raise ValueError(f"unknown node name {node!r}")
            return ni
        ni = int(node)
        if not 0 <= ni < self.ec.num_nodes:
            raise ValueError(
                f"node {node} out of range for a cluster of "
                f"{self.ec.num_nodes} nodes"
            )
        return ni

    def apply_bind(self, bind_id: str, node, requests) -> None:
        """Commit one pod-sized usage delta to the base state. The next
        query sees it — no trace rebuild, only the touched node's used
        row is recomputed."""
        if bind_id in self._binds:
            raise ValueError(f"bind {bind_id!r} is already active")
        ni = self._node_id(node)
        self._binds[bind_id] = (ni, self._req_vector(requests))
        self._node_binds.setdefault(ni, []).append(bind_id)
        self._dirty.add(ni)

    def apply_release(self, bind_id: str) -> None:
        """Release one active bind (completion delta)."""
        ent = self._binds.pop(bind_id, None)
        if ent is None:
            raise ValueError(f"unknown bind {bind_id!r}")
        ni = ent[0]
        self._node_binds[ni].remove(bind_id)
        self._dirty.add(ni)

    def apply_evict(self, node) -> List[str]:
        """Evict every active bind on ``node`` (chaos/operator delta);
        returns the released bind ids in insertion order."""
        ni = self._node_id(node)
        victims = list(self._node_binds.get(ni, ()))
        for bid in victims:
            self._binds.pop(bid, None)
        if victims:
            self._node_binds[ni] = []
            self._dirty.add(ni)
        return victims

    def _used_row(self, ni: int) -> np.ndarray:
        row = np.zeros(self._alloc.shape[1], dtype=np.float32)
        for bid in self._node_binds.get(ni, ()):
            row = row + self._binds[bid][1]
        return row

    def _refresh_dirty(self) -> None:
        for ni in sorted(self._dirty):
            row = self._used_row(ni)
            if not row.any():
                self._used_rows.pop(ni, None)
                self._node_perts.pop(ni, None)
                continue
            self._used_rows[ni] = row
            perts: List[Perturbation] = []
            for ri in range(self._alloc.shape[1]):
                alloc = float(self._alloc[ni, ri])
                used = float(row[ri])
                if used <= 0.0 or alloc <= 0.0:
                    continue
                factor = max((alloc - used) / alloc, 0.0)
                perts.append(
                    Perturbation(
                        op="scale_capacity",
                        nodes=np.array([ni]),
                        resource=self._rname[ri],
                        factor=factor,
                    )
                )
            self._node_perts[ni] = perts
        self._dirty.clear()

    def base_perturbations(self) -> List[Perturbation]:
        """The base state as perturbations — prepended to EVERY scenario
        (baseline included), so queries run against the live cluster
        through the exact same ScenarioSet path a one-off run takes."""
        self._refresh_dirty()
        out: List[Perturbation] = []
        for ni in sorted(self._node_perts):
            out.extend(self._node_perts[ni])
        return out

    def base_state(self) -> dict:
        self._refresh_dirty()
        return {
            "binds": len(self._binds),
            "nodes_used": len(self._used_rows),
        }

    # -- query admission --------------------------------------------------

    def parse_query(self, q: dict) -> DefragQuery:
        """Validate one wire dict. Raises ``ValueError`` on anything
        malformed — the serve loop turns that into a ``query-error``
        row and keeps serving."""
        if not isinstance(q, dict):
            raise ValueError("query must be a JSON object")
        fam = q.get("op")
        if fam not in _QUERY_FAMILIES:
            raise ValueError(
                f"op: unknown query family {fam!r} (known: "
                f"{', '.join(_QUERY_FAMILIES)})"
            )
        tenant = str(q.get("tenant") or "default")
        self._qseq += 1
        qid = str(q.get("id") or f"q{self._qseq}")
        raw_nodes = q.get("nodes")
        if not raw_nodes:
            raise ValueError("nodes: a defrag query must name >= 1 node")
        nodes = sorted({self._node_id(n) for n in raw_nodes})
        drain_at = float(q.get("drainAt", 0.0))
        if not math.isfinite(drain_at) or drain_at < 0:
            raise ValueError(
                f"drainAt: must be a finite value >= 0, got {drain_at!r}"
            )
        recover_at = q.get("recoverAt")
        if recover_at is not None:
            recover_at = float(recover_at)
            if not math.isfinite(recover_at) or recover_at <= drain_at:
                raise ValueError(
                    "recoverAt: must be > drainAt (or null to leave "
                    "the nodes drained)"
                )
        gran = q.get("granularity")
        if gran is not None:
            from .telemetry import _LEVELS

            if gran not in _LEVELS:
                raise ValueError(
                    f"granularity: must be one of {', '.join(_LEVELS)}"
                )
        return DefragQuery(
            tenant=tenant, qid=qid, nodes=nodes, drain_at=drain_at,
            recover_at=recover_at, granularity=gran,
        )

    def submit(self, q: dict) -> Tuple[str, str]:
        """Admit one query; returns ``(tenant, id)``. Flushes the batch
        when it fills; otherwise arms the deadline (checked at the next
        submit/poll)."""
        if self._closed:
            raise ValueError("service is closed")
        dq = self.parse_query(q)
        key = (dq.tenant, dq.qid)
        if key in self._inflight_ids:
            raise ValueError(
                f"duplicate query id {dq.qid!r} for tenant "
                f"{dq.tenant!r} (poll results before reusing ids)"
            )
        dq.submit_t = self._clock()
        self._inflight_ids.add(key)
        self._pending.append(dq)
        self.stats_.queries += 1
        if self.writer is not None:
            self.writer.write(
                {
                    "kind": "query",
                    "tenant": dq.tenant,
                    "query": dq.qid,
                    "family": dq.family,
                    "queue_depth": len(self._pending),
                }
            )
        if self._deadline is None:
            self._deadline = dq.submit_t + self.batch_deadline_s
        if len(self._pending) >= self.max_batch:
            self.flush()
        return dq.tenant, dq.qid

    def poll(self, tenant: Optional[str] = None) -> List[dict]:
        """Drain finished results (for one tenant, or all). Flushes the
        admission queue first when its deadline has expired."""
        if (
            self._pending
            and self._deadline is not None
            and self._clock() >= self._deadline
        ):
            self.flush()
        if tenant is not None:
            return self._results.pop(tenant, [])
        out: List[dict] = []
        for t in sorted(self._results):
            out.extend(self._results[t])
        self._results.clear()
        return out

    def deadline_remaining(self) -> Optional[float]:
        """Seconds until the armed batch deadline (None when idle) —
        the serve loop sizes its input wait with this."""
        if self._deadline is None or not self._pending:
            return None
        return max(self._deadline - self._clock(), 0.0)

    # -- scenario synthesis (shared with the parity oracle) ---------------

    def base_scenario(self) -> Scenario:
        """The clean-baseline scenario (slot 0 / padding)."""
        return Scenario(perturbations=self.base_perturbations())

    def query_scenario(self, dq: DefragQuery) -> Scenario:
        """The device scenario for one defrag query: base state plus a
        drain(/recover) timeline through the chaos eviction path. The
        parity tests run THIS through a fresh one-off engine — the
        conversion is the single source of truth."""
        events = [
            NodeEvent(time=dq.drain_at, kind="node_down", node=n)
            for n in dq.nodes
        ]
        if dq.recover_at is not None:
            events.extend(
                NodeEvent(time=dq.recover_at, kind="node_up", node=n)
                for n in dq.nodes
            )
        return Scenario(
            perturbations=self.base_perturbations(), events=events
        )

    # -- engine pool -------------------------------------------------------

    def _pool_key(self, dq: DefragQuery) -> Tuple[str, str]:
        return (dq.family, dq.granularity or self.granularity)

    def _acquire(
        self, key: Tuple[str, str], scens: List[Scenario]
    ) -> Tuple[WhatIfEngine, bool]:
        eng = self._pool.get(key)
        if eng is not None:
            try:
                eng.set_scenarios(scens)
                self._pool.move_to_end(key)
                self.stats_.warm_hits += 1
                return eng, True
            except ValueError:
                # Shape/envelope drift — fall through to a cold build.
                del self._pool[key]
        eng = WhatIfEngine(
            self.ec,
            self.ep,
            scens,
            self.config,
            preemption="kube",
            retry_buffer=self.retry_buffer,
            telemetry=key[1],
            **self.engine_kw,
        )
        self.stats_.cold_builds += 1
        self._pool[key] = eng
        while len(self._pool) > self.max_engines:
            self._pool.popitem(last=False)
            self.stats_.evicted_engines += 1
        return eng, False

    # -- flush: coalesce, run, demux ---------------------------------------

    def flush(self) -> int:
        """Run every pending query now; returns the number answered.
        Queries group by (family, granularity) — each group coalesces
        onto the scenario axis of its pool engine."""
        batch, self._pending, self._deadline = self._pending, [], None
        if not batch:
            return 0
        groups: "OrderedDict[Tuple[str, str], List[DefragQuery]]" = (
            OrderedDict()
        )
        for dq in batch:
            groups.setdefault(self._pool_key(dq), []).append(dq)
        for key, qs in groups.items():
            self._run_group(key, qs)
        return len(batch)

    def _run_group(self, key: Tuple[str, str], qs: List[DefragQuery]):
        t0 = self._clock()
        base = self.base_scenario()
        scens = [base] + [self.query_scenario(dq) for dq in qs]
        while len(scens) < self.S:
            scens.append(self.base_scenario())
        eng, warm = self._acquire(key, scens)
        res = eng.run()
        latency = self._clock() - t0
        occupancy = len(qs) / self.max_batch
        self.stats_.batches += 1
        if self.flight is not None:
            self.flight.query(
                batch=self.stats_.batches,
                queued=len(qs),
                occupancy=occupancy,
                warm=warm,
                latency_s=latency,
                engines=len(self._pool),
            )

        def _opt(arr, si):
            if arr is None:
                return None
            v = float(arr[si])
            return None if math.isnan(v) else v

        for slot, dq in enumerate(qs):
            si = slot + 1
            row = {
                "kind": "query-result",
                "tenant": dq.tenant,
                "query": dq.qid,
                "family": dq.family,
                "batch": self.stats_.batches,
                "slot": slot,
                "batch_occupancy": round(occupancy, 4),
                "warm": bool(warm),
                "latency_s": round(latency, 6),
                "queue_wait_s": round(max(t0 - dq.submit_t, 0.0), 6),
                "placed": int(res.placed[si]),
                "unschedulable": int(res.unschedulable[si]),
                "placed_delta": int(res.placed[si] - res.placed[0]),
                # Disruption price: chaos evictions through the drain.
                "evictions": (
                    int(res.evictions[si])
                    if res.evictions is not None else None
                ),
                "evict_rescheduled": (
                    int(res.evict_rescheduled[si])
                    if res.evict_rescheduled is not None else None
                ),
                "evict_stranded": (
                    int(res.evict_stranded[si])
                    if res.evict_stranded is not None else None
                ),
                "evict_latency_mean": _opt(res.evict_latency_mean, si),
                # Compaction benefit: fragmentation economics vs the
                # baseline slot of the SAME batch (same base state).
                "stranded_cpu": _opt(res.stranded_cpu, si),
                "frag_index_cpu": _opt(res.frag_index_cpu, si),
                "packing_efficiency": _opt(res.packing_efficiency, si),
                "baseline_stranded_cpu": _opt(res.stranded_cpu, 0),
                "baseline_frag_index_cpu": _opt(res.frag_index_cpu, 0),
                "baseline_packing_efficiency": _opt(
                    res.packing_efficiency, 0
                ),
            }
            if res.scenario_telemetry is not None:
                tel = res.scenario_telemetry[si]
                if tel is not None:
                    row["telemetry"] = tel.query_view()
            self._inflight_ids.discard((dq.tenant, dq.qid))
            self._results.setdefault(dq.tenant, []).append(row)
            if self.writer is not None:
                from ..utils.metrics import _scrub_timing

                self.writer.write(_scrub_timing(dict(row)))

    # -- lifecycle ---------------------------------------------------------

    def stats(self) -> dict:
        from .jax_runtime import compiled_cache_size

        self.stats_.compile_counts = {
            "/".join(k): compiled_cache_size(eng._chunk_fn)
            for k, eng in self._pool.items()
        }
        d = dict(self.stats_.__dict__)
        d["engines"] = len(self._pool)
        return d

    def close(self) -> List[dict]:
        """Flush whatever is queued, drop the engine pool, and return
        any undelivered results."""
        if self._closed:
            return []
        self.flush()
        self._closed = True
        self._pool.clear()
        return self.poll()


def serve_lines(service: QueryService, lines, writer) -> dict:
    """Drive a :class:`QueryService` from an iterable of NDJSON lines
    (the ``serve`` CLI hands it stdin or a named pipe). A malformed or
    torn line becomes a structured ``query-error`` row and the loop
    KEEPS SERVING — the engine pool never tears down on bad input.
    Finished results stream through the service's writer as they
    demux; EOF flushes the tail. Returns the final stats dict."""
    import json

    for raw in lines:
        raw = raw.strip()
        if not raw:
            continue
        try:
            q = json.loads(raw)
            service.submit(q)
        except ValueError as e:
            # json.JSONDecodeError is a ValueError: one handler covers
            # torn/partial lines and semantically invalid queries.
            service.stats_.errors += 1
            if writer is not None:
                writer.write(
                    {
                        "kind": "query-error",
                        "error": str(e)[:500],
                        "raw": raw[:200],
                    }
                )
            continue
        service.poll()  # deadline check between lines (cooperative)
    service.close()
    return service.stats()
