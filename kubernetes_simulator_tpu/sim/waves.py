"""Wave packing — the rectangular schedule the device scan walks.

Pods (in arrival order) are packed into fixed-width "waves" of W slots such
that no pod-group (gang) spans waves. The JAX engine scans waves; within a
wave, slots are processed sequentially (pod k sees pod k-1's speculative
bindings — SURVEY.md §7 hard part #1), and gang commit/rollback happens at
the wave boundary as one masked update (hard part #3).

Gangs larger than the wave width raise; callers size W from the trace's max
group size (Borg alloc sets are small).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..models.encode import PAD, EncodedPods


@dataclass
class WaveBatch:
    idx: np.ndarray  # [num_waves, W] i32 pod ids (PAD = empty slot)
    wave_width: int

    @property
    def num_waves(self) -> int:
        return self.idx.shape[0]


def pack_waves(
    ep: EncodedPods, wave_width: int = 8, order: Optional[np.ndarray] = None,
    page_pods: Optional[int] = None,
) -> WaveBatch:
    """Pack schedulable pods into waves. ``order`` defaults to arrival order
    of unbound pods (stable; deterministic). Uses the native C++ packer
    (kubernetes_simulator_tpu.native) when available — ~40× faster at 1M
    pods; this Python path is the semantic reference and fallback.

    ``page_pods`` (round 14 paged mode): number of pod SLOTS per streamed
    page. Validated here against the largest gang — a gang split across
    pages could see its later members arrive after the page carrying its
    earlier ones was evicted, so the guard mirrors the wave-width check
    (and runs on BOTH the native and reference paths)."""
    if order is None:
        unbound = np.nonzero(ep.bound_node == PAD)[0]
        order = unbound[np.argsort(ep.arrival[unbound], kind="stable")]
    if page_pods is not None:
        gids = ep.group_id[np.asarray(order)]
        gids = gids[gids != PAD]
        max_gang = int(np.bincount(gids).max()) if gids.size else 1
        if page_pods < max_gang:
            raise ValueError(
                f"paged mode: page of {page_pods} pod slots is smaller than "
                f"the largest gang ({max_gang} pods) — a gang must fit in "
                f"one page; raise chunk_waves/wave_width so that "
                f"chunk_waves * wave_width >= {max_gang}, or disable paging"
            )
    from ..native import pack_waves_native

    idx_native = pack_waves_native(np.asarray(order), ep.group_id, wave_width)
    if idx_native is not None:
        return WaveBatch(idx=idx_native, wave_width=wave_width)
    members: Dict[int, List[int]] = {}
    for p in order:
        g = int(ep.group_id[p])
        if g != PAD:
            members.setdefault(g, []).append(int(p))
    max_group = max((len(v) for v in members.values()), default=1)
    if max_group > wave_width:
        raise ValueError(
            f"gang of size {max_group} exceeds wave width {wave_width}; "
            f"use wave_width >= {max_group}"
        )
    waves: List[List[int]] = []
    current: List[int] = []
    consumed = set()

    def flush():
        nonlocal current
        if current:
            waves.append(current)
            current = []

    for p in order:
        p = int(p)
        if p in consumed:
            continue
        g = int(ep.group_id[p])
        batch = [p] if g == PAD else members[g]
        if len(current) + len(batch) > wave_width:
            flush()
        current.extend(batch)
        consumed.update(batch)
    flush()

    idx = np.full((max(len(waves), 1), wave_width), PAD, dtype=np.int32)
    for i, w in enumerate(waves):
        idx[i, : len(w)] = w
    return WaveBatch(idx=idx, wave_width=wave_width)
