"""Synthetic cluster/workload generators for the [BASELINE] eval configs.

Config 1: 100 nodes / 1k pods, NodeResourcesFit + LeastAllocated.
Config 2: 5k nodes / 50k pods, full default plugin set (affinity, taints,
topology-spread). Config 4's Borg-2019-like 10k×1M generator (gangs,
priorities, alloc sets) lives in :mod:`.borg`.

All generators are seeded and deterministic (SURVEY.md §4.3).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..models.core import (
    Cluster,
    LabelSelector,
    MatchExpression,
    Node,
    NodeAffinitySpec,
    NodeSelectorTerm,
    Pod,
    PodAffinitySpec,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)

MACHINE_SHAPES = [  # (cpu cores, memory GiB) mimicking heterogeneous fleets
    (16, 64),
    (32, 128),
    (64, 256),
    (96, 384),
]


def make_cluster(
    num_nodes: int,
    seed: int = 0,
    num_zones: int = 8,
    taint_fraction: float = 0.0,
    extended_resources: Optional[dict] = None,
) -> Cluster:
    """Heterogeneous nodes across zones/racks; optional taints and extended
    resources (e.g. ``{"google.com/tpu": 8}`` on a fraction of nodes)."""
    rng = np.random.default_rng(seed)
    nodes: List[Node] = []
    for i in range(num_nodes):
        cpu, mem = MACHINE_SHAPES[rng.integers(len(MACHINE_SHAPES))]
        labels = {
            "topology.kubernetes.io/zone": f"zone-{i % num_zones}",
            "topology.kubernetes.io/rack": f"rack-{i % (num_zones * 4)}",
            "node.kubernetes.io/instance-type": f"type-{cpu}",
            "tier": "hot" if i % 5 == 0 else "standard",
        }
        taints = []
        if taint_fraction and rng.random() < taint_fraction:
            taints.append(Taint("dedicated", "batch", "NoSchedule"))
        capacity = {"cpu": float(cpu), "memory": float(mem) * 2**30, "pods": 110}
        if extended_resources:
            for r, (count, frac) in extended_resources.items():
                if rng.random() < frac:
                    capacity[r] = float(count)
                    labels["accelerator"] = r.split("/")[-1]
        nodes.append(Node(name=f"node-{i}", capacity=capacity, labels=labels, taints=taints))
    return Cluster(nodes=nodes)


def make_workload(
    num_pods: int,
    seed: int = 0,
    arrival_rate: float = 100.0,
    duration_mean: Optional[float] = None,
    with_affinity: bool = False,
    with_spread: bool = False,
    with_tolerations: bool = False,
    num_apps: int = 20,
    gang_fraction: float = 0.0,
    gang_size: int = 4,
    extended_resource: Optional[Tuple[str, int, float]] = None,
) -> Tuple[List[Pod], dict]:
    """Pods in arrival order with app labels; optional affinity/spread/
    toleration terms, gangs, extended-resource requests."""
    rng = np.random.default_rng(seed + 1)
    pods: List[Pod] = []
    t = 0.0
    gang_id = 0
    gang_left = 0
    gang_name = None
    for i in range(num_pods):
        t += float(rng.exponential(1.0 / arrival_rate))
        app = f"app-{int(rng.integers(num_apps))}"
        labels = {"app": app, "role": "worker" if rng.random() < 0.8 else "leader"}
        requests = {
            "cpu": float(rng.choice([0.25, 0.5, 1.0, 2.0, 4.0])),
            "memory": float(rng.choice([0.5, 1.0, 2.0, 8.0])) * 2**30,
        }
        pod = Pod(
            name=f"pod-{i}",
            labels=labels,
            requests=requests,
            priority=int(rng.choice([0, 0, 0, 100, 1000])),
            arrival_time=t,
            duration=float(rng.exponential(duration_mean)) if duration_mean else None,
        )
        if with_tolerations and rng.random() < 0.3:
            pod.tolerations.append(Toleration(key="dedicated", operator="Equal", value="batch"))
        if with_affinity:
            r = rng.random()
            if r < 0.10:
                pod.pod_affinity = PodAffinitySpec(
                    required=(
                        PodAffinityTerm(
                            label_selector=LabelSelector.make({"app": app}),
                            topology_key="topology.kubernetes.io/zone",
                        ),
                    )
                )
            elif r < 0.18:
                pod.pod_anti_affinity = PodAffinitySpec(
                    required=(
                        PodAffinityTerm(
                            label_selector=LabelSelector.make({"app": app, "role": "leader"}),
                            topology_key="kubernetes.io/hostname",
                        ),
                    )
                )
            elif r < 0.35:
                pod.node_affinity = NodeAffinitySpec(
                    preferred=(
                        PreferredSchedulingTerm(
                            weight=int(rng.integers(1, 100)),
                            term=NodeSelectorTerm(
                                (MatchExpression.make("tier", "In", ["hot"]),)
                            ),
                        ),
                    )
                )
        if with_spread and rng.random() < 0.25:
            pod.topology_spread.append(
                TopologySpreadConstraint(
                    max_skew=int(rng.choice([1, 2, 5])),
                    topology_key="topology.kubernetes.io/zone",
                    when_unsatisfiable="DoNotSchedule" if rng.random() < 0.5 else "ScheduleAnyway",
                    label_selector=LabelSelector.make({"app": app}),
                )
            )
        if extended_resource is not None:
            rname, count, frac = extended_resource
            if rng.random() < frac:
                pod.requests[rname] = float(rng.choice([1, 2, count]))
        if gang_fraction and gang_left == 0 and rng.random() < gang_fraction:
            gang_name = f"gang-{gang_id}"
            gang_id += 1
            gang_left = gang_size
        if gang_left > 0:
            pod.pod_group = gang_name
            gang_left -= 1
        pods.append(pod)
    meta = {"num_gangs": gang_id, "makespan": t}
    return pods, meta


def make_chaos_timeline(
    num_nodes: int,
    seed: int = 0,
    horizon: float = 100.0,
    mtbf: float = 200.0,
    mttr: float = 20.0,
    node_fraction: float = 0.2,
    max_events: Optional[int] = None,
):
    """Seeded chaos campaign: per-node exponential failure/recovery pairs.

    Each node in a ``node_fraction`` sample draws failure gaps from
    ``Exp(mtbf)`` and outage lengths from ``Exp(mttr)``, emitting
    ``node_down``/``node_up`` pairs until ``horizon``. ``mttr=0`` means
    nodes stay down (pure-failure campaign, no ``node_up``). Events are
    returned sorted by time — ready for ``validate_node_events`` and any
    engine's ``node_events=`` argument. Deterministic per seed.
    """
    from .runtime import NodeEvent, validate_node_events

    if mtbf <= 0:
        raise ValueError(f"chaos mtbf must be > 0, got {mtbf}")
    if mttr < 0:
        raise ValueError(f"chaos mttr must be >= 0, got {mttr}")
    if not 0.0 < node_fraction <= 1.0:
        raise ValueError(
            f"chaos node_fraction must be in (0, 1], got {node_fraction}"
        )
    rng = np.random.default_rng(seed)
    n_pick = max(1, int(round(num_nodes * node_fraction)))
    targets = rng.choice(num_nodes, size=min(n_pick, num_nodes), replace=False)
    events: List = []
    for node in sorted(int(n) for n in targets):
        t = float(rng.exponential(mtbf))
        while t < horizon:
            events.append(NodeEvent(time=t, kind="node_down", node=node))
            if mttr <= 0:
                break  # stays down for the rest of the campaign
            up = t + max(float(rng.exponential(mttr)), 1e-9)
            if up >= horizon:
                break
            events.append(NodeEvent(time=up, kind="node_up", node=node))
            t = up + max(float(rng.exponential(mtbf)), 1e-9)
    events.sort(key=lambda e: (e.time, e.node))
    if max_events is not None and len(events) > max_events:
        # Truncate at a pair boundary: never strand a node_up whose
        # node_down was cut (validation would reject it).
        events = events[:max_events]
        down = set()
        kept = []
        for e in events:
            if e.kind == "node_up" and e.node not in down:
                continue
            if e.kind == "node_down":
                down.add(e.node)
            elif e.kind == "node_up":
                down.discard(e.node)
            kept.append(e)
        events = kept
    return validate_node_events(events, num_nodes)


def config1(num_nodes: int = 100, num_pods: int = 1000, seed: int = 0):
    """[BASELINE] config #1: default kube-scheduler shape, fit+LeastAllocated."""
    cluster = make_cluster(num_nodes, seed=seed)
    pods, _ = make_workload(num_pods, seed=seed)
    plugins = [{"name": "NodeResourcesFit", "args": {"strategy": "LeastAllocated"}}]
    return cluster, pods, plugins


def config2(num_nodes: int = 5000, num_pods: int = 50_000, seed: int = 0):
    """[BASELINE] config #2: full default plugin set at 5k/50k scale."""
    cluster = make_cluster(num_nodes, seed=seed, taint_fraction=0.1)
    pods, _ = make_workload(
        num_pods, seed=seed, with_affinity=True, with_spread=True, with_tolerations=True
    )
    return cluster, pods, None  # None → full default plugin set


def config5_multitenant(num_nodes: int = 1000, num_pods: int = 10_000, seed: int = 0):
    """[BASELINE] config #5 shape: extended resources + pod-group coscheduling."""
    cluster = make_cluster(
        num_nodes, seed=seed, extended_resources={"google.com/tpu": (8, 0.25)}
    )
    pods, meta = make_workload(
        num_pods,
        seed=seed,
        gang_fraction=0.05,
        gang_size=4,
        extended_resource=("google.com/tpu", 8, 0.2),
        with_tolerations=True,
    )
    return cluster, pods, None
