"""What-if scenario engine (SURVEY.md §3.2): S perturbed cluster states
evaluated as ONE SPMD program.

The reference evaluates scenarios with its per-pod loop, one scenario at a
time ([BASELINE]); here the scenario axis is a ``vmap`` dimension sharded
over the TPU mesh, so ``whatIf(1024 scenarios)`` is a single jitted scan
whose every step evaluates ``[S_local, N]`` masks/scores per pod.

Perturbation DSL (cluster-state perturbations, per [BASELINE]):
- ``scale_capacity(nodes, resource, factor)``
- ``node_down(nodes)`` (allocatable → 0)
- ``add_taint(nodes, key, value, effect)`` (spare taint slots are added)
- ``set_label(nodes, key, value)`` (topology domains are re-derived)

Pod-side tensors are shared across scenarios (the trace is common); only
node-side tensors are stacked ``[S, ...]``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace as dc_replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.framework import FrameworkConfig
from ..models.core import Effect
from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import init_state
from ..ops import tpu as T
from ..parallel import dcn
from ..parallel.mesh import (
    SCENARIO_AXIS,
    make_mesh,
    replicate_tree,
    replicated,
    scenario_sharding,
    shard_scenario_tree,
    spans_processes,
)
from .jax_runtime import StepSpec, make_wave_step
from .waves import pack_waves


@dataclass
class Perturbation:
    """One mutation of the base cluster. ``nodes`` is a boolean mask or
    index array over nodes."""

    op: str  # "scale_capacity" | "node_down" | "add_taint" | "set_label"
    nodes: np.ndarray
    resource: Optional[str] = None
    factor: float = 1.0
    key: Optional[str] = None
    value: Optional[str] = None
    effect: str = "NoSchedule"


@dataclass
class Scenario:
    perturbations: List[Perturbation] = field(default_factory=list)
    # Timed failure/recovery timeline (chaos campaigns, round 7): a list
    # of sim.runtime.NodeEvent applied to THIS scenario at chunk
    # boundaries through its host mirror — node_down evicts bound pods
    # (NoExecute) into the retry buffer, node_up/capacity_scale re-shape
    # allocatable mid-replay. Requires kube mode (the mirrors); static
    # t=0 perturbations above need no mirror and work everywhere.
    events: List = field(default_factory=list)


class ScenarioSet:
    """Stacked [S, ...] node-side tensors for a batch of scenarios."""

    def __init__(self, ec: EncodedCluster, scenarios: Sequence[Scenario],
                 spare_taint_slots: int = 2, keep_host_stacks: bool = False):
        self.ec = ec
        self.num_scenarios = len(scenarios)
        S = self.num_scenarios
        vocab = ec.vocab

        # Spare taint slots so add_taint has room (shared shape across S).
        TT = ec.taint_key.shape[1] + spare_taint_slots
        base_tk = np.full((ec.num_nodes, TT), PAD, np.int32)
        base_tv = np.full((ec.num_nodes, TT), PAD, np.int32)
        base_te = np.zeros((ec.num_nodes, TT), np.int32)
        base_tk[:, : ec.taint_key.shape[1]] = ec.taint_key
        base_tv[:, : ec.taint_key.shape[1]] = ec.taint_kv
        base_te[:, : ec.taint_key.shape[1]] = ec.taint_effect

        alloc = np.repeat(ec.allocatable[None], S, axis=0).copy()
        tk = np.repeat(base_tk[None], S, axis=0).copy()
        tv = np.repeat(base_tv[None], S, axis=0).copy()
        te = np.repeat(base_te[None], S, axis=0).copy()
        lk = np.repeat(ec.node_label_key[None], S, axis=0).copy()
        lv = np.repeat(ec.node_label_kv[None], S, axis=0).copy()
        ln = np.repeat(ec.node_label_num[None], S, axis=0).copy()
        labels_dirty = np.zeros(S, dtype=bool)
        ov_sets: Dict[int, set] = {}  # scenario → perturbed-label node ids

        for si, sc in enumerate(scenarios):
            for pt in sc.perturbations:
                mask = np.zeros(ec.num_nodes, dtype=bool)
                mask[pt.nodes] = True
                if pt.op == "scale_capacity":
                    ri = vocab._r.get(pt.resource)
                    if ri is None:
                        continue
                    alloc[si, mask, ri] = alloc[si, mask, ri] * pt.factor
                elif pt.op == "node_down":
                    alloc[si, mask, :] = 0.0
                elif pt.op == "add_taint":
                    kid = vocab.key(pt.key)
                    kvid = vocab.kv(pt.key, pt.value or "")
                    eff = int(Effect.parse(pt.effect))
                    for n in np.nonzero(mask)[0]:
                        free = np.nonzero(tk[si, n] == PAD)[0]
                        if free.size == 0:
                            raise ValueError("no spare taint slot; raise spare_taint_slots")
                        tk[si, n, free[0]] = kid
                        tv[si, n, free[0]] = kvid
                        te[si, n, free[0]] = eff
                elif pt.op == "set_label":
                    kid = vocab.key(pt.key)
                    kvid = vocab.kv(pt.key, pt.value or "")
                    try:
                        num = float(pt.value)
                    except (TypeError, ValueError):
                        num = np.nan
                    for n in np.nonzero(mask)[0]:
                        slots = np.nonzero(lk[si, n] == kid)[0]
                        slot = slots[0] if slots.size else np.nonzero(lk[si, n] == PAD)[0][0]
                        lk[si, n, slot] = kid
                        lv[si, n, slot] = kvid
                        ln[si, n, slot] = num
                        ov_sets.setdefault(si, set()).add(int(n))
                    labels_dirty[si] = True
                else:
                    raise ValueError(f"unknown perturbation op {pt.op!r}")

        # Re-derive topology domains where labels changed (domain ids are
        # ranks of kv ids among values present — matches the encoder's
        # sorted-unique ordering because kv ids were interned in vocab order;
        # we rank by label VALUE string to stay consistent).
        nd = np.repeat(ec.node_domain[None], S, axis=0).copy()
        ndom = np.repeat(ec.num_domains[None], S, axis=0).copy()
        dirty = np.nonzero(labels_dirty)[0]
        kv_by_topo: Dict[int, np.ndarray] = {}  # ti → [Sd, N] kv ids
        if dirty.size:
            # Vectorized over nodes (the old per-node Python scan was
            # O(S·T·N·slots) and dominated label-perturbation setup).
            n_kv = len(vocab.kvs)
            lk_d = lk[dirty]  # [Sd, N, L]
            lv_d = lv[dirty]
            for ti, tkey in enumerate(vocab.topo_keys):
                kid = vocab._k.get(tkey)
                if kid is None:
                    continue
                # Global string-order position per kv id of this key: the
                # per-scenario dense rank of present values then matches the
                # encoder's sorted-unique ordering.
                kv_of_key = [
                    i for i in range(n_kv) if vocab.kvs[i][0] == tkey
                ]
                kv_of_key.sort(key=lambda i: vocab.kvs[i][1])
                gpos = np.full(n_kv + 1, -1, np.int64)
                for pos, i in enumerate(kv_of_key):
                    gpos[i] = pos
                is_k = lk_d == kid  # [Sd, N, L]
                has = is_k.any(axis=2)
                slot = is_k.argmax(axis=2)
                vals = np.where(
                    has,
                    np.take_along_axis(lv_d, slot[..., None], 2)[..., 0],
                    -1,
                )  # [Sd, N] kv ids
                g = np.where(vals >= 0, gpos[np.clip(vals, 0, n_kv)], -1)
                kv_by_topo[ti] = vals
                for s_i, si in enumerate(dirty):
                    row = g[s_i]
                    present = row >= 0
                    uniq = np.unique(row[present])
                    out = np.full(ec.num_nodes, PAD, np.int32)
                    out[present] = np.searchsorted(uniq, row[present]).astype(
                        np.int32
                    )
                    nd[si, ti] = out
                    ndom[si, ti] = len(uniq)
        self.max_domains = max(int(ndom.max()) if ndom.size else 1, ec.max_domains, 1)
        self.labels_dirty = bool(labels_dirty.any())
        # v3 with per-scenario DynTables (round 3): keep the base (shared)
        # expansion tables and thread tiny per-scenario corrections through
        # the wave step. Domain ids are APPEND-style — existing label values
        # keep their base ids, new values get ids past the base count.
        # Internal ids are semantics-free (all consumers use per-domain
        # counts / existence / sizes), so this differs from the v2 path's
        # rank-style re-derivation without changing any observable result.
        self.dyn = None
        if self.labels_dirty:
            self.dyn = self._build_dyn(
                ec, S, dirty, ov_sets, kv_by_topo
            )
        # Injected PreferNoSchedule taints re-enable the taint score row
        # (StepSpec.taint_score is derived from the base cluster only).
        self.injected_prefer_taint = any(
            pt.op == "add_taint"
            and int(Effect.parse(pt.effect)) == int(Effect.PREFER_NO_SCHEDULE)
            for sc in scenarios
            for pt in sc.perturbations
        )

        # Host copies for the kube boundary passes (labels are excluded
        # by the engine gate, so only alloc/taints vary per scenario).
        self.host_stacks = (
            {"alloc": alloc, "tk": tk, "tv": tv, "te": te}
            if keep_host_stacks
            else None
        )
        self.dc = self._build_dc(ec, S, alloc, lk, lv, ln, tk, tv, te, nd, ndom)

    def host_clusters(self, ec: EncodedCluster) -> List[EncodedCluster]:
        """Per-scenario EncodedCluster twins (requires keep_host_stacks)
        for the kube boundary passes: the CPU plugin path then sees each
        scenario's perturbed allocatable/taints exactly."""
        from dataclasses import replace as dc_replace

        hs = self.host_stacks
        return [
            dc_replace(
                ec,
                allocatable=hs["alloc"][s],
                taint_key=hs["tk"][s],
                taint_kv=hs["tv"][s],
                taint_effect=hs["te"][s],
            )
            for s in range(self.num_scenarios)
        ]

    def _build_dc(self, ec, S, alloc, lk, lv, ln, tk, tv, te, nd, ndom):
        return T.DevCluster(
            allocatable=jnp.asarray(alloc),
            node_label_key=jnp.asarray(lk),
            node_label_kv=jnp.asarray(lv),
            node_label_num=jnp.asarray(ln),
            taint_key=jnp.asarray(tk),
            taint_kv=jnp.asarray(tv),
            taint_effect=jnp.asarray(te),
            node_domain=jnp.asarray(nd),
            num_domains=jnp.asarray(ndom),
            expr_key=jnp.asarray(np.repeat(ec.expr_key[None], S, 0)),
            expr_op=jnp.asarray(np.repeat(ec.expr_op[None], S, 0)),
            expr_vals=jnp.asarray(np.repeat(ec.expr_vals[None], S, 0)),
            expr_num=jnp.asarray(np.repeat(ec.expr_num[None], S, 0)),
            group_topo=jnp.asarray(np.repeat(ec.group_topo[None], S, 0)),
        )

    def _build_dyn(self, ec, S, dirty, ov_sets, kv_by_topo):
        """Append-style per-scenario domain tables (ScenarioDyn docstring).
        All host-side numpy; every array is tiny ([S, G, K] / [S, G, D])."""
        vocab = ec.vocab
        Tn = ec.node_domain.shape[0]
        K = max((len(v) for v in ov_sets.values()), default=0)
        if K == 0:
            return None
        from ..ops.tpu3 import DMAX_COARSE

        dirty_pos = {int(si): i for i, si in enumerate(dirty)}
        # Base value→domain maps per topology (from the base label arrays;
        # vectorized — a per-node Python loop here would re-dominate
        # labels_dirty setup at Borg scale, the round-2 finding).
        base_kv2dom = []
        for ti, tkey in enumerate(vocab.topo_keys):
            m = {}
            kid = vocab._k.get(tkey)
            if kid is not None:
                is_k = ec.node_label_key == kid  # [N, L]
                has = is_k.any(axis=1)
                slot = is_k.argmax(axis=1)
                kvv = np.where(
                    has,
                    np.take_along_axis(ec.node_label_kv, slot[:, None], 1)[:, 0],
                    -1,
                )
                bm = ec.node_domain[ti]
                sel = has & (bm >= 0)
                kv_u, first = np.unique(kvv[sel], return_index=True)
                dom_u = bm[sel][first]
                m = dict(zip(kv_u.tolist(), dom_u.tolist()))
            base_kv2dom.append(m)
        base_nd = [int(ec.num_domains[t]) for t in range(Tn)]
        coarse_t = [base_nd[t] <= DMAX_COARSE for t in range(Tn)]
        # Appended ids for values absent from the base (sorted by kv id —
        # the choice is semantics-free; only counts/existence/size matter).
        app_ids = {}
        Dext = max([nd for t, nd in enumerate(base_nd) if coarse_t[t]] + [1])
        for si, nodes in ov_sets.items():
            s_i = dirty_pos[si]
            for ti in range(Tn):
                kvv = kv_by_topo.get(ti)
                if kvv is None:
                    continue
                newkvs = {
                    int(kvv[s_i, n])
                    for n in nodes
                    if int(kvv[s_i, n]) >= 0
                    and int(kvv[s_i, n]) not in base_kv2dom[ti]
                }
                ids = {
                    kv: base_nd[ti] + r for r, kv in enumerate(sorted(newkvs))
                }
                app_ids[(si, ti)] = ids
                if coarse_t[ti]:
                    Dext = max(Dext, base_nd[ti] + len(ids))
        # Per-domain node counts → existence. Coarse topologies only:
        # host-scale ones (hostname at Borg scale) would make this an
        # O(S·T·N) allocation, and they never change here (host_changed
        # forces v2 otherwise) — their nd_exist is the base count.
        cnt = np.zeros((S, Tn, Dext), np.int64)
        for t in range(Tn):
            if not coarse_t[t]:
                continue
            bm = ec.node_domain[t]
            labeled = bm[bm >= 0]
            if labeled.size:
                bc = np.bincount(labeled, minlength=Dext)[:Dext]
                cnt[:, t, :] = bc[None, :]
        ov_nodes = np.full((S, K), PAD, np.int32)
        new_tn = np.full((S, Tn, K), float(PAD), np.float32)
        old_tn = np.full((S, Tn, K), float(PAD), np.float32)
        for si, nodes in ov_sets.items():
            s_i = dirty_pos[si]
            nlist = sorted(nodes)
            ov_nodes[si, : len(nlist)] = nlist
            for ti in range(Tn):
                kvv = kv_by_topo.get(ti)
                bm = ec.node_domain[ti]
                for j, n in enumerate(nlist):
                    old = int(bm[n])
                    if kvv is None:
                        newd = old  # topology untouched by any set_label
                    else:
                        kv = int(kvv[s_i, n])
                        if kv < 0:
                            newd = PAD
                        else:
                            newd = base_kv2dom[ti].get(kv)
                            if newd is None:
                                newd = app_ids[(si, ti)][kv]
                    new_tn[si, ti, j] = newd
                    old_tn[si, ti, j] = old
                    if coarse_t[ti] and newd != old:
                        if old >= 0:
                            cnt[si, ti, old] -= 1
                        if newd >= 0:
                            cnt[si, ti, newd] += 1
        ex = cnt > 0
        nd_exist = ex.sum(axis=2)  # [S, Tn]
        for t in range(Tn):
            if not coarse_t[t]:
                nd_exist[:, t] = base_nd[t]  # unchanged (host_changed gate)
        # A perturbation that moves a node's domain under a HOST-scale
        # topology cannot be corrected (host planes are node-space) — the
        # engine must fall back to v2 for the whole batch.
        # PAD-padded slots have new == old == PAD, so the inequality
        # alone suffices.
        host_changed = any(
            not coarse_t[t] and (new_tn[:, t, :] != old_tn[:, t, :]).any()
            for t in range(Tn)
        )
        G = max(ec.num_groups, 1)
        gt = (
            ec.group_topo[:G]
            if ec.group_topo.shape[0] >= G
            else np.full(G, PAD, np.int32)
        )
        ov_gdom = np.full((S, G, K), float(PAD), np.float32)
        ov_old = np.full((S, G, K), float(PAD), np.float32)
        dexist = np.zeros((S, G, Dext), np.float32)  # coarse width only
        sp_w = np.full(
            (S, G), np.float32(np.log(np.float64(2.0))), np.float32
        )  # nd=0 groups: log(0+2), matching _spread_w_table
        for g in range(G):
            t = int(gt[g])
            if t < 0:
                continue
            ov_gdom[:, g, :] = new_tn[:, t, :]
            ov_old[:, g, :] = old_tn[:, t, :]
            if coarse_t[t]:
                dexist[:, g, :] = ex[:, t, :]
            sp_w[:, g] = np.log(
                nd_exist[:, t].astype(np.float64) + 2.0
            ).astype(np.float32)
        dyn = ScenarioDyn(ov_nodes, ov_gdom, ov_old, dexist, sp_w, Dext)
        dyn.host_changed = host_changed
        # Key-presence changes (a node gaining/losing a topology key) are
        # rare; when absent the wave step statically drops the validity-
        # flip half of its correction matmul.
        dyn.has_presence_change = bool(
            ((new_tn >= 0) != (old_tn >= 0)).any()
        )
        return dyn


class ScenarioDyn:
    """Per-scenario domain tables for v3 labels_dirty batches (append-style
    ids; see ScenarioSet). All arrays lead with the scenario axis and are
    tiny relative to the [S, N] planes:

    - ``ov_nodes`` [S, K] i32 — label-perturbed node ids (PAD-padded)
    - ``ov_gdom`` [S, G, K] f32 — the node's NEW domain under each group's
      topology (== base where that topology is unchanged; PAD where the
      group has no topology or the node lacks the key)
    - ``ov_old`` [S, G, K] f32 — the node's BASE domain (PAD likewise)
    - ``dexist`` [S, G, Dcap] f32 — 1.0 where the domain has ≥1 node
    - ``sp_w_g`` [S, G] f32 — upstream log(size+2) with size = number of
      EXISTING domains per scenario (f64 log on host, matching the CPU
      path value-for-value)
    """

    def __init__(self, ov_nodes, ov_gdom, ov_old, dexist, sp_w_g, Dcap):
        self.ov_nodes = ov_nodes
        self.ov_gdom = ov_gdom
        self.ov_old = ov_old
        self.dexist = dexist
        self.sp_w_g = sp_w_g
        self.Dcap = int(Dcap)  # required Dcap (base + appended values)

    @property
    def K(self) -> int:
        return self.ov_nodes.shape[1]


@dataclass
class WhatIfResult:
    placed: np.ndarray  # [S] i32
    unschedulable: np.ndarray  # [S] i32
    total_placed: int
    wall_clock_s: float
    placements_per_sec: float  # aggregate over all scenarios
    assignments: Optional[np.ndarray] = None  # [S, P] when collected
    utilization_cpu: Optional[np.ndarray] = None  # [S]
    # Which semantics this batch actually ran under (round 4: two batches
    # evaluated under different semantics must be programmatically
    # distinguishable — advisor round 3).
    completions_on: bool = False
    engine: str = "v3"
    # Per-scenario eviction counts (kube batches, round 5) and
    # retry-buffer drops — nonzero drops mean placements were lost to
    # buffer CAPACITY, not infeasibility (VERDICT r4 weak #2). Round 6:
    # ``retry_dropped`` is reported by EVERY engine that can drop pods —
    # the kube host mirrors AND the non-kube device retry path (its
    # in-scan FIFO counts overflow exactly like the host analogue).
    preemptions: Optional[np.ndarray] = None  # [S] i32
    retry_dropped: Optional[np.ndarray] = None  # [S] i32
    # Per-scenario chaos disruption (kube batches, round 7): node_down
    # NoExecute evictions through the host mirrors, DISTINCT from
    # scheduler-initiated `preemptions`. `evict_latency_mean` is the mean
    # virtual eviction→re-bind time (boundary-granular).
    evictions: Optional[np.ndarray] = None  # [S] i32
    evict_rescheduled: Optional[np.ndarray] = None  # [S] i32
    evict_stranded: Optional[np.ndarray] = None  # [S] i32
    evict_latency_mean: Optional[np.ndarray] = None  # [S] f64
    # Per-scenario first-bind scheduling-latency quantiles (telemetry
    # layer, kube batches only — the host mirrors are the only per-
    # scenario bind-time carrier; plain/batch paths report None, their
    # placements are all wave placements with latency 0 by construction).
    # NaN where a scenario bound nothing.
    latency_p50: Optional[np.ndarray] = None  # [S] f64
    latency_p90: Optional[np.ndarray] = None  # [S] f64
    latency_p99: Optional[np.ndarray] = None  # [S] f64
    # Per-scenario fragmentation economics (round 13, kube batches only —
    # like the latency quantiles, the host mirrors are the only carrier
    # of per-scenario committed state + pending sets; plain/batch paths
    # report None). Bit-matches the single kube replay's
    # ReplayResult.fragmentation on the same scenario.
    stranded_cpu: Optional[np.ndarray] = None  # [S] f64
    frag_index_cpu: Optional[np.ndarray] = None  # [S] f64
    packing_efficiency: Optional[np.ndarray] = None  # [S] f64
    # Per-scenario ReplayTelemetry (kube batches at series+; else None).
    scenario_telemetry: Optional[list] = None
    # Fleet-merged ReplayTelemetry (round 12): every process's partial
    # telemetry merged via ReplayTelemetry.merge — it rides the ONE
    # end-of-replay gather, never adds a collective. Phase timers are
    # kept distinct per process ("p<pid>/<phase>"); latency/rejection
    # aggregates are exact merges, so the 2-process fleet view bit-matches
    # the single-process oracle (tests/test_dcn.py). None at telemetry
    # granularity "off".
    fleet_telemetry: Optional["ReplayTelemetry"] = None
    # Mesh provenance (round 10): which parallel configuration produced
    # the numbers — bench rounds and tuner runs stamp these so results
    # from different device counts are never silently compared.
    n_devices: int = 1
    mesh_shape: Optional[dict] = None  # {axis_name: size} or None
    # DCN provenance (round 11): how many processes contributed scenario
    # blocks. >1 means run() gathered per-process results exactly once at
    # assembly; n_devices/mesh_shape then describe the GLOBAL device
    # footprint (process_count × local devices).
    process_count: int = 1


class WhatIfEngine:
    """Batched scenario evaluation: ``vmap`` over local scenarios, optional
    mesh sharding over devices (config #3 / #5 shapes)."""

    def __init__(
        self,
        ec: EncodedCluster,
        pods: EncodedPods,
        scenarios: Sequence[Scenario],
        config: Optional[FrameworkConfig] = None,
        wave_width: int = 8,
        chunk_waves: int = 1024,
        mesh=None,
        collect_assignments: bool = False,
        fork_checkpoint: Optional[str] = None,
        preemption: bool = False,
        completions: Optional[bool] = None,
        retry_buffer: int = 0,
        granularity_guard: bool = True,
        telemetry=None,
        policies=None,
        node_shards: int = 0,
        _dcn_recovery: Optional[dict] = None,
    ):
        """``fork_checkpoint``: path to a JaxReplayEngine checkpoint — the
        what-if FORK POINT (SURVEY.md §5 checkpoint/resume): every scenario
        starts from that replay's mid-trace state and continues with its own
        perturbed cluster over the remaining waves.

        ``completions``: chunk-granular pod completions per scenario (the
        JaxReplayEngine mechanism, applied to each scenario's own
        placements). Default ON since round 3 (``None`` = on): release
        folding runs one chunk behind the device pipeline (boundary b
        sees chunks ≤ b−2 — the one-chunk slack, shared with the greedy
        anchor), so the host-side deltas overlap the in-flight chunk
        instead of stalling it. Requires the v3 engine;
        when a batch with finite durations cannot honor them the engine
        WARNS and reverts to arrivals-only semantics — pass an explicit
        ``completions=True`` to get a ``ValueError`` instead, or read
        ``WhatIfResult.completions_on``. A trace with no finite durations
        runs arrivals-only silently (the semantics are identical).
        Round 5 (VERDICT r4 #4): tier preemption × completions is a
        SUPPORTED batch configuration on the no-mesh path — folds run
        EAGERLY per chunk (evictions must precede the next boundary's
        release decisions; the slack becomes an explicit bind-chunk
        gate), released non-gang pods also drop the per-scenario tier
        planes via compact device-side scatters, and evicted pods never
        release. Under a mesh the batch stays arrivals-only (loudly):
        the eager per-chunk fetch would serialize the scenario axis.
        Anchored by ``greedy_replay(preemption='tier',
        completions_chunk_waves=...)`` per scenario.

        ``retry_buffer`` (round 4): device-path unschedulable RETRY — the
        [K8S] activeQ flush-on-event analogue. Non-gang pods that miss
        placement enter a per-scenario FIFO buffer (capacity rounded up
        to a wave multiple; overflow drops the newest); at every chunk
        boundary, after releases apply, one bounded retry pass re-runs
        the normal wave step over the buffer. Pods placed on retry start
        AT THE BOUNDARY: they release at the first boundary whose start
        time reaches ``t_b + duration`` (f32), at least ``b+1``, via a
        pending list capped at the same size (its releases ride the same
        commit-block core as the static lists, so the full default
        plugin set is covered). Semantics anchored by
        ``greedy_replay(retry_buffer=...)``. Requires the device-release
        completions path without DynTables; 0 = off (the r01–r03
        semantics).

        ``policies`` (round 9, sim.tuner): a [S, len(ops.tpu.POLICY_COLS)]
        f32 array of PER-SCENARIO policy vectors — score-plugin weights
        plus the NodeResourcesFit strategy selector — threaded into the
        score fold as a traced input on the scenario axis. The whole
        population compiles ONCE (only vector VALUES differ per
        scenario); swap values between runs with :meth:`set_policies`.
        Supported on the plain, device-release and host pending-fold
        paths (vmap and mesh); kube/tier preemption, retry_buffer and
        fork checkpoints keep static weights."""
        from .greedy import normalize_preemption
        from .telemetry import TelemetryConfig

        self.telemetry_cfg = TelemetryConfig.resolve(telemetry)
        if node_shards and int(node_shards) > 1:
            raise NotImplementedError(
                "node_shards (intra-scenario node-plane sharding, round 14) "
                "is a single-replay feature: the what-if batch already "
                "spends the mesh on the scenario axis. Run the big scenario "
                "through the 'jax' strategy / JaxReplayEngine(node_shards=...)"
            )
        pmode = normalize_preemption(preemption)
        # "kube" (round 5): the EXACT minimal-victims PostFilter runs in
        # per-scenario HOST boundary passes (sim.boundary) against the
        # plain batched chunk program — each scenario carries its own
        # host mirror of the perturbed cluster, so the decision
        # arithmetic is the CPU engine's verbatim. Sized for small/
        # moderate S (the passes are S× host work per boundary).
        self.kube = pmode == "kube"
        if self.kube:
            if mesh is not None:
                raise ValueError(
                    "kube preemption requires a no-mesh batch (the eager "
                    "per-chunk folds would serialize the scenario axis)"
                )
            if fork_checkpoint is not None:
                raise ValueError(
                    "kube preemption does not support fork checkpoints"
                )
            if not retry_buffer:
                raise ValueError(
                    "preemption='kube' requires retry_buffer > 0 (failed "
                    "pods reach the PostFilter through the boundary retry "
                    "pass)"
                )
            if completions is False:
                raise ValueError(
                    "completions=False is not supported with kube "
                    "preemption (the boundary pass owns releases) — "
                    "same rule as the single-replay engine"
                )
        preemption = pmode == "tier"
        # ---- Multi-host DCN replay (round 11, parallel.dcn) ----
        # Each process takes the contiguous ``jax.process_index()`` block
        # of the scenario axis and runs the ENTIRE chunk loop on it
        # process-locally (the mesh is localized below, the boundary host
        # mirrors exist only for local scenarios, _fetch/_fold touch only
        # addressable shards); the processes meet exactly once per replay,
        # in run()'s end-of-replay gather. Engine-level gates the
        # single-process oracle derives from the FULL scenario list
        # (taint-score enable, bf16 host-plane exactness) are computed
        # here from the full list BEFORE slicing, so the compiled chunk
        # programs — and therefore the results — stay bit-identical
        # across process counts.
        scenarios = list(scenarios)
        self.S_global = len(scenarios)
        self._dcn_sliced = False
        self._dcn_spare = False
        # Round 18 work-stealing queue: run() routes through _run_workqueue
        # instead of the static chunk loop; _dcn_wq_info marks a BLOCK
        # engine built by _wq_exec_block (rides in via _dcn_recovery).
        self._dcn_wq = False
        self._wq_exec_chunks = 0
        self._dcn_recovery = dict(_dcn_recovery) if _dcn_recovery else None
        self._dcn_wq_info = (
            dict(self._dcn_recovery.get("wq") or {})
            if self._dcn_recovery is not None and self._dcn_recovery.get("wq")
            else None
        )
        # Everything a survivor needs to rebuild a DEAD sibling's engine
        # bit-identically (round 15): the FULL scenario list plus the raw
        # ctor knobs. Captured only on the sliced path — recovery re-runs
        # through a second WhatIfEngine with ``_dcn_recovery`` set.
        self._dcn_rebuild: Optional[dict] = None
        self._proc_lo = 0
        self._dcn_prefer_taint = False
        self._dcn_scales_pods = False
        # Full-tensor replications performed by _fetch this run — the
        # round-11 contract pins this at ZERO inside the chunk loop
        # (tests/test_dcn.py): replication may happen at most once per
        # replay, at result assembly, never per chunk.
        self._replicate_count = 0
        nproc = jax.process_count()
        if self._dcn_recovery is not None:
            # Round 15 survivor rebalance: this engine re-executes a dead
            # sibling's contiguous block. Slicing and the engine gates are
            # dictated by the claimant (they were derived from the full
            # list once, in the original ctor) — never re-derived, so the
            # compiled chunk programs match the dead process's exactly.
            lo, hi = (int(x) for x in self._dcn_recovery["block"])
            self._dcn_prefer_taint = bool(
                self._dcn_recovery.get("prefer_taint", False)
            )
            self._dcn_scales_pods = bool(
                self._dcn_recovery.get("scales_pods", False)
            )
            scenarios = scenarios[lo:hi]
            self._proc_lo = lo
            if policies is not None:
                pol_g = np.asarray(policies)
                if pol_g.ndim == 2 and pol_g.shape[0] == self.S_global:
                    policies = pol_g[lo:hi]
        elif nproc > 1 and self.S_global:
            if any(
                pt.op == "set_label"
                for sc in scenarios
                for pt in sc.perturbations
            ):
                raise ValueError(
                    "set_label perturbations are not supported in "
                    "multi-process (DCN) runs: labels_dirty batches "
                    "derive per-scenario domain tables and the engine "
                    "choice from the WHOLE batch, which would diverge "
                    "across process-local slices. Run label sweeps "
                    "single-process, or split them into their own batch."
                )
            workers = dcn.worker_count()
            if self.S_global % workers == 0:
                self._dcn_prefer_taint = any(
                    pt.op == "add_taint"
                    and int(Effect.parse(pt.effect))
                    == int(Effect.PREFER_NO_SCHEDULE)
                    for sc in scenarios
                    for pt in sc.perturbations
                )
                self._dcn_scales_pods = any(
                    pt.op == "scale_capacity"
                    and pt.resource == "pods"
                    and pt.factor > 1
                    for sc in scenarios
                    for pt in sc.perturbations
                )
                sl = dcn.local_slice(self.S_global)
                self._dcn_rebuild = dict(
                    scenarios=list(scenarios),
                    config=config,
                    wave_width=wave_width,
                    chunk_waves=chunk_waves,
                    collect_assignments=collect_assignments,
                    fork_checkpoint=fork_checkpoint,
                    preemption=pmode,
                    completions=completions,
                    retry_buffer=retry_buffer,
                    granularity_guard=granularity_guard,
                    telemetry=telemetry,
                    policies=(
                        None if policies is None else np.asarray(policies)
                    ),
                )
                scenarios = scenarios[sl]
                self._proc_lo = sl.start
                self._dcn_sliced = True
                # Spare processes (KSIM_DCN_SPARES tail pids, round 15)
                # own no block: construction proceeds on the mirrored
                # slice for shapes only; run() skips the chunk loop and
                # sits in the gather as claim-eligible elastic capacity.
                self._dcn_spare = dcn.is_spare()
                # Work-stealing queue (round 18): the slice above is kept
                # only for shapes/compile warm-up parity — run() leases
                # scenario BLOCKS from the KV queue instead of executing
                # the static slice, and every process (workers, spares,
                # joiners) drains the same queue.
                self._dcn_wq = dcn.wq_enabled()
                if policies is not None:
                    pol_g = np.asarray(policies)
                    if pol_g.ndim == 2 and pol_g.shape[0] == self.S_global:
                        policies = pol_g[sl]
            else:
                from ..utils.metrics import log

                log.warning(
                    "DCN: %d scenarios do not divide over %d worker "
                    "processes — running fully replicated (every process "
                    "computes all scenarios; no gather). Pad the batch to "
                    "a multiple of the worker count to scale.",
                    self.S_global, workers,
                )
        mesh = dcn.localize_mesh(mesh)
        # Per-scenario timed failure/recovery timelines (chaos campaigns,
        # round 7): applied through the per-scenario host mirrors at
        # chunk boundaries — which only exist in kube mode.
        # Validation enforces time-sortedness, so the lists are kept as
        # given (an unsorted timeline must ERROR, not be silently fixed).
        self._timelines = [
            list(getattr(sc, "events", None) or []) for sc in scenarios
        ]
        if any(self._timelines):
            if not self.kube:
                raise ValueError(
                    "per-scenario timed event timelines (Scenario.events) "
                    "require preemption='kube' with retry_buffer > 0: "
                    "events apply through the per-scenario host mirrors "
                    "at chunk boundaries, and node_down evictions requeue "
                    "victims through the boundary retry pass. Use static "
                    "t=0 Perturbations for mirror-free batches."
                )
            from .runtime import validate_node_events

            for si, tl in enumerate(self._timelines):
                try:
                    validate_node_events(tl, ec.num_nodes)
                except ValueError as e:
                    raise ValueError(f"scenario {si}: {e}") from None
        self.ec = ec
        self.pods = pods
        self._config = config
        self.spec = StepSpec.from_config(ec, config, pods)
        # "auto": measured optimum is W=8 across shapes (see JaxReplayEngine).
        self.wave_width = wave_width = 8 if wave_width == "auto" else wave_width
        self.chunk_waves = chunk_waves
        self.mesh = mesh
        # Always False after localize_mesh above; result paths branch on
        # this instead of process_count (a local mesh in a DCN run needs
        # no global-array plumbing).
        self._mesh_spans_procs = spans_processes(mesh)
        self.collect_assignments = collect_assignments
        self.fork_checkpoint = fork_checkpoint
        self.sset = ScenarioSet(ec, scenarios, keep_host_stacks=self.kube)
        self.S = self.sset.num_scenarios
        if (
            self.sset.injected_prefer_taint or self._dcn_prefer_taint
        ) and not self.spec.taint_score:
            self.spec = dc_replace(self.spec, taint_score=True)
        if mesh is not None:
            ndev = mesh.devices.size
            if self.S % ndev != 0:
                raise ValueError(f"num scenarios {self.S} must divide over {ndev} devices")
        self.D = max(self.sset.max_domains, 1)
        # v3 unless the labels_dirty batch falls outside the DynTables
        # envelope (per-scenario domain tables; round 3): host-scale
        # topologies, pre-bound pods, preemption, forks and >32 perturbed
        # nodes per scenario stay on the v2 parity engine.
        self.engine = "v3"
        self._dyn = None
        if self.sset.labels_dirty:
            # DynTables batches honor completions on the DEVICE-release
            # path since round 4 (per-scenario release domain
            # corrections); off that path the gate below WARNS/raises.
            # Either way prefer the ~4× faster DynTables v3 over v2.
            dyn = self.sset.dyn
            if (
                dyn is not None
                and dyn.K <= 32
                and not dyn.host_changed
                and not preemption
                and fork_checkpoint is None
                and not bool((pods.bound_node >= 0).any())
            ):
                self._dyn = dyn
            else:
                self.engine = "v2"
                # The fallback costs ~4× — say so (VERDICT r3 weak #3:
                # an adversarial 33-node relabel silently cost it). The
                # reasons mirror the gate's predicates one-for-one; a
                # future gate condition the list doesn't cover reports
                # "unhandled gate condition" rather than mislabeling.
                reasons = []
                if dyn is None:
                    reasons.append("no DynTables")
                else:
                    if dyn.host_changed:
                        reasons.append("host-scale topology change")
                    if dyn.K > 32:
                        reasons.append(
                            f">{32} perturbed nodes/scenario (K={dyn.K})"
                        )
                if preemption:
                    reasons.append("preemption")
                if fork_checkpoint is not None:
                    reasons.append("fork checkpoint")
                if bool((pods.bound_node >= 0).any()):
                    reasons.append("pre-bound pods")
                reason = (
                    ", ".join(reasons) if reasons
                    else "unhandled gate condition"
                )
                from ..utils.metrics import log

                log.info(
                    "what-if: labels_dirty batch outside the DynTables "
                    "envelope (%s) — v2 fallback engine (~4x slower); "
                    "WhatIfResult.engine reports it",
                    reason,
                )
        self.preemption = preemption
        if self.kube and (self.engine != "v3" or self.sset.labels_dirty):
            raise ValueError(
                "kube preemption requires the v3 engine with no label "
                "perturbations (the per-scenario host mirrors share the "
                "base topology-domain tables)"
            )
        if preemption and (self.engine != "v3" or fork_checkpoint):
            raise ValueError(
                "what-if preemption requires the v3 engine (no label "
                "perturbations) and no fork checkpoint"
            )
        if preemption and bool((pods.bound_node >= 0).any()):
            # The aggregate tally cannot distinguish pre-bound victims from
            # replay placements; use JaxReplayEngine for that combination.
            raise ValueError(
                "what-if preemption does not support pre-bound pods"
            )
        self._scales_pods = False
        if self.engine == "v3":
            from ..ops import tpu3 as V3
            from .jax_runtime import rep_slots_for

            # Perturbations that scale the "pods" capacity can exceed the
            # bf16 host-plane exactness bound.
            scales_pods = self._dcn_scales_pods or any(
                pt.op == "scale_capacity" and pt.resource == "pods" and pt.factor > 1
                for sc in scenarios
                for pt in sc.perturbations
            )
            # Remembered so set_scenarios can refuse a swapped-in batch
            # that needs the f32 host plane this engine was built without.
            self._scales_pods = scales_pods
            self.static3 = V3.V3Static.build(
                ec, pods, self.spec, preemption=preemption,
                allow_bf16_host=not scales_pods,
                dcap_min=(self._dyn.Dcap if self._dyn is not None else 0),
            )
            self.shared3 = V3.Shared3.build(ec, self.static3)
            self.rep_slots = rep_slots_for(self.static3, pods)
        if self.engine == "v3" and self._dyn is not None:
            from ..ops import tpu3 as V3

            d = self._dyn
            self._dyn_dev = V3.DynTables(
                ov_nodes=jnp.asarray(d.ov_nodes),
                ov_gdom=jnp.asarray(d.ov_gdom),
                ov_old=jnp.asarray(d.ov_old),
                dexist=jnp.asarray(d.dexist),
                sp_w_g=jnp.asarray(d.sp_w_g),
            )
        else:
            self._dyn_dev = None
        self._replicate_fn = None
        self._sub_jit = None
        if self._dyn is not None and self.spec.sp_norm_f32:
            # Per-scenario spread weights (appended domains) can exceed the
            # bound under which the f32 normalize division is exactly the
            # integer division — re-validate with the per-scenario maxima
            # and drop the fast form if they might.
            from .jax_runtime import _spread_norm_f32_ok

            sp_w_max = tuple(
                float(x) for x in self._dyn.sp_w_g.max(axis=0)
            )
            if not _spread_norm_f32_ok(sp_w_max, pods):
                self.spec = dc_replace(self.spec, sp_norm_f32=False)
        self.waves = pack_waves(pods, self.wave_width)
        rel = pods.arrival + np.where(
            np.isfinite(pods.duration), pods.duration, np.inf
        )
        self._rel_time = rel
        # Loud, not silent (round 4): a batch that cannot honor the
        # default-on completions WARNS (or raises, when the caller passed
        # an explicit True); the outcome is exposed on the result. A trace
        # with no finite durations is exempt — arrivals-only and
        # completions-on semantics coincide there.
        want = completions is not False  # None (the default) = on
        have_durations = bool(np.isfinite(rel).any())
        # Structural eligibility of the DEVICE-release path (used both
        # for the gate below and to decide whether a DynTables batch can
        # honor completions at all — the host fold path cannot apply
        # per-scenario domain corrections, the device commit blocks can).
        dev_ok = False
        if self.engine == "v3":
            s3 = self.static3
            # Round 10: the device-release path runs UNDER A MESH too —
            # the bucketed release fns and the vassign fold are
            # per-scenario programs, so shard_map wraps them like the
            # chunk program (replicated release tables, sharded
            # state/vassign). Only label-perturbation DynTables batches
            # stay off it there: their per-scenario domain-override
            # corrections would need the override tables threaded through
            # every bucketed release call's shard specs.
            dev_ok = bool(
                not collect_assignments
                and not preemption
                and not self.kube  # BoundaryOps owns releases in kube mode
                and fork_checkpoint is None
                and (self.mesh is None or self._dyn is None)
                and s3.single_g[s3.mc_h_ids].all()
                and s3.single_g[s3.anti_h_ids].all()
                and s3.single_g[s3.pref_h_ids].all()
            )
        blockers = []
        if self.engine != "v3":
            blockers.append(
                "the v2 fallback engine (label perturbations outside the "
                "DynTables envelope)"
            )
        # Tier preemption × completions is SUPPORTED since round 5 on the
        # no-mesh batch path (eager eviction-aware host folds, the
        # single-replay round-4 mechanism S-stacked; VERDICT r4 next #4).
        # Under a mesh the eager per-chunk fetch + scatter-applied tier
        # releases would serialize the scenario axis — still arrivals-only
        # there, loudly.
        if preemption and mesh is not None:
            blockers.append("device tier preemption under a mesh")
        if self._dyn is not None and not dev_ok:
            # _dyn is only set with fork_checkpoint None and engine v3,
            # so the failing dev_ok condition is one of these three.
            why = []
            if self.mesh is not None:
                why.append("mesh")
            if collect_assignments:
                why.append("collect_assignments")
            if not why:
                why.append("non-singleton host-scale count planes")
            blockers.append(
                "labels_dirty DynTables batches off the device-release "
                f"path ({'/'.join(why)} — per-scenario release domain "
                "corrections need the device path)"
            )
        self.completions_on = bool(want and have_durations and not blockers)
        if want and have_durations and blockers:
            msg = (
                "what-if completions cannot be honored with "
                + "; ".join(blockers)
                + " — this batch runs ARRIVALS-ONLY (placed pods never "
                "release resources)"
            )
            if completions is True:
                raise ValueError(msg)
            import warnings

            warnings.warn(msg, stacklevel=2)
        # DEVICE-side releases (round 3, generalized round 4): on the
        # perf path the release bookkeeping lives on device — static
        # per-boundary release lists applied as one-hot commit blocks,
        # placements folded into a wave-order vassign buffer — because
        # ANY per-chunk choice fetch stalls the pipeline (and through a
        # tunneled device, dominates it). Round 4 widened the envelope
        # to anti/pref planes, multi-topology traces and host-scale
        # rows; the one remaining structural gate is NON-SINGLETON
        # host-scale topologies (their [H, N] planes broadcast a domain
        # aggregate across member nodes — the release delta would need
        # an [N, N]-class regroup; hostname, the host-scale case that
        # exists in practice, is singleton). Everything else keeps the
        # host pending-fold path.
        self._completions_dev = bool(self.completions_on and dev_ok)
        if (
            self.completions_on
            and not self._completions_dev
            and self.engine == "v3"
        ):
            # The device-release fast path is gated — say WHY (VERDICT r4
            # missing #6: the non-singleton host-scale regroup gate was
            # silent; the host pending-fold path honors the same
            # semantics at a measured cost — see COVERAGE.md).
            s3 = self.static3
            why = []
            if self.mesh is not None and self._dyn is not None:
                why.append("mesh with label-perturbation DynTables")
            if collect_assignments:
                why.append("collect_assignments")
            if preemption:
                why.append("preemption (eager eviction-aware folds)")
            if self.kube:
                why.append(
                    "kube preemption (per-scenario boundary passes own "
                    "the releases)"
                )
            if fork_checkpoint is not None:
                why.append("fork checkpoint")
            if not (
                s3.single_g[s3.mc_h_ids].all()
                and s3.single_g[s3.anti_h_ids].all()
                and s3.single_g[s3.pref_h_ids].all()
            ):
                why.append(
                    "non-singleton host-scale count planes (the release "
                    "delta would need an [N, N]-class regroup)"
                )
            from ..utils.metrics import log

            log.info(
                "what-if completions run on the HOST pending-fold path "
                "(%s) — semantics identical, per-chunk choice fetches "
                "instead of device-side releases",
                "; ".join(why) or "unhandled gate condition",
            )

        if self.completions_on:
            # Granularity-envelope guard (round 5, VERDICT r4 #2): a trace
            # whose durations are ≪ the chunk arrival span silently loses
            # most placements under chunk-granular releases — warn and
            # shrink the chunks toward the duration scale (see
            # sim.granularity). Opt out with granularity_guard=False.
            from .granularity import guard as _gran_guard

            self.chunk_waves, retry_buffer = _gran_guard(
                pods, self.waves.idx, self.chunk_waves, retry_buffer,
                enabled=granularity_guard, engine_name="what-if engine",
            )

        self.retry_buffer = int(retry_buffer)
        if self.retry_buffer:
            # Round up to a wave multiple (the retry pass reuses the
            # normal W-wide wave step).
            self.retry_buffer = (
                -(-self.retry_buffer // wave_width) * wave_width
            )
            if not self.kube and not (self._completions_dev and self._dyn is None):
                # kube mode: the buffer lives in the host BoundaryOps,
                # not the device retry pass — no device-release gate.
                raise ValueError(
                    "retry_buffer requires the device-release completions "
                    "path (v3 engine, finite durations, no "
                    "collect_assignments/preemption/fork, singleton "
                    "host-scale topologies) without label-perturbation "
                    "DynTables (meshes are supported since round 10)"
                )
        # Host-side completions need per-scenario choices even when the
        # caller only wants counts; the device path never fetches them.
        # kube mode folds every chunk into the host mirrors.
        self._need_choices = collect_assignments or self.kube or (
            self.completions_on and not self._completions_dev
        )
        # Per-scenario policy vectors (round 9 tuner). Validated AFTER the
        # retry/granularity resolution above: the gates below read the
        # final self.retry_buffer, not the requested one.
        self._policies = None
        if policies is not None:
            pol = np.asarray(policies, dtype=np.float32)
            K = len(T.POLICY_COLS)
            if pol.ndim != 2 or pol.shape[1] != K:
                raise ValueError(
                    f"policies must be [num_scenarios, {K}] (columns "
                    f"{T.POLICY_COLS}), got shape {pol.shape}"
                )
            if pol.shape[0] != self.S:
                raise ValueError(
                    f"policies rows ({pol.shape[0]}) must match "
                    f"num_scenarios ({self.S})"
                )
            blockers_p = []
            if self.kube:
                blockers_p.append("kube preemption")
            if self.preemption:
                blockers_p.append("tier preemption")
            if self.retry_buffer:
                blockers_p.append("retry_buffer")
            if fork_checkpoint is not None:
                blockers_p.append("fork checkpoints")
            if blockers_p:
                raise ValueError(
                    "per-scenario policies run on the plain/completions "
                    "what-if paths — not supported with "
                    + ", ".join(blockers_p)
                )
            self._policies = pol
        self._rel_fn_cache: Dict[tuple, Callable] = {}
        self._rel_core: Optional[Callable] = None
        self._dev_rel_stage: Optional[dict] = None
        self._chunk_fn = self._build_chunk_fn()
        # Device-resident slot sources (one upload per engine): the chunk
        # loop then gathers rows on device — see ops.tpu.SlotSource.
        # Scenario-shared, so under a mesh they replicate ONCE and every
        # device gathers its chunk rows locally (round 10: the mesh path
        # stopped host-gathering slots per chunk).
        self._slot_srcs = None
        if self.engine == "v3":
            from ..ops import tpu3 as V3

            srcs = (
                T.SlotSource.build(pods),
                V3.ExtraSource.build(self.static3, pods.num_pods),
            )
            if self.mesh is not None:
                srcs = replicate_tree(self.mesh, srcs)
            self._slot_srcs = srcs

    def set_policies(self, policies) -> None:
        """Swap the per-scenario policy VECTORS without rebuilding the
        engine: the compiled chunk program takes the vectors as a traced
        [S, K] input, so same-shape updates reuse the executable — the
        round 9 tuner runs its whole search against one compile (pinned
        by tests/test_tuner.py via ``_chunk_fn._cache_size()``)."""
        if self._policies is None:
            raise ValueError(
                "engine was built without policies — pass policies=[S, K] "
                "at construction to enable the policy axis"
            )
        pol = np.asarray(policies, dtype=np.float32)
        # DCN: callers hand the GLOBAL [S_global, K] population; every
        # process slices its own contiguous block (same rows the engine
        # took at construction).
        if (
            self._dcn_sliced
            and pol.ndim == 2
            and pol.shape[0] == self.S_global
            and self.S_global != self.S
        ):
            pol = pol[self._proc_lo : self._proc_lo + self.S]
        if pol.shape != self._policies.shape:
            raise ValueError(
                f"policies shape {pol.shape} must match the engine's "
                f"{self._policies.shape} (the compiled program is "
                "shape-specialized)"
            )
        self._policies = pol

    def set_scenarios(self, scenarios) -> None:
        """Swap the scenario BATCH without rebuilding the engine.

        The compiled chunk program takes the scenario cluster stacks as
        traced ``[S, ...]`` inputs, so a same-shape batch reuses the
        executable exactly like ``set_policies`` reuses it for policy
        vectors — this is what lets a resident ``SimulatorService``
        answer warm queries with zero recompilation. Everything
        per-batch that ``run()`` reads is rebuilt here (``ScenarioSet``
        stacks + chaos timelines); everything baked into the compile
        (shapes, dtypes, engine mode, domain capacity) is checked and
        REFUSED on mismatch rather than silently recompiled.
        """
        if self._dcn_sliced or self._dcn_recovery is not None:
            raise ValueError(
                "set_scenarios is single-process only: a DCN-sliced "
                "engine owns a contiguous block of a global batch and "
                "cannot swap scenarios underneath the slice bookkeeping"
            )
        if self.engine != "v3":
            raise ValueError(
                "set_scenarios requires the v3 engine (the v2 parity "
                "fallback rebuilds per-batch state at trace time)"
            )
        if self.sset.labels_dirty:
            raise ValueError(
                "set_scenarios does not support engines built with "
                "label perturbations (DynTables are baked per batch) — "
                "rebuild the engine instead"
            )
        scenarios = list(scenarios)
        if len(scenarios) != self.S:
            raise ValueError(
                f"scenario count ({len(scenarios)}) must match the "
                f"engine's ({self.S}) — the compiled program is "
                "shape-specialized"
            )
        timelines = [
            list(getattr(sc, "events", None) or []) for sc in scenarios
        ]
        if any(timelines):
            if not self.kube:
                raise ValueError(
                    "per-scenario timed event timelines (Scenario."
                    "events) require preemption='kube' with "
                    "retry_buffer > 0"
                )
            from .runtime import validate_node_events

            for si, tl in enumerate(timelines):
                try:
                    validate_node_events(tl, self.ec.num_nodes)
                except ValueError as e:
                    raise ValueError(f"scenario {si}: {e}") from None
        sset = ScenarioSet(self.ec, scenarios, keep_host_stacks=self.kube)
        if sset.labels_dirty:
            raise ValueError(
                "set_scenarios does not support label perturbations "
                "(the swapped batch would need fresh DynTables) — "
                "rebuild the engine instead"
            )
        if max(sset.max_domains, 1) != self.D:
            raise ValueError(
                f"scenario batch needs domain capacity "
                f"{max(sset.max_domains, 1)} but the engine compiled "
                f"with {self.D}"
            )
        if sset.injected_prefer_taint and not self.spec.taint_score:
            raise ValueError(
                "scenario batch injects prefer-taints but the engine "
                "compiled without taint scoring — rebuild the engine"
            )
        if not self._scales_pods and any(
            pt.op == "scale_capacity"
            and pt.resource == "pods"
            and pt.factor > 1
            for sc in scenarios
            for pt in sc.perturbations
        ):
            raise ValueError(
                "scenario batch scales the 'pods' capacity up but the "
                "engine compiled on the bf16 host plane — rebuild the "
                "engine with such a scenario present"
            )

        def _sig(dc):
            return [
                (tuple(x.shape), str(x.dtype))
                for x in jax.tree_util.tree_leaves(dc)
            ]

        if _sig(sset.dc) != _sig(self.sset.dc):
            raise ValueError(
                "scenario batch changes the compiled array shapes/"
                "dtypes — the executable is shape-specialized; rebuild "
                "the engine for this batch"
            )
        self.sset = sset
        self._timelines = timelines

    def _build_chunk_fn(self):
        collect = self._need_choices
        spec, wave_width = self.spec, self.wave_width
        pol_on = self._policies is not None

        def finalize(fn, axes, donate):
            """jit the vmapped per-scenario program; under a mesh, wrap
            it in shard_map first. shard_map, NOT jit-with-shardings: the
            scenario axis is embarrassingly parallel, and shard_map makes
            that a compile-time guarantee — each device runs the
            per-scenario program on its local slice and the partitioner
            never sees the whole computation. Under GSPMD (jit +
            in_shardings) sharding propagation is free to "help" by
            splitting REPLICATED slot-derived intermediates across
            devices (wave-width-8 axes match the 8-device mesh) and
            gathering them back — real all-gathers inside the chunk scan,
            pinned absent by tests/test_mesh_hlo.py. The shard specs
            derive from the vmap axes one-for-one: mapped (0) arguments
            shard over the scenario axis, broadcast (None) arguments
            replicate."""
            if self.mesh is None:
                return jax.jit(fn, donate_argnums=donate)
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            sh, rp = P(SCENARIO_AXIS), P()
            return jax.jit(
                shard_map(
                    fn,
                    mesh=self.mesh,
                    in_specs=tuple(sh if a == 0 else rp for a in axes),
                    out_specs=sh,
                    check_rep=False,
                ),
                donate_argnums=donate,
            )

        if self.engine == "v3":
            from ..ops import tpu3 as V3

            st3, sh3, reps = self.static3, self.shared3, self.rep_slots

            pre_on = self.preemption
            dyn_on = self._dyn_dev is not None
            narrow = self.ec.num_nodes < 2**15 - 1
            dev_rel = self._completions_dev
            dyn_flip = bool(
                self._dyn is not None
                and getattr(self._dyn, "has_presence_change", True)
            )

            def per_scenario(dc, state, slots, extra, dyn=None, wvec=None):
                d = T.Derived.build(dc)
                cmasks = V3.class_masks(dc, d, st3, spec, reps)
                wave_step = V3.make_wave_step3(
                    dc, d, sh3, st3, wave_width, spec, cmasks, dyn=dyn,
                    dyn_flip=dyn_flip, wvec=wvec,
                )

                def step(st, batch):
                    st, out = wave_step(st, batch)
                    if pre_on:
                        choices, ev_node, ev_tier, ev_prior, ev_total = out
                        placed_w = (
                            jnp.sum((choices >= 0) & batch[0].valid) - ev_prior
                        ).astype(jnp.int32)
                        out = (
                            (choices, ev_node, ev_tier)
                            if collect
                            else placed_w
                        )
                        return st, out
                    choices = out
                    placed_w = jnp.sum((choices >= 0) & batch[0].valid).astype(jnp.int32)
                    if dev_rel:
                        # Device-release path: choices stay ON DEVICE for
                        # the assignment fold; counts ride along.
                        return st, (choices, placed_w)
                    if collect and narrow:
                        # Completions fetch choices back every chunk; with
                        # N < 2^15 an int16 stream halves the D2H volume.
                        choices = choices.astype(jnp.int16)
                    return st, (choices if collect else placed_w)

                state, outs = jax.lax.scan(step, state, (slots, extra))
                return state, outs

            # Device-side slot gathers INSIDE the jitted program: one
            # dispatch per chunk, only indices as per-chunk input
            # (scenario-shared → gathered once, not per scenario).
            def per_scenario_src(dc, state, src, xsrc, idx, dyn=None, wvec=None):
                slots = T.gather_slots_device(src, idx)
                from ..ops import tpu3 as V3m

                extra = V3m.gather_extra_device(xsrc, idx)
                return per_scenario(dc, state, slots, extra, dyn, wvec)

            if self._completions_dev:
                def per_scenario_rel(
                    dc, state, src, xsrc, idx, b, vassign, dyn=None,
                    wvec=None,
                ):
                    # Static releases run in the separate bucketed
                    # _release_fn BEFORE this call (ordering by data
                    # dependency on state/vassign). Here: the normal
                    # chunk scan + the WAVE-ORDER assignment fold —
                    # a dynamic_update_slice (pure DMA), not a
                    # [C·W]-index scatter: choices land at their flat
                    # wave positions, which is exactly how the static
                    # release lists address them (rel_pos).
                    state, out = per_scenario_src(
                        dc, state, src, xsrc, idx, dyn, wvec
                    )
                    choices, counts = out
                    vassign = jax.lax.dynamic_update_slice(
                        vassign,
                        choices.reshape(-1),
                        (b * idx.size,),
                    )
                    return state, vassign, counts

                if self.retry_buffer:
                    RB = self.retry_buffer
                    RBW = RB // wave_width
                    BIG = 1 << 30

                    rel_core = self._release_core()

                    def per_scenario_retry(
                        dc, state, src, xsrc, mgt, antit, preft,
                        prefwt, durt, tbt,
                        idx, t_b, b,
                        vassign, rbuf, rcount,
                        pend_id, pend_node, pend_relb, rdrop,
                    ):
                        """The device-release chunk call with the
                        bounded unschedulable-retry pass (semantics:
                        sim.greedy.greedy_replay(retry_buffer=...)).
                        Static releases ran in the separate bucketed
                        _release_fn before this call. Order here:
                        pend releases → retry pass → buffer
                        compaction → main chunk scan (with failure
                        appends) → assignment fold."""
                        d = T.Derived.build(dc)
                        cmasks = V3.class_masks(dc, d, st3, spec, reps)
                        wave_step = V3.make_wave_step3(
                            dc, d, sh3, st3, wave_width, spec, cmasks
                        )
                        # 1. releases of retried-placed pods whose
                        # boundary arrived (relb encodes the f32 time
                        # comparison already).
                        due_p = (pend_id >= 0) & (pend_relb <= b)
                        safe_p = jnp.clip(pend_id, 0)
                        nd_p = jnp.where(due_p, pend_node, -1)
                        state = rel_core(
                            state, nd_p, src.requests[safe_p],
                            mgt[safe_p], antit[safe_p],
                            preft[safe_p], prefwt[safe_p],
                        )
                        # 2. bounded retry pass: the NORMAL wave step
                        # over the buffer (empty slots are invalid
                        # no-ops), FIFO order preserved by the wave
                        # packing below.
                        rb_waves = rbuf.reshape(RBW, wave_width)
                        slots_r = T.gather_slots_device(src, rb_waves)
                        extra_r = V3.gather_extra_device(xsrc, rb_waves)
                        state, choices_r = jax.lax.scan(
                            wave_step, state, (slots_r, extra_r)
                        )
                        flat_cr = choices_r.reshape(RB)
                        placed_r = (flat_cr >= 0) & (rbuf >= 0)
                        retry_placed = placed_r.sum().astype(jnp.int32)
                        # 3. pend append (placed pods start NOW: f32
                        # boundary search, at least b+1) + stable
                        # compaction, drop-newest on overflow.
                        dur_r = durt[jnp.clip(rbuf, 0)]
                        rbn = jnp.searchsorted(
                            tbt, t_b + dur_r, side="left"
                        )
                        relb_new = jnp.where(
                            placed_r & (rbn < tbt.shape[0]),
                            jnp.maximum(rbn, b + 1),
                            BIG,
                        ).astype(jnp.int32)
                        add = placed_r & (relb_new < BIG)
                        keep_old = (pend_id >= 0) & ~due_p
                        ids_cat = jnp.concatenate([
                            jnp.where(keep_old, pend_id, -1),
                            jnp.where(add, rbuf, -1),
                        ])
                        node_cat = jnp.concatenate(
                            [pend_node, flat_cr]
                        )
                        relb_cat = jnp.concatenate(
                            [pend_relb, relb_new]
                        )
                        op = jnp.argsort(ids_cat < 0, stable=True)[:RB]
                        pend_id = jnp.where(
                            ids_cat[op] >= 0, ids_cat[op], -1
                        ).astype(jnp.int32)
                        pend_node = node_cat[op].astype(jnp.int32)
                        pend_relb = relb_cat[op].astype(jnp.int32)
                        # 4. rbuf compaction: placed pods leave; the
                        # rest keep FIFO order.
                        keep_q = (rbuf >= 0) & (flat_cr < 0)
                        oq = jnp.argsort(~keep_q, stable=True)
                        rbuf = jnp.where(
                            keep_q[oq], rbuf[oq], -1
                        ).astype(jnp.int32)
                        rcount = keep_q.sum().astype(jnp.int32)
                        # 5. main chunk scan with failure appends.
                        slots = T.gather_slots_device(src, idx)
                        extra = V3.gather_extra_device(xsrc, idx)

                        def step(carry, xs):
                            st, rbuf, rcount, rdrop = carry
                            slots_w, extra_w, rows = xs
                            st, choices = wave_step(
                                st, (slots_w, extra_w)
                            )
                            placed_w = jnp.sum(
                                (choices >= 0) & slots_w.valid
                            ).astype(jnp.int32)
                            fail = (
                                (choices < 0)
                                & slots_w.valid
                                & (slots_w.group < 0)
                            )
                            posk = (
                                rcount
                                + jnp.cumsum(fail.astype(jnp.int32))
                                - 1
                            )
                            pos = jnp.where(
                                fail & (posk < RB), posk, RB
                            )
                            rbuf = rbuf.at[pos].set(rows, mode="drop")
                            nfail = fail.sum().astype(jnp.int32)
                            # Overflow drops the newest — COUNTED,
                            # like the host BoundaryOps analogue
                            # (pend overflow is not: there the pod
                            # keeps its resources, not dropped).
                            rdrop = rdrop + jnp.maximum(
                                rcount + nfail - RB, 0
                            )
                            rcount = jnp.minimum(
                                rcount + nfail, RB
                            ).astype(jnp.int32)
                            return (st, rbuf, rcount, rdrop), (
                                choices, placed_w
                            )

                        (state, rbuf, rcount, rdrop), (
                            choices, counts
                        ) = jax.lax.scan(
                            step,
                            (state, rbuf, rcount, rdrop),
                            (slots, extra, idx),
                        )
                        # 6. fold arrival-chunk placements at their
                        # flat wave positions (retried placements do
                        # NOT enter vassign: their releases ride pend
                        # exclusively, and their arrival slot keeps
                        # PAD so the static entry never fires).
                        vassign = jax.lax.dynamic_update_slice(
                            vassign,
                            choices.reshape(-1),
                            (b * idx.size,),
                        )
                        return (
                            state, vassign, rbuf, rcount,
                            pend_id, pend_node, pend_relb, rdrop,
                            (counts, retry_placed),
                        )

                    axes_retry = (
                        0, 0, None, None, None, None, None,
                        None, None, None,
                        None, None, None,
                        0, 0, 0, 0, 0, 0, 0,
                    )
                    vmapped_retry = jax.vmap(
                        per_scenario_retry, in_axes=axes_retry
                    )
                    return finalize(
                        vmapped_retry, axes_retry,
                        (1, 13, 14, 15, 16, 17, 18, 19),
                    )

                # vmap matches in_axes against the args actually
                # passed; with policies on, a literal None rides the
                # dyn slot (no leaves — its axis spec is inert) and
                # the [S, K] policy matrix maps on axis 0.
                axes_rel = [0, 0, None, None, None, None, 0]
                if dyn_on:
                    axes_rel.append(0)
                elif pol_on:
                    axes_rel.append(None)
                if pol_on:
                    axes_rel.append(0)
                vmapped_rel = jax.vmap(
                    per_scenario_rel, in_axes=tuple(axes_rel)
                )
                return finalize(vmapped_rel, tuple(axes_rel), (1, 6))
            # vmap matches in_axes against the args actually passed,
            # so the defaulted dyn arg needs no wrapper.
            axes_src = [0, 0, None, None, None]
            if dyn_on:
                axes_src.append(0)
            elif pol_on:
                axes_src.append(None)
            if pol_on:
                axes_src.append(0)
            vmapped_src = jax.vmap(
                per_scenario_src, in_axes=tuple(axes_src)
            )
            return finalize(vmapped_src, tuple(axes_src), (1,))

        def per_scenario(dc, state, slots, wvec=None):
            d = T.Derived.build(dc)
            wave_step = make_wave_step(dc, d, wave_width, spec, wvec=wvec)

            def step(st, slot_batch):
                st, choices = wave_step(st, slot_batch)
                placed_w = jnp.sum((choices >= 0) & slot_batch.valid).astype(jnp.int32)
                out = choices if collect else placed_w
                return st, out

            state, outs = jax.lax.scan(step, state, slots)
            return state, outs

        axes_v2 = (0, 0, None, 0) if pol_on else (0, 0, None)
        vmapped = jax.vmap(per_scenario, in_axes=axes_v2)
        return finalize(vmapped, axes_v2, (1,))

    def _release_core(self):
        """Shared device release-update core (cached): subtract a K-list
        of released placements from every carried plane via one-hot
        commit blocks — used by the bucketed static-release fns AND the
        retry path's pending releases. Covers the full plane set: used,
        coarse domain planes (per-topology static matmuls), singleton
        host-scale rows, anti/pref when the trace carries the terms,
        match_total. Returns ``core(state, nd, req, mg, an, pf, pw,
        want_raw=False)``; with ``want_raw`` also returns the UNMASKED
        node-space accumulator stack (the DynTables correction input)."""
        if self._rel_core is not None:
            return self._rel_core
        from ..ops import tpu3 as V3

        st3 = self.static3
        ec = self.ec
        Dcap = st3.Dcap
        N = ec.num_nodes
        G = st3.G
        gdom = V3._gdom_table(ec, G)  # [G, N] np
        gate_np = np.asarray(
            (ec.group_topo[:G] >= 0) & (st3.nd_g > 0), np.float32
        )
        vdom = jnp.asarray(
            (gdom >= 0).astype(np.float32) * gate_np[:, None]
        )  # [G, N]
        gt = ec.group_topo[:G]
        coarse = (~st3.is_host) & (gt >= 0)
        topo_tables = []
        for t in sorted(set(gt[coarse].tolist())):
            ids = np.nonzero(coarse & (gt == t))[0]
            oh_t = (
                ec.node_domain[t][:, None]
                == np.arange(Dcap, dtype=np.int64)[None, :]
            ) & (ec.node_domain[t][:, None] >= 0)
            topo_tables.append(
                (jnp.asarray(ids), jnp.asarray(oh_t.astype(np.float32)))
            )
        h_sel = [
            jnp.asarray(np.asarray(ids, np.int32))
            for ids in (st3.mc_h_ids, st3.anti_h_ids, st3.pref_h_ids)
        ]
        ar_G = jnp.arange(G, dtype=jnp.int32)[None, None, :]
        want_an = bool(st3.maintain_anti)
        want_pf = bool(st3.maintain_pref)
        nparts = 1 + want_an + want_pf

        def coarse_delta(rc):
            delta = jnp.zeros((G, Dcap), jnp.float32)
            for ids, oh_t in topo_tables:
                delta = delta.at[ids].set(rc[ids] @ oh_t)
            return delta

        def core(state, nd, req_rows, mg_rows, an_rows, pf_rows, pw_rows,
                 want_raw=False):
            K = nd.shape[0]
            Wr = 256 if K % 256 == 0 else K
            nb = K // Wr
            iota = jnp.arange(N, dtype=jnp.int32)
            R = req_rows.shape[1]

            def body(carry, xs):
                u, rc = carry
                nd_b, req_b, mg_b, an_b, pf_b, pw_b = xs
                oh = (nd_b[:, None] == iota[None, :]).astype(jnp.float32)
                u = u - jnp.einsum("wn,wr->rn", oh, req_b)
                parts = [(mg_b[:, :, None] == ar_G).sum(1)]
                if want_an:
                    parts.append((an_b[:, :, None] == ar_G).sum(1))
                if want_pf:
                    parts.append(
                        ((pf_b[:, :, None] == ar_G) * pw_b[:, :, None])
                        .sum(1)
                    )
                mm = jnp.concatenate(parts, axis=1).astype(jnp.float32)
                rc = rc + jnp.einsum("wn,wk->kn", oh, mm)
                return (u, rc), None

            (used, rc), _ = jax.lax.scan(
                body,
                (state.used, jnp.zeros((nparts * G, N), jnp.float32)),
                (
                    nd.reshape(nb, Wr),
                    req_rows.reshape(nb, Wr, R),
                    mg_rows.reshape(nb, Wr, mg_rows.shape[1]),
                    an_rows.reshape(nb, Wr, an_rows.shape[1]),
                    pf_rows.reshape(nb, Wr, pf_rows.shape[1]),
                    pw_rows.reshape(nb, Wr, pw_rows.shape[1]),
                ),
            )
            rc_raw = rc
            rc = rc * jnp.tile(vdom, (nparts, 1))
            chunks = jnp.split(rc, nparts, axis=0)
            rc_mc = chunks[0]
            rc_an = chunks[1] if want_an else None
            rc_pf = chunks[1 + want_an] if want_pf else None
            new = {
                "used": used,
                "mc_dom": state.mc_dom - coarse_delta(rc_mc),
                "match_total": state.match_total - rc_mc.sum(-1),
            }
            if want_an:
                new["anti_dom"] = state.anti_dom - coarse_delta(rc_an)
            if want_pf:
                new["pref_dom"] = state.pref_dom - coarse_delta(rc_pf)
            for pkey, ids, rcx in (
                ("mc_host", h_sel[0], rc_mc),
                ("anti_host", h_sel[1], rc_an),
                ("pref_host", h_sel[2], rc_pf),
            ):
                if ids.shape[0] and rcx is not None:
                    plane = getattr(state, pkey)
                    new[pkey] = plane - rcx[ids].astype(plane.dtype)
            out = state._replace(**new)
            return (out, rc_raw) if want_raw else out

        core.nparts = nparts
        core.want_an = want_an
        core.want_pf = want_pf
        self._rel_core = core
        return core

    def _release_fn(self, K: int):
        """Jitted static-release application for a pow2 bucket size K
        (device-release path). Separate from the chunk program so each
        boundary pays only its own (bucketed) release-list width instead
        of the global maximum — the Borg duration distribution makes the
        max ~2.4× the mean.

        The update is a scan over 256-wide one-hot COMMIT blocks (the
        wave-commit trick, measured 4×+ faster than a [K]-index scatter
        on TPU — scatter serializes colliding indices): each block builds
        the [Wr, N] placement one-hot once and contracts it with both the
        request rows (→ used delta) and the matched-group matrix (→ a
        node-space [G, N] released-count accumulator). The count planes
        then drop to domain space through ONE static node→domain one-hot
        matmul; match_total is its row sum. Exactness: one-hot operands
        are 0/1 (each product term exact) and the summed quantities are
        the bucketed k8s magnitudes the engine already relies on being
        associative-exact (ops/tpu3.py module docstring)."""
        dyn_mode = self._dyn is not None
        key = (K, dyn_mode)
        fn = self._rel_fn_cache.get(key)
        if fn is not None:
            return fn
        core = self._release_core()
        Dcap = self.static3.Dcap
        nparts = core.nparts
        want_an, want_pf = core.want_an, core.want_pf

        def rel_one(state, vassign, rel_pos, rel_req, rel_mg,
                    rel_anti, rel_pref, rel_prefw,
                    ov_nodes=None, ov_gdom=None, ov_old=None):
            node_k = vassign[rel_pos]  # sentinel pos → the PAD tail slot
            nd = jnp.where(node_k >= 0, node_k, -1)  # -1 matches no node
            if not dyn_mode:
                return core(
                    state, nd, rel_req, rel_mg, rel_anti, rel_pref,
                    rel_prefw,
                )
            # DynTables correction layered on the base update: a node the
            # scenario relabeled releases into its OVERRIDDEN domain (and
            # base validity doesn't apply — a node that gained the key
            # releases into the appended domain the bind counted). Uses
            # the UNMASKED accumulator; old/new one-hots encode validity.
            state, rc_raw = core(
                state, nd, rel_req, rel_mg, rel_anti, rel_pref,
                rel_prefw, want_raw=True,
            )
            raw_chunks = jnp.split(rc_raw, nparts, axis=0)
            safe_ov = jnp.where(ov_nodes >= 0, ov_nodes, 0)
            ok_ov = (ov_nodes >= 0).astype(jnp.float32)  # [K32]
            ar_D = jnp.arange(Dcap, dtype=jnp.float32)
            mk_oh = lambda a: (
                (a[..., None] == ar_D) & (a[..., None] >= 0)
            ).astype(jnp.float32)  # [G, K, Dcap]
            doh = mk_oh(ov_gdom) - mk_oh(ov_old)

            def corr_of(raw):
                rv = raw[:, safe_ov] * ok_ov[None, :]  # [G, K32]
                return jnp.einsum("gk,gkd->gd", rv, doh)

            corr_mc = corr_of(raw_chunks[0])
            new = {
                "mc_dom": state.mc_dom - corr_mc,
                "match_total": state.match_total - corr_mc.sum(-1),
            }
            if want_an:
                new["anti_dom"] = state.anti_dom - corr_of(raw_chunks[1])
            if want_pf:
                new["pref_dom"] = state.pref_dom - corr_of(
                    raw_chunks[1 + want_an]
                )
            return state._replace(**new)

        axes = (
            (0, 0, None, None, None, None, None, None, 0, 0, 0)
            if dyn_mode
            else (0, 0, None, None, None, None, None, None)
        )
        fn_v = jax.vmap(rel_one, in_axes=axes)
        if self.mesh is not None:
            # Same shard_map discipline as the chunk program (round 10):
            # sharded state/vassign, replicated release tables — each
            # device rewinds its local scenarios, no collectives.
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P

            sh, rp = P(SCENARIO_AXIS), P()
            fn_v = shard_map(
                fn_v,
                mesh=self.mesh,
                in_specs=tuple(sh if a == 0 else rp for a in axes),
                out_specs=sh,
                check_rep=False,
            )
        fn = jax.jit(fn_v, donate_argnums=(0,))
        self._rel_fn_cache[key] = fn
        return fn

    def _state_proto(self):
        if self.engine == "v3":
            from ..ops import tpu3 as V3

            # Real domain width: host_part indexes planes with actual
            # domain ids, so width-1 placeholders would go out of bounds.
            D = max(self.ec.max_domains, 1)
            z = np.zeros((self.static3.G, D), np.float32)
            return V3.DevState3.from_host(
                np.zeros((self.ec.num_nodes, self.ec.num_resources), np.float32),
                z, z, z, self.ec, self.static3,
            )
        return T.DevState.init(self.ec)

    def _load_fork_or_init(self):
        """Fork bookkeeping shared by every engine path: (used, match_count)
        host arrays, with ``_fork_waves_done``/``_fork_choices`` set. The
        source replay pads its wave list to a multiple of its chunk size —
        clamp to the REAL wave count so padded tail waves aren't treated
        as already-scheduled."""
        self._fork_waves_done = 0
        self._fork_choices = None
        if self.fork_checkpoint:
            from .checkpoint import ReplayCheckpoint

            ck = ReplayCheckpoint.load(self.fork_checkpoint)
            if ck.boundary is not None:
                raise ValueError(
                    "cannot fork from a boundary-mode (retry/kube) "
                    "checkpoint: its placements live in the host mirror, "
                    "not the saved outs; resume it on a matching "
                    "JaxReplayEngine instead"
                )
            self._fork_ck = ck
            if ck.outs:
                fork = np.concatenate(ck.outs, axis=0)  # [waves(+pad), W]
                self._fork_waves_done = min(
                    fork.shape[0], self.waves.idx.shape[0]
                )
                self._fork_choices = fork[: self._fork_waves_done]
            return ck.used, ck.match_count
        host = init_state(self.ec, self.pods)  # pre-bound pods
        return host.used, host.match_count

    def _init_states(self) -> T.DevState:
        self._load_fork_or_init()  # sets fork bookkeeping
        if self.fork_checkpoint:
            ck = self._fork_ck
            host = init_state(self.ec, self.pods, apply_prebound=False)
            host.used = ck.used
            host.match_count = ck.match_count
            host.anti_active = ck.anti_active
            host.pref_wsum = ck.pref_wsum
        else:
            host = init_state(self.ec, self.pods)  # pre-bound pods
        if self.engine == "v3":
            from ..ops import tpu3 as V3

            one = V3.DevState3.from_host(
                host.used, host.match_count, host.anti_active, host.pref_wsum,
                self.ec, self.static3, ep=self.pods,
            )
            # ONE jitted broadcast dispatch: per-leaf jnp.repeat round-trips
            # cost 12.5s through the tunneled device at the north-star shape.
            S = self.S
            return jax.jit(
                lambda s: jax.tree.map(
                    lambda a: jnp.broadcast_to(a[None], (S,) + a.shape), s
                )
            )(one)
        G, D = host.match_count.shape[0], self.D
        # Domain dim may have grown (label perturbations) → pad.
        mc = np.zeros((G, D), np.float32)
        mc[:, : host.match_count.shape[1]] = host.match_count
        aa = np.zeros((G, D), np.float32)
        aa[:, : host.anti_active.shape[1]] = host.anti_active
        pw = np.zeros((G, D), np.float32)
        pw[:, : host.pref_wsum.shape[1]] = host.pref_wsum
        # Node-space state depends on each scenario's node→domain table
        # (label perturbations change domains).
        nd = np.asarray(self.sset.dc.node_domain)  # [S, T, N]
        gt = np.clip(self.ec.group_topo, 0, None)
        gdom_s = np.where(
            self.ec.group_topo[None, :, None] >= 0, nd[:, gt, :], PAD
        )  # [S, G, N]
        to_nodes = lambda arr: jnp.asarray(
            np.stack([T.domain_to_node_space(arr, gdom_s[s]) for s in range(self.S)])
        )
        rep = lambda a: jnp.asarray(np.repeat(a[None], self.S, axis=0))
        return T.DevState(
            used=rep(host.used),
            match_count=to_nodes(mc),
            anti_active=to_nodes(aa),
            pref_wsum=to_nodes(pw),
            match_total=rep(mc.sum(axis=1).astype(np.float32)),
        )

    def _subtract_stacked_planes(self, states, used_d, mc_d, aa_d, pw_d):
        """Scenario-stacked host-layout delta planes ([S, N, R] /
        [S, G, D]) → v3 device layout, subtracted from the carried
        states (shared by the release path and the kube boundary
        passes; the transform is linear)."""
        from ..ops import tpu3 as V3

        ec, st3 = self.ec, self.static3
        S, N = self.S, ec.num_nodes
        D = mc_d.shape[2]
        Dcap = st3.Dcap
        w = min(D, Dcap)

        def dom_part(arr):
            out = np.zeros((S, st3.G, Dcap), np.float32)
            out[:, : arr.shape[1], :w] = np.where(
                st3.is_host[None, : arr.shape[1], None], 0.0, arr[:, :, :w]
            )
            return out

        gdom = V3._gdom_table(ec, st3.G)

        def host_part(arr, ids, dtype):
            H = len(ids)
            out = np.zeros((S, H, N), np.float32)
            for li, g in enumerate(ids):
                if g < arr.shape[1]:
                    dg = gdom[g]
                    valid = dg >= 0
                    out[:, li, valid] = arr[:, g, np.clip(dg, 0, None)][:, valid]
            return out.astype(dtype)

        delta = V3.DevState3(
            used=jnp.asarray(
                np.ascontiguousarray(np.transpose(used_d, (0, 2, 1)))
            ),
            mc_dom=jnp.asarray(dom_part(mc_d)),
            anti_dom=jnp.asarray(dom_part(aa_d)),
            pref_dom=jnp.asarray(dom_part(pw_d)),
            # .dtype on the jax array directly — np.asarray here forced a
            # full device→host copy of the [S, H, N] plane per release
            # chunk just to read its dtype (advisor round-2).
            mc_host=jnp.asarray(
                host_part(mc_d, st3.mc_h_ids, states.mc_host.dtype)
            ),
            anti_host=jnp.asarray(
                host_part(aa_d, st3.anti_h_ids, states.anti_host.dtype)
            ),
            pref_host=jnp.asarray(
                host_part(pw_d, st3.pref_h_ids, np.float32)
            ),
            match_total=jnp.asarray(
                np.pad(
                    mc_d.sum(axis=2), ((0, 0), (0, st3.G - mc_d.shape[1]))
                ).astype(np.float32)
                if mc_d.shape[1] < st3.G
                else mc_d.sum(axis=2).astype(np.float32)
            ),
            used_tier=jnp.zeros_like(states.used_tier),
            npods_tier=jnp.zeros_like(states.npods_tier),
        )
        if self.mesh is not None:
            delta = shard_scenario_tree(self.mesh, delta)
        return self._donated_subtract(states, delta)

    def _donated_subtract(self, states, delta):
        """Subtract a delta tree from the carried chunk-loop states with
        the STATES buffers donated (round 11 donation audit): the eager
        ``jax.tree.map(jnp.subtract, ...)`` here allocated a second full
        state copy per release/boundary chunk. Cached on the engine — jit
        caches by function identity."""
        if self._sub_jit is None:
            self._sub_jit = jax.jit(
                lambda s, d: jax.tree.map(jnp.subtract, s, d),
                donate_argnums=(0,),
            )
        return self._sub_jit(states, delta)

    def _apply_stacked_boundary_delta(self, states, subs, adds):
        """Per-scenario (pods, nodes) array pairs from the kube boundary
        passes (sub = releases + evictions, add = retried/preempting
        binds) → one stacked device delta. The domain tables are the
        BASE cluster's for every scenario (label perturbations are
        rejected in kube mode), so release_delta against the base ec is
        exact per scenario."""
        from ..models.state import release_delta

        ec = self.ec
        S, N, R = self.S, ec.num_nodes, ec.num_resources
        G = max(ec.num_groups, 1)
        D = max(ec.max_domains, 1)
        used_d = np.zeros((S, N, R), np.float32)
        mc_d = np.zeros((S, G, D), np.float32)
        aa_d = np.zeros((S, G, D), np.float32)
        pw_d = np.zeros((S, G, D), np.float32)
        any_delta = False
        for s in range(S):
            for (pids, pnds), sign in ((subs[s], 1.0), (adds[s], -1.0)):
                if not pids.size:
                    continue
                any_delta = True
                du, dmc, daa, dpw = release_delta(
                    ec, self.pods, pids, pnds
                )
                used_d[s] += sign * du
                mc_d[s] += sign * dmc
                aa_d[s] += sign * daa
                pw_d[s] += sign * dpw
        if not any_delta:
            return states
        return self._subtract_stacked_planes(
            states, used_d, mc_d, aa_d, pw_d
        )

    def _apply_releases(self, states, host_assign, released, cand):
        """Subtract completed pods' contributions per scenario (the
        JaxReplayEngine chunk-boundary mechanism, scenario-stacked; one
        batched scatter pass across all scenarios — at Borg scale every
        pod releases once, so per-scenario Python would dominate).
        Mutates ``released`` in place. ``cand``: [K] pod ids — this
        boundary's static candidate bucket (staged once per run: the
        earliest boundary where ``rel_time <= tb[b]`` AND the one-chunk
        slack has elapsed is known up front, so the per-boundary work is
        [S, K] instead of the old [S, P] mask — K is the handful of pods
        completing at this boundary, which is what fixes the S-scaling)."""
        from ..ops import tpu3 as V3

        ec, ep, st3 = self.ec, self.pods, self.static3
        due = (host_assign[:, cand] != PAD) & ~released[:, cand]
        if not due.any():
            return states
        s_idx, k_idx = np.nonzero(due)
        p_idx = cand[k_idx]
        released[s_idx, p_idx] = True
        nodes = host_assign[s_idx, p_idx]
        S, N, R = self.S, ec.num_nodes, ec.num_resources
        G = max(ec.num_groups, 1)
        D = max(ec.max_domains, 1)
        used_d = np.zeros((S, N, R), np.float32)
        np.add.at(used_d, (s_idx, nodes), ep.requests[p_idx])
        gt = ec.group_topo[:G]
        dom = np.where(
            (gt >= 0)[:, None], ec.node_domain[np.clip(gt, 0, None)][:, nodes], PAD
        )  # [G, K]
        mc_d = np.zeros((S, G, D), np.float32)
        aa_d = np.zeros((S, G, D), np.float32)
        pw_d = np.zeros((S, G, D), np.float32)
        sel = (dom >= 0) & ep.pod_matches_group[p_idx].T[:G]
        gg, kk = np.nonzero(sel)
        np.add.at(mc_d, (s_idx[kk], gg, dom[gg, kk]), 1.0)
        for col in range(ep.anti_req.shape[1]):
            g = ep.anti_req[p_idx, col]
            ok = (g >= 0) & (dom[np.clip(g, 0, None), np.arange(len(p_idx))] >= 0)
            if ok.any():
                np.add.at(
                    aa_d,
                    (s_idx[ok], g[ok], dom[g[ok], np.nonzero(ok)[0]]),
                    1.0,
                )
        for col in range(ep.pref_aff.shape[1]):
            g = ep.pref_aff[p_idx, col]
            w = ep.pref_aff_w[p_idx, col]
            ok = (g >= 0) & (dom[np.clip(g, 0, None), np.arange(len(p_idx))] >= 0)
            if ok.any():
                np.add.at(
                    pw_d,
                    (s_idx[ok], g[ok], dom[g[ok], np.nonzero(ok)[0]]),
                    w[ok].astype(np.float32),
                )

        states = self._subtract_stacked_planes(
            states, used_d, mc_d, aa_d, pw_d
        )
        if self.preemption and states.used_tier.shape[1]:  # [S, Tt, R, N]
            # Tier planes drop completed NON-GANG pods too (pod tiers are
            # static, so releases are attributable; gangs never enter the
            # tier planes — the single-replay round-4 rule, S-stacked).
            # Compact (s, tier, node, req) scatter on device: the dense
            # [S, Tt, R, N] host delta would be 8x the base-plane traffic.
            ng = ep.group_id[p_idx] == PAD
            if ng.any():
                si = s_idx[ng].astype(np.int32)
                ti = st3.pod_tier[p_idx[ng]].astype(np.int32)
                nd = nodes[ng].astype(np.int32)
                rq = ep.requests[p_idx[ng]].astype(np.float32)
                K = len(si)
                pad = 1 << max(K - 1, 0).bit_length()  # pow2 bucket
                if pad > K:
                    z = np.zeros(pad - K, np.int32)
                    si, ti, nd = (
                        np.concatenate([si, z]),
                        np.concatenate([ti, z]),
                        np.concatenate([nd, z]),
                    )
                    rq = np.concatenate(
                        [rq, np.zeros((pad - K, rq.shape[1]), np.float32)]
                    )
                states = states._replace(
                    used_tier=self._tier_rel_fn()(
                        states.used_tier, si, ti, nd, rq
                    ),
                    npods_tier=self._npods_rel_fn()(
                        states.npods_tier, si, ti, nd,
                        (np.arange(pad) < K).astype(np.float32),
                    ),
                )
        return states

    def _tier_rel_fn(self):
        """Cached jit: used_tier[S, Tt, R, N] -= scatter of [K] release
        rows (zero-padded rows subtract 0 — index 0 is safe)."""
        if getattr(self, "_tier_rel_jit", None) is None:
            def f(ut, si, ti, nd, rq):
                R = ut.shape[2]
                Kp = si.shape[0]
                s = jnp.repeat(si, R)
                t = jnp.repeat(ti, R)
                r = jnp.tile(jnp.arange(R, dtype=jnp.int32), Kp)
                n = jnp.repeat(nd, R)
                return ut.at[s, t, r, n].add(-rq.reshape(-1))

            self._tier_rel_jit = jax.jit(f, donate_argnums=(0,))
        return self._tier_rel_jit

    def _npods_rel_fn(self):
        if getattr(self, "_npods_rel_jit", None) is None:
            def f(nt, si, ti, nd, w):
                return nt.at[si, ti, nd].add(-w)

            self._npods_rel_jit = jax.jit(f, donate_argnums=(0,))
        return self._npods_rel_jit

    def _fold(self, host_assign, rows, choices) -> None:
        """Apply a chunk's choices to the per-scenario assignment table.
        ``choices``: device [S, C, W] from the scan, or host [C, W] shared
        pre-fork placements."""
        ch = np.asarray(choices) if isinstance(choices, np.ndarray) else (
            self._fetch(choices)
        )
        v = rows >= 0
        if ch.ndim == 2:
            host_assign[:, rows[v]] = ch[v][None, :]
        else:
            host_assign[:, rows[v]] = ch.reshape((self.S,) + rows.shape)[:, v]

    def _fetch(self, x) -> np.ndarray:
        """Device→host for a result tensor. Round 11: under DCN the
        engine's mesh is process-LOCAL (localize_mesh in __init__), every
        shard is addressable, and this is a plain local copy — the
        per-chunk cross-process replication that used to live here (the
        round-10 ``process_count() > 1`` branch) is gone; processes meet
        once per replay in run()'s gather instead. The replication branch
        survives only for a caller handing in a genuinely cross-process
        mesh, and counts itself so tests can pin it at zero."""
        if self._mesh_spans_procs:
            self._replicate_count += 1
            if self._replicate_fn is None:
                self._replicate_fn = jax.jit(
                    lambda a: a, out_shardings=replicated(self.mesh)
                )
            x = self._replicate_fn(x)
        return np.asarray(x)

    def _stage_dev_rel(self, idx: np.ndarray, C: int) -> dict:
        """Host bucketing + device staging for the device-release path —
        all static per engine (wave packing, durations, chunk layout), so
        it runs once; repeated run() calls reuse the device arrays."""
        from ..ops import tpu3 as V3

        P = self.pods.num_pods
        W = idx.shape[1]
        nchunks = idx.shape[0] // C
        flat_all = idx.reshape(-1)
        vmask = flat_all >= 0
        # Flat WAVE position per pod — release entries address the
        # vassign fold by position (static), not by pod id.
        pos_of = np.full(P, -1, np.int64)
        pos_of[flat_all[vmask]] = np.nonzero(vmask)[0]
        chunk_of = np.full(P, 1 << 30, np.int64)
        chunk_of[flat_all[vmask]] = np.nonzero(vmask)[0] // (C * W)
        prebound = np.nonzero(self.pods.bound_node >= 0)[0]
        Wtot = flat_all.shape[0]
        # Pre-bound pods live in a static tail region of vassign; the
        # final slot is a dedicated PAD sentinel (padded release entries
        # point there and read "not placed").
        chunk_of[prebound] = -2
        pos_of[prebound] = Wtot + np.arange(prebound.size)
        SENT = Wtot + prebound.size
        matched = V3._matched_idx(
            self.pods.pod_matches_group,
            np.ones(self.pods.pod_matches_group.shape[1], bool),
        )
        if matched.shape[1] == 0:
            matched = np.full((P, 1), PAD, np.int32)
        first = idx[:, 0]
        wave_t = np.where(
            first >= 0, self.pods.arrival[np.clip(first, 0, None)], np.inf
        )
        # First boundary each pod is eligible at, in f64 on host — the
        # non-finite boundary tail (PAD-only waves) never releases.
        tb_all = wave_t[0 :: C][:nchunks]
        nfin = int(np.isfinite(tb_all).sum())
        elig = np.searchsorted(
            tb_all[:nfin], self._rel_time, side="left"
        ).astype(np.int64)
        elig_ok = np.isfinite(self._rel_time) & (elig < nfin)
        # The boundary each pod releases at is STATIC: first boundary ≥
        # its eligibility that also respects the one-chunk slack (chunks
        # ≤ b−2 folded). Bucket pods per boundary on host so the device
        # touches only that boundary's K_b pods (padded to a pow2
        # bucket, NOT the global max — the Borg duration skew makes the
        # max ~2.4× the mean).
        b_rel = np.maximum(elig, chunk_of + 2)
        ok = elig_ok & (b_rel < nchunks) & (pos_of >= 0)
        pods_ok = np.nonzero(ok)[0].astype(np.int64)
        b_ok = b_rel[pods_ok]
        order = np.lexsort((pods_ok, b_ok))
        pods_s = pods_ok[order]
        b_s = b_ok[order]
        counts = np.bincount(b_s, minlength=nchunks)
        starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        R = self.ec.num_resources
        Mm = matched.shape[1]
        # Per-pod anti/pref term tables (the bind-side contributions the
        # release must rewind; width ≥ 1 so the commit-block reshapes
        # stay non-degenerate).
        def _w1(a, fill, dt):
            if a.shape[1] == 0:
                return np.full((a.shape[0], 1), fill, dt)
            return a.astype(dt)

        anti_t = _w1(self.pods.anti_req, PAD, np.int32)
        pref_t = _w1(self.pods.pref_aff, PAD, np.int32)
        prefw_t = _w1(self.pods.pref_aff_w, 0.0, np.float32)
        Ma, Mp = anti_t.shape[1], pref_t.shape[1]
        rel_calls = []  # per boundary: None | device (pos, req, mg, ...)
        for bb in range(nchunks):
            k = int(counts[bb])
            if k == 0:
                rel_calls.append(None)
                continue
            # pow2 bucket, floor = the commit-block width (small
            # boundaries must not pay a 4096-wide padded scan; each
            # distinct Kp compiles one small release fn, cache-persisted).
            Kp = 1 << max(8, (k - 1).bit_length())
            seg = pods_s[starts[bb] : starts[bb] + k]
            posb = np.full(Kp, SENT, np.int64)
            posb[:k] = pos_of[seg]
            reqb = np.zeros((Kp, R), np.float32)
            reqb[:k] = self.pods.requests[seg]
            mgb = np.full((Kp, Mm), PAD, np.int32)
            mgb[:k] = matched[seg]
            antib = np.full((Kp, Ma), PAD, np.int32)
            antib[:k] = anti_t[seg]
            prefb = np.full((Kp, Mp), PAD, np.int32)
            prefb[:k] = pref_t[seg]
            prefwb = np.zeros((Kp, Mp), np.float32)
            prefwb[:k] = prefw_t[seg]
            rel_calls.append((
                jnp.asarray(posb.astype(np.int32)),
                jnp.asarray(reqb),
                jnp.asarray(mgb),
                jnp.asarray(antib),
                jnp.asarray(prefb),
                jnp.asarray(prefwb),
            ))
        va = np.full(Wtot + prebound.size + 1, PAD, np.int32)
        va[Wtot : Wtot + prebound.size] = self.pods.bound_node[prebound]
        stg = {
            "rel_calls": rel_calls,
            "b_c": [jnp.asarray(np.int32(bb)) for bb in range(nchunks)],
            "va": jnp.asarray(va),
        }
        if self.retry_buffer:
            stg["mgt"] = jnp.asarray(matched.astype(np.int32))
            stg["antit"] = jnp.asarray(anti_t)
            stg["preft"] = jnp.asarray(pref_t)
            stg["prefwt"] = jnp.asarray(prefw_t)
            stg["durt"] = jnp.asarray(self.pods.duration.astype(np.float32))
            stg["tbt"] = jnp.asarray(tb_all[:nfin].astype(np.float32))
            stg["tb_c"] = [
                jnp.asarray(np.float32(tb_all[b])) for b in range(nchunks)
            ]
        return stg

    def _dcn_recover_block(self, dead_pid: int, gen: int = 0) -> dict:
        """``recover`` callback for :func:`parallel.dcn.gather` (round
        15): rebuild ``dead_pid``'s contiguous scenario block through a
        fresh engine over THIS process's local mesh, resuming from the
        dead process's newest published checkpoint when one exists. The
        replay is deterministic, so the returned payload is byte-
        identical to what ``dead_pid`` would have published itself.
        ``gen`` (round 17) is the claim generation — nonzero when an
        earlier claimant died mid-recovery and this call is the fenced
        hand-off; it rides into the recovery engine's fleet telemetry."""
        rb = self._dcn_rebuild
        if rb is None:
            raise RuntimeError(
                "DCN recovery callback invoked on an engine that was "
                "never scenario-sliced"
            )
        per = self.S_global // dcn.worker_count()
        lo, hi = int(dead_pid) * per, (int(dead_pid) + 1) * per
        if dcn.heartbeat_every() > 0:
            # Immediate liveness under OUR pid with the claimed block
            # named, BEFORE the (possibly compile-heavy) engine build —
            # a second failure during recovery must be attributed to the
            # claimant, and siblings must not open the next claim
            # generation while we are still warming up.
            dcn.heartbeat(
                -1, block=(lo, hi), state="recover",
                extra={
                    "recovering_for": int(dead_pid),
                    # Round 21: the fenced claim generation, surfaced by
                    # dcn_launch --watch as recovering-p<dead>@g<gen>.
                    "recover_gen": int(gen),
                },
            )
        eng = WhatIfEngine(
            self.ec, self.pods, rb["scenarios"],
            config=rb["config"],
            wave_width=rb["wave_width"],
            chunk_waves=rb["chunk_waves"],
            mesh=self.mesh,
            collect_assignments=rb["collect_assignments"],
            fork_checkpoint=rb["fork_checkpoint"],
            preemption=rb["preemption"],
            completions=rb["completions"],
            retry_buffer=rb["retry_buffer"],
            granularity_guard=rb["granularity_guard"],
            telemetry=rb["telemetry"],
            policies=rb["policies"],
            _dcn_recovery=dict(
                block=(lo, hi),
                for_pid=int(dead_pid),
                gen=int(gen),
                epoch=dcn.gather_seq(),
                prefer_taint=self._dcn_prefer_taint,
                scales_pods=self._dcn_scales_pods,
            ),
        )
        res = eng.run()
        return dict(
            placed=res.placed,
            assignments=res.assignments,
            util=res.utilization_cpu,
            preemptions=res.preemptions,
            dropped=res.retry_dropped,
            evictions=res.evictions,
            resched=res.evict_rescheduled,
            stranded=res.evict_stranded,
            evict_lat=res.evict_latency_mean,
            lat50=res.latency_p50,
            lat90=res.latency_p90,
            lat99=res.latency_p99,
            frag_stranded=res.stranded_cpu,
            frag_index=res.frag_index_cpu,
            frag_pack=res.packing_efficiency,
            telemetry=res.scenario_telemetry,
            fleet=res.fleet_telemetry,
        )

    def _run_spare(self) -> WhatIfResult:
        """Round 15 elastic spare (tail pids under ``KSIM_DCN_SPARES``):
        owns no scenario block — publish liveness, enter the gather
        immediately as claim-eligible capacity (its sentinel payload is
        available at once, so no worker ever waits on a spare), and
        assemble the same gathered result every worker returns. Fork
        checkpoints are not supported on the spare path."""
        from .telemetry import ReplayTelemetry

        t0 = time.perf_counter()
        if dcn.heartbeat_every() > 0:
            dcn.heartbeat(-1, state="spare", wall_s=0.0)
        parts = dcn.gather(
            "whatif",
            {"spare": True},
            recover=(
                self._dcn_recover_block
                if self._dcn_rebuild is not None
                else None
            ),
        )
        parts = [
            p for p in parts
            if not (isinstance(p, dict) and p.get("spare"))
        ]

        def _cat(k):
            if parts[0][k] is None:
                return None
            return np.concatenate([p[k] for p in parts], axis=0)

        placed = _cat("placed")
        fleet_tel = None
        if parts[0].get("fleet") is not None:
            fleet_tel = ReplayTelemetry.merge(
                [p["fleet"] for p in parts],
                process_ids=list(range(len(parts))),
            )
        wall = time.perf_counter() - t0
        to_schedule = int((self.waves.idx >= 0).sum())
        total = int(placed.sum())
        ndev_local = (
            int(self.mesh.devices.size) if self.mesh is not None else 1
        )
        dev_scale = len(parts)
        return WhatIfResult(
            placed=placed,
            unschedulable=(to_schedule - placed).astype(np.int32),
            total_placed=total,
            wall_clock_s=wall,
            placements_per_sec=total / wall if wall > 0 else 0.0,
            assignments=_cat("assignments"),
            utilization_cpu=_cat("util"),
            completions_on=self.completions_on,
            engine=self.engine,
            preemptions=_cat("preemptions"),
            retry_dropped=_cat("dropped"),
            evictions=_cat("evictions"),
            evict_rescheduled=_cat("resched"),
            evict_stranded=_cat("stranded"),
            evict_latency_mean=_cat("evict_lat"),
            latency_p50=_cat("lat50"),
            latency_p90=_cat("lat90"),
            latency_p99=_cat("lat99"),
            stranded_cpu=_cat("frag_stranded"),
            frag_index_cpu=_cat("frag_index"),
            packing_efficiency=_cat("frag_pack"),
            scenario_telemetry=(
                None
                if parts[0]["telemetry"] is None
                else [t for p in parts for t in p["telemetry"]]
            ),
            fleet_telemetry=fleet_tel,
            n_devices=ndev_local * dev_scale,
            mesh_shape=(
                dict(zip(
                    self.mesh.axis_names,
                    (
                        int(d) * dev_scale
                        for d in self.mesh.devices.shape
                    ),
                ))
                if self.mesh is not None
                else None
            ),
            process_count=jax.process_count(),
        )

    def _wq_exec_block(
        self, bid, lo, hi, resume_pid, gen, speculative, queue_depth
    ) -> dict:
        """``execute`` callback for :func:`parallel.dcn.wq_run`: run
        scenario block ``[lo, hi)`` through a fresh engine on THIS
        process's local mesh and return the 17-key gather payload. The
        chunk program is a pure function of the block contents and the
        full-list engine gates (dictated below, never re-derived), so any
        process executing the block — holder, speculator, or thief —
        produces byte-identical results. ``resume_pid >= 0`` resumes from
        that pid's newest published checkpoint for this block's own
        (negative) epoch; speculative/steal provenance rides into the
        block engine's fleet telemetry via the ``wq`` info dict."""
        rb = self._dcn_rebuild
        if rb is None:
            raise RuntimeError(
                "work-queue execute callback invoked on an engine that "
                "was never scenario-sliced"
            )
        if dcn.heartbeat_every() > 0:
            dcn.heartbeat(
                -1, block=(int(lo), int(hi)),
                state="spec" if speculative else "run",
                extra={
                    "wq_block": int(bid),
                    "leased_blocks": 1,
                    "queue_depth": int(queue_depth),
                },
            )
        eng = WhatIfEngine(
            self.ec, self.pods, rb["scenarios"],
            config=rb["config"],
            wave_width=rb["wave_width"],
            chunk_waves=rb["chunk_waves"],
            mesh=self.mesh,
            collect_assignments=rb["collect_assignments"],
            fork_checkpoint=rb["fork_checkpoint"],
            preemption=rb["preemption"],
            completions=rb["completions"],
            retry_buffer=rb["retry_buffer"],
            granularity_guard=rb["granularity_guard"],
            telemetry=rb["telemetry"],
            policies=rb["policies"],
            _dcn_recovery=dict(
                block=(int(lo), int(hi)),
                for_pid=int(resume_pid),
                gen=int(gen),
                epoch=dcn.wq_ckpt_epoch(dcn.gather_seq(), int(bid)),
                prefer_taint=self._dcn_prefer_taint,
                scales_pods=self._dcn_scales_pods,
                wq=dict(
                    block=int(bid),
                    speculative=bool(speculative),
                    queue_depth=int(queue_depth),
                ),
            ),
        )
        res = eng.run()
        dcn.note_block_chunks(eng._wq_exec_chunks)
        return dict(
            placed=res.placed,
            assignments=res.assignments,
            util=res.utilization_cpu,
            preemptions=res.preemptions,
            dropped=res.retry_dropped,
            evictions=res.evictions,
            resched=res.evict_rescheduled,
            stranded=res.evict_stranded,
            evict_lat=res.evict_latency_mean,
            lat50=res.latency_p50,
            lat90=res.latency_p90,
            lat99=res.latency_p99,
            frag_stranded=res.stranded_cpu,
            frag_index=res.frag_index_cpu,
            frag_pack=res.packing_efficiency,
            telemetry=res.scenario_telemetry,
            fleet=res.fleet_telemetry,
        )

    def _run_workqueue(self) -> WhatIfResult:
        """Round 18 work-stealing scenario-block queue: every process
        (worker, spare, mid-replay joiner) drains
        :func:`parallel.dcn.wq_run` and assembles the per-block payloads
        in block order — structurally the :meth:`_run_spare` assembly,
        keyed by block id instead of pid, so the result is byte-identical
        to the static-slicing oracle for ANY lease interleaving."""
        from .telemetry import ReplayTelemetry

        t0 = time.perf_counter()
        if dcn.heartbeat_every() > 0:
            dcn.heartbeat(
                -1, state="run", wall_s=0.0,
                extra={"leased_blocks": 0},
            )
        blocks = dcn.wq_blocks(self.S_global)
        parts = dcn.wq_run("whatif", blocks, self._wq_exec_block)

        def _cat(k):
            if parts[0][k] is None:
                return None
            return np.concatenate([p[k] for p in parts], axis=0)

        placed = _cat("placed")
        fleet_tel = None
        if parts[0].get("fleet") is not None:
            fleet_tel = ReplayTelemetry.merge(
                [p["fleet"] for p in parts],
                process_ids=list(range(len(parts))),
            )
        wall = time.perf_counter() - t0
        # Mirror the single-process path's to_schedule: waves already
        # covered by a fork checkpoint are not demand, so they must not
        # count against placed when deriving unschedulable. The outer
        # wq engine never runs _init_states (only block engines do), so
        # load the fork bookkeeping here.
        self._fork_waves_done = 0
        if self.fork_checkpoint:
            self._load_fork_or_init()
        idx = self.waves.idx
        if self._fork_waves_done:
            idx = idx[self._fork_waves_done:]
        to_schedule = int((idx >= 0).sum())
        total = int(placed.sum())
        ndev_local = (
            int(self.mesh.devices.size) if self.mesh is not None else 1
        )
        dev_scale = dcn.worker_count()
        return WhatIfResult(
            placed=placed,
            unschedulable=(to_schedule - placed).astype(np.int32),
            total_placed=total,
            wall_clock_s=wall,
            placements_per_sec=total / wall if wall > 0 else 0.0,
            assignments=_cat("assignments"),
            utilization_cpu=_cat("util"),
            completions_on=self.completions_on,
            engine=self.engine,
            preemptions=_cat("preemptions"),
            retry_dropped=_cat("dropped"),
            evictions=_cat("evictions"),
            evict_rescheduled=_cat("resched"),
            evict_stranded=_cat("stranded"),
            evict_latency_mean=_cat("evict_lat"),
            latency_p50=_cat("lat50"),
            latency_p90=_cat("lat90"),
            latency_p99=_cat("lat99"),
            stranded_cpu=_cat("frag_stranded"),
            frag_index_cpu=_cat("frag_index"),
            packing_efficiency=_cat("frag_pack"),
            scenario_telemetry=(
                None
                if parts[0]["telemetry"] is None
                else [t for p in parts for t in p["telemetry"]]
            ),
            fleet_telemetry=fleet_tel,
            n_devices=ndev_local * dev_scale,
            mesh_shape=(
                dict(zip(
                    self.mesh.axis_names,
                    (
                        int(d) * dev_scale
                        for d in self.mesh.devices.shape
                    ),
                ))
                if self.mesh is not None
                else None
            ),
            process_count=jax.process_count(),
        )

    def run(self) -> WhatIfResult:
        # Per-run counter for the round-11 contract test: full-tensor
        # cross-process replication in _fetch must be 0 for this replay.
        self._replicate_count = 0
        if self._dcn_wq:
            # Work-queue mode subsumes the spare path: a spare is just a
            # process that loses every generation-0 lease race and waits
            # for stealable/speculation-eligible work.
            return self._run_workqueue()
        if self._dcn_spare:
            return self._run_spare()
        states = self._init_states()  # sets fork bookkeeping first
        idx = self.waves.idx
        if self._fork_waves_done:
            idx = idx[self._fork_waves_done :]
            if idx.shape[0] == 0:
                idx = np.full((1, self.waves.wave_width), PAD, np.int32)
        C = min(self.chunk_waves, max(idx.shape[0], 1))
        pad_to = ((idx.shape[0] + C - 1) // C) * C
        if pad_to != idx.shape[0]:
            idx = np.concatenate([idx, np.full((pad_to - idx.shape[0], idx.shape[1]), PAD, np.int32)])
        dc = self.sset.dc
        if self.mesh is not None:
            dc = shard_scenario_tree(self.mesh, dc)
            states = shard_scenario_tree(self.mesh, states)
        comp_on = (
            self.completions_on
            and not self._completions_dev
            and not self.kube  # BoundaryOps owns releases in kube mode
        )
        dev_rel = self._completions_dev
        if dev_rel:
            # Everything here is static per engine — staged ONCE and
            # cached (a second run() pays zero host bucketing/upload).
            if self._dev_rel_stage is None:
                self._dev_rel_stage = self._stage_dev_rel(idx, C)
            stg = self._dev_rel_stage
            rel_calls, b_c = stg["rel_calls"], stg["b_c"]
            # vassign is donated through the chunk calls — fresh per run.
            # Under a mesh it materializes SHARDED (each device holds its
            # scenarios' buffer; the broadcast never builds a global copy).
            _bc = lambda a: jnp.broadcast_to(a[None], (self.S,) + a.shape)
            vassign_d = (
                jax.jit(_bc, out_shardings=scenario_sharding(self.mesh))
                if self.mesh is not None
                else jax.jit(_bc)
            )(stg["va"])
            if self.retry_buffer:
                RB = self.retry_buffer
                mgt_d, durt_d = stg["mgt"], stg["durt"]
                antit_d, preft_d, prefwt_d = (
                    stg["antit"], stg["preft"], stg["prefwt"]
                )
                tbt_d, tb_c = stg["tbt"], stg["tb_c"]
                sh_s = (
                    (lambda a: jax.device_put(
                        a, scenario_sharding(self.mesh)
                    ))
                    if self.mesh is not None
                    else (lambda a: a)
                )
                zs = lambda fill, dt: sh_s(jnp.full(
                    (self.S, RB), fill, dtype=dt
                ))
                rbuf_d = zs(PAD, jnp.int32)
                rcount_d = sh_s(jnp.zeros(self.S, jnp.int32))
                pend_id_d = zs(PAD, jnp.int32)
                pend_node_d = zs(PAD, jnp.int32)
                pend_relb_d = zs(0, jnp.int32)
                rdrop_d = sh_s(jnp.zeros(self.S, jnp.int32))
        pending_fold = None  # (rows, choices) of the not-yet-folded chunk
        if comp_on:
            from .jax_runtime import wave_start_times

            wave_t = wave_start_times(self.pods, idx)
            host_assign = np.tile(
                np.where(
                    self.pods.bound_node >= 0, self.pods.bound_node, PAD
                ).astype(np.int32),
                (self.S, 1),
            )
            if self._fork_choices is not None:
                # Fold pre-fork placements except the SOURCE's last chunk,
                # which stays pending — restoring the one-chunk slack the
                # uninterrupted source run would be carrying here.
                C_src = (
                    self._fork_ck.outs[0].shape[0]
                    if self._fork_ck.outs
                    else 0
                )
                cut = (
                    min((self._fork_ck.chunk_cursor - 1) * C_src,
                        self._fork_waves_done)
                    if C_src
                    else self._fork_waves_done
                )
                cut = max(cut, 0)
                pidx = self.waves.idx[:cut].reshape(-1)
                pch = self._fork_choices[:cut].reshape(-1)
                pv = pidx >= 0
                host_assign[:, pidx[pv]] = pch[pv][None, :]
                if cut < self._fork_waves_done:
                    pending_fold = (
                        self.waves.idx[cut : self._fork_waves_done],
                        self._fork_choices[cut : self._fork_waves_done],
                    )
            released = np.zeros((self.S, self.pods.num_pods), bool)
            if self.fork_checkpoint and self._fork_waves_done:
                # The forked state already carries the source replay's
                # pre-fork releases (completions default ON there): seed
                # from the persisted mask, or reconstruct what the source
                # applied at its own chunk boundaries — else the first
                # post-fork boundary re-subtracts every pre-fork release,
                # driving count planes negative (advisor round-2 medium).
                ck = self._fork_ck
                if ck.released is not None:
                    rel0 = ck.released.astype(bool)
                else:
                    from .jax_runtime import rebuild_fork_state

                    C_src = ck.outs[0].shape[0] if ck.outs else 0
                    full_first = self.waves.idx[:, 0]
                    full_t = np.where(
                        full_first >= 0,
                        self.pods.arrival[np.clip(full_first, 0, None)],
                        np.inf,
                    )
                    if C_src:
                        # The source padded ITS wave list to a multiple of
                        # C_src — mirror that so chunk rows line up.
                        # (slack=0: a maskless checkpoint predates the
                        # slack rule — see JaxReplayEngine.replay.)
                        idx_src = self.waves.idx
                        need = ck.chunk_cursor * C_src
                        if idx_src.shape[0] < need:
                            idx_src = np.concatenate([
                                idx_src,
                                np.full(
                                    (need - idx_src.shape[0], idx_src.shape[1]),
                                    PAD, np.int32,
                                ),
                            ])
                            full_t = np.concatenate([
                                full_t,
                                np.full(need - full_t.shape[0], np.inf),
                            ])
                        _, rel0 = rebuild_fork_state(
                            self.pods, idx_src, C_src, ck.outs,
                            full_t, ck.chunk_cursor, slack=0,
                        )
                    else:
                        rel0 = np.zeros(self.pods.num_pods, bool)
                released |= rel0[None, :]
        dyn_sharded = self._dyn_dev
        if dyn_sharded is not None and self.mesh is not None:
            # Chunk-invariant: shard once, not per chunk.
            dyn_sharded = shard_scenario_tree(self.mesh, dyn_sharded)
        pol_d = None
        if self._policies is not None:
            # Per-scenario policy vectors (round 9): value-only input to
            # the compiled chunk program — set_policies + run() reuses the
            # executable. Sharded once (chunk-invariant) under a mesh.
            pol_d = jnp.asarray(self._policies)
            if self.mesh is not None:
                pol_d = shard_scenario_tree(self.mesh, pol_d)
        srcs = self._slot_srcs
        idx_chunks = (
            [jnp.asarray(idx[c0 : c0 + C]) for c0 in range(0, idx.shape[0], C)]
            if srcs is not None
            else None
        )
        pre_comp = comp_on and self.preemption
        kbops = None
        if self.kube:
            # Per-scenario host mirrors over the PERTURBED clusters: the
            # PostFilter pass then runs the CPU engine's arithmetic per
            # scenario, and deltas land stacked (sim.boundary docstring).
            from dataclasses import replace as cfg_replace

            from ..framework.framework import (
                FrameworkConfig as _FC,
                SchedulerFramework,
            )
            from .boundary import BoundaryOps
            from .waves import WaveBatch

            cfgk = cfg_replace(
                self._config if self._config is not None else _FC(),
                enable_preemption=True,
            )
            from .telemetry import TelemetryCollector

            wb = WaveBatch(idx=idx, wave_width=self.wave_width)
            # One collector per scenario: the host mirrors are the only
            # carrier of per-scenario bind times / rejection reasons.
            ktel = [
                TelemetryCollector(self.telemetry_cfg)
                if self.telemetry_cfg.enabled
                else None
                for _ in range(self.S)
            ]
            kbops = [
                BoundaryOps(
                    ec_s, self.pods, SchedulerFramework(ec_s, self.pods, cfgk),
                    wb, self.wave_width, C,
                    retry_buffer=self.retry_buffer, kube=True, lazy=True,
                    telemetry=ktel[si],
                )
                for si, ec_s in enumerate(self.sset.host_clusters(self.ec))
            ]
            from .jax_runtime import wave_start_times

            kube_wave_t = wave_start_times(self.pods, idx)
            # Lazy boundary sync (round 6): per chunk, fetch only a [S]
            # non-gang failure count; the full choices fetch + mirror
            # folds run AFTER the next dispatch (overlapped) unless some
            # scenario's retry pass will actually read its mirror.
            # Series telemetry disables the deferral entirely: every
            # boundary SAMPLES the mirror's occupancy planes
            # (BoundaryOps.boundary's tel.sample), so the fold must land
            # pre-boundary at every chunk — otherwise WHICH boundaries
            # see chunk ci-1's binds depends on the batch-mates' failure
            # clustering, and the per-scenario gauge series would differ
            # across DCN slicings of the same scenario list (round 15:
            # survivor-rebuilt blocks must bit-match the dead process).
            kwant_series = self.telemetry_cfg.want_series
            kube_ng = jnp.asarray(self.pods.group_id == PAD)
            if getattr(self, "_kfail_jit", None) is None:
                self._kfail_jit = jax.jit(
                    lambda ch, ix, ng: (
                        (ix >= 0)[None]
                        & (ch.reshape((ch.shape[0],) + ix.shape) < 0)
                        & ng[jnp.clip(ix, 0)][None]
                    ).sum(axis=(1, 2), dtype=jnp.int32)
                )
            kpending = None  # (ci, rows, choices_dev, nfail_dev[S])

            def _kfold_pending():
                nonlocal kpending
                if kpending is not None:
                    ci_p, rows_p, out_p, _nf = kpending
                    # run_phases is bound later in run() — always before
                    # the first call site (the chunk loop).
                    with run_phases.tick("host_mirror"):
                        ch = jax.device_get(out_p)
                        for s in range(self.S):
                            kbops[s].fold_chunk(ci_p, rows_p, ch[s])
                    kpending = None

            # Per-scenario timed timelines (chaos campaigns, round 7).
            # The mirrors' EncodedCluster twins hold VIEWS of
            # host_stacks["alloc"][s], so mutating the stack rows keeps
            # host and (re-uploaded) device allocatable in lockstep.
            hs = self.sset.host_stacks
            ktimelines = self._timelines
            kev_cursor = [0] * self.S
            khas_events = any(ktimelines)
            if khas_events:
                ksaved_alloc = hs["alloc"].copy()  # [S, N, R] at t=0
        if pre_comp:
            # Eager eviction-aware folds (the single-replay round-4 rule,
            # S-stacked): eviction events must land in the host
            # bookkeeping BEFORE the next boundary's release decisions,
            # so the one-chunk slack becomes an explicit bind-chunk gate
            # instead of a fold lag.
            from .jax_runtime import bind_chunk_of

            chunk_of = bind_chunk_of(self.pods, idx, C)
            nongang = self.pods.group_id == PAD
        rel_bkt = None
        if comp_on:
            # Static release buckets (round 6): each pod's earliest
            # eligible boundary — rel_time <= tb[b] and the one-chunk
            # slack elapsed — is known up front, so boundary b scans only
            # its own candidates ([S, K_b]) instead of an [S, P] mask.
            # The dynamic residue (actually assigned, not yet released /
            # evicted) is re-checked in _apply_releases; a pod still PAD
            # at its bucket boundary stays PAD forever on these paths, so
            # the single check is exact.
            from .jax_runtime import bind_chunk_of as _bco

            chunk_of_rel = _bco(self.pods, idx, C)
            if self._fork_choices is not None and not pre_comp:
                # Lagged-fold fork semantics: pre-fork folded pods can
                # release from boundary 0 (floor -2+2), the source's
                # pending last chunk from boundary 1 (floor -1+2 = 1).
                # (Under pre_comp the eager gate keys off THIS run's idx
                # only — pre-fork pods keep the 'absent' sentinel there,
                # matching the eager mask exactly.)
                C_src = (
                    self._fork_ck.outs[0].shape[0]
                    if self._fork_ck.outs
                    else 0
                )
                cut = (
                    min((self._fork_ck.chunk_cursor - 1) * C_src,
                        self._fork_waves_done)
                    if C_src
                    else self._fork_waves_done
                )
                cut = max(cut, 0)
                fidx = self.waves.idx[:cut].reshape(-1)
                chunk_of_rel[fidx[fidx >= 0]] = -2
                hidx = self.waves.idx[cut : self._fork_waves_done].reshape(-1)
                chunk_of_rel[hidx[hidx >= 0]] = -1
            tb_rel = wave_t[0::C]
            nfin_rel = int(np.isfinite(tb_rel).sum())
            b_rel = np.maximum(
                np.searchsorted(
                    tb_rel[:nfin_rel], self._rel_time, side="left"
                ),
                chunk_of_rel + 2,
            )
            rcand = np.nonzero(b_rel < nfin_rel)[0].astype(np.int64)
            rcand = rcand[np.argsort(b_rel[rcand], kind="stable")]
            roff = np.concatenate(
                ([0], np.cumsum(
                    np.bincount(b_rel[rcand], minlength=max(nfin_rel, 1))
                ))
            ).astype(np.int64)
            rel_bkt = (rcand, roff, nfin_rel)
        ppending = None  # pre_comp deferred chunk: dict, see closures
        if pre_comp:
            from .jax_runtime import preemption_walk

            def _pre_walk():
                """Fetch the [S] eviction summary of the deferred chunk
                and walk ONLY the evicting scenarios (rare). Idempotent —
                caches the fetches on the entry."""
                e = ppending
                if e is None or e["ev"] is not None:
                    return
                ev = np.asarray(jax.device_get(e["ev_d"])).astype(bool)
                e["ev"] = ev
                if ev.any():
                    ch, evn, evt = jax.device_get(
                        (e["out"][0], e["out"][1], e["out"][2])
                    )
                    e["ch"] = ch
                    rows = e["rows"]
                    for s in np.nonzero(ev)[0]:
                        preemption_walk(
                            host_assign[s], rows,
                            ch[s].reshape(rows.shape), evn[s], evt[s],
                            self.static3.pod_tier, nongang,
                            released=released[s],
                        )

            def _pre_finish():
                """Complete the deferred chunk: eviction walks (if not
                already done), then ONE vectorized fold for every
                no-eviction scenario — with zero events the walk is
                exactly `assignments[rows] = finals`, so the bulk
                assignment is bit-identical to S per-scenario walks."""
                nonlocal ppending
                e = ppending
                if e is None:
                    return
                _pre_walk()
                quiet = np.nonzero(~e["ev"])[0]
                if quiet.size:
                    ch = e["ch"]
                    if ch is None:
                        ch = np.asarray(jax.device_get(e["out"][0]))
                    rows = e["rows"]
                    flat = rows.reshape(-1)
                    v = np.nonzero(flat >= 0)[0]
                    if v.size:
                        host_assign[np.ix_(quiet, flat[v])] = (
                            ch.reshape(self.S, -1)[np.ix_(quiet, v)]
                        )
                ppending = None

            if getattr(self, "_evany_jit", None) is None:
                self._evany_jit = jax.jit(
                    lambda evn: (evn >= 0).any(axis=1)
                )
        outs = []
        # Engine-level wall-clock phase breakdown (round 12): the what-if
        # chunk loop gets the same PHASE_NAMES timers the single-replay
        # paths carry, feeding heartbeats, the fleet telemetry merge, and
        # the bench `phases` detail.
        from .telemetry import PhaseTimers, ReplayTelemetry
        from ..utils.profiling import annotate as _prof_ann
        from ..utils.profiling import profiling_active as _prof_on

        run_phases = PhaseTimers()
        # PUBLISH_STATS / RETRY_STATS / CRC_STATS are cumulative module
        # state — snapshot them so the fleet phases below surface only
        # THIS run's publications, KV retries and CRC fallbacks (a prior
        # run in the same process must not leak into the phase map).
        _ps_start = dcn.publish_stats()
        _bg_start = dcn.bg_publish_stats()
        _rs_start = dcn.retry_stats()
        _cs_start = dcn.crc_stats()
        import contextlib as _ctxlib

        _null = _ctxlib.nullcontext()
        _prof = _prof_on()
        _cann = (
            (lambda i: _prof_ann(f"chunk:{i}")) if _prof else (lambda i: _null)
        )
        _pann = _prof_ann if _prof else (lambda name: _null)
        n_chunks = len(range(0, idx.shape[0], C))
        # Liveness heartbeats (round 12): one overwritten KV beacon per
        # process on a chunk cadence — plain puts, never a gather. A
        # recovery engine (round 15) beats too, under the CLAIMANT's own
        # pid with state="recover" and the claimed block named, so a
        # SECOND failure during recovery is attributed to the claimant.
        recovering = self._dcn_recovery is not None
        wq_info = self._dcn_wq_info  # block engine under the round-18 queue
        hb_on = (
            self._dcn_sliced or recovering
        ) and dcn.heartbeat_every() > 0
        hb_block = (self._proc_lo, self._proc_lo + self.S)
        if wq_info is not None:
            # Work-queue block engine: beats under our OWN pid with the
            # lease named (dcn.heartbeat also renews the lease on every
            # beat). wq_rate — chunks per wall second, the straggler
            # watermark's input — is refreshed per beat in the loop.
            hb_kw = dict(
                state="spec" if wq_info.get("speculative") else "run",
                extra={
                    "wq_block": int(wq_info.get("block", -1)),
                    "leased_blocks": 1,
                    "queue_depth": int(wq_info.get("queue_depth", 0)),
                    "wq_rate": 0.0,
                },
            )
        elif recovering:
            hb_kw = dict(
                state="recover",
                extra={
                    "recovering_for": int(
                        self._dcn_recovery.get("for_pid", -1)
                    ),
                    "recover_gen": int(self._dcn_recovery.get("gen", 0)),
                },
            )
        else:
            hb_kw = {}
        # Recoverable work-queue (round 15, parallel.dcn): on a chunk
        # cadence, publish a compressed host snapshot of the loop
        # carriers so a survivor can resume THIS block mid-replay after
        # a host loss. Supported on the device-carrier paths (plain
        # v3/v2 and device-release ± retry, where the whole block state
        # lives in `states`/`vassign`/retry tensors plus `outs`); the
        # host-fold modes (completions host path, kube mirrors) carry
        # state in per-scenario host structures instead — a claimed
        # block there re-executes from chunk 0, still byte-identical.
        ck_ok = kbops is None and not comp_on
        # Queue block engines checkpoint too (under the block's own
        # negative epoch) — that is what a speculator or thief resumes.
        ck_every = (
            dcn.ckpt_every()
            if ck_ok
            and (
                (self._dcn_sliced and not self._dcn_spare)
                or wq_info is not None
            )
            else 0
        )

        def _carriers():
            c = {"states": states}
            if dev_rel:
                c["vassign"] = vassign_d
                if self.retry_buffer:
                    c["retry"] = (
                        rbuf_d, rcount_d, pend_id_d, pend_node_d,
                        pend_relb_d, rdrop_d,
                    )
            return c

        _ck_sig = [
            self.engine, bool(dev_rel), int(self.retry_buffer),
            int(self.S), int(C), int(n_chunks),
        ]
        start_ci = 0
        # for_pid < 0 is a generation-0 queue lease: nobody ran this block
        # before us, so there is no checkpoint to resume — execute from
        # chunk 0 (steals/speculation name the holder via for_pid >= 0).
        resume_pid, resume_epoch = -1, None
        if recovering and ck_ok:
            resume_pid = int(self._dcn_recovery.get("for_pid", -1))
            resume_epoch = self._dcn_recovery.get("epoch")
        elif (
            ck_ok
            and ck_every > 0
            and wq_info is None
            and self._dcn_sliced
            and not self._dcn_spare
            and dcn.resume_enabled()
            and dcn.durable_dir()
        ):
            # Durable ground (round 20): a restarted fleet (dcn_launch
            # --resume after whole-fleet death) seeds each process's OWN
            # static block from its newest complete durable checkpoint.
            # Epoch defaults to checkpoint_epoch(), which matches the
            # dead fleet's — the gather sequence replays
            # deterministically — and load_checkpoint merges the journal
            # mirror into its candidate walk, so the torn-newest-cursor
            # fallback applies to journal files too.
            resume_pid = dcn.process_info()[1]
        if resume_pid >= 0:
            from ..utils.metrics import log as _log
            from .jax_runtime import restore_carriers

            dead = resume_pid
            # Round 17: walk the dead process's checkpoints newest-first.
            # dcn.load_checkpoint already skips CRC-invalid blobs; this
            # loop additionally falls back past blobs that validate on
            # the wire but turn out unusable here (signature or carrier-
            # shape mismatch), via `before_cursor`, instead of giving up
            # on the whole resume.
            before = None
            while True:
                ckd = dcn.load_checkpoint(
                    dead,
                    epoch=resume_epoch,
                    before_cursor=before,
                )
                if ckd is None:
                    if before is not None:
                        _log.warning(
                            "dcn: no usable checkpoint left for process "
                            "%d — re-executing its block from chunk 0",
                            dead,
                        )
                    break
                before = int(ckd["cursor"])
                pay = ckd["payload"]
                if not (
                    isinstance(pay, dict)
                    and tuple(ckd["block"])
                    == (int(hb_block[0]), int(hb_block[1]))
                    and pay.get("sig") == _ck_sig
                ):
                    _log.warning(
                        "dcn: ignoring mismatched checkpoint (cursor %d) "
                        "for process %d — trying an older one",
                        before, dead,
                    )
                    continue
                try:
                    carr = restore_carriers(_carriers(), pay["leaves"])
                except ValueError as e:
                    _log.warning(
                        "dcn: process %d's checkpoint at cursor %d is "
                        "unusable (%s) — trying an older one",
                        dead, before, e,
                    )
                    continue
                states = carr["states"]
                if dev_rel:
                    vassign_d = carr["vassign"]
                    if self.retry_buffer:
                        (
                            rbuf_d, rcount_d, pend_id_d, pend_node_d,
                            pend_relb_d, rdrop_d,
                        ) = carr["retry"]
                outs = list(pay["outs"])
                start_ci = int(pay["cursor"])
                _log.warning(
                    "dcn: resumed process %d's block [%d, %d) from "
                    "its newest checkpoint at chunk %d/%d",
                    dead, hb_block[0], hb_block[1], start_ci, n_chunks,
                )
                break
        # Chunks this engine will actually execute (resumes skip the
        # carried prefix) — the queue driver charges these to
        # spec_wasted_chunks when a speculative duplicate is discarded.
        self._wq_exec_chunks = max(n_chunks - start_ci, 0)
        t0 = time.perf_counter()
        for ci, c0 in enumerate(range(0, idx.shape[0], C)):
            if ci < start_ci:
                continue  # chunks already carried by the resumed state
            if ck_every and ci and ci % ck_every == 0:
                from .jax_runtime import checkpoint_payload

                # Round-19 split: only the device→host snapshot stays on
                # the loop thread (it must see the state exactly as of
                # chunk ci); encode + CRC framing + the retried KV sets
                # — and the round-20 durable-journal mirror — ride the
                # single-flight publisher thread, newest-wins. Drained
                # before the final gather below — the one place this
                # leg needs a durable cursor.
                with run_phases.tick("checkpoint"):
                    dcn.publish_checkpoint_async(
                        ci,
                        checkpoint_payload(ci, _ck_sig, _carriers(), outs),
                        hb_block,
                        epoch=(self._dcn_recovery or {}).get("epoch"),
                    )
            if hb_on:
                if wq_info is not None and ci > start_ci:
                    wall_now = time.perf_counter() - t0
                    if wall_now > 0:
                        hb_kw["extra"]["wq_rate"] = round(
                            (ci - start_ci) / wall_now, 4
                        )
                dcn.maybe_heartbeat(
                    ci - 1,
                    total=n_chunks,
                    block=hb_block,
                    wall_s=time.perf_counter() - t0,
                    phases=run_phases.acc,
                    **hb_kw,
                )
            if kbops is not None:
                t_now = kube_wave_t[c0]
                due_any = khas_events and any(
                    kev_cursor[s] < len(ktimelines[s])
                    and ktimelines[s][kev_cursor[s]].time <= t_now
                    for s in range(self.S)
                )
                if kpending is not None and (
                    kwant_series
                    or np.asarray(kpending[3]).any()
                    or any(b.retry_q for b in kbops)
                    or due_any
                ):
                    # Some scenario's retry pass will read its mirror —
                    # or a due node_down must evict against bookkeeping
                    # current through chunk ci-1: resolve the deferred
                    # fold (all scenarios — failures cluster, and the
                    # boundary pass needs every mirror current anyway).
                    _kfold_pending()
                chaos = None
                if due_any:
                    chaos = []  # per-scenario eviction PairArrays (or None)
                    dirty_alloc = False
                    for s in range(self.S):
                        tl, cur = ktimelines[s], kev_cursor[s]
                        cps, cns = [], []
                        while cur < len(tl) and tl[cur].time <= t_now:
                            ev = tl[cur]
                            cur += 1
                            dirty_alloc = True
                            if (
                                ktel[s] is not None
                                and ktel[s].cfg.want_timeline
                                and ev.kind in ("node_down", "node_up")
                            ):
                                ktel[s].event(
                                    ev.kind, float(ev.time), -1, int(ev.node)
                                )
                            if ev.kind == "node_down":
                                hs["alloc"][s, ev.node] = 0.0
                                cp, cn = kbops[s].evict_node(
                                    ev.node, ci, float(t_now)
                                )
                                if cp.size:
                                    cps.append(cp)
                                    cns.append(cn)
                            elif ev.kind == "node_up":
                                hs["alloc"][s, ev.node] = ksaved_alloc[
                                    s, ev.node
                                ]
                            elif ev.kind == "capacity_scale":
                                hs["alloc"][s, ev.node] = (
                                    ksaved_alloc[s, ev.node] * ev.scale
                                )
                        kev_cursor[s] = cur
                        chaos.append(
                            (np.concatenate(cps), np.concatenate(cns))
                            if cps
                            else None
                        )
                    if dirty_alloc:
                        # One [S, N, R] upload per event-bearing boundary
                        # — events are sparse in virtual time, so this
                        # stays off the steady-state chunk path.
                        dc = dc._replace(
                            allocatable=jnp.asarray(hs["alloc"])
                        )
                subs = []
                adds = []
                any_bdelta = False
                for s, b in enumerate(kbops):
                    rel, binds, evicts = b.boundary(ci, kube_wave_t[c0])
                    cev = chaos[s] if chaos is not None else None
                    sub = (
                        np.concatenate(
                            [rel[0], evicts[0]]
                            + ([cev[0]] if cev is not None else [])
                        ),
                        np.concatenate(
                            [rel[1], evicts[1]]
                            + ([cev[1]] if cev is not None else [])
                        ),
                    )
                    if sub[0].size or binds[0].size:
                        any_bdelta = True
                    subs.append(sub)
                    adds.append(binds)
                if any_bdelta:
                    with run_phases.tick("boundary_fold"), _pann(
                        "boundary_fold"
                    ):
                        states = self._apply_stacked_boundary_delta(
                            states, subs, adds
                        )
            if comp_on and ci < rel_bkt[2]:
                cand_b = rel_bkt[0][rel_bkt[1][ci] : rel_bkt[1][ci + 1]]
                if cand_b.size:
                    if pre_comp and ppending is not None:
                        # Evicting scenarios must walk chunk ci-1 BEFORE
                        # the release decision (evicted pods never
                        # release); quiet scenarios' folds stay deferred —
                        # their ci-1 binds are not candidates here.
                        _pre_walk()
                    with run_phases.tick("boundary_fold"):
                        states = self._apply_releases(
                            states, host_assign, released, cand_b
                        )
            if dev_rel:
                # Static releases first (the bucketed fn; ordering is by
                # data dependency on states/vassign), then the chunk.
                rc = rel_calls[ci]
                if rc is not None:
                    args = (states, vassign_d) + rc
                    if self._dyn is not None:
                        # Per-scenario domain overrides: releases of
                        # relabeled nodes land in the overridden domain.
                        args = args + (
                            self._dyn_dev.ov_nodes,
                            self._dyn_dev.ov_gdom,
                            self._dyn_dev.ov_old,
                        )
                    with run_phases.tick("boundary_fold"):
                        states = self._release_fn(rc[0].shape[0])(*args)
            # Dispatch phase (the chunk-fn if/elif chain below runs exactly
            # one branch): timed via add() rather than a context manager so
            # the chain's indentation is untouched; the profiler chunk
            # marker brackets it the same way.
            _ann = _cann(ci)
            _ann.__enter__()
            _t_disp = time.perf_counter()
            if dev_rel and self.retry_buffer:
                (
                    states, vassign_d, rbuf_d, rcount_d,
                    pend_id_d, pend_node_d, pend_relb_d, rdrop_d, out,
                ) = self._chunk_fn(
                    dc, states, srcs[0], srcs[1], mgt_d, antit_d,
                    preft_d, prefwt_d, durt_d, tbt_d,
                    idx_chunks[ci], tb_c[ci], b_c[ci],
                    vassign_d, rbuf_d, rcount_d,
                    pend_id_d, pend_node_d, pend_relb_d, rdrop_d,
                )
            elif dev_rel:
                args = (
                    dc, states, srcs[0], srcs[1], idx_chunks[ci],
                    b_c[ci], vassign_d,
                )
                if dyn_sharded is not None:
                    args = args + (dyn_sharded,)
                elif pol_d is not None:
                    args = args + (None,)  # dyn slot
                if pol_d is not None:
                    args = args + (pol_d,)
                states, vassign_d, out = self._chunk_fn(*args)
            elif self.engine == "v3":
                # Fused device-side gather + wave scan: one dispatch per
                # chunk, indices pre-staged (ops.tpu.SlotSource). Under a
                # mesh the sources are replicated once per engine and
                # every device gathers its chunk rows locally.
                args = (dc, states, srcs[0], srcs[1], idx_chunks[ci])
                if dyn_sharded is not None:
                    args = args + (dyn_sharded,)
                elif pol_d is not None:
                    args = args + (None,)  # dyn slot
                if pol_d is not None:
                    args = args + (pol_d,)
                states, out = self._chunk_fn(*args)
            else:
                slots = T.gather_slots(self.pods, idx[c0 : c0 + C])
                if self.mesh is not None:
                    slots = replicate_tree(self.mesh, slots)
                args = (dc, states, slots)
                if pol_d is not None:
                    args = args + (pol_d,)
                states, out = self._chunk_fn(*args)
            run_phases.add("dispatch", time.perf_counter() - _t_disp)
            _ann.__exit__(None, None, None)
            if pre_comp:
                # Deferred eviction-aware fold (round 6): fetch only the
                # [S] eviction summary now; the previous chunk resolves
                # here — its D2H copies were launched an iteration ago
                # and this chunk is already in flight, so the host work
                # overlaps device compute. Evicting scenarios take the
                # per-scenario walk; the (common) no-eviction scenarios
                # get one vectorized fold.
                ev_d = self._evany_jit(out[1])
                for a in (out[0], out[1], out[2]):
                    if hasattr(a, "copy_to_host_async"):
                        a.copy_to_host_async()
                _pre_finish()
                ppending = {
                    "rows": idx[c0 : c0 + C], "out": out, "ev_d": ev_d,
                    "ev": None, "ch": None,
                }
                continue  # host_assign is the result carrier — outs unused
            if kbops is not None:
                # Deferred fold into the scenario host mirrors (round 6):
                # only the [S] failure count is fetched per chunk; the
                # full choices land after the next dispatch (or eagerly
                # at the next boundary if any retry pass needs them).
                ix_dev = (
                    idx_chunks[ci]
                    if idx_chunks is not None
                    else jnp.asarray(idx[c0 : c0 + C])
                )
                nf_d = self._kfail_jit(out, ix_dev, kube_ng)
                if hasattr(out, "copy_to_host_async"):
                    out.copy_to_host_async()
                _kfold_pending()
                kpending = (ci, idx[c0 : c0 + C], out, nf_d)
                continue  # the mirrors carry the result — outs unused
            outs.append(out)
            if comp_on:
                # Fold the PREVIOUS chunk's choices AFTER dispatching this
                # one: the blocking fetch overlaps the in-flight chunk and
                # boundary b only ever sees chunks ≤ b−2 (one-chunk slack,
                # shared with JaxReplayEngine and the greedy anchor).
                if pending_fold is not None:
                    with run_phases.tick("host_mirror"):
                        self._fold(host_assign, *pending_fold)
                if hasattr(out, "copy_to_host_async"):
                    out.copy_to_host_async()  # overlap D2H with the chunk
                pending_fold = (idx[c0 : c0 + C], out)
        if pre_comp:
            _pre_finish()  # the last chunk's deferred walk/fold
        if kbops is not None:
            # Trailing boundary (the single-replay/greedy twin): last-
            # chunk failures still get their PostFilter attempt. The
            # final chunk's fold must land first (bookkeeping parity).
            _kfold_pending()
            subs = []
            adds = []
            any_bdelta = False
            for b in kbops:
                rel, binds, evicts = b.boundary(idx.shape[0] // C, np.inf)
                sub = (
                    np.concatenate([rel[0], evicts[0]]),
                    np.concatenate([rel[1], evicts[1]]),
                )
                if sub[0].size or binds[0].size:
                    any_bdelta = True
                subs.append(sub)
                adds.append(binds)
            if any_bdelta:
                with run_phases.tick("boundary_fold"), _pann(
                    "boundary_fold"
                ):
                    states = self._apply_stacked_boundary_delta(
                        states, subs, adds
                    )
            if khas_events:
                # The stack rows were mutated in lockstep with the
                # mirrors — restore the t=0 view so the engine (and its
                # ScenarioSet) stays reusable.
                hs["alloc"][...] = ksaved_alloc
        with run_phases.tick("device_wait"), _pann("device_wait"):
            jax.block_until_ready(states)
        if ck_every:
            # Round-19 durable-cursor boundary: every queued background
            # publication must be on the KV plane before this process
            # beacons "gather" / completes its work-queue block — a
            # sibling recovering after that point may only be offered
            # cursors that are actually complete. Drain wall is exposed
            # loop wall, attributed to the checkpoint phase.
            with run_phases.tick("checkpoint"):
                dcn.drain_publisher()
        wall = time.perf_counter() - t0

        to_schedule = int((idx >= 0).sum())
        kube_preempt = kube_dropped = None
        kube_evict = kube_resched = kube_stranded = kube_lat = None
        sc_lat_p50 = sc_lat_p90 = sc_lat_p99 = sc_telemetry = None
        frag_stranded = frag_index = frag_pack = None
        stel = None
        if kbops is not None:
            host_k = np.stack([b.assignments for b in kbops])
            assignments = host_k if self.collect_assignments else None
            scheduled = self.pods.bound_node == PAD
            placed = (
                (host_k[:, scheduled] >= 0).sum(axis=1).astype(np.int32)
            )
            # One counter tuple per mirror (BoundaryOps.counters owns the
            # field list — result assembly and the DCN gather can't drift).
            cnt = np.asarray([b.counters() for b in kbops], np.float64)
            kube_preempt = cnt[:, 0].astype(np.int32)
            kube_dropped = cnt[:, 1].astype(np.int32)
            kube_evict = cnt[:, 2].astype(np.int32)
            kube_resched = cnt[:, 3].astype(np.int32)
            kube_stranded = cnt[:, 4].astype(np.int32)
            kube_lat = cnt[:, 5]
            # Fragmentation economics (round 13): each mirror holds the
            # scenario's committed state, its restored allocatable view
            # (hs["alloc"][s] — put back above when events ran), and the
            # still-pending set — exactly the inputs the single-replay
            # engines hand to the same helper, so the [S] gauges
            # bit-match the per-scenario kube replays.
            from ..utils.metrics import fragmentation_gauges

            frag_stranded = np.zeros(self.S, np.float64)
            frag_index = np.zeros(self.S, np.float64)
            frag_pack = np.zeros(self.S, np.float64)
            for s, b in enumerate(kbops):
                b.flush_planes()
                pend = scheduled & (host_k[s] == PAD)
                fr = fragmentation_gauges(
                    b.ec.allocatable, b.st.used,
                    self.pods.requests[pend], b.ec.vocab._r,
                )
                frag_stranded[s] = fr["stranded"].get("cpu", 0.0)
                frag_index[s] = fr["frag_index"].get("cpu", 0.0)
                frag_pack[s] = fr["packing_efficiency"]
            if self.telemetry_cfg.enabled:
                stel = [t.result() for t in ktel]
                lat_q = np.full((3, self.S), np.nan, np.float64)
                for s, t in enumerate(stel):
                    if t is not None and t.latency is not None:
                        lat_q[:, s] = (
                            t.latency["p50"],
                            t.latency["p90"],
                            t.latency["p99"],
                        )
                sc_lat_p50, sc_lat_p90, sc_lat_p99 = lat_q
                sc_telemetry = (
                    stel if self.telemetry_cfg.want_series else None
                )
        elif comp_on and self.preemption:
            # The eager eviction-aware folds ARE the walk (see the chunk
            # loop); host_assign is the result carrier. Counting device
            # finals would overcount later-evicted pods.
            assignments = host_assign if self.collect_assignments else None
            scheduled = self.pods.bound_node == PAD
            placed = (
                (host_assign[:, scheduled] >= 0).sum(axis=1).astype(np.int32)
            )
        elif self.collect_assignments and self.preemption:
            choices = np.concatenate([self._fetch(o[0]) for o in outs], axis=1)
            ev_node = np.concatenate([self._fetch(o[1]) for o in outs], axis=1)
            ev_tier = np.concatenate([self._fetch(o[2]) for o in outs], axis=1)
            from .jax_runtime import preemption_walk

            assignments = np.full((self.S, self.pods.num_pods), PAD, np.int32)
            prebound = self.pods.bound_node >= 0
            assignments[:, prebound] = self.pods.bound_node[prebound]
            for s in range(self.S):
                preemption_walk(
                    assignments[s], idx, choices[s], ev_node[s], ev_tier[s],
                    self.static3.pod_tier, self.pods.group_id == PAD,
                )
            scheduled = ~prebound
            placed = (assignments[:, scheduled] >= 0).sum(axis=1).astype(np.int32)
        elif self.collect_assignments:
            choices = np.concatenate(
                [self._fetch(o) for o in outs], axis=1
            )  # [S, Cw, W]
            flat_idx = idx.reshape(-1)
            valid = flat_idx >= 0
            assignments = np.full((self.S, self.pods.num_pods), PAD, np.int32)
            assignments[:, self.pods.bound_node >= 0] = self.pods.bound_node[
                self.pods.bound_node >= 0
            ]
            flat_choice = choices.reshape(self.S, -1)
            assignments[:, flat_idx[valid]] = flat_choice[:, valid]
            if self._fork_choices is not None:
                # Pre-fork placements are common to every scenario.
                pidx = self.waves.idx[: self._fork_waves_done].reshape(-1)
                pch = self._fork_choices.reshape(-1)
                pv = pidx >= 0
                assignments[:, pidx[pv]] = pch[pv][None, :]
            placed = (flat_choice[:, valid] >= 0).sum(axis=1).astype(np.int32)
        else:
            assignments = None
            if self._need_choices:
                # Completions forced per-pod choices; count from them.
                choices = np.concatenate([self._fetch(o) for o in outs], axis=1)
                flat_idx = idx.reshape(-1)
                valid = flat_idx >= 0
                placed = (
                    (choices.reshape(self.S, -1)[:, valid] >= 0)
                    .sum(axis=1)
                    .astype(np.int32)
                )
            elif self.retry_buffer:
                # (counts [S, C], retry_placed [S]) per chunk: placements
                # from arrival waves plus boundary retry passes.
                placed = self._fetch(
                    jax.jit(
                        lambda o: (
                            jnp.concatenate(
                                [c for c, _ in o], axis=1
                            ).sum(axis=1, dtype=jnp.int32)
                            + jnp.stack([r for _, r in o], axis=1).sum(
                                axis=1, dtype=jnp.int32
                            )
                        )
                    )(outs)
                ).astype(np.int32)
            else:
                # Device-side reduce, ONE small D2H: per-array np.asarray
                # round-trips through the tunneled device add seconds.
                placed = self._fetch(
                    jax.jit(
                        lambda o: jnp.concatenate(o, axis=1).sum(
                            axis=1, dtype=jnp.int32
                        )
                    )(outs)
                ).astype(np.int32)

        util = None
        ri = self.ec.vocab._r.get("cpu")
        if ri is not None:
            v3_layout = self.engine == "v3"

            def _util(used, alloc):
                a = alloc[:, :, ri]  # [S, N]
                u_row = used[:, ri, :] if v3_layout else used[:, :, ri]
                u = jnp.where(a > 0, u_row / jnp.where(a > 0, a, 1.0), 0.0)
                return u.mean(axis=1)

            # [S] floats instead of the full [S, R, N] used plane D2H
            # (11.7s through the tunnel at the north-star shape).
            util = self._fetch(
                jax.jit(_util)(states.used, self.sset.dc.allocatable)
            )
        dropped = kube_dropped
        if dropped is None and dev_rel and self.retry_buffer:
            # The device retry path counts overflow drops in-scan now
            # (round 6): every drop-capable engine reports them.
            dropped = np.asarray(self._fetch(rdrop_d)).astype(np.int32)
        # This process's partial fleet telemetry (round 12): per-scenario
        # collectors merged same-process (phases key-wise summed would be
        # wrong here — the fleet view wants the ENGINE's wall clocks, so
        # they are overwritten below), shipped through the one gather.
        fleet_local = None
        if self.telemetry_cfg.enabled:
            fleet_local = (
                ReplayTelemetry.merge(stel) if stel is not None else None
            )
            if fleet_local is None:
                fleet_local = ReplayTelemetry(
                    granularity=self.telemetry_cfg.granularity
                )
            fleet_local.phases = run_phases.summary()
            # DCN checkpoint-publication attribution (round 16): the
            # cumulative encode+push wall, publication count and encoded
            # MiB ride the fleet phase map (merged under this pid's
            # namespace). Only present when this process actually
            # published — single-process runs keep the pinned phase set.
            _ps = dcn.publish_stats()
            if _ps["count"] > _ps_start["count"]:
                fleet_local.phases["ckpt_publish"] = round(
                    _ps["wall_s"] - _ps_start["wall_s"], 6
                )
                fleet_local.phases["ckpt_publish_count"] = float(
                    _ps["count"] - _ps_start["count"]
                )
                fleet_local.phases["ckpt_publish_mib"] = round(
                    (_ps["bytes"] - _ps_start["bytes"]) / 2**20, 3
                )
            # Background-publisher attribution (round 19): submissions,
            # newest-wins coalesces and drain wall — with the publisher
            # on, ``ckpt_publish`` above is HIDDEN (worker-thread) wall
            # and the drain wait is the only exposed remainder. Only
            # present when the publisher actually ran, so overlap-off
            # and single-process runs keep the pinned phase set.
            _bg = dcn.bg_publish_stats()
            if _bg["submitted"] > _bg_start["submitted"]:
                fleet_local.phases["ckpt_publish_bg_submitted"] = float(
                    _bg["submitted"] - _bg_start["submitted"]
                )
                fleet_local.phases["ckpt_publish_bg_coalesced"] = float(
                    _bg["coalesced"] - _bg_start["coalesced"]
                )
                fleet_local.phases["ckpt_publish_drain_s"] = round(
                    _bg["drain_wait_s"] - _bg_start["drain_wait_s"], 6
                )
            # Faultline attribution (round 17): KV retries burned and CRC
            # fallbacks taken during THIS run ride the same phase map,
            # again only when nonzero — clean runs keep the pinned phase
            # set byte-identical to pre-round-17.
            _rs = dcn.retry_stats()
            if (
                _rs["retries"] > _rs_start["retries"]
                or _rs["giveups"] > _rs_start["giveups"]
            ):
                fleet_local.phases["kv_retry"] = round(
                    _rs["backoff_s"] - _rs_start["backoff_s"], 6
                )
                fleet_local.phases["kv_retry_count"] = float(
                    _rs["retries"] - _rs_start["retries"]
                )
                fleet_local.phases["kv_retry_giveups"] = float(
                    _rs["giveups"] - _rs_start["giveups"]
                )
            _cs = dcn.crc_stats()
            if _cs["fallbacks"] > _cs_start["fallbacks"]:
                fleet_local.phases["ckpt_crc_fallback_count"] = float(
                    _cs["fallbacks"] - _cs_start["fallbacks"]
                )
            if self._dcn_wq_info is not None:
                # Work-queue provenance (round 18): which block this
                # engine executed, at which lease generation, and whether
                # it was a speculative re-execution — the telemetry trail
                # the straggler tests pin.
                fleet_local.phases["wq_block"] = float(
                    self._dcn_wq_info.get("block", -1)
                )
                fleet_local.phases["wq_gen"] = float(
                    self._dcn_recovery.get("gen", 0)
                )
                if self._dcn_wq_info.get("speculative"):
                    fleet_local.phases["wq_spec"] = 1.0
            elif self._dcn_recovery is not None:
                # Claim-generation fencing (round 17): which claim
                # attempt produced this block, and for whom. gen > 0
                # marks a hand-off after a claimant death mid-recovery.
                fleet_local.phases["recovery_gen"] = float(
                    self._dcn_recovery.get("gen", 0)
                )
                fleet_local.phases["recovery_for"] = float(
                    self._dcn_recovery.get("for_pid", -1)
                )
        fleet_tel = None
        # ---- THE end-of-replay gather (round 11, parallel.dcn) ----
        # The one point per replay where processes exchange data: every
        # per-scenario result array is concatenated across the contiguous
        # per-process blocks, in process order — bit-identical to what the
        # single-process mesh run assembles. Everything above this line
        # (the whole chunk loop, the boundary passes, the result fetches)
        # was process-local.
        process_count = 1
        if self._dcn_sliced:
            if hb_on:
                # Final beacon before blocking in the gather: siblings'
                # attributed-timeout diagnostics see "state=gather" rather
                # than a stale mid-replay chunk.
                dcn.heartbeat(
                    n_chunks - 1,
                    total=n_chunks,
                    block=hb_block,
                    wall_s=wall,
                    phases=run_phases.acc,
                    state="gather",
                    # Fleet utilization gauge (round 13): this process's
                    # mean CPU utilization over its local scenario block —
                    # already computed above, so the beacon stays free of
                    # extra D2H. dcn_launch --watch renders it next to
                    # the live-buffer gauge.
                    extra=(
                        {"util_cpu": round(float(np.mean(util)), 4)}
                        if util is not None and len(util)
                        else None
                    ),
                )
            parts = dcn.gather(
                "whatif",
                dict(
                    placed=placed,
                    assignments=assignments,
                    util=util,
                    preemptions=kube_preempt,
                    dropped=dropped,
                    evictions=kube_evict,
                    resched=kube_resched,
                    stranded=kube_stranded,
                    evict_lat=kube_lat,
                    lat50=sc_lat_p50,
                    lat90=sc_lat_p90,
                    lat99=sc_lat_p99,
                    frag_stranded=frag_stranded,
                    frag_index=frag_index,
                    frag_pack=frag_pack,
                    telemetry=sc_telemetry,
                    fleet=fleet_local,
                ),
                # Survivor rebalance (round 15): with KSIM_DCN_RECOVER on,
                # a stale sibling's block is claimed and re-executed
                # through this callback instead of failing the fleet.
                recover=(
                    self._dcn_recover_block
                    if self._dcn_rebuild is not None
                    else None
                ),
            )
            # Spare processes contribute liveness, not scenarios — their
            # sentinel parts are dropped before concatenation (worker
            # parts are the contiguous pids 0..workers-1, still in global
            # scenario order).
            parts = [
                p for p in parts
                if not (isinstance(p, dict) and p.get("spare"))
            ]

            def _cat(k):
                if parts[0][k] is None:
                    return None
                return np.concatenate([p[k] for p in parts], axis=0)

            placed = _cat("placed")
            assignments = _cat("assignments")
            util = _cat("util")
            kube_preempt = _cat("preemptions")
            dropped = _cat("dropped")
            kube_evict = _cat("evictions")
            kube_resched = _cat("resched")
            kube_stranded = _cat("stranded")
            kube_lat = _cat("evict_lat")
            sc_lat_p50 = _cat("lat50")
            sc_lat_p90 = _cat("lat90")
            sc_lat_p99 = _cat("lat99")
            frag_stranded = _cat("frag_stranded")
            frag_index = _cat("frag_index")
            frag_pack = _cat("frag_pack")
            sc_telemetry = (
                None
                if parts[0]["telemetry"] is None
                else [t for p in parts for t in p["telemetry"]]
            )
            if parts[0].get("fleet") is not None:
                # Fleet merge: phases land under "p<pid>/<phase>", the
                # aggregates are exact merges over the global scenario
                # order — bit-matching the single-process oracle. A part
                # recovered by a claimant arrives with its phases ALREADY
                # scoped "p<claimant>/..." (see _dcn_recover_block) —
                # merge passes "/"-scoped keys through unprefixed, so
                # recovered wall clock lands under the pid that spent it.
                fleet_tel = ReplayTelemetry.merge(
                    [p["fleet"] for p in parts],
                    process_ids=list(range(len(parts))),
                )
            process_count = jax.process_count()
            # Device-footprint provenance counts block-owning workers
            # only: spares ran no scenario over their devices.
            dev_scale = len(parts)
        elif fleet_local is not None:
            # Single-process runs get the SAME shape ("p0/..." phase keys)
            # so consumers never branch on process_count. A recovery
            # engine (round 15) scopes its phases under the CLAIMANT's
            # pid, keeping per-process attribution honest after a merge.
            fleet_tel = ReplayTelemetry.merge(
                [fleet_local],
                process_ids=[
                    jax.process_index()
                    if self._dcn_recovery is not None
                    else 0
                ],
            )
            dev_scale = process_count
        else:
            dev_scale = process_count
        total = int(placed.sum())
        ndev_local = int(self.mesh.devices.size) if self.mesh is not None else 1
        return WhatIfResult(
            placed=placed,
            unschedulable=(to_schedule - placed).astype(np.int32),
            total_placed=total,
            wall_clock_s=wall,
            placements_per_sec=total / wall if wall > 0 else 0.0,
            assignments=assignments,
            utilization_cpu=util,
            completions_on=self.completions_on,
            engine=self.engine,
            preemptions=kube_preempt,
            retry_dropped=dropped,
            evictions=kube_evict,
            evict_rescheduled=kube_resched,
            evict_stranded=kube_stranded,
            evict_latency_mean=kube_lat,
            latency_p50=sc_lat_p50,
            latency_p90=sc_lat_p90,
            latency_p99=sc_lat_p99,
            stranded_cpu=frag_stranded,
            frag_index_cpu=frag_index,
            packing_efficiency=frag_pack,
            scenario_telemetry=sc_telemetry,
            fleet_telemetry=fleet_tel,
            # Global footprint: worker count × local devices when the
            # scenario axis was DCN-sliced (the local mesh is one worker's
            # share of the fleet that produced the gathered result; spare
            # processes contribute no compute).
            n_devices=ndev_local * dev_scale,
            mesh_shape=(
                dict(zip(
                    self.mesh.axis_names,
                    (
                        int(d) * dev_scale
                        for d in self.mesh.devices.shape
                    ),
                ))
                if self.mesh is not None
                else None
            ),
            process_count=process_count,
        )


def uniform_scenarios(
    ec: EncodedCluster,
    num_scenarios: int,
    seed: int = 0,
    p_node_down: float = 0.02,
    p_capacity: float = 0.3,
    p_taint: float = 0.1,
) -> List[Scenario]:
    """Random cluster-state perturbation sampler (the [BASELINE] eval shape:
    vmap over cluster-state perturbations). Scenario 0 is always the
    unperturbed base for reference."""
    rng = np.random.default_rng(seed)
    out = [Scenario()]
    N = ec.num_nodes
    for _ in range(num_scenarios - 1):
        pts: List[Perturbation] = []
        if rng.random() < p_node_down:
            k = int(rng.integers(1, max(2, N // 50)))
            pts.append(Perturbation("node_down", nodes=rng.choice(N, size=k, replace=False)))
        if rng.random() < p_capacity:
            k = int(rng.integers(1, max(2, N // 10)))
            pts.append(
                Perturbation(
                    "scale_capacity",
                    nodes=rng.choice(N, size=k, replace=False),
                    resource="cpu",
                    factor=float(rng.choice([0.5, 0.75, 1.25, 1.5])),
                )
            )
        if rng.random() < p_taint:
            k = int(rng.integers(1, max(2, N // 20)))
            pts.append(
                Perturbation(
                    "add_taint",
                    nodes=rng.choice(N, size=k, replace=False),
                    key="whatif/injected",
                    value="true",
                    effect="NoSchedule",
                )
            )
        out.append(Scenario(pts))
    return out
