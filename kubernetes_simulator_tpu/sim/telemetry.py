"""Simulation telemetry — layer L7 (SURVEY.md §5).

Cross-engine observability signals collected DURING replay and reduced to
compact summaries on ``ReplayResult``/``WhatIfResult``:

* **Per-pod scheduling latency** — arrival → *first* bind in virtual time.
  The CPU event engine records exact event-clock latencies; the device path
  is chunk-granular (wave-placed pods bind in their arrival wave ⇒ latency
  0, boundary-retry binds record ``t_boundary − arrival``). Both engines
  reduce through :func:`latency_summary`, so at W=1/C=1 on
  boundary-cadence-aligned traces the histograms bit-match.

* **Filter-rejection attribution** — kube-style "0/N nodes available"
  breakdown: for each fully-failed scheduling attempt, every node is
  charged to the FIRST plugin (in Filter order) that rejected it. Two
  counters are kept:

  - ``reasons`` — per *unschedulable episode*: counted once when a pod
    first goes unschedulable (and again only after an eviction starts a
    new episode). Invariant to retry cadence, so it bit-matches across
    engines wherever placements do.
  - ``rejection_attempts`` — accumulated across every failed attempt.
    Engine-cadence-dependent (the CPU queue uses exponential backoff, the
    device path retries at chunk boundaries); bit-matches only on traces
    whose retry instants coincide.

* **Virtual-time series** (``series`` granularity) — queue/retry-buffer
  depth sampled at event instants (CPU) or chunk boundaries (device).

* **Wall-clock phase breakdown** — perf-counter timers over dispatch /
  device step / boundary fold / host mirror, attached at every
  granularity except ``off``.

* **Timeline events** (``timeline`` granularity) — bind / preempt / evict
  / node_down / node_up instants in virtual time, exportable as a Chrome
  trace (Perfetto-loadable) via :func:`write_chrome_trace`.

Granularity knob (``telemetry:`` YAML section, ``TelemetryConfig``):

    off      — collect nothing, ``ReplayResult.telemetry`` is None.
    summary  — latency histogram + phase timers. Never changes a device
               program: the plain scan stays byte-identical (bench-safe).
    series   — + rejection attribution + virtual-time series. On the
               plain device path this swaps in an instrumented chunk
               program carrying in-scan per-plugin reject counters.
    timeline — + timeline events + Chrome-trace export.

Checkpoint note: telemetry state is deliberately EXCLUDED from boundary
checkpoint blobs — blobs stay bit-identical with telemetry on or off.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Fixed exponential bucket edges (virtual seconds), kube-histogram style.
# The overflow bucket is implicit (label "+Inf").
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0,
)

_LEVELS = ("off", "summary", "series", "timeline")


@dataclass(frozen=True)
class TelemetryConfig:
    granularity: str = "summary"

    def __post_init__(self):
        if self.granularity not in _LEVELS:
            raise ValueError(
                f"telemetry granularity {self.granularity!r} must be one of "
                f"{', '.join(_LEVELS)}"
            )

    @classmethod
    def resolve(cls, v) -> "TelemetryConfig":
        """None → default (summary); str → validated; config → itself."""
        if v is None:
            return cls()
        if isinstance(v, cls):
            return v
        return cls(granularity=str(v))

    @property
    def enabled(self) -> bool:
        return self.granularity != "off"

    @property
    def want_series(self) -> bool:
        return _LEVELS.index(self.granularity) >= 2

    @property
    def want_timeline(self) -> bool:
        return _LEVELS.index(self.granularity) >= 3


def latency_summary(
    zero_count: int, values: Sequence[float]
) -> Optional[dict]:
    """Reduce first-bind latencies (``zero_count`` exact zeros + explicit
    ``values``) to count/mean/p50/p90/p99 plus fixed-bucket cumulative
    counts. Shared by BOTH engines — quantiles use ``np.percentile``
    with ``method='lower'`` (an exact data value), so engines that record
    the same latency multiset produce bit-identical summaries."""
    vals = np.asarray(list(values), dtype=np.float64)
    n = int(zero_count) + vals.size
    if n == 0:
        return None
    arr = np.concatenate([np.zeros(int(zero_count), dtype=np.float64), vals])
    arr.sort()
    buckets: Dict[str, int] = {}
    # Cumulative "le" buckets (kube-style); searchsorted on the sorted array.
    idx = np.searchsorted(arr, np.asarray(LATENCY_BUCKETS), side="right")
    for edge, c in zip(LATENCY_BUCKETS, idx):
        buckets[f"le_{edge:g}"] = int(c)
    buckets["le_inf"] = n
    p50, p90, p99 = (
        float(np.percentile(arr, q, method="lower")) for q in (50, 90, 99)
    )
    return {
        "count": n,
        "mean": float(arr.mean()),
        "max": float(arr[-1]),
        "p50": p50,
        "p90": p90,
        "p99": p99,
        "buckets": buckets,
    }


# Canonical phase-timer names instrumented by the replay engines. Scripts
# (scripts/northstar.py, bench consumers) key on these strings when
# attributing wall-clock, so they are API: renaming one is a breaking
# change pinned by tests/test_telemetry.py.
PHASE_NAMES = ("dispatch", "device_wait", "boundary_fold", "host_mirror")


class PhaseTimers:
    """Accumulating wall-clock phase breakdown. ``tick(phase)`` returns a
    context manager; overhead is two ``perf_counter`` calls per use, so it
    is safe at chunk cadence (never per pod)."""

    def __init__(self):
        self.acc: Dict[str, float] = {}

    class _Tick:
        __slots__ = ("timers", "phase", "t0")

        def __init__(self, timers: "PhaseTimers", phase: str):
            self.timers = timers
            self.phase = phase

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.timers.add(self.phase, time.perf_counter() - self.t0)
            return False

    def tick(self, phase: str) -> "_Tick":
        return PhaseTimers._Tick(self, phase)

    def add(self, phase: str, dt: float) -> None:
        self.acc[phase] = self.acc.get(phase, 0.0) + dt

    def summary(self) -> Dict[str, float]:
        return {k: round(v, 6) for k, v in sorted(self.acc.items())}


@dataclass
class ReplayTelemetry:
    """Telemetry attached to ``ReplayResult.telemetry`` (None at ``off``).

    Leaves are plain picklable data (dicts/lists/ints/floats) end to
    end, NEVER device arrays — round 11 ships per-scenario instances
    through the host-side DCN gather (parallel.dcn.gather) at what-if
    result assembly, and the single-process oracle must see identical
    objects after the pickle round-trip (pinned in tests/test_dcn.py)."""

    granularity: str
    # Latency histogram (see latency_summary); None when nothing bound.
    latency: Optional[dict] = None
    # Per-episode first-reject counts by plugin name ("unschedulable
    # reasons" — each sums to num_nodes per episode).
    reasons: Optional[Dict[str, int]] = None
    # Per-attempt first-reject counts (cadence-dependent; >= reasons).
    rejection_attempts: Optional[Dict[str, int]] = None
    # Virtual-time series: {"t": [...], "<depth name>": [...], ...}.
    series: Optional[Dict[str, List[float]]] = None
    # Wall-clock phase accumulators (seconds).
    phases: Dict[str, float] = field(default_factory=dict)
    # Raw first-bind latencies for pods that did NOT bind in their arrival
    # instant/wave (pod → virtual seconds) + count of exact-zero binds.
    # Kept for the timeline exporter and tests; not in summary().
    bind_latency: Dict[int, float] = field(default_factory=dict)
    zero_latency_binds: int = 0
    # Timeline events: (kind, t, pod, node) with pod/node = -1 when n/a.
    events: List[Tuple[str, float, int, int]] = field(default_factory=list)

    def summary(self) -> dict:
        out: dict = {"granularity": self.granularity, "phases": self.phases}
        if self.latency is not None:
            out["latency"] = self.latency
        if self.reasons is not None:
            out["reasons"] = dict(self.reasons)
            out["rejection_attempts"] = dict(self.rejection_attempts or {})
        if self.series is not None:
            out["series_samples"] = len(self.series.get("t", ()))
        if self.events:
            out["timeline_events"] = len(self.events)
        return out

    def query_view(self) -> dict:
        """JSON-ready per-scenario view for serving-plane query-result
        rows (round 22, sim.service): :meth:`summary` plus the raw
        virtual-time series. Phase timers are dropped — the wall clocks
        of a shared batch replay belong to the batch, not to any one
        tenant's query. Series values are virtual-time-deterministic,
        so a batched query's view bit-matches its sequential oracle's
        (the round-15 batch-composition-independence bar)."""
        out = self.summary()
        out.pop("phases", None)
        if self.series is not None:
            out["series"] = {
                k: [float(v) for v in vs] for k, vs in self.series.items()
            }
        return out

    @classmethod
    def merge(
        cls,
        parts: Sequence[Optional["ReplayTelemetry"]],
        process_ids: Optional[Sequence[int]] = None,
    ) -> Optional["ReplayTelemetry"]:
        """Merge telemetries over disjoint pod/scenario populations into
        one fleet view (round 12). The merge is EXACT, order-normalized
        and associative where the semantics allow:

        * latency — recomputed by :func:`latency_summary` over the union
          of raw first-bind latencies (the summary sorts before every
          reduction), so a 2-process merge bit-matches the single-process
          oracle over the same multiset;
        * ``reasons`` / ``rejection_attempts`` — key-wise integer sums
          (None only when absent from every part);
        * ``series`` / ``events`` — concatenated in part order (parts
          arrive in process order off the DCN gather, which is global
          scenario order);
        * ``phases`` — wall clocks of different hosts never sum
          meaningfully, so with ``process_ids`` given (one per part,
          aligned) part *i*'s timers land under ``p<pid>/<phase>`` and
          stay distinct; without, parts are same-process and timers are
          key-wise summed. Keys already containing ``/`` are assumed
          scoped and pass through (re-merging a merge never
          double-prefixes).

        Raw ``bind_latency`` values are re-keyed by running index: merged
        parts span scenarios, so original pod ids collide and are not
        preserved. ``None`` parts (telemetry off) are skipped; returns
        None when nothing remains.

        Elastic recovery (round 15) keeps this merge byte-stable: a
        survivor that claims a dead process's block republishes that
        block's telemetry under the DEAD pid's gather slot, so parts
        still arrive one per scenario block in global scenario order and
        the result-bearing fields (latency/reasons/series/events)
        bit-match the no-failure fleet. Only the ``p<pid>/<phase>``
        timers are attributed to the block's pid while having been
        *measured* on the claimant's host — wall clocks are
        host-relative either way and are never compared across parts."""
        if process_ids is not None and len(process_ids) != len(parts):
            raise ValueError(
                f"process_ids ({len(process_ids)}) must align 1:1 with "
                f"parts ({len(parts)})"
            )
        keep = [(i, p) for i, p in enumerate(parts) if p is not None]
        if not keep:
            return None
        gran = keep[0][1].granularity
        for _, p in keep:
            if p.granularity != gran:
                raise ValueError(
                    "cannot merge telemetries of different granularity: "
                    f"{p.granularity!r} vs {gran!r}"
                )
        zero = sum(int(p.zero_latency_binds) for _, p in keep)
        vals: List[float] = []
        for _, p in keep:
            vals.extend(float(v) for v in p.bind_latency.values())

        def _sum_counters(attr: str) -> Optional[Dict[str, int]]:
            present = [
                getattr(p, attr) for _, p in keep
                if getattr(p, attr) is not None
            ]
            if not present:
                return None
            out: Dict[str, int] = {}
            for d in present:
                for k, v in d.items():
                    out[k] = out.get(k, 0) + int(v)
            return out

        series: Optional[Dict[str, List[float]]] = None
        if any(p.series is not None for _, p in keep):
            series = {}
            for _, p in keep:
                for k, v in (p.series or {}).items():
                    series.setdefault(k, []).extend(v)
        phases: Dict[str, float] = {}
        for i, p in keep:
            prefix = (
                "" if process_ids is None else f"p{process_ids[i]}/"
            )
            for k, v in p.phases.items():
                key = k if "/" in k else f"{prefix}{k}"
                phases[key] = round(phases.get(key, 0.0) + float(v), 6)
        tel = cls(
            granularity=gran,
            latency=latency_summary(zero, vals),
            phases=phases,
            bind_latency={i: v for i, v in enumerate(vals)},
            zero_latency_binds=zero,
            events=[e for _, p in keep for e in p.events],
        )
        tel.reasons = _sum_counters("reasons")
        tel.rejection_attempts = _sum_counters("rejection_attempts")
        tel.series = series
        return tel


class TelemetryCollector:
    """Mutable per-replay accumulator. Engines call the record hooks (all
    cheap, most gated behind granularity properties); :meth:`result`
    freezes into a :class:`ReplayTelemetry`.

    Episode semantics for rejection attribution: a pod is *attributed*
    after its first fully-failed attempt is charged to ``reasons``;
    further failed attempts only grow ``rejection_attempts`` until a bind
    or an eviction (``clear_episode``) re-arms it."""

    def __init__(self, config: Optional[TelemetryConfig] = None):
        self.cfg = TelemetryConfig.resolve(config)
        self.phases = PhaseTimers()
        self._lat: Dict[int, float] = {}
        self._zero = 0
        self._reasons: Dict[str, int] = {}
        self._attempts: Dict[str, int] = {}
        self._attributed: set = set()
        self._series: Dict[str, List[float]] = {}
        self._events: List[Tuple[str, float, int, int]] = []

    # -- latency ----------------------------------------------------------

    def bind_zero(self, n: int = 1) -> None:
        """n pods bound at their arrival instant/wave (latency exactly 0)."""
        self._zero += int(n)

    def bind_latency(self, pod: int, lat: float) -> None:
        """First bind of ``pod`` at ``lat`` virtual seconds after arrival.
        Caller guarantees first-bind (re-binds after eviction/preemption
        must not re-record)."""
        self._lat[int(pod)] = float(lat)

    # -- rejection attribution -------------------------------------------

    def rejection(self, pod: int, counts: Dict[str, int]) -> None:
        """One fully-failed scheduling attempt for ``pod`` with first-reject
        ``counts`` by plugin name."""
        for k, v in counts.items():
            self._attempts[k] = self._attempts.get(k, 0) + int(v)
        if pod not in self._attributed:
            self._attributed.add(pod)
            for k, v in counts.items():
                self._reasons[k] = self._reasons.get(k, 0) + int(v)

    def rejection_bulk(self, names: Sequence[str], vec) -> None:
        """In-scan device counters: [K] totals in plugin order. On the plain
        path every failure is both terminal and a fresh episode, so the
        vector feeds both counters."""
        for k, v in zip(names, np.asarray(vec).tolist()):
            if v:
                self._attempts[k] = self._attempts.get(k, 0) + int(v)
                self._reasons[k] = self._reasons.get(k, 0) + int(v)

    def clear_episode(self, pod: int) -> None:
        """A bind or an eviction ends the pod's unschedulable episode."""
        self._attributed.discard(int(pod))

    def is_attributed(self, pod: int) -> bool:
        return int(pod) in self._attributed

    def mark_attributed(self, pod: int) -> None:
        """Pod already charged to ``reasons`` elsewhere (e.g. the in-scan
        failure that routed it into the retry buffer)."""
        self._attributed.add(int(pod))

    # -- series / timeline ------------------------------------------------

    def sample(self, t: float, **depths: float) -> None:
        self._series.setdefault("t", []).append(float(t))
        for k, v in depths.items():
            self._series.setdefault(k, []).append(float(v))

    def event(self, kind: str, t: float, pod: int = -1, node: int = -1) -> None:
        self._events.append((kind, float(t), int(pod), int(node)))

    # -- finalize ---------------------------------------------------------

    def result(self) -> Optional[ReplayTelemetry]:
        if not self.cfg.enabled:
            return None
        tel = ReplayTelemetry(
            granularity=self.cfg.granularity,
            latency=latency_summary(self._zero, list(self._lat.values())),
            phases=self.phases.summary(),
            bind_latency=dict(self._lat),
            zero_latency_binds=self._zero,
        )
        if self.cfg.want_series:
            # Zero entries are dropped so engine comparisons see the same
            # dict regardless of which plugins happened to run (the CPU
            # Filter chain short-circuits; the device one does not).
            tel.reasons = {k: v for k, v in self._reasons.items() if v}
            tel.rejection_attempts = {
                k: v for k, v in self._attempts.items() if v
            }
            tel.series = {k: list(v) for k, v in self._series.items()}
        if self.cfg.want_timeline:
            tel.events = list(self._events)
        return tel


def first_reject_counts_host(
    plugins, ctx, st, p: int, num_nodes: int
) -> Tuple[np.ndarray, Dict[str, int]]:
    """Host-side first-reject attribution: run the Filter chain charging
    each node to the first plugin that rejects it. Returns (final mask,
    counts). Counting mirrors ``SchedulerFramework.feasible_mask``'s
    short-circuit exactly — once the running mask is empty every later
    plugin rejects 0 additional nodes, so stopping early is lossless."""
    mask = np.ones(num_nodes, dtype=bool)
    counts: Dict[str, int] = {}
    for pl in plugins:
        counts[pl.name] = 0
        m = pl.filter(ctx, st, p)
        if m is not None:
            counts[pl.name] = int((mask & ~m).sum())
            mask &= m
    return mask, counts


# -- Chrome-trace (Perfetto) export --------------------------------------


def _trace_events(
    res,
    arrival: Optional[np.ndarray] = None,
    duration: Optional[np.ndarray] = None,
    process_id: Optional[int] = None,
    requests: Optional[np.ndarray] = None,
    rindex: Optional[Dict[str, int]] = None,
) -> List[dict]:
    """Trace events for ONE result. With ``process_id`` None (the
    single-process export) pids are 0 ("cluster") / 1 ("chaos") exactly
    as before round 12; with ``process_id`` p, the pair becomes one track
    GROUP per process — pids 2p / 2p+1 named "cluster (p<p>)" /
    "chaos (p<p>)" — so merged fleet traces render side by side in one
    Perfetto timeline."""
    tel = getattr(res, "telemetry", None)
    assignments = np.asarray(res.assignments)
    makespan = float(getattr(res, "virtual_makespan", 0.0))
    p_ = process_id
    pid_cluster = 0 if p_ is None else 2 * int(p_)
    pid_chaos = pid_cluster + 1
    suffix = "" if p_ is None else f" (p{int(p_)})"
    ev: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid_cluster,
         "args": {"name": f"cluster{suffix}"}},
        {"name": "process_name", "ph": "M", "pid": pid_chaos,
         "args": {"name": f"chaos{suffix}"}},
    ]
    used_nodes = sorted({int(n) for n in assignments if n >= 0})
    for n in used_nodes:
        ev.append({"name": "thread_name", "ph": "M", "pid": pid_cluster,
                   "tid": n, "args": {"name": f"node{n}"}})
    lat = tel.bind_latency if tel is not None else {}
    spans: List[tuple] = []  # (pod, node, start, end) — spans + counters
    if arrival is not None:
        placed = np.nonzero(assignments >= 0)[0]
        for p in placed.tolist():
            start = float(arrival[p]) + float(lat.get(p, 0.0))
            end = makespan
            if duration is not None and np.isfinite(duration[p]):
                end = min(end, start + float(duration[p]))
            spans.append((p, int(assignments[p]), start, end))
            ev.append({
                "name": f"pod{p}", "ph": "X", "pid": pid_cluster,
                "tid": int(assignments[p]),
                "ts": start * 1e6, "dur": max(end - start, 0.0) * 1e6,
            })
    if requests is not None and rindex is not None and spans:
        # Per-node utilization counter tracks (round 13): the pod spans
        # above double as change-points of a running cpu/mem usage sum,
        # emitted as Chrome "C" counter events — Perfetto renders one
        # stacked-area track per node next to its span row.
        req = np.asarray(requests, dtype=np.float64)
        cols = [
            (rn, ri) for rn, ri in sorted(rindex.items(), key=lambda kv: kv[1])
            if rn in ("cpu", "memory")
        ]
        deltas: Dict[int, Dict[float, np.ndarray]] = {}
        for p, n, start, end in spans:
            d = deltas.setdefault(n, {})
            r = req[p, [ri for _, ri in cols]]
            d[start] = d.get(start, 0.0) + r
            d[end] = d.get(end, 0.0) - r
        for n in sorted(deltas):
            run = np.zeros(len(cols), dtype=np.float64)
            for t in sorted(deltas[n]):
                run = run + deltas[n][t]
                ev.append({
                    "name": f"node{n} usage", "ph": "C", "pid": pid_cluster,
                    "tid": n, "ts": t * 1e6,
                    "args": {
                        rn: round(float(run[k]), 6)
                        for k, (rn, _) in enumerate(cols)
                    },
                })
    down_at: Dict[int, float] = {}
    for kind, t, pod, node in (tel.events if tel is not None else ()):
        if kind == "node_down":
            down_at[node] = t
        elif kind == "node_up":
            t0 = down_at.pop(node, t)
            ev.append({"name": f"node{node} down", "ph": "X",
                       "pid": pid_chaos, "tid": node, "ts": t0 * 1e6,
                       "dur": max(t - t0, 0.0) * 1e6})
        else:
            ev.append({
                "name": kind, "ph": "i", "s": "t", "pid": pid_cluster,
                "tid": node if node >= 0 else 0, "ts": t * 1e6,
                "args": ({"pod": pod} if pod >= 0 else {}),
            })
    for node, t0 in sorted(down_at.items()):
        # Unrecovered failure: span runs to the makespan.
        ev.append({"name": f"node{node} down", "ph": "X", "pid": pid_chaos,
                   "tid": node, "ts": t0 * 1e6,
                   "dur": max(makespan - t0, 0.0) * 1e6})
    return ev


def write_chrome_trace(
    path: str,
    res,
    arrival: Optional[np.ndarray] = None,
    duration: Optional[np.ndarray] = None,
    process_id: Optional[int] = None,
    requests: Optional[np.ndarray] = None,
    rindex: Optional[Dict[str, int]] = None,
) -> int:
    """Export the SIMULATED cluster timeline as a Chrome trace JSON
    (load in Perfetto / chrome://tracing). Virtual seconds map to trace
    microseconds. Rows (tids) are nodes under the "cluster" process;
    chaos node_down→node_up windows render as spans under "chaos".

    Pod spans are drawn from each pod's FIRST bind (arrival + recorded
    latency) to its completion (or the makespan); disruptions (preempt /
    evict / boundary re-binds) appear as instant events on the node row.
    ``process_id`` scopes the track group for multi-process exports (see
    :func:`_trace_events`); the default keeps the round-7 pid 0/1 layout.
    ``requests`` ([P, R] pod requests) + ``rindex`` (resource → column)
    additionally emit per-node cpu/mem usage counter tracks (round 13).
    Returns the number of trace events written."""
    ev = _trace_events(
        res, arrival, duration, process_id, requests=requests, rindex=rindex
    )
    with open(path, "w") as f:
        json.dump({"traceEvents": ev, "displayTimeUnit": "ms"}, f)
    return len(ev)


def write_chrome_trace_merged(
    path: str,
    parts: Sequence[tuple],
    rindex: Optional[Dict[str, int]] = None,
) -> int:
    """Merge per-process timelines into ONE Chrome trace (round 12): each
    element of ``parts`` is ``(res, arrival, duration)`` — or, round 13,
    ``(res, arrival, duration, requests)`` to add that process's per-node
    usage counter tracks (``rindex`` maps resource → request column; the
    fleet shares one vocabulary) — in process order, and process *i*'s
    events land in its own track group ("cluster (pi)" / "chaos (pi)"),
    so a 2-process DCN replay renders as a single Perfetto timeline.
    Returns the number of trace events written."""
    ev: List[dict] = []
    for i, part in enumerate(parts):
        res, arrival, duration = part[0], part[1], part[2]
        requests = part[3] if len(part) > 3 else None
        ev.extend(_trace_events(
            res, arrival, duration, process_id=i,
            requests=requests, rindex=rindex,
        ))
    with open(path, "w") as f:
        json.dump({"traceEvents": ev, "displayTimeUnit": "ms"}, f)
    return len(ev)
