"""Shared chunk-boundary semantics for the greedy anchor and the device
replay engine (SURVEY.md §2 L3/L4; VERDICT r4 next #1/#3).

A "boundary" is the host synchronization point between device chunks —
the same point where chunk-granular completions already apply. Three
passes run there, in order:

1. **Pending releases** — boundary-placed pods (retried/preempted binds)
   whose scheduled release boundary has arrived free their contributions.
2. **Static releases** — arrival-placed pods whose ``arrival + duration``
   is at or before the boundary's start time, bound in chunks ≤ b−2 (the
   one-chunk slack shared with the device pipeline).
3. **Bounded retry / preemption pass** — the [K8S] activeQ analogue:
   failed non-gang pods retry placement FIFO; under ``kube=True`` a pod
   that still fails runs the EXACT kube PostFilter
   (``SchedulerFramework._post_filter_preempt``: fewest victims, lowest
   max victim priority, only the victims needed for THIS pod's fit,
   lowest-priority-first eviction order) — victims are unbound with a
   full count rewind (no phantom counts) and re-enter the queue, exactly
   as the CPU event engine requeues them.

The class owns the host bookkeeping (a live :class:`SchedState` mirror,
assignments, counters). ``greedy_replay`` drives it slot-by-slot;
``JaxReplayEngine`` folds whole device chunks into it and applies the
returned (release, bind, evict) lists to the device carry as rank-1
plane deltas through the existing release machinery — the kube
preemption algorithm itself never enters the compiled program. That is
the TPU-first shape of this feature: preemption is a rare, branchy,
data-dependent search (victim prefixes over per-node sorted pod lists)
that would poison the fused wave scan, but it only ever needs to run for
the handful of pods that failed placement — so it runs on host at the
sync points the engine already pays for, with the device program
unchanged and the decision arithmetic bit-identical to the CPU engine's
by construction (it IS the CPU engine's PostFilter).

Fidelity is chunk-granular: a pod preempts at the first boundary after
its failed chunk, not at its failure instant. At ``wave_width=1,
chunk_waves=1`` the boundary follows every pod and placements match
``CpuReplayEngine(enable_preemption=True)`` exactly on queue-trivial
traces (tests/test_kube_preempt.py); at production chunk sizes the
divergence is a measured, pinned number — the same contract as
completions (tests/test_divergence_pin.py).

Node sharding (round 14): the boundary pass is sharding-agnostic by
construction. The mirror lives in HOST layout over the real node count
(never the shard padding), the device choices it folds are GLOBAL node
ids (ops.tpu.select_node_sharded reduces shard-local winners to the
global argmax before anything leaves the chunk program), and the
(release, bind, evict) lists it returns land on the sharded carry
through the same pad-and-shard transform as every other host delta
(JaxReplayEngine._to_dev_state_v2). Nothing here branches on the shard
count — which is what keeps checkpoint blobs and JSONL byte-identical
across node_shards ∈ {1, 2, 4} (tests/test_node_sharding.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework.framework import SchedulerFramework
from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import bind, init_state, release_delta, unbind
from .waves import WaveBatch

# (pods, nodes) int arrays collected for device delta application.
PairArrays = Tuple[np.ndarray, np.ndarray]

_NEVER = 1 << 30  # bind_chunk sentinel: never statically released


def _empty_pairs() -> PairArrays:
    return np.zeros(0, np.int64), np.zeros(0, np.int64)


class BoundaryOps:
    """Host bookkeeping + boundary passes shared by the greedy anchor and
    the device engine. All semantics here are THE semantics — the two
    callers must only disagree in how placements inside a chunk are
    produced (slot loop vs compiled wave scan), which the existing
    greedy↔device parity suites pin."""

    def __init__(
        self,
        ec: EncodedCluster,
        ep: EncodedPods,
        fw: SchedulerFramework,
        waves: WaveBatch,
        wave_width: int,
        chunk_waves: int,
        retry_buffer: int = 0,
        kube: bool = False,
        lazy: bool = False,
        telemetry=None,
    ):
        if kube and not retry_buffer:
            raise ValueError(
                "preemption='kube' requires retry_buffer > 0 (failed pods "
                "reach the PostFilter through the boundary retry pass)"
            )
        self.ec, self.ep, self.fw = ec, ep, fw
        self.kube = kube
        # Lazy mode (device engines only): plane folds are appended to an
        # op log instead of applied; the log flushes — in eager order —
        # only when the retry pass actually needs to READ the planes
        # (``schedule_one``). The greedy anchor reads planes every slot and
        # must stay eager. Bookkeeping (bound/assignments/bind_chunk/
        # queues/counters) is ALWAYS eager, so checkpoint blobs are
        # bit-identical across modes.
        self.lazy = lazy
        self.wave_width = wave_width
        self.chunk_waves = chunk_waves
        # Telemetry (sim.telemetry.TelemetryCollector | None). The mirror
        # records boundary-granular signals: retry-bind latency
        # (t_boundary − arrival, first binds only), first-reject
        # attribution for failed slots/retries, retry/pend depth series,
        # and timeline events. Telemetry state is deliberately NOT part of
        # to_blob()/restore() — checkpoint blobs stay bit-identical with
        # telemetry on or off.
        self.tel = telemetry
        self._ever_bound: Optional[np.ndarray] = (
            (ep.bound_node >= 0).copy() if telemetry is not None else None
        )
        self._last_finite_t = 0.0
        self._plane_log: List[tuple] = []  # (key, sign, pods, nodes)
        self.plane_folds = 0  # applied plane deltas (test/bench probe)
        if retry_buffer:
            # Wave-multiple rounding shared with the device retry pass
            # (sim.whatif) — the caps must agree or placed counts diverge
            # once a buffer fills past the raw capacity.
            retry_buffer = -(-retry_buffer // wave_width) * wave_width
        self.retry_buffer = retry_buffer
        P = ep.num_pods
        self.st = init_state(ec, ep)
        self.assignments = np.where(
            ep.bound_node >= 0, ep.bound_node, PAD
        ).astype(np.int32)
        self.released = np.zeros(P, bool)
        self.rel_time = ep.arrival + np.where(
            np.isfinite(ep.duration), ep.duration, np.inf
        )
        # Chunk index each pod was bound in (pre-bound = -2): boundary b
        # releases only pods bound in chunks <= b-2 (one-chunk slack).
        self.bind_chunk = np.full(P, _NEVER, np.int64)
        self.bind_chunk[ep.bound_node >= 0] = -2
        self.retry_q: List[int] = []
        self.pend: List[list] = []  # [relb, pod, node]
        self.placed_total = 0
        self.preemptions = 0
        # [K8S] keeps every pending pod; the bounded analogue sheds load —
        # loudly (VERDICT r4 weak #2: drops must be a reported number).
        self.retry_dropped = 0
        # Chaos disruption: node_down NoExecute evictions (evict_node),
        # DISTINCT from scheduler-initiated `preemptions`. `_evict_time`
        # maps each still-displaced pod to its eviction boundary time — a
        # retry-pass re-bind pops it (rescheduled, latency accumulated);
        # whatever remains at trace end is stranded.
        self.evictions = 0
        self.evict_rescheduled = 0
        self._evict_lat_sum = 0.0
        self._evict_time: Dict[int, float] = {}
        # Boundary start times: f64 for the static release schedule, f32
        # finite prefix for the retry pend schedule (matching the device's
        # staged f32 table bit-for-bit).
        firsts = waves.idx[0::chunk_waves, 0]
        tb_all = np.where(
            firsts >= 0, ep.arrival[np.clip(firsts, 0, None)], np.inf
        )
        nfin = int(np.isfinite(tb_all).sum())
        self.tb32: Optional[np.ndarray] = None
        if retry_buffer:
            self.tb32 = tb_all[:nfin].astype(np.float32)
        # Static release schedule: each pod's earliest eligible boundary is
        # known up front (rel_time <= tb[b]  <=>  b >= searchsorted(tb,
        # rel_time, 'left'), floored by the one-chunk slack bind_chunk+2).
        # Bucketing candidates per boundary replaces the per-boundary
        # full-[P] mask scan; boundary() re-checks the dynamic parts
        # (still bound, not released, not retry-placed).
        chunk_of = np.full(P, _NEVER, np.int64)
        flat = waves.idx.reshape(-1)
        fv = flat >= 0
        if fv.any():
            chunk_of[flat[fv]] = np.nonzero(fv)[0] // (
                chunk_waves * waves.idx.shape[1]
            )
        chunk_of[ep.bound_node >= 0] = -2
        elig = np.searchsorted(tb_all[:nfin], self.rel_time, side="left")
        b_rel = np.maximum(elig, chunk_of + 2)
        ok = b_rel < nfin  # inf rel_time / absent pods fall out naturally
        cand = np.nonzero(ok)[0].astype(np.int64)
        order = np.argsort(b_rel[cand], kind="stable")  # pod-asc within b
        cand = cand[order]
        counts = np.bincount(b_rel[cand], minlength=max(nfin, 1))
        self._rel_bucket_off = np.concatenate(
            ([0], np.cumsum(counts))
        ).astype(np.int64)
        self._rel_bucket_pods = cand
        self._n_rel_buckets = nfin

    # -- checkpoint / resume (round 5) --------------------------------------

    def to_blob(self) -> dict:
        """The mirror's resume state as small named arrays (the count
        planes ride the main checkpoint — only the per-pod bookkeeping
        and the queues live here). ``mode`` records the writer's
        (kube, retry_buffer, chunk_waves, wave_width) so a resume on a
        differently-configured engine — including a different chunk grid,
        which silently shifts every boundary time — is rejected instead
        of diverging."""
        return {
            "mode": np.asarray(
                [
                    int(self.kube),
                    self.retry_buffer,
                    self.chunk_waves,
                    self.wave_width,
                ],
                np.int64,
            ),
            "bound": self.st.bound.copy(),
            "assignments": self.assignments.copy(),
            "released": self.released.copy(),
            "bind_chunk": self.bind_chunk.copy(),
            "retry_q": np.asarray(self.retry_q, np.int64),
            "pend": (
                np.asarray(self.pend, np.int64).reshape(-1, 3)
                if self.pend
                else np.zeros((0, 3), np.int64)
            ),
            "counters": np.asarray(
                [self.placed_total, self.preemptions, self.retry_dropped],
                np.int64,
            ),
            "chaos": np.asarray(
                [self.evictions, self.evict_rescheduled], np.int64
            ),
            "evict_lat": np.asarray([self._evict_lat_sum], np.float64),
            "evict_times": (
                np.asarray(
                    [[p, t] for p, t in sorted(self._evict_time.items())],
                    np.float64,
                ).reshape(-1, 2)
            ),
        }

    def restore(self, blob: dict, used, mc, aa, pw) -> None:
        """Rebuild the mirror from a checkpoint: the count planes come
        from the main checkpoint arrays (domain space — the mirror's own
        layout), the rest from :meth:`to_blob`."""
        mode = blob.get("mode")
        if mode is not None and (
            bool(mode[0]) != self.kube or int(mode[1]) != self.retry_buffer
        ):
            want = ("kube" if mode[0] else "retry-only", int(mode[1]))
            raise ValueError(
                f"checkpoint was written by a {want[0]} boundary replay "
                f"with retry_buffer={want[1]}; resume with the same "
                f"configuration (this engine: "
                f"{'kube' if self.kube else 'retry-only'}, "
                f"retry_buffer={self.retry_buffer})"
            )
        if mode is not None and len(mode) >= 4:
            # Chunk-grid guard: boundary indices (bind_chunk, retry_q pend
            # relb) are meaningless on a different grid. Blobs from before
            # this field have len(mode) == 2 and skip the check.
            if (
                int(mode[2]) != self.chunk_waves
                or int(mode[3]) != self.wave_width
            ):
                raise ValueError(
                    f"checkpoint was written on a chunk grid of "
                    f"chunk_waves={int(mode[2])}, wave_width="
                    f"{int(mode[3])}; this engine uses chunk_waves="
                    f"{self.chunk_waves}, wave_width={self.wave_width}. "
                    f"Boundary bookkeeping (bind chunks, pending release "
                    f"boundaries) does not transfer across grids — resume "
                    f"with the original wave_width/completions_chunk_waves "
                    f"or restart the replay from scratch."
                )
        self._plane_log.clear()  # planes below are authoritative
        self.st.used[:] = used
        self.st.match_count[:] = mc
        self.st.anti_active[:] = aa
        self.st.pref_wsum[:] = pw
        self.st.bound[:] = blob["bound"]
        self.assignments[:] = blob["assignments"]
        self.released[:] = blob["released"].astype(bool)
        self.bind_chunk[:] = blob["bind_chunk"]
        self.retry_q = [int(p) for p in blob["retry_q"]]
        self.pend = [list(map(int, row)) for row in blob["pend"]]
        c = blob["counters"]
        self.placed_total = int(c[0])
        self.preemptions = int(c[1])
        self.retry_dropped = int(c[2])
        # Chaos keys absent = a pre-chaos blob (zero disruption so far).
        ch = blob.get("chaos")
        self.evictions = int(ch[0]) if ch is not None else 0
        self.evict_rescheduled = int(ch[1]) if ch is not None else 0
        el = blob.get("evict_lat")
        self._evict_lat_sum = float(el[0]) if el is not None else 0.0
        et = blob.get("evict_times")
        self._evict_time = (
            {int(p): float(t) for p, t in et} if et is not None else {}
        )

    # -- plane folds (eager or logged) --------------------------------------

    def _apply_planes(self, sign: float, pods: np.ndarray, nodes: np.ndarray):
        du, dmc, daa, dpw = release_delta(self.ec, self.ep, pods, nodes)
        st = self.st
        if sign > 0:
            st.used += du
            st.match_count += dmc
            st.anti_active += daa
            st.pref_wsum += dpw
        else:
            st.used -= du
            st.match_count -= dmc
            st.anti_active -= daa
            st.pref_wsum -= dpw
        self.plane_folds += 1

    def _plane_op(self, key: tuple, sign: float, pods, nodes) -> None:
        pods = np.asarray(pods, np.int64)
        nodes = np.asarray(nodes, np.int64)
        if not pods.size:
            return
        if self.lazy:
            self._plane_log.append((key, sign, pods, nodes))
        else:
            self._apply_planes(sign, pods, nodes)

    def flush_planes(self) -> None:
        """Apply every logged plane delta in eager order: boundary ``b``'s
        releases (key ``(b, 0)``) before chunk ``b``'s binds (key
        ``(b, 1)``). The per-delta sums are associative-exact (bucketed
        k8s magnitudes — the same invariant fold_chunk already leans on),
        so the mirror planes land bit-identical to the eager path."""
        if not self._plane_log:
            return
        for _key, sign, pods, nodes in sorted(
            self._plane_log, key=lambda e: e[0]
        ):
            self._apply_planes(sign, pods, nodes)
        self._plane_log.clear()

    # -- chunk-side hooks ---------------------------------------------------

    def offer_failure(self, p: int) -> None:
        """A non-gang pod that missed placement enters the FIFO buffer
        (overflow drops the newest — counted)."""
        if not self.retry_buffer or self.ep.group_id[p] != PAD:
            return
        if len(self.retry_q) < self.retry_buffer:
            self.retry_q.append(int(p))
        else:
            self.retry_dropped += 1

    def fold_chunk(self, ci: int, rows: np.ndarray, choices: np.ndarray) -> None:
        """Fold one device chunk's placements into the host mirror (batch
        form of the per-slot binds the greedy anchor performs inline; the
        aggregate f32 sums are the same multiset in the same wave order).
        Failures enter the retry buffer in wave order."""
        ch = np.asarray(choices).reshape(rows.shape)
        v = rows >= 0
        ids = rows[v]
        nd = ch[v]
        placed = nd >= 0
        pid = ids[placed]
        pnd = nd[placed]
        tel = self.tel
        if tel is not None and tel.cfg.want_series and (~placed).any():
            # First-reject attribution for the chunk's failed slots,
            # computed against the pre-chunk mirror state (exact at
            # W=1/C=1 where a chunk IS one slot; chunk-granular
            # otherwise). A failed slot whose mirror mask is non-empty is
            # a gang revert (the pod itself was feasible) — the CPU
            # engine records no attempt for those either.
            self.flush_planes()  # attribution reads the count planes
            for p in ids[~placed]:
                rc: Dict[str, int] = {}
                if not self.fw.feasible_mask(self.st, int(p), reject_counts=rc).any():
                    tel.rejection(int(p), rc)
        if pid.size:
            self._plane_op((ci, 1), 1.0, pid, pnd)
            self.st.bound[pid] = pnd
            self.assignments[pid] = pnd
            self.bind_chunk[pid] = ci
            self.placed_total += int(pid.size)
            if tel is not None:
                # Wave-placed pods bind in their arrival wave: latency 0.
                tel.bind_zero(int((~self._ever_bound[pid]).sum()))
                self._ever_bound[pid] = True
                if tel.cfg.want_timeline:
                    for p, n in zip(pid.tolist(), pnd.tolist()):
                        tel.event("bind", float(self.ep.arrival[p]), p, n)
        for p in ids[~placed]:
            self.offer_failure(int(p))

    def counters(self) -> tuple:
        """Per-scenario result counters in one tuple — (preemptions,
        retry_dropped, evictions, evict_rescheduled, evict_stranded,
        evict_latency_mean). The exact fields the what-if engine stacks
        per scenario at result assembly; keeping the list HERE means the
        round-11 end-of-replay DCN gather and the single-process oracle
        can never drift on which counters a boundary mirror reports."""
        return (
            self.preemptions, self.retry_dropped, self.evictions,
            self.evict_rescheduled, self.evict_stranded,
            self.evict_latency_mean,
        )

    # -- chaos eviction (node_down NoExecute) -------------------------------

    @property
    def evict_stranded(self) -> int:
        """Evicted pods not re-placed (yet) — final value read at trace end."""
        return len(self._evict_time)

    @property
    def evict_latency_mean(self) -> float:
        """Mean virtual time from eviction to re-bind (boundary-granular)."""
        return (
            self._evict_lat_sum / self.evict_rescheduled
            if self.evict_rescheduled
            else 0.0
        )

    def evict_node(self, node: int, b: int, t_chunk: float) -> PairArrays:
        """NoExecute eviction of every pod the mirror holds bound on
        ``node`` at boundary ``b`` — the device twin of the CPU event
        engine's ``node_down`` handling. Victims are unbound with a FULL
        count rewind, their scheduled releases are cancelled, and non-gang
        victims re-enter the retry buffer exactly like preemption victims
        (overflow counted in ``retry_dropped``). Gang victims cannot
        re-assemble through the boundary retry pass (Permit is in-wave on
        the device), so they stay displaced and surface as stranded.
        Returns the (pods, nodes) pair for the device carry delta; the
        caller must have the mirror current through chunk ``b-1``
        (``fold_chunk``/``_fold_pending``) before calling."""
        ec, ep, st = self.ec, self.ep, self.st
        victims = np.nonzero(st.bound == node)[0]
        if not victims.size:
            return _empty_pairs()
        # unbind reads/writes the live count planes — logged deltas must
        # land first (chaos is rare; quiet runs never pay this flush).
        self.flush_planes()
        for v in victims:
            v = int(v)
            if self.tel is not None:
                # Eviction starts a fresh unschedulable episode.
                self.tel.clear_episode(v)
                if self.tel.cfg.want_timeline:
                    self.tel.event("evict", float(t_chunk), v, int(node))
            unbind(ec, ep, st, v)
            self.evictions += 1
            self._evict_time[v] = float(t_chunk)
            # Same bookkeeping as a preemption victim: a displaced pod's
            # pending release no longer frees anything, and a later
            # re-placement starts at THAT boundary — the arrival-based
            # static release must never fire.
            self.pend[:] = [e for e in self.pend if e[1] != v]
            self.bind_chunk[v] = _NEVER
            if self.assignments[v] >= 0:
                self.assignments[v] = PAD
                if ep.bound_node[v] == PAD:
                    self.placed_total -= 1
            if self.retry_buffer and ep.group_id[v] == PAD:
                if len(self.retry_q) < self.retry_buffer:
                    self.retry_q.append(v)
                else:
                    self.retry_dropped += 1
        return victims.astype(np.int64), np.full(
            victims.size, int(node), np.int64
        )

    # -- the boundary -------------------------------------------------------

    def boundary(
        self, b: int, t_chunk: float
    ) -> Tuple[PairArrays, PairArrays, PairArrays]:
        """Run boundary ``b`` (start time ``t_chunk``). Returns
        ``(releases, binds, evictions)`` as (pods, nodes) int array pairs
        — the device engine turns them into carry-plane deltas; the
        greedy anchor ignores them (its state IS self.st).

        Split since round 10 into ``boundary_releases`` (passes 1–2) +
        ``boundary_retry`` (pass 3): the release passes only read state
        from chunks ≤ b−2 (the one-chunk slack pins the static mask to
        ``bind_chunk < b−1`` and pend entries were scheduled ≥ one
        boundary ahead), so the double-buffered runtime stages them
        BEFORE folding chunk b−1 — overlapping host release bookkeeping
        with device compute — while the retry pass, which reads the
        folded planes through schedule_one, stays after the fold.
        Composing the two here is byte-for-byte the old single pass."""
        rel = self.boundary_releases(b, t_chunk)
        binds, evicts = self.boundary_retry(b, t_chunk)
        return rel, binds, evicts

    def boundary_releases(self, b: int, t_chunk: float) -> PairArrays:
        """Passes 1–2 of boundary ``b``: pend + static-bucket releases.
        Safe to run before chunk b−1's fold (see ``boundary``)."""
        st = self.st
        if np.isfinite(t_chunk):
            # Retry binds at the trailing (t=inf) boundary record latency
            # clamped to the last finite boundary time — the same
            # boundary-granular envelope the chaos reschedule latency uses.
            self._last_finite_t = float(t_chunk)
        # 1. Pending releases of boundary-placed pods (relb encodes the
        # time comparison already — no finite-t gate).
        rel_pods: List[int] = []
        still = []
        for entry in self.pend:
            if entry[0] <= b:
                rel_pods.append(int(entry[1]))
            else:
                still.append(entry)
        self.pend[:] = still
        # 2. Static releases (pods that started at arrival): candidates
        # come from the per-boundary bucket; the dynamic residue — still
        # bound, not already released, not retry-placed (those release
        # through pend only) — is re-checked here. One batched rewind
        # replaces the per-pod unbind loop; the sums are associative-exact
        # (see flush_planes), so the planes match the sequential path.
        if b < self._n_rel_buckets and np.isfinite(t_chunk):
            cand = self._rel_bucket_pods[
                self._rel_bucket_off[b] : self._rel_bucket_off[b + 1]
            ]
            if cand.size:
                m = (
                    (st.bound[cand] >= 0)
                    & ~self.released[cand]
                    & (self.bind_chunk[cand] < b - 1)
                )
                if m.any():
                    rel_pods.extend(cand[m].tolist())
        if rel_pods:
            rel_p = np.asarray(rel_pods, np.int64)
            rel_n = st.bound[rel_p].astype(np.int64)
            self._plane_op((b, 0), -1.0, rel_p, rel_n)
            st.bound[rel_p] = PAD
            self.released[rel_p] = True
            return (rel_p, rel_n)
        return _empty_pairs()

    def boundary_retry(
        self, b: int, t_chunk: float
    ) -> Tuple[PairArrays, PairArrays]:
        """Pass 3 of boundary ``b``: the bounded retry (+ kube
        preemption) walk and the telemetry occupancy sample. Reads the
        folded count planes — must run AFTER chunk b−1's fold."""
        ec, ep, st = self.ec, self.ep, self.st
        tel = self.tel
        binds_l: List[Tuple[int, int]] = []
        evicts_l: List[Tuple[int, int]] = []
        # 3. Bounded retry (+ kube preemption) pass, FIFO order. Victims
        # re-enter the walked queue and are attempted later in the SAME
        # pass — mirroring the CPU event engine, which requeues victims
        # into the activeQ at the preemption instant.
        if self.retry_buffer and self.retry_q:
            # The pass reads the count planes through schedule_one — any
            # logged deltas must land first (rare path; quiet runs never
            # get here and never pay a fold).
            self.flush_planes()
            q = self.retry_q
            still_q: List[int] = []
            i = 0
            want_reasons = tel is not None and tel.cfg.want_series
            while i < len(q):
                p = q[i]
                i += 1
                res = self.fw.schedule_one(
                    st, p, allow_preemption=self.kube, want_reasons=want_reasons
                )
                if res.node == PAD:
                    if want_reasons and res.reasons is not None:
                        # Grows rejection_attempts every boundary; charges
                        # `reasons` only if the pod's in-scan failure was
                        # not already attributed (episode semantics).
                        tel.rejection(int(p), res.reasons)
                    still_q.append(p)
                    continue
                for v in res.victims:
                    v = int(v)
                    if tel is not None:
                        tel.clear_episode(v)
                        if tel.cfg.want_timeline:
                            tel.event(
                                "preempt", self._last_finite_t, v, int(st.bound[v])
                            )
                    evicts_l.append((v, int(st.bound[v])))
                    unbind(ec, ep, st, v)  # FULL count rewind — no phantoms
                    self.preemptions += 1
                    # A victim with a scheduled pending release no longer
                    # holds what that release would free — cancel it; and
                    # if re-placed later it starts at THAT boundary, so its
                    # arrival-based static release must never fire.
                    self.pend[:] = [e for e in self.pend if e[1] != v]
                    self.bind_chunk[v] = 1 << 30
                    if self.assignments[v] >= 0:
                        self.assignments[v] = PAD
                        if ep.bound_node[v] == PAD:
                            self.placed_total -= 1
                    if (len(q) - i) + len(still_q) < self.retry_buffer:
                        q.append(v)
                    else:
                        self.retry_dropped += 1
                bind(ec, ep, st, p, res.node)
                binds_l.append((p, int(res.node)))
                self.assignments[p] = res.node
                if tel is not None:
                    tel.clear_episode(p)
                    t_bind = (
                        float(t_chunk)
                        if np.isfinite(t_chunk)
                        else self._last_finite_t
                    )
                    if not self._ever_bound[p]:
                        # First bind through the retry pass: latency is
                        # boundary-granular virtual wait since arrival.
                        self._ever_bound[p] = True
                        lat = t_bind - float(ep.arrival[p])
                        if lat <= 0.0:
                            tel.bind_zero()
                        else:
                            tel.bind_latency(p, lat)
                    if tel.cfg.want_timeline:
                        tel.event("bind", t_bind, int(p), int(res.node))
                if ep.bound_node[p] == PAD:
                    self.placed_total += 1
                if p in self._evict_time:
                    # A chaos-evicted pod re-bound: boundary-granular
                    # reschedule latency (the trailing boundary's inf
                    # start time contributes 0 — the re-bind still counts).
                    t_ev = self._evict_time.pop(p)
                    self.evict_rescheduled += 1
                    if np.isfinite(t_chunk):
                        self._evict_lat_sum += float(t_chunk) - t_ev
                # Release schedule: f32 boundary search, >= b+1 — the pod
                # STARTS now, not at arrival.
                dur = np.float32(ep.duration[p])
                if np.isfinite(dur) and len(self.pend) < self.retry_buffer:
                    rb = int(
                        np.searchsorted(
                            self.tb32,
                            np.float32(t_chunk) + dur,
                            side="left",
                        )
                    )
                    if rb < len(self.tb32):
                        self.pend.append([max(rb, b + 1), p, int(res.node)])
            self.retry_q = still_q
        if tel is not None and tel.cfg.want_series and np.isfinite(t_chunk):
            # Post-boundary occupancy in virtual time (the device twin of
            # the CPU engine's per-event queue-depth samples). Utilization
            # gauges need the mirror's committed planes — flush the lazy
            # plane log first (cheap/idempotent when empty; the caller
            # already forced a pre-boundary fold under want_series).
            self.flush_planes()
            from ..utils.metrics import series_gauges

            tel.sample(
                float(t_chunk),
                retry_depth=len(self.retry_q),
                pend_depth=len(self.pend),
                **series_gauges(
                    self.st.used, self.ec.allocatable, self.ec.vocab._r
                ),
            )

        def _pairs(lst: List[Tuple[int, int]]) -> PairArrays:
            if not lst:
                return _empty_pairs()
            a = np.asarray(lst, np.int64)
            return a[:, 0], a[:, 1]

        return _pairs(binds_l), _pairs(evicts_l)
