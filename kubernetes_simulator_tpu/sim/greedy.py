"""Greedy wave replay, numpy host edition.

Implements EXACTLY the algorithm the JAX engine compiles — arrival-order
waves, sequential slots with speculative binds, wave-boundary gang
commit/rollback, no queue/backoff/preemption — but on the host, reusing the
tested CPU plugin path. This is the parity anchor for the device scan
(SURVEY.md §4.2): for any workload, `greedy_replay` and the `jax` strategy
must produce identical placements.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..framework.framework import FrameworkConfig, SchedulerFramework
from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import bind, init_state, unbind
from .runtime import ReplayResult
from .waves import WaveBatch, pack_waves


def greedy_replay(
    ec: EncodedCluster,
    ep: EncodedPods,
    config: Optional[FrameworkConfig] = None,
    waves: Optional[WaveBatch] = None,
    wave_width: int = 8,
) -> ReplayResult:
    config = config or FrameworkConfig()
    config.enable_preemption = False  # greedy semantics: no PostFilter
    fw = SchedulerFramework(ec, ep, config)
    if waves is None:
        waves = pack_waves(ep, wave_width)
    st = init_state(ec, ep)
    assignments = np.full(ep.num_pods, PAD, dtype=np.int32)
    placed_total = 0
    t0 = time.perf_counter()
    for wave in waves.idx:
        slot_choice: List[int] = []
        slot_pods: List[int] = []
        for p in wave:
            if p < 0:
                continue
            p = int(p)
            res = fw.schedule_one(st, p)
            if res.node != PAD:
                bind(ec, ep, st, p, res.node)
            slot_pods.append(p)
            slot_choice.append(res.node)
        # Gang commit: a group fails if ANY member slot went unplaced.
        failed_groups = {
            int(ep.group_id[p])
            for p, c in zip(slot_pods, slot_choice)
            if c == PAD and ep.group_id[p] != PAD
        }
        for p, c in zip(slot_pods, slot_choice):
            g = int(ep.group_id[p])
            if c != PAD and g in failed_groups:
                unbind(ec, ep, st, p)
            elif c != PAD:
                assignments[p] = c
                placed_total += 1
    wall = time.perf_counter() - t0
    to_schedule = int((ep.bound_node == PAD).sum())
    util = {}
    for rname in ("cpu", "memory"):
        ri = ec.vocab._r.get(rname)
        if ri is not None:
            alloc = ec.allocatable[:, ri]
            with np.errstate(invalid="ignore", divide="ignore"):
                u = np.where(alloc > 0, st.used[:, ri] / np.where(alloc > 0, alloc, 1), 0)
            util[rname] = float(u.mean())
    return ReplayResult(
        assignments=assignments,
        placed=placed_total,
        unschedulable=to_schedule - placed_total,
        preemptions=0,
        attempts=to_schedule,
        wall_clock_s=wall,
        placements_per_sec=placed_total / wall if wall > 0 else 0.0,
        virtual_makespan=float(ep.arrival.max()) if ep.num_pods else 0.0,
        utilization=util,
        state=st,
    )
