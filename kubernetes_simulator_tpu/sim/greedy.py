"""Greedy wave replay, numpy host edition.

Implements EXACTLY the algorithm the JAX engine compiles — arrival-order
waves, sequential slots with speculative binds, wave-boundary gang
commit/rollback — but on the host, reusing the tested CPU plugin path.
This is the parity anchor for the device scan (SURVEY.md §4.2): for any
workload, `greedy_replay` and the `jax` strategy must produce identical
placements.

``preemption=True`` adds the greedy engines' TIER preemption (the device
semantics — NOT kube's minimal-victims PostFilter, which lives in the CPU
event engine): when a pod is unschedulable, a node may be chosen where
evicting ALL lower-priority non-gang pods makes it fit (resource fit +
taint/node-affinity + the count-based masks at their CURRENT, pre-eviction
values); candidates rank by (fewest victims, lowest max victim tier,
lowest index). Evicted pods become unplaced and are NOT re-queued, and
their affinity/spread count contributions are NOT rewound ("phantom
counts") — aggregate state can't attribute counts to individual victims.
At most one preemption fires per wave; gang pods neither preempt nor get
evicted.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..framework.framework import FrameworkConfig, SchedulerFramework
from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import bind, init_state, unbind
from .runtime import ReplayResult
from .waves import WaveBatch, pack_waves


def priority_tiers(ep: EncodedPods):
    """(tiers [T] ascending distinct priorities, pod_tier [P] i32)."""
    tiers, inv = np.unique(ep.priority, return_inverse=True)
    return tiers.astype(np.int64), inv.astype(np.int32)


def _try_tier_preempt(fw, ec, ep, st, p, pod_tier):
    """The anchor's preemption decision. Returns (node, victims) or None.
    Mirrors ops.tpu3's device arithmetic exactly (see module docstring)."""
    tp = int(pod_tier[p])
    if ep.group_id[p] != PAD or tp == 0:
        return None
    bound = st.bound
    lower = np.nonzero(
        (bound >= 0) & (pod_tier < tp) & (ep.group_id == PAD)
    )[0]
    if lower.size == 0:
        return None
    N = ec.num_nodes
    victims_n = np.zeros(N, np.int64)
    np.add.at(victims_n, bound[lower], 1)
    lower_used = np.zeros((N, ec.num_resources), np.float32)
    np.add.at(lower_used, bound[lower], ep.requests[lower])
    # Fit after evict-all-lower (same eps form as ops.cpu.fit_mask).
    pre_fit = np.all(
        st.used - lower_used + ep.requests[p][None, :] <= ec.allocatable + 1e-6,
        axis=1,
    )
    # All non-fit filters at their current (pre-eviction) values.
    masks = np.ones(N, bool)
    for pl in fw.plugins:
        if pl.name == "NodeResourcesFit":
            continue
        m = pl.filter(fw.ctx, st, p)
        if m is not None:
            masks &= m
    cand = pre_fit & masks & (victims_n > 0)
    if not cand.any():
        return None
    maxtier_n = np.full(N, -1, np.int64)
    np.maximum.at(maxtier_n, bound[lower], pod_tier[lower].astype(np.int64))
    score = victims_n * 1024 + maxtier_n
    score = np.where(cand, score, np.iinfo(np.int64).max)
    n = int(np.argmin(score))  # lowest index on ties
    victims = lower[bound[lower] == n]
    return n, victims


def greedy_replay(
    ec: EncodedCluster,
    ep: EncodedPods,
    config: Optional[FrameworkConfig] = None,
    waves: Optional[WaveBatch] = None,
    wave_width: int = 8,
    preemption: bool = False,
    completions_chunk_waves: Optional[int] = None,
    retry_buffer: int = 0,
) -> ReplayResult:
    """``completions_chunk_waves``: mirror the device engines' chunk-granular
    completions — before each chunk of that many waves, pods whose
    ``arrival + duration`` is at or before the chunk's start time release
    their resources and count contributions (they stay in ``assignments``:
    a completed pod ran to completion, it is not unschedulable).

    ``retry_buffer`` (round 4, [K8S] activeQ flush-on-event analogue):
    non-gang pods that miss placement enter a FIFO retry buffer (capacity
    ``retry_buffer``; overflow drops the newest — they stay permanently
    unscheduled as before). At each chunk boundary, AFTER releases apply,
    one bounded retry pass re-attempts every buffered pod in order;
    placed pods leave the buffer and start at the boundary's time — they
    release at the first boundary whose start time reaches ``t_b +
    duration`` (computed in f32, exactly as the device does; at least
    ``b+1``), through a pending list also capped at ``retry_buffer``
    (overflow = the release is dropped and the pod holds its resources to
    the end). Requires ``completions_chunk_waves``. Mirrors
    WhatIfEngine(retry_buffer=...)'s device semantics exactly."""
    config = config or FrameworkConfig()
    config.enable_preemption = False  # greedy semantics: no kube PostFilter
    if retry_buffer and not completions_chunk_waves:
        raise ValueError("retry_buffer requires completions_chunk_waves")
    if retry_buffer and preemption:
        raise ValueError("retry_buffer is not supported with preemption")
    if retry_buffer:
        # Same rounding as the device twin (its retry pass reuses the
        # W-wide wave step) — the two caps must agree or placed counts
        # diverge once a buffer fills past the raw capacity.
        retry_buffer = -(-retry_buffer // wave_width) * wave_width
    fw = SchedulerFramework(ec, ep, config)
    if waves is None:
        waves = pack_waves(ep, wave_width)
    st = init_state(ec, ep)
    _, pod_tier = priority_tiers(ep)
    # Pre-bound pods appear in assignments (matching the device engines)
    # but never count toward placed_total (they were not scheduled here).
    assignments = np.where(ep.bound_node >= 0, ep.bound_node, PAD).astype(np.int32)
    placed_total = 0
    preemptions = 0
    rel_time = ep.arrival + np.where(np.isfinite(ep.duration), ep.duration, np.inf)
    released = np.zeros(ep.num_pods, bool)
    # Chunk index each pod was bound in (pre-bound = -2). Boundary b
    # releases only pods bound in chunks <= b-2 — the ONE-CHUNK SLACK that
    # lets the device engines overlap host release computation with the
    # in-flight chunk (round 3; matched here so the anchor stays exact).
    bind_chunk = np.full(ep.num_pods, 1 << 30, np.int64)
    bind_chunk[ep.bound_node >= 0] = -2
    retry_q: List[int] = []  # FIFO waiting pods (ids)
    pend: List[list] = []  # [relb, pod, node] retried-placed awaiting release
    tb32 = None
    if retry_buffer:
        # Boundary start times in f32 (finite prefix), matching the
        # device's staged f32 table bit-for-bit.
        C = completions_chunk_waves
        firsts = waves.idx[0::C, 0]
        tb_all = np.where(
            firsts >= 0, ep.arrival[np.clip(firsts, 0, None)], np.inf
        )
        nfin = int(np.isfinite(tb_all).sum())
        tb32 = tb_all[:nfin].astype(np.float32)
    t0 = time.perf_counter()
    for wi, wave in enumerate(waves.idx):
        if completions_chunk_waves and wi % completions_chunk_waves == 0:
            b = wi // completions_chunk_waves
            first = int(wave[0]) if wave.shape[0] else -1
            t_chunk = float(ep.arrival[first]) if first >= 0 else np.inf
            # 1. Pending releases of retried-placed pods (relb encodes
            # the time comparison already — no finite-t gate).
            still = []
            for entry in pend:
                if entry[0] <= b:
                    unbind(ec, ep, st, int(entry[1]))
                    released[entry[1]] = True
                else:
                    still.append(entry)
            pend[:] = still
            # 2. Static releases (pods that started at arrival).
            if np.isfinite(t_chunk):
                due = np.nonzero(
                    (st.bound >= 0)
                    & ~released
                    & np.isfinite(rel_time)
                    & (rel_time <= t_chunk)
                    & (bind_chunk < b - 1)
                )[0]
                for p in due:
                    unbind(ec, ep, st, int(p))  # assignments keep the node
                    released[p] = True
            # 3. Bounded retry pass over the buffer, FIFO order.
            if retry_buffer and retry_q:
                still_q = []
                for p in retry_q:
                    res = fw.schedule_one(st, p)
                    if res.node == PAD:
                        still_q.append(p)
                        continue
                    bind(ec, ep, st, p, res.node)
                    assignments[p] = res.node
                    placed_total += 1
                    # Release schedule: f32 boundary search, >= b+1 —
                    # the pod STARTS now, not at arrival.
                    dur = np.float32(ep.duration[p])
                    if np.isfinite(dur) and len(pend) < retry_buffer:
                        rb = int(
                            np.searchsorted(
                                tb32,
                                np.float32(t_chunk) + dur,
                                side="left",
                            )
                        )
                        if rb < len(tb32):
                            pend.append([max(rb, b + 1), p, res.node])
                retry_q[:] = still_q
        slot_choice: List[int] = []
        slot_pods: List[int] = []
        evicted_in_wave: set = set()
        preempted_this_wave = False
        for p in wave:
            if p < 0:
                continue
            p = int(p)
            res = fw.schedule_one(st, p)
            node = res.node
            if node == PAD and preemption and not preempted_this_wave:
                hit = _try_tier_preempt(fw, ec, ep, st, p, pod_tier)
                if hit is not None:
                    node, victims = hit
                    preempted_this_wave = True
                    preemptions += len(victims)
                    for v in victims:
                        v = int(v)
                        vn = int(st.bound[v])
                        # Resources-only unbind: counts stay (phantom).
                        st.used[vn] -= ep.requests[v]
                        st.bound[v] = PAD
                        if assignments[v] >= 0:
                            assignments[v] = PAD
                            if ep.bound_node[v] == PAD:  # scheduled here
                                placed_total -= 1
                        elif v in slot_pods:
                            evicted_in_wave.add(v)
            if node != PAD:
                bind(ec, ep, st, p, node)
            slot_pods.append(p)
            slot_choice.append(node)
        # Gang commit: a group fails if ANY member slot went unplaced.
        failed_groups = {
            int(ep.group_id[p])
            for p, c in zip(slot_pods, slot_choice)
            if c == PAD and ep.group_id[p] != PAD
        }
        for p, c in zip(slot_pods, slot_choice):
            if p in evicted_in_wave:
                continue  # evicted mid-wave: never committed
            g = int(ep.group_id[p])
            if c != PAD and g in failed_groups:
                unbind(ec, ep, st, p)
            elif c != PAD:
                assignments[p] = c
                placed_total += 1
                if completions_chunk_waves:
                    bind_chunk[p] = wi // completions_chunk_waves
            elif (
                retry_buffer
                and g == PAD
                and len(retry_q) < retry_buffer
            ):
                # Failed non-gang pod enters the retry buffer (slot
                # order within the wave; overflow drops the newest).
                retry_q.append(p)
    wall = time.perf_counter() - t0
    to_schedule = int((ep.bound_node == PAD).sum())
    util = {}
    for rname in ("cpu", "memory"):
        ri = ec.vocab._r.get(rname)
        if ri is not None:
            alloc = ec.allocatable[:, ri]
            with np.errstate(invalid="ignore", divide="ignore"):
                u = np.where(alloc > 0, st.used[:, ri] / np.where(alloc > 0, alloc, 1), 0)
            util[rname] = float(u.mean())
    return ReplayResult(
        assignments=assignments,
        placed=placed_total,
        unschedulable=to_schedule - placed_total,
        preemptions=preemptions,
        attempts=to_schedule,
        wall_clock_s=wall,
        placements_per_sec=placed_total / wall if wall > 0 else 0.0,
        virtual_makespan=float(ep.arrival.max()) if ep.num_pods else 0.0,
        utilization=util,
        state=st,
    )
