"""Greedy wave replay, numpy host edition.

Implements EXACTLY the algorithm the JAX engine compiles — arrival-order
waves, sequential slots with speculative binds, wave-boundary gang
commit/rollback — but on the host, reusing the tested CPU plugin path.
This is the parity anchor for the device scan (SURVEY.md §4.2): for any
workload, `greedy_replay` and the `jax` strategy must produce identical
placements.

``preemption="tier"`` (or ``True``) adds the greedy engines' TIER
preemption (the fast in-scan approximation — NOT kube's minimal-victims
PostFilter): when a pod is unschedulable, a node may be chosen where
evicting ALL lower-priority non-gang pods makes it fit (resource fit +
taint/node-affinity + the count-based masks at their CURRENT, pre-eviction
values); candidates rank by (fewest victims, lowest max victim tier,
lowest index). Evicted pods become unplaced and are NOT re-queued, and
their affinity/spread count contributions are NOT rewound ("phantom
counts") — aggregate state can't attribute counts to individual victims.
At most one preemption fires per wave; gang pods neither preempt nor get
evicted.

``preemption="kube"`` (round 5) is the kube-EXACT minimal-victims
PostFilter, run at chunk boundaries through the retry buffer
(:mod:`.boundary`): a failed non-gang pod retries at each boundary and,
still failing, preempts per upstream defaultpreemption — fewest victims,
lowest max victim priority, victims chosen lowest-priority-first, ONLY
the victims needed for this pod's fit, with a FULL count rewind (no
phantom counts). Victims re-enter the retry buffer exactly as the CPU
event engine requeues them. Requires ``completions_chunk_waves`` (the
boundary grid) and ``retry_buffer > 0``. In-wave attempts never preempt —
fidelity is chunk-granular (exact vs CpuReplayEngine at W=1/C=1 on
queue-trivial traces; measured divergence at production chunk sizes).
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..framework.framework import FrameworkConfig, SchedulerFramework
from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import bind, unbind
from ..utils.metrics import fragmentation_gauges, utilization_means
from .runtime import ReplayResult
from .waves import WaveBatch, pack_waves


def priority_tiers(ep: EncodedPods):
    """(tiers [T] ascending distinct priorities, pod_tier [P] i32)."""
    tiers, inv = np.unique(ep.priority, return_inverse=True)
    return tiers.astype(np.int64), inv.astype(np.int32)


def _try_tier_preempt(fw, ec, ep, st, p, pod_tier):
    """The anchor's preemption decision. Returns (node, victims) or None.
    Mirrors ops.tpu3's device arithmetic exactly (see module docstring)."""
    tp = int(pod_tier[p])
    if ep.group_id[p] != PAD or tp == 0:
        return None
    bound = st.bound
    lower = np.nonzero(
        (bound >= 0) & (pod_tier < tp) & (ep.group_id == PAD)
    )[0]
    if lower.size == 0:
        return None
    N = ec.num_nodes
    victims_n = np.zeros(N, np.int64)
    np.add.at(victims_n, bound[lower], 1)
    lower_used = np.zeros((N, ec.num_resources), np.float32)
    np.add.at(lower_used, bound[lower], ep.requests[lower])
    # Fit after evict-all-lower (same eps form as ops.cpu.fit_mask).
    pre_fit = np.all(
        st.used - lower_used + ep.requests[p][None, :] <= ec.allocatable + 1e-6,
        axis=1,
    )
    # All non-fit filters at their current (pre-eviction) values.
    masks = np.ones(N, bool)
    for pl in fw.plugins:
        if pl.name == "NodeResourcesFit":
            continue
        m = pl.filter(fw.ctx, st, p)
        if m is not None:
            masks &= m
    cand = pre_fit & masks & (victims_n > 0)
    if not cand.any():
        return None
    maxtier_n = np.full(N, -1, np.int64)
    np.maximum.at(maxtier_n, bound[lower], pod_tier[lower].astype(np.int64))
    score = victims_n * 1024 + maxtier_n
    score = np.where(cand, score, np.iinfo(np.int64).max)
    n = int(np.argmin(score))  # lowest index on ties
    victims = lower[bound[lower] == n]
    return n, victims


def normalize_preemption(preemption) -> Optional[str]:
    """False/None → None; True → "tier"; "tier"/"kube" pass through."""
    if preemption in (False, None):
        return None
    if preemption is True:
        return "tier"
    if preemption in ("tier", "kube"):
        return preemption
    raise ValueError(
        f"preemption must be False/True/'tier'/'kube', got {preemption!r}"
    )


def greedy_replay(
    ec: EncodedCluster,
    ep: EncodedPods,
    config: Optional[FrameworkConfig] = None,
    waves: Optional[WaveBatch] = None,
    wave_width: int = 8,
    preemption=False,
    completions_chunk_waves: Optional[int] = None,
    retry_buffer: int = 0,
) -> ReplayResult:
    """``completions_chunk_waves``: mirror the device engines' chunk-granular
    completions — before each chunk of that many waves, pods whose
    ``arrival + duration`` is at or before the chunk's start time release
    their resources and count contributions (they stay in ``assignments``:
    a completed pod ran to completion, it is not unschedulable).

    ``retry_buffer`` (round 4, [K8S] activeQ flush-on-event analogue):
    non-gang pods that miss placement enter a FIFO retry buffer (capacity
    ``retry_buffer``; overflow drops the newest — they stay permanently
    unscheduled as before). At each chunk boundary, AFTER releases apply,
    one bounded retry pass re-attempts every buffered pod in order;
    placed pods leave the buffer and start at the boundary's time — they
    release at the first boundary whose start time reaches ``t_b +
    duration`` (computed in f32, exactly as the device does; at least
    ``b+1``), through a pending list also capped at ``retry_buffer``
    (overflow = the release is dropped and the pod holds its resources to
    the end). Requires ``completions_chunk_waves``. Mirrors
    WhatIfEngine(retry_buffer=...)'s device semantics exactly."""
    from .boundary import BoundaryOps

    from dataclasses import replace as dc_replace

    mode = normalize_preemption(preemption)
    # kube PostFilter runs ONLY through the boundary pass; in-wave
    # attempts pass allow_preemption=False below. Copy, don't write
    # through the caller's config object.
    config = dc_replace(
        config or FrameworkConfig(), enable_preemption=mode == "kube"
    )
    if retry_buffer and not completions_chunk_waves:
        raise ValueError("retry_buffer requires completions_chunk_waves")
    if retry_buffer and mode == "tier":
        raise ValueError("retry_buffer is not supported with tier preemption")
    if mode == "kube" and not completions_chunk_waves:
        raise ValueError(
            "preemption='kube' requires completions_chunk_waves (the "
            "boundary grid the PostFilter pass runs on)"
        )
    fw = SchedulerFramework(ec, ep, config)
    if waves is None:
        waves = pack_waves(ep, wave_width)
    ops = BoundaryOps(
        ec, ep, fw, waves, wave_width, completions_chunk_waves or 1,
        retry_buffer=retry_buffer, kube=mode == "kube",
    )
    st = ops.st
    _, pod_tier = priority_tiers(ep)
    # Pre-bound pods appear in assignments (matching the device engines)
    # but never count toward placed_total (they were not scheduled here).
    assignments = ops.assignments
    preemptions = 0  # tier evictions (kube evictions live in ops)
    t0 = time.perf_counter()
    for wi, wave in enumerate(waves.idx):
        if completions_chunk_waves and wi % completions_chunk_waves == 0:
            b = wi // completions_chunk_waves
            first = int(wave[0]) if wave.shape[0] else -1
            t_chunk = float(ep.arrival[first]) if first >= 0 else np.inf
            ops.boundary(b, t_chunk)
        slot_choice: List[int] = []
        slot_pods: List[int] = []
        evicted_in_wave: set = set()
        preempted_this_wave = False
        for p in wave:
            if p < 0:
                continue
            p = int(p)
            res = fw.schedule_one(st, p, allow_preemption=False)
            node = res.node
            if node == PAD and mode == "tier" and not preempted_this_wave:
                hit = _try_tier_preempt(fw, ec, ep, st, p, pod_tier)
                if hit is not None:
                    node, victims = hit
                    preempted_this_wave = True
                    preemptions += len(victims)
                    for v in victims:
                        v = int(v)
                        vn = int(st.bound[v])
                        # Resources-only unbind: counts stay (phantom).
                        st.used[vn] -= ep.requests[v]
                        st.bound[v] = PAD
                        if assignments[v] >= 0:
                            assignments[v] = PAD
                            if ep.bound_node[v] == PAD:  # scheduled here
                                ops.placed_total -= 1
                        elif v in slot_pods:
                            evicted_in_wave.add(v)
            if node != PAD:
                bind(ec, ep, st, p, node)
            slot_pods.append(p)
            slot_choice.append(node)
        # Gang commit: a group fails if ANY member slot went unplaced.
        failed_groups = {
            int(ep.group_id[p])
            for p, c in zip(slot_pods, slot_choice)
            if c == PAD and ep.group_id[p] != PAD
        }
        for p, c in zip(slot_pods, slot_choice):
            if p in evicted_in_wave:
                continue  # evicted mid-wave: never committed
            g = int(ep.group_id[p])
            if c != PAD and g in failed_groups:
                unbind(ec, ep, st, p)
            elif c != PAD:
                assignments[p] = c
                ops.placed_total += 1
                if completions_chunk_waves:
                    ops.bind_chunk[p] = wi // completions_chunk_waves
            else:
                # Failed non-gang pod enters the retry buffer (slot
                # order within the wave; overflow drops the newest).
                ops.offer_failure(p)
    if mode == "kube":
        # Trailing boundary: pods that failed in the LAST chunk still get
        # their PostFilter attempt (the CPU engine preempts at the failure
        # instant; without this a late high-priority pod would never
        # preempt). t = inf ⇒ no static releases, no pend scheduling.
        ops.boundary(
            -(-waves.idx.shape[0] // (completions_chunk_waves or 1)), np.inf
        )
    wall = time.perf_counter() - t0
    placed_total = ops.placed_total
    preemptions += ops.preemptions
    to_schedule = int((ep.bound_node == PAD).sum())
    util = utilization_means(st.used, ec.allocatable, ec.vocab._r)
    pending = (ep.bound_node == PAD) & (assignments == PAD)
    frag = fragmentation_gauges(
        ec.allocatable, st.used, ep.requests[pending], ec.vocab._r
    )
    return ReplayResult(
        assignments=assignments,
        placed=placed_total,
        unschedulable=to_schedule - placed_total,
        preemptions=preemptions,
        attempts=to_schedule,
        wall_clock_s=wall,
        placements_per_sec=placed_total / wall if wall > 0 else 0.0,
        virtual_makespan=float(ep.arrival.max()) if ep.num_pods else 0.0,
        utilization=util,
        state=st,
        retry_dropped=ops.retry_dropped,
        fragmentation=frag,
    )
