"""Checkpoint / resume (SURVEY.md §5): snapshot the replay carry every K
chunks so a 1M-pod replay can resume after interruption; the snapshot also
doubles as a what-if fork point (snapshot → perturb → fan out).

Plain ``.npz`` — the state is four dense tensors plus a cursor; orbax would
add dependency weight for no benefit at this size. Count tensors are stored
in DOMAIN space ``[G, D]`` (the canonical semantic form — scenario-
independent), and converted to/from the device engine's node space
``[G, N]`` at save/load (see ops.tpu.DevState).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

import numpy as np


@dataclass
class ReplayCheckpoint:
    chunk_cursor: int  # next chunk index to execute
    used: np.ndarray
    match_count: np.ndarray  # [G, D] domain space
    anti_active: np.ndarray  # [G, D]
    pref_wsum: np.ndarray  # [G, D]
    outs: List[np.ndarray]  # per-chunk collected outputs so far
    # [P] bool — pods whose completion releases are ALREADY subtracted from
    # the saved state (completions-on replays). Forking consumers must seed
    # their released mask from this or they re-subtract every pre-fork
    # release at the first post-fork boundary (advisor round-2 finding).
    # None on checkpoints written before the field existed — treated as
    # "reconstruct from outs" by the loaders that need it.
    released: Optional[np.ndarray] = None
    # Boundary-mode host-mirror state (round 5; retry/kube replays):
    # a dict of small arrays from sim.boundary.BoundaryOps.to_blob().
    # Present ⟺ the checkpoint came from a boundary-mode replay — such
    # checkpoints resume only on a matching boundary-mode engine (the
    # what-if fork path rejects them; outs are empty by design, the
    # mirror's assignments carry the placements).
    boundary: Optional[dict] = None

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        extra = {}
        if self.released is not None:
            extra["released"] = self.released.astype(bool)
        if self.boundary is not None:
            extra.update({f"bd_{k}": v for k, v in self.boundary.items()})
        np.savez_compressed(
            tmp,
            chunk_cursor=np.int64(self.chunk_cursor),
            used=self.used,
            match_count=self.match_count,
            anti_active=self.anti_active,
            pref_wsum=self.pref_wsum,
            num_outs=np.int64(len(self.outs)),
            **{f"out_{i}": o for i, o in enumerate(self.outs)},
            **extra,
        )
        os.replace(tmp + ".npz" if not tmp.endswith(".npz") else tmp, path)

    @classmethod
    def load(cls, path: str) -> "ReplayCheckpoint":
        with np.load(path) as z:
            n = int(z["num_outs"])
            bd = {
                k[len("bd_"):]: z[k] for k in z.files if k.startswith("bd_")
            }
            return cls(
                chunk_cursor=int(z["chunk_cursor"]),
                used=z["used"],
                match_count=z["match_count"],
                anti_active=z["anti_active"],
                pref_wsum=z["pref_wsum"],
                outs=[z[f"out_{i}"] for i in range(n)],
                released=z["released"] if "released" in z.files else None,
                boundary=bd or None,
            )


def state_to_checkpoint(
    state, gdom: np.ndarray, D: int, cursor: int, outs: List[np.ndarray]
) -> ReplayCheckpoint:
    from ..ops.tpu import node_space_to_domain

    return ReplayCheckpoint(
        chunk_cursor=cursor,
        used=np.asarray(state.used),
        match_count=node_space_to_domain(np.asarray(state.match_count), gdom, D),
        anti_active=node_space_to_domain(np.asarray(state.anti_active), gdom, D),
        pref_wsum=node_space_to_domain(np.asarray(state.pref_wsum), gdom, D),
        outs=[np.asarray(o) for o in outs],
    )


def checkpoint_to_state(ckpt: ReplayCheckpoint, gdom: np.ndarray):
    import jax.numpy as jnp

    from ..ops.tpu import DevState, domain_to_node_space

    return DevState(
        used=jnp.asarray(ckpt.used),
        match_count=jnp.asarray(domain_to_node_space(ckpt.match_count, gdom)),
        anti_active=jnp.asarray(domain_to_node_space(ckpt.anti_active, gdom)),
        pref_wsum=jnp.asarray(domain_to_node_space(ckpt.pref_wsum, gdom)),
        match_total=jnp.asarray(ckpt.match_count.sum(axis=1).astype(np.float32)),
    )
