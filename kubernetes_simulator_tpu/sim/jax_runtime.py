"""The `jax` scheduling strategy — the whole replay as one compiled TPU
program (SURVEY.md §3.1 "device boundary", §3.5).

The host feeds chunks of wave-packed pods; a jitted ``lax.scan`` walks the
waves, evaluating every enabled plugin's Filter mask and Score over all
nodes at once, selecting with a deterministic argmax, and updating the
carried state with scatter-adds. Gang commit/rollback is a masked update at
each wave boundary. Selected through the strategy registry ([BASELINE]: the
CPU plugin path stays the default; `jax` is opt-in).

Semantics = :mod:`.greedy` exactly (the parity anchor): arrival-order
greedy waves with chunk-granular completions ON BY DEFAULT (pods with
finite duration release resources and count contributions at chunk
boundaries, one-chunk slack — see ``JaxReplayEngine.replay``).
Preemption is opt-in: ``preemption="kube"`` runs the EXACT kube
minimal-victims PostFilter in the chunk-boundary pass (round 5,
:mod:`.boundary`); ``"tier"``/``True`` keeps the in-scan tier
approximation. Exact-timestamp event ordering and queue
re-ordering/backoff remain CPU-event-engine-only; batched what-if over
scenarios builds on
this module via ``vmap``/``shard_map`` (:mod:`.whatif`, :mod:`..parallel`).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.framework import FrameworkConfig
from ..framework.registry import register_strategy
from ..models.core import Effect
from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import SchedState, init_state
from ..ops import tpu as T
from ..plugins.builtin import DEFAULT_WEIGHTS
from ..utils.metrics import (
    fragmentation_gauges,
    series_gauges,
    utilization_means,
)
from .runtime import ReplayResult, events_hash, validate_node_events
from .telemetry import TelemetryCollector, TelemetryConfig
from .waves import WaveBatch, pack_waves


class _NullCtx:
    """No-op context for phase ticks when telemetry is off."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


def _make_tick(tel):
    """Phase-tick factory shared by both replay paths: the telemetry
    phase timer when collecting, stacked under a
    ``jax.profiler.TraceAnnotation`` when ``KSIM_PROFILE_DIR`` is armed
    (round 12 device-profiler hooks) — the annotation names the
    PHASE_NAMES phase in XLA traces. ``profiling_active`` is consulted
    ONCE per replay, here; with profiling off the returned callable is
    exactly the pre-round-12 lambda."""
    base = (
        (lambda name: tel.phases.tick(name))
        if tel is not None
        else (lambda name: _NULL_CTX)
    )
    from ..utils.profiling import annotate, profiling_active

    if not profiling_active():
        return base
    import contextlib

    @contextlib.contextmanager
    def _tick(name):
        with annotate(name), base(name):
            yield

    return _tick


def _chunk_ann(ci: int):
    """Chunk-dispatch annotation: ``chunk:<ci>`` marker in device traces
    when profiling is armed, else the shared no-op context."""
    from ..utils.profiling import annotate, profiling_active

    if not profiling_active():
        return _NULL_CTX
    return annotate(f"chunk:{ci}")


def _file_bytes(path: str) -> int:
    """Blob size for flight-recorder checkpoint rows (0 when unreadable —
    observability never takes the replay down)."""
    import os

    try:
        return os.path.getsize(path)
    except OSError:
        return 0

DEFAULT_PLUGINS = (
    "NodeResourcesFit",
    "TaintToleration",
    "NodeAffinity",
    "InterPodAffinity",
    "PodTopologySpread",
)


@dataclass(frozen=True)
class StepSpec:
    """Static (trace-time) description of the fused Filter+Score step."""

    fit: bool = True
    taints: bool = True
    node_affinity: bool = True
    interpod: bool = True
    spread: bool = True
    fit_strategy: str = "LeastAllocated"
    weights: Tuple[Tuple[str, float], ...] = ()
    resource_weights: Tuple[float, ...] = ()  # [R]
    shape_x: Tuple[float, ...] = (0.0, 100.0)
    shape_y: Tuple[float, ...] = (0.0, 100.0)
    # Static trace properties: gate work the trace can never trigger.
    has_symmetric_pref: bool = True  # any preferred (anti-)affinity terms
    has_gangs: bool = True  # any pod-group membership (gang rollback)
    # Any PreferNoSchedule taint can exist (cluster or injected): when
    # False the taint score row is a constant 100 on every node (raw ≡ 0 →
    # reverse max-normalize), which never changes the argmax — dropped.
    taint_score: bool = True
    # [G] upstream PodTopologySpread topologyNormalizingWeight table:
    # log(size + 2) per match-group's topology ([K8S] scoring.go).
    sp_w_g: Tuple[float, ...] = ()
    # Static guarantee that every possible spread raw ≤ 83886, making the
    # f32 form of the normalize division exactly equal to integer division
    # (see ops.tpu.spread_norm_from_extrema).
    sp_norm_f32: bool = False

    @classmethod
    def from_config(
        cls,
        ec: EncodedCluster,
        config: Optional[FrameworkConfig],
        pods: Optional[EncodedPods] = None,
    ) -> "StepSpec":
        entries = (config.plugins if config and config.plugins is not None else None)
        if entries is None:
            entries = [{"name": n} for n in DEFAULT_PLUGINS]
        names = {e["name"] for e in entries}
        weights = dict(DEFAULT_WEIGHTS)
        if config and config.weights:
            weights.update(config.weights)
        fit_strategy = "LeastAllocated"
        res = {"cpu": 1.0, "memory": 1.0}
        shape = [{"utilization": 0, "score": 0}, {"utilization": 100, "score": 10}]
        for e in entries:
            if e["name"] == "NodeResourcesFit":
                args = e.get("args", {})
                fit_strategy = args.get("strategy", fit_strategy)
                res = args.get("resources", res)
                shape = args.get("shape", shape)
        rw = np.zeros(ec.num_resources, dtype=np.float32)
        for rname, w in res.items():
            ri = ec.vocab._r.get(rname)
            if ri is not None:
                rw[ri] = w
        # Static trace gates: a plugin whose terms never occur in the trace
        # contributes exactly 0 to every mask and normalized score (its raw
        # is all-zero → normalize yields 0), so disabling it is exact.
        na_on = "NodeAffinity" in names
        ip_on = "InterPodAffinity" in names
        sp_on = "PodTopologySpread" in names
        if pods is not None:
            na_on = na_on and bool(
                pods.na_has_req.any() or (pods.na_pref >= 0).any()
            )
            ip_on = ip_on and bool(
                (pods.aff_req >= 0).any()
                or (pods.anti_req >= 0).any()
                or (pods.pref_aff >= 0).any()
            )
            sp_on = sp_on and bool((pods.spread_g >= 0).any())
        return cls(
            fit="NodeResourcesFit" in names,
            taints="TaintToleration" in names,
            taint_score=bool((ec.taint_effect == int(Effect.PREFER_NO_SCHEDULE)).any()),
            node_affinity=na_on,
            interpod=ip_on,
            spread=sp_on,
            fit_strategy=fit_strategy,
            weights=tuple(sorted(weights.items())),
            resource_weights=tuple(float(x) for x in rw),
            shape_x=tuple(float(pt["utilization"]) for pt in shape),
            shape_y=tuple(float(pt["score"]) * 10.0 for pt in shape),
            has_symmetric_pref=(
                bool((pods.pref_aff >= 0).any()) if pods is not None else True
            ),
            has_gangs=(bool((pods.group_id >= 0).any()) if pods is not None else True),
            sp_w_g=(sp_w := _spread_w_table(ec)),
            sp_norm_f32=_spread_norm_f32_ok(sp_w, pods) if sp_on else False,
        )


def _spread_norm_f32_ok(sp_w, pods: Optional[EncodedPods]) -> bool:
    """True when NO trace state can push a spread raw score past 83886 —
    the bound under which the f32 normalize division is exactly the
    integer division (ops.tpu.spread_norm_from_extrema). Conservative:
    per-group counts are bounded by the total pods matching the group
    (plus a wave-correction margin), summed over the pod's constraint
    width at the largest weight/skew."""
    if pods is None:
        return False
    SPw = pods.spread_g.shape[1]
    if SPw == 0:
        return True
    pmg_tot = pods.pod_matches_group.sum(axis=0).astype(np.float64)
    w = np.asarray(sp_w, np.float64)
    L = min(len(pmg_tot), len(w))
    gm = float((pmg_tot[:L] * w[:L]).max()) if L else 0.0
    skew_max = float(pods.spread_skew.max()) if pods.spread_skew.size else 0.0
    bound = SPw * (gm + 64.0 * w.max(initial=0.0) + max(skew_max - 1.0, 0.0))
    return bound <= 80_000.0


def _spread_w_table(ec: EncodedCluster) -> Tuple[float, ...]:
    """[G] upstream topologyNormalizingWeight (log(size + 2)) per
    match-group, matching ops.cpu.spread_weight value-for-value: f64 log
    cast once to f32."""
    G = max(ec.num_groups, 1)
    gt = (
        ec.group_topo[:G]
        if ec.group_topo.shape[0] >= G
        else np.full(G, PAD, np.int32)
    )
    nd_g = np.where(gt >= 0, ec.num_domains[np.clip(gt, 0, None)], 0)
    w = np.log(nd_g.astype(np.float64) + 2.0).astype(np.float32)
    return tuple(float(x) for x in w)


def spec_plugin_names(spec: StepSpec) -> Tuple[str, ...]:
    """Active Filter plugins in evaluation order — the key order for the
    in-scan rejection counters. Must stay aligned with both
    :func:`eval_pod`'s mask chain and the CPU ``make_plugins`` default
    order (plugins.builtin.PLUGIN_FACTORIES)."""
    names = []
    if spec.fit:
        names.append("NodeResourcesFit")
    if spec.taints:
        names.append("TaintToleration")
    if spec.node_affinity:
        names.append("NodeAffinity")
    if spec.interpod:
        names.append("InterPodAffinity")
    if spec.spread:
        names.append("PodTopologySpread")
    return tuple(names)


def eval_pod(
    dc: T.DevCluster,
    d: T.Derived,
    st: T.DevState,
    s: T.PodSlot,
    spec: StepSpec,
    want_masks: bool = False,
):
    """Fused Filter + Score for one slot against all nodes → (feasible [N],
    scores [N]). Mirrors SchedulerFramework.feasible_mask/score_nodes.
    ``want_masks=True`` (telemetry instrumentation) additionally returns
    the ordered per-plugin masks for first-reject attribution."""
    N = dc.allocatable.shape[0]
    masks = []
    feasible = jnp.ones(N, dtype=bool)
    if spec.fit:
        m = T.fit_mask(dc, st, s)
        masks.append(m)
        feasible = feasible & m
    if spec.taints:
        m = T.taint_mask(dc, s)
        masks.append(m)
        feasible = feasible & m
    if spec.node_affinity:
        m = T.node_affinity_mask(d, s)
        masks.append(m)
        feasible = feasible & m
    if spec.interpod:
        m = T.interpod_filter_mask(d, st, s)
        masks.append(m)
        feasible = feasible & m
    if spec.spread:
        m = T.spread_filter_mask(d, st, s)
        masks.append(m)
        feasible = feasible & m

    w = dict(spec.weights)
    total = jnp.zeros(N, dtype=jnp.float32)
    if spec.fit and w.get("NodeResourcesFit", 1.0) != 0:
        rw = np.asarray(spec.resource_weights, dtype=np.float32)  # static
        if spec.fit_strategy == "LeastAllocated":
            raw = T.least_allocated_score(dc, st, s, rw)
        elif spec.fit_strategy == "MostAllocated":
            raw = T.most_allocated_score(dc, st, s, rw)
        else:
            raw = T.requested_to_capacity_ratio_score(
                dc, st, s, rw, spec.shape_x, spec.shape_y
            )
        total = total + w.get("NodeResourcesFit", 1.0) * raw
    if spec.taints and spec.taint_score and w.get("TaintToleration", 1.0) != 0:
        raw = T.taint_prefer_count(dc, s)
        total = total + w.get("TaintToleration", 1.0) * T.normalize_max(raw, feasible, reverse=True)
    if spec.node_affinity and w.get("NodeAffinity", 1.0) != 0:
        raw = T.node_affinity_score(d, s)
        total = total + w.get("NodeAffinity", 1.0) * T.normalize_max(raw, feasible)
    if spec.interpod and w.get("InterPodAffinity", 1.0) != 0:
        raw = T.interpod_score(d, st, s, spec.has_symmetric_pref)
        total = total + w.get("InterPodAffinity", 1.0) * T.normalize_min_max(raw, feasible)
    if spec.spread and w.get("PodTopologySpread", 1.0) != 0:
        raw, ignored, any_sp = T.spread_score_upstream(
            d, st, s, T._padded_w_table(spec.sp_w_g, d.gdom_f.shape[0])
        )
        total = total + w.get("PodTopologySpread", 1.0) * T.spread_upstream_normalize(
            raw, ignored, feasible, any_sp, spec.sp_norm_f32
        )
    if want_masks:
        return feasible, total, masks
    return feasible, total


def make_wave_step(
    dc: T.DevCluster, d: T.Derived, wave_width: int, spec: StepSpec, wvec=None
):
    """Build the scan body: one wave = W sequential slot placements +
    wave-boundary gang commit (SURVEY.md §3.3 Permit-as-masked-commit).

    ``wvec``: optional traced policy vector (ops.tpu.POLICY_COLS) replacing
    the static score weights — the round 9 tuner's population axis.

    ``dc``/``d`` are loop invariants CLOSED OVER, not carried — keeping them
    out of the scan carry stops XLA copying ~10s of MB per iteration (the
    single biggest perf bug in the earlier [G, D]-carry design).

    The per-slot evaluation is the fused path (ops.tpu.build_wave_pre +
    eval_pod_fused): all state-independent tensors are computed for the
    whole wave in one batched shot, and each slot's sequential chain is
    ~12 non-fusable ops instead of ~30 — bit-identical to :func:`eval_pod`
    (pinned by the parity suites)."""

    def wave_step(st: T.DevState, slot_batch: T.PodSlot):
        pre = T.build_wave_pre(dc, d, slot_batch, spec)
        widths = T.wave_widths(slot_batch, spec)
        choices, placeds = [], []
        for wslot in range(wave_width):
            s = jax.tree.map(lambda a: a[wslot], slot_batch)
            p = jax.tree.map(lambda a: a[wslot], pre)
            feasible, scores, any_f = T.eval_pod_fused(
                dc, d, st, s, p, spec, widths, wvec=wvec
            )
            node, _ = T.select_node(scores, feasible)  # XLA CSEs the any()
            placed = any_f & s.valid
            st = T.apply_binding(d, st, s, node, placed)
            choices.append(node)
            placeds.append(placed)
        choice = jnp.stack(choices)  # [W]
        placed = jnp.stack(placeds)  # [W]
        if spec.has_gangs:
            groups = slot_batch.group  # [W]
            same = (groups[:, None] == groups[None, :]) & (groups[:, None] >= 0)
            fail = jnp.any(same & ~placed[None, :], axis=1)  # gang all-or-nothing
            revert = placed & fail
            st = T.apply_unbind_wave(d, st, slot_batch, choice, revert)
            final = jnp.where(placed & ~fail, choice, PAD).astype(jnp.int32)
        else:
            final = jnp.where(placed, choice, PAD).astype(jnp.int32)
        return st, final

    return wave_step


def make_chunk_fn(wave_width: int, spec: StepSpec):
    """jit-compiled: (DevCluster, DevState, slots[C, W]) → (DevState,
    choices[C, W]). Derived tensors are rebuilt inside jit from the cluster
    tensors, so perturbed clusters reuse the same executable. The state
    buffers are donated — the carry updates in place across chunk calls."""

    def chunk_fn(dc: T.DevCluster, state: T.DevState, slots: T.PodSlot):
        d = T.Derived.build(dc)
        wave_step = make_wave_step(dc, d, wave_width, spec)
        state, choices = jax.lax.scan(wave_step, state, slots)
        return state, choices

    return jax.jit(chunk_fn, donate_argnums=(1,))


def make_wave_step_rej(dc: T.DevCluster, d: T.Derived, wave_width: int, spec: StepSpec):
    """Instrumented v2 wave step (telemetry ``series``+ on the plain
    path): same placements as :func:`make_wave_step` — via the reference
    :func:`eval_pod`, bit-identical to the fused path by the parity
    suites — plus a carried [K] i32 vector of in-scan first-reject
    counts (ops.tpu.first_reject_counts) in ``spec_plugin_names`` order.
    Only fully-failed VALID slots charge counts; gang-reverted members
    (individually feasible, rolled back by Permit) charge nothing —
    matching the CPU engine, which records no attempt for them."""

    def wave_step(carry, slot_batch: T.PodSlot):
        st, rej = carry
        choices, placeds = [], []
        for wslot in range(wave_width):
            s = jax.tree.map(lambda a: a[wslot], slot_batch)
            feasible, scores, masks = eval_pod(dc, d, st, s, spec, want_masks=True)
            node, placed_any = T.select_node(scores, feasible)
            placed = placed_any & s.valid
            rej = rej + T.first_reject_counts(masks, (~placed_any) & s.valid)
            st = T.apply_binding(d, st, s, node, placed)
            choices.append(node)
            placeds.append(placed)
        choice = jnp.stack(choices)  # [W]
        placed = jnp.stack(placeds)  # [W]
        if spec.has_gangs:
            groups = slot_batch.group  # [W]
            same = (groups[:, None] == groups[None, :]) & (groups[:, None] >= 0)
            fail = jnp.any(same & ~placed[None, :], axis=1)
            revert = placed & fail
            st = T.apply_unbind_wave(d, st, slot_batch, choice, revert)
            final = jnp.where(placed & ~fail, choice, PAD).astype(jnp.int32)
        else:
            final = jnp.where(placed, choice, PAD).astype(jnp.int32)
        return (st, rej), final

    return wave_step


def make_chunk_fn_rej(wave_width: int, spec: StepSpec):
    """jit: (DevCluster, DevState, rej [K] i32, slots [C, W]) → (DevState,
    rej, choices[C, W]) — :func:`make_chunk_fn` with the rejection counter
    threaded through the scan carry and fetched once per replay, never per
    pod. Built lazily by ``replay()`` only at telemetry ``series``+."""

    def chunk_fn(dc: T.DevCluster, state: T.DevState, rej, slots: T.PodSlot):
        d = T.Derived.build(dc)
        wave_step = make_wave_step_rej(dc, d, wave_width, spec)
        (state, rej), choices = jax.lax.scan(wave_step, (state, rej), slots)
        return state, rej, choices

    return jax.jit(chunk_fn, donate_argnums=(1, 2))


def _node_plane_specs():
    """(DevCluster, DevState) PartitionSpec trees for the node-sharded
    chunk program (round 14): [N, ...] leading-axis tensors shard the
    node axis, [*, N] trailing-axis planes shard the last axis, and the
    group/expr tables plus ``match_total`` (replicated semantic state —
    every shard applies the identical scalar updates) carry P()."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import NODE_AXIS

    dc_specs = T.DevCluster(
        allocatable=P(NODE_AXIS),
        node_label_key=P(NODE_AXIS),
        node_label_kv=P(NODE_AXIS),
        node_label_num=P(NODE_AXIS),
        taint_key=P(NODE_AXIS),
        taint_kv=P(NODE_AXIS),
        taint_effect=P(NODE_AXIS),
        node_domain=P(None, NODE_AXIS),
        num_domains=P(),
        expr_key=P(),
        expr_op=P(),
        expr_vals=P(),
        expr_num=P(),
        group_topo=P(),
    )
    st_specs = T.DevState(
        used=P(NODE_AXIS),
        match_count=P(None, NODE_AXIS),
        anti_active=P(None, NODE_AXIS),
        pref_wsum=P(None, NODE_AXIS),
        match_total=P(),
    )
    return dc_specs, st_specs


def make_wave_step_sharded(
    dc: T.DevCluster, d: T.Derived, wave_width: int, spec: StepSpec,
    ctx: "T.ShardCtx",
):
    """:func:`make_wave_step` over one NODE SHARD (round 14 big-scenario
    mode; runs inside shard_map — ``dc``/``d``/``st`` carry the local
    node block). Three deltas from the replicated body, each exact (see
    ops.tpu's sharded section): the fused eval takes ``shard_ctx``;
    selection is the two-stage :func:`ops.tpu.select_node_sharded` whose
    global (score, node-id, bind-domain-row) exchange also yields
    ``placed`` (the local ``any_f`` never decides placement); and the
    winner's [G] domain row is stacked across the wave so gang rollback
    can undo count-plane updates without re-reading the owner shard."""

    def wave_step(st: T.DevState, slot_batch: T.PodSlot):
        pre = T.build_wave_pre(dc, d, slot_batch, spec)
        widths = T.wave_widths(slot_batch, spec)
        choices, placeds, gdoms, hasdoms = [], [], [], []
        for wslot in range(wave_width):
            s = jax.tree.map(lambda a: a[wslot], slot_batch)
            p = jax.tree.map(lambda a: a[wslot], pre)
            feasible, scores, _any_f = T.eval_pod_fused(
                dc, d, st, s, p, spec, widths, shard_ctx=ctx
            )
            node, placed_any, gdom_at, has_dom = T.select_node_sharded(
                scores, feasible, d.gdom_f, ctx
            )
            placed = placed_any & s.valid
            st = T.apply_binding_sharded(
                d, st, s, node, placed, gdom_at, has_dom, ctx
            )
            choices.append(node)
            placeds.append(placed)
            gdoms.append(gdom_at)
            hasdoms.append(has_dom)
        choice = jnp.stack(choices)  # [W] GLOBAL node ids
        placed = jnp.stack(placeds)  # [W]
        if spec.has_gangs:
            groups = slot_batch.group  # [W]
            same = (groups[:, None] == groups[None, :]) & (groups[:, None] >= 0)
            fail = jnp.any(same & ~placed[None, :], axis=1)
            revert = placed & fail
            st = T.apply_unbind_wave_sharded(
                d, st, slot_batch, choice, revert,
                jnp.stack(gdoms), jnp.stack(hasdoms), ctx,
            )
            final = jnp.where(placed & ~fail, choice, PAD).astype(jnp.int32)
        else:
            final = jnp.where(placed, choice, PAD).astype(jnp.int32)
        return st, final

    return wave_step


def make_chunk_fn_sharded(
    wave_width: int, spec: StepSpec, mesh, ctx: "T.ShardCtx"
):
    """:func:`make_chunk_fn` under shard_map over the NODE axis: each
    device scans the same waves against its node-plane block; the slots
    replicate; the choices come out replicated (every shard computes the
    same global winner — out_spec P()). shard_map, not jit-with-
    shardings, for the same reason as the what-if mesh path: the sharding
    becomes a compile-time guarantee and the ONLY collectives are the
    tiny per-slot exchanges the sharded primitives spell out (pinned by
    tests/test_mesh_hlo.py)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    dc_specs, st_specs = _node_plane_specs()

    def body(dc: T.DevCluster, state: T.DevState, slots: T.PodSlot):
        d = T.Derived.build(dc)
        wave_step = make_wave_step_sharded(dc, d, wave_width, spec, ctx)
        state, choices = jax.lax.scan(wave_step, state, slots)
        return state, choices

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(dc_specs, st_specs, P()),
        out_specs=(st_specs, P()),
        check_rep=False,
    )
    return jax.jit(fn, donate_argnums=(1,))


def replicated_resident_bytes(
    ec: EncodedCluster, pods: EncodedPods, pods_resident: bool = True
) -> int:
    """Per-device HBM estimate of the REPLICATED single-scenario
    residency: the DevCluster tensors, the DevState planes, and (when
    ``pods_resident`` — the v3 unpaged layout) the whole-trace
    SlotSource/ExtraSource rows. The ``KSIM_MAX_REPLICATED_BYTES`` gate
    in JaxReplayEngine refuses replicated runs past this estimate with a
    pointer at node_shards/paged — the Borg-scale shapes (10k nodes ×
    1M pods) are exactly the ones that OOM one chip silently otherwise."""
    dc_fields = (
        ec.allocatable, ec.node_label_key, ec.node_label_kv,
        ec.node_label_num, ec.taint_key, ec.taint_kv, ec.taint_effect,
        ec.node_domain, ec.num_domains, ec.expr_key, ec.expr_op,
        ec.expr_vals, ec.expr_num, ec.group_topo,
    )
    total = sum(int(np.asarray(a).nbytes) for a in dc_fields)
    N, R = ec.num_nodes, ec.num_resources
    G = max(ec.num_groups, 1)
    total += 4 * (N * R + 3 * G * N + G)  # DevState planes (f32)
    if pods_resident:
        pod_fields = (
            pods.requests, pods.tol_key, pods.tol_kv, pods.tol_effect,
            pods.na_req, pods.na_has_req, pods.na_pref, pods.na_pref_w,
            pods.aff_req, pods.anti_req, pods.pref_aff, pods.pref_aff_w,
            pods.spread_g, pods.spread_skew, pods.spread_dns,
            pods.pod_matches_group, pods.group_id,
        )
        total += sum(int(np.asarray(a).nbytes) for a in pod_fields)
    return total


def _pager_thread_enabled() -> bool:
    """Round-19 A/B gate for the threaded pager. Read at pager
    construction (every ``replay()`` builds a fresh pager), so tests and
    the ``overlap:`` config section flip it per run: set
    ``KSIM_PAGER_THREAD=0`` to fetch pages on the chunk-loop thread as
    rounds 14–18 did."""
    return os.environ.get("KSIM_PAGER_THREAD", "1") not in ("", "0")


class _PodPager:
    """Rolling two-deep host→device page prefetcher (round 14 paged pod
    waves): ``get(ci)`` returns chunk ci's staged page (staging it now if
    the prefetch missed — first chunk, resume jumps); ``prefetch(ci)`` is
    called right after dispatching a chunk, so the next page's H2D copies
    are issued while the device is still scanning — the paged twin of the
    double-buffered boundary staging.

    Round 19 (``threaded=True``, the default via ``KSIM_PAGER_THREAD``):
    ``prefetch`` hands the encode/pack + ``device_put`` to ONE background
    worker (a bounded single-slot hand-off — the queue depth stays 2
    counting the in-flight chunk's own page), so a prefetch only costs
    loop wall when the fetch genuinely outruns chunk compute. Pages are
    pure functions of the chunk index, so the staged values are
    bit-identical wherever the fetch runs. Attribution:

    * ``stalls`` / ``stall_s`` — EXPOSED wall: synchronous misses plus
      (threaded) blocking waits on a still-in-flight prefetch. Miss
      COUNTS are deterministic (first chunk, resume jumps); wait counts
      ride ``waits`` because whether a wait occurs is a race outcome.
    * ``prefetch_wall_s`` — the prefetch fetches' own wall: HIDDEN when
      threaded, loop-exposed when not. Overlap efficiency is
      ``prefetch_wall_s / (prefetch_wall_s + stall_s)`` under threading.
    * ``invalidations`` — staged pages discarded because ``get`` asked
      for a different chunk (a resume jump): the stale page is dropped
      and the requested fetch re-issued instead of silently serving a
      plain miss (round-19 fix — previously indistinguishable from a
      cold stall in the flight ``page`` rows)."""

    def __init__(self, fetch, threaded: bool = False):
        self._fetch = fetch
        self._next = None  # (ci, page) or (ci, Future) when threaded
        self.stalls = 0
        self.stall_s = 0.0
        self.last_stall_s = 0.0
        self.prefetches = 0
        self.waits = 0
        self.wait_s = 0.0
        self.prefetch_wall_s = 0.0
        self.invalidations = 0
        self.threaded = bool(threaded)
        self._pool = None
        if self.threaded:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ksim-pager"
            )

    @property
    def depth(self) -> int:
        """Pages currently staged ahead (0 or 1 — the prefetcher is
        two-deep counting the in-flight chunk's own page)."""
        return 0 if self._next is None else 1

    def _timed_fetch(self, ci: int):
        # Runs on the worker thread when threaded — its wall is the
        # HIDDEN side of the overlap ledger.
        t0 = time.perf_counter()
        page = self._fetch(ci)
        self.prefetch_wall_s += time.perf_counter() - t0
        return page

    def _resolve(self, staged):
        """Staged entry → page, charging any blocking wait as exposed
        stall wall (the fetch outran chunk compute)."""
        from concurrent.futures import Future

        if not isinstance(staged, Future):
            return staged
        if staged.done():
            return staged.result()
        t0 = time.perf_counter()
        page = staged.result()
        dt = time.perf_counter() - t0
        self.waits += 1
        self.wait_s += dt
        self.stall_s += dt
        self.last_stall_s = dt
        return page

    def get(self, ci: int):
        staged, self._next = self._next, None
        if staged is not None and staged[0] != ci:
            # Resume jump: the staged page is for another chunk. Drop it
            # (draining the worker so the single slot is free again) and
            # re-issue the fetch for the chunk actually requested.
            self.invalidations += 1
            try:
                self._resolve_quietly(staged[1])
            except Exception:
                pass
            staged = None
        if staged is not None:
            return self._resolve(staged[1])
        t0 = time.perf_counter()
        page = self._fetch(ci)
        self.last_stall_s = time.perf_counter() - t0
        self.stall_s += self.last_stall_s
        self.stalls += 1
        return page

    def _resolve_quietly(self, staged) -> None:
        from concurrent.futures import Future

        if isinstance(staged, Future) and not staged.cancel():
            staged.result()

    def prefetch(self, ci: int) -> None:
        self.prefetches += 1
        if self._pool is not None:
            self._next = (ci, self._pool.submit(self._timed_fetch, ci))
        else:
            self._next = (ci, self._timed_fetch(ci))

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


def make_chunk_fn3_src(static3, shared3, rep_slots, wave_width: int, spec: StepSpec):
    """The v3 chunk program with the slot gathers INSIDE the jit:
    (dc, state, SlotSource, ExtraSource, idx [C, W]) → (state, choices).
    One dispatch per chunk and only the index array as per-chunk input —
    the tunneled-device round-trip latency of separate gather dispatches
    was a measurable slice of the north-star wall."""
    from ..ops import tpu3 as V3

    def chunk_fn(dc: T.DevCluster, state, src, xsrc, idx):
        slots = T.gather_slots_device(src, idx)
        extra = V3.gather_extra_device(xsrc, idx)
        d = T.Derived.build(dc)
        cmasks = V3.class_masks(dc, d, static3, spec, rep_slots)
        step = V3.make_wave_step3(
            dc, d, shared3, static3, wave_width, spec, cmasks
        )
        state, choices = jax.lax.scan(step, state, (slots, extra))
        return state, choices

    return jax.jit(chunk_fn, donate_argnums=(1,))


def wave_start_times(pods: EncodedPods, idx: np.ndarray) -> np.ndarray:
    """Arrival time of each wave's first valid pod (inf for padding) —
    the boundary clock shared by both engines, BoundaryOps and the
    granularity guard."""
    first = idx[:, 0]
    safe = np.clip(first, 0, None)
    return np.where(first >= 0, pods.arrival[safe], np.inf)


def bind_chunk_of(pods: EncodedPods, idx: np.ndarray, C: int) -> np.ndarray:
    """[P] chunk index each pod's wave belongs to (pre-bound = −2,
    unscheduled = huge) — the bind-chunk side of the one-chunk-slack
    release rule, shared by the single-replay engine and the batch
    what-if eager folds (the rule must stay identical for anchor
    parity)."""
    W = idx.shape[1]
    flat = idx.reshape(-1)
    v = flat >= 0
    out = np.full(pods.num_pods, 1 << 30, np.int64)
    out[flat[v]] = np.nonzero(v)[0] // (C * W)
    out[pods.bound_node >= 0] = -2
    return out


def preemption_walk(assignments: np.ndarray, idx: np.ndarray, finals: np.ndarray,
                    ev_node: np.ndarray, ev_tier: np.ndarray,
                    pod_tier: np.ndarray, nongang: np.ndarray,
                    released: Optional[np.ndarray] = None) -> None:
    """Reconstruct assignments under tier evictions, in place: walk waves
    in order, unassigning prior-wave lower-tier non-gang victims at each
    eviction event, then applying the wave's choices (in-wave victims are
    already PAD in the device output). ``released``: completed pods keep
    their assignment but can no longer be evicted (their resources are
    gone — the device tier planes already dropped them). Shared by the
    replay engine and the what-if collect/completions paths.

    Vectorized (round 5): eviction events are rare, so the walk is bulk
    segment folds between event waves plus one [P] mask per event — the
    S-stacked eager folds of the batch preemption × completions path
    would otherwise pay a Python iteration per (scenario, wave)."""

    def fold(lo: int, hi: int) -> None:
        r = idx[lo:hi].reshape(-1)
        ch = finals[lo:hi].reshape(-1)
        ok = r >= 0
        assignments[r[ok]] = ch[ok]

    ev_waves = np.nonzero(np.asarray(ev_node) >= 0)[0]
    start = 0
    for w in ev_waves:
        w = int(w)
        fold(start, w)  # waves before the event commit first
        vict = (
            (assignments == int(ev_node[w]))
            & (pod_tier < int(ev_tier[w]))
            & nongang
        )
        if released is not None:
            vict &= ~released
        assignments[vict] = PAD
        start = w
    fold(start, idx.shape[0])


def rebuild_fork_state(pods: EncodedPods, idx: np.ndarray, C: int, outs,
                       wave_times: np.ndarray, upto_chunk: int,
                       reconstruct_released: bool = True,
                       slack: int = 1):
    """Replay saved per-chunk choices for chunks 0..upto_chunk-1 and apply
    the completions an uninterrupted completions-on run would have released
    at each boundary. Returns (host_assign [P], released [P]).

    A release is due at boundary b when the pod was placed in a chunk
    ≤ b−2 (pre-bound pods count as chunk −2, eligible at every boundary)
    and its arrival+duration is at or before the boundary's start time —
    the one-chunk slack that lets the live engines overlap host release
    computation with the in-flight chunk. Shared by JaxReplayEngine.replay
    resume and the what-if fork path (which previously started released
    all-False and re-subtracted every pre-fork release — advisor round-2)."""
    host_assign = np.where(pods.bound_node >= 0, pods.bound_node, PAD).astype(
        np.int32
    )
    chunk_of = np.where(pods.bound_node >= 0, -2, 1 << 30).astype(np.int64)
    rel_time = pods.arrival + np.where(
        np.isfinite(pods.duration), pods.duration, np.inf
    )
    for cj in range(upto_chunk):
        rows = idx[cj * C : (cj + 1) * C]
        ch = np.asarray(outs[cj]).reshape(rows.shape)
        v = rows >= 0
        host_assign[rows[v]] = ch[v]
        chunk_of[rows[v]] = cj
    released = np.zeros(pods.num_pods, bool)
    if reconstruct_released:
        # O(upto_chunk × P) — callers holding a persisted mask skip this.
        for b in range(upto_chunk):
            tb = wave_times[b * C]
            if np.isfinite(tb):
                released |= (
                    (host_assign != PAD)
                    & (chunk_of < b - slack)
                    & np.isfinite(rel_time)
                    & (rel_time <= tb)
                )
    return host_assign, released


def snapshot_carriers(tree) -> list:
    """Host-layout leaf list of a chunk-loop carrier tree (round 15 DCN
    recovery checkpoints). Flattening drops the container structure on
    purpose: the restoring process rebuilds an IDENTICAL fresh carrier
    tree (same engine ctor args, deterministic dict order) and matches
    leaves positionally, so NamedTuple/dataclass containers never need to
    round-trip through the gather payload walker."""
    import jax

    return [
        np.asarray(jax.device_get(leaf))
        for leaf in jax.tree_util.tree_leaves(tree)
    ]


def checkpoint_payload(cursor: int, sig, carriers, outs) -> dict:
    """The one checkpoint-blob payload schema every chunk-loop resume
    path seeds from — claimant recovery (round 15), work-queue steals
    and speculation (round 18), and the durable-journal whole-fleet
    restart (round 20): the loop cursor, the engine signature the
    restorer must match, the host-layout carrier leaves, and the
    per-chunk outputs accumulated so far (host-resident, so the payload
    is device-free and survives pickling into the KV store and the
    filesystem journal alike)."""
    import jax

    return {
        "cursor": int(cursor),
        "sig": list(sig),
        "leaves": snapshot_carriers(carriers),
        "outs": jax.device_get(outs),
    }


def restore_carriers(tree, host_leaves):
    """Inverse of :func:`snapshot_carriers` against a freshly-built
    carrier ``tree`` of identical structure: each host leaf is cast to
    the fresh leaf's dtype and ``device_put`` with the fresh leaf's
    sharding, so the restored tree is layout-identical to one the chunk
    loop produced locally. Raises ValueError on any structural mismatch —
    callers treat that as \"checkpoint unusable\" and re-execute the
    block from chunk 0 (still byte-identical, just slower)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten(tree)
    if len(flat) != len(host_leaves):
        raise ValueError(
            f"checkpoint carries {len(host_leaves)} leaves but the fresh "
            f"carriers have {len(flat)} — engine modes differ"
        )
    out = []
    for k, (fresh, host) in enumerate(zip(flat, host_leaves)):
        host = np.asarray(host)
        shape = tuple(getattr(fresh, "shape", np.shape(fresh)))
        if shape != tuple(host.shape):
            raise ValueError(
                f"checkpoint leaf {k}: shape {tuple(host.shape)} != fresh "
                f"{shape}"
            )
        dtype = getattr(fresh, "dtype", None)
        if dtype is not None and host.dtype != np.dtype(dtype):
            host = host.astype(dtype)
        if isinstance(fresh, jax.Array):
            host = jax.device_put(host, fresh.sharding)
        out.append(host)
    return jax.tree_util.tree_unflatten(treedef, out)


def compiled_cache_size(fn) -> Optional[int]:
    """Number of compiled executables a jitted callable holds, or None
    where the jaxlib in play doesn't expose ``_cache_size`` (same guard
    the round-9 tuner uses). The serving plane pins this at 1 per pool
    engine — a warm query must never recompile."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def rep_slots_for(static3, pods: EncodedPods):
    """(tol_reps, na_reps) PodSlot batches of class representatives. Empty
    gathers when the class path is off — keeps unused (possibly huge)
    constants out of the jitted closures."""
    none = np.zeros(0, np.int32)
    return (
        T.gather_slots(pods, static3.tol_rep if static3.use_tol_classes else none),
        T.gather_slots(pods, static3.na_rep if static3.use_na_classes else none),
    )


class JaxReplayEngine:
    def __init__(
        self,
        ec: EncodedCluster,
        pods: EncodedPods,
        config: Optional[FrameworkConfig] = None,
        wave_width: int = 8,
        chunk_waves: int = 2048,
        engine: str = "v3",
        dmax_coarse: int = 128,
        preemption=False,
        completions: Optional[bool] = None,
        retry_buffer: int = 0,
        granularity_guard: bool = True,
        lazy_boundary: bool = True,
        double_buffer: bool = True,
        telemetry=None,
        node_shards: int = 0,
        paged: bool = False,
        flight_recorder=None,
    ):
        """``engine``: "v3" (domain-space state, wave-deferred commits — the
        fast path) or "v2" (node-space planes; also the whatif fallback when
        label perturbations change topology domains). ``preemption``:
        ``"tier"``/``True`` = the greedy engines' in-scan tier preemption
        (sim.greedy docstring), v3 only; ``"kube"`` (round 5) = the EXACT
        kube minimal-victims PostFilter run at chunk boundaries through the
        retry buffer (sim.boundary docstring — the device program is
        unchanged; victims/binds land on the carry as rank-1 plane deltas).
        ``"kube"`` requires ``retry_buffer > 0``.
        ``completions``: chunk-granular pod completions — before each chunk,
        placed pods whose ``arrival + duration`` is at or before the chunk
        start release their resources and count contributions (host-computed
        delta planes subtracted from the carry). Active when the trace has
        finite durations. Works WITH tier ``preemption`` since round 4:
        releases also drop the per-tier planes (pod tiers are static), folds
        run eagerly so eviction events precede the next boundary's release
        decisions, and evicted pods never release (their assignment is PAD
        by the time their boundary arrives); completed pods can no longer
        be evicted. Anchored by
        ``greedy_replay(preemption=True, completions_chunk_waves=...)``.
        ``retry_buffer`` (round 5, task r4-#3): the [K8S] activeQ analogue
        on the single-replay engine — failed non-gang pods re-attempt
        placement at every chunk boundary via the host boundary pass
        (sim.boundary), bit-identical to
        ``greedy_replay(retry_buffer=...)``.
        ``lazy_boundary`` (round 6): quiet chunks — no failures, empty
        retry queue — skip the mirror plane fold entirely and overlap the
        choices fetch with the next chunk's dispatch; only a scalar
        failure count blocks per chunk. Bit-identical to the eager path
        (set False to force the old per-chunk blocking folds).
        ``double_buffer`` (round 10, on top of lazy): stage boundary
        b's RELEASE passes (sim.boundary.boundary_releases) before
        blocking on chunk b−1's failure scalar — the host release
        bookkeeping overlaps device compute instead of serializing after
        the fetch. Exact by the one-chunk slack (the release decision
        never reads chunk b−1); skipped per-boundary when chaos events
        are due or series-level telemetry is sampling. Bit-identical
        results and checkpoint blobs either way (pinned by
        tests/test_double_buffer.py).
        ``telemetry``: granularity knob (str | sim.telemetry.TelemetryConfig
        | None → "summary"). "summary" never changes any device program
        (latency bookkeeping + phase timers only); "series" adds rejection
        attribution — through the boundary mirror in retry/kube modes,
        via an instrumented reference (v2) chunk program on the plain
        path — plus boundary-sampled depth series; "timeline" adds the
        event log for the Chrome-trace export. "off" disables everything
        (``ReplayResult.telemetry`` is None).
        ``flight_recorder`` (round 16): None (default, off), a JSONL path,
        or a :class:`sim.flight.FlightRecorderConfig` — streams one
        in-flight event per chunk boundary (sim.flight docstring).
        Bit-parity pinned: placements, deterministic JSONL and checkpoint
        blobs are identical with the recorder on or off
        (tests/test_flight.py)."""
        from ..ops import tpu3 as V3
        from .greedy import normalize_preemption

        mode = normalize_preemption(preemption)
        if mode == "tier" and engine != "v3":
            raise ValueError("device tier preemption requires engine='v3'")
        if mode == "tier" and retry_buffer:
            raise ValueError(
                "retry_buffer is not supported with tier preemption"
            )
        if mode == "kube" and not retry_buffer:
            raise ValueError(
                "preemption='kube' requires retry_buffer > 0 (failed pods "
                "reach the PostFilter through the boundary retry pass)"
            )
        # Round 14 big-scenario mode: shard ONE scenario's node planes over
        # the local devices (node_shards > 1) and/or stream pod pages
        # host->device (paged) instead of keeping the whole trace resident.
        self.node_shards = int(node_shards or 0)
        self.paged = bool(paged)
        if self.node_shards > 1 and mode == "tier":
            raise ValueError(
                "node_shards is not supported with tier preemption: the "
                "node-sharded chunk program is the node-space (v2) engine "
                "and tier preemption is v3-only — use preemption='kube'"
            )
        if self.paged and (mode == "kube" or retry_buffer):
            raise ValueError(
                "paged=True is not supported with retry_buffer / "
                "preemption='kube' yet — the boundary mirror pre-stages the "
                "whole wave index tensor; run paged replays on the plain path"
            )
        if self.node_shards > 1 and engine == "v3":
            from ..utils.metrics import log

            log.info(
                "node_shards=%d: forcing engine='v2' — the node-sharded "
                "chunk program runs on the node-space planes (the v3 "
                "domain-space layout replicates exactly the per-domain "
                "state node sharding is meant to split)",
                self.node_shards,
            )
            engine = "v2"
        self.ec = ec
        self.pods = pods
        self.spec = StepSpec.from_config(ec, config, pods)
        self._config = config
        self.chunk_waves = chunk_waves
        self.engine = engine
        self.dmax_coarse = dmax_coarse
        # self.preemption stays the TIER flag (the in-scan feature the
        # compiled program and the what-if collect paths key off).
        self.preemption = mode == "tier"
        self.kube = mode == "kube"
        self.retry_buffer = int(retry_buffer)
        self.lazy_boundary = bool(lazy_boundary)
        self.double_buffer = bool(double_buffer)
        self.completions = completions
        self.granularity_guard = granularity_guard
        self.telemetry_cfg = TelemetryConfig.resolve(telemetry)
        # Flight recorder (round 16): validate the spec up front (a bad
        # path string should fail at construction, not mid-replay); each
        # replay() opens its own stream from it.
        from .flight import FlightRecorder, FlightRecorderConfig

        self.flight_recorder = (
            flight_recorder
            if isinstance(flight_recorder, FlightRecorder)
            else FlightRecorderConfig.resolve(flight_recorder)
        )
        # Replicated-residency refusal (Borg-scale guard): with a per-device
        # byte budget set, a replicated run whose single-scenario planes
        # exceed it is refused UP FRONT with the fix spelled out, instead of
        # dying in an opaque device OOM mid-replay.
        import os

        budget = os.environ.get("KSIM_MAX_REPLICATED_BYTES")
        if budget and self.node_shards <= 1:
            est = replicated_resident_bytes(
                ec, pods, pods_resident=(engine == "v3" and not self.paged)
            )
            if est > int(budget):
                raise ValueError(
                    f"replicated single-scenario residency ~{est / 2**20:.0f} "
                    f"MiB/device exceeds KSIM_MAX_REPLICATED_BYTES "
                    f"({int(budget) / 2**20:.0f} MiB): shard the node axis "
                    "across devices (node_shards=...) and/or stream pod "
                    "pages (paged=True) instead of the replicated path"
                )
        if self.node_shards > 1:
            from ..parallel import mesh as M

            self._node_mesh = M.make_node_mesh(self.node_shards)
            n_real = ec.num_nodes
            n_local = -(-n_real // self.node_shards)
            self._n_real = n_real
            self._n_pad = n_local * self.node_shards
            self._shard_ctx = T.ShardCtx(
                axis=M.NODE_AXIS, n_local=n_local, n_real=n_real,
                nshards=self.node_shards,
            )
            self.dc = self._shard_cluster(ec)
        else:
            self.dc = T.DevCluster.from_encoded(ec)
        # "auto": measured optimum is W=8 across shapes (W=16 loses to the
        # W² in-wave coupling even on coarse-only traces) — kept as a
        # resolution point for when the kernel cost model changes.
        if wave_width == "auto":
            wave_width = 8
        self.wave_width = wave_width
        if engine == "v3":
            self.static3 = V3.V3Static.build(
                ec, pods, self.spec, dmax_coarse, preemption=self.preemption
            )
            self.shared3 = V3.Shared3.build(ec, self.static3)
            self.chunk_fn = make_chunk_fn3_src(
                self.static3, self.shared3, rep_slots_for(self.static3, pods),
                wave_width, self.spec,
            )
        elif self.node_shards > 1:
            self.chunk_fn = make_chunk_fn_sharded(
                wave_width, self.spec, self._node_mesh, self._shard_ctx
            )
        else:
            self.chunk_fn = make_chunk_fn(wave_width, self.spec)
        self.waves = pack_waves(
            pods, wave_width,
            page_pods=(chunk_waves * wave_width if self.paged else None),
        )
        # Slot data lives on device once; chunks gather rows inside jit
        # (ops.tpu.SlotSource) — only wave indices cross the host boundary.
        # v3-only: the v2 fallback engine still host-gathers, so the device
        # copies would be dead HBM weight there. Paged mode keeps slots on
        # host and streams per-chunk pages instead (SlotSource.page).
        self._slot_src = (
            T.SlotSource.build(pods)
            if engine == "v3" and not self.paged
            else None
        )
        self._extra_src = (
            V3.ExtraSource.build(self.static3, pods.num_pods)
            if engine == "v3" and not self.paged
            else None
        )

    def _shard_cluster(self, ec: EncodedCluster) -> T.DevCluster:
        """Padded + node-sharded device cluster. Node-axis tensors are
        padded to the shard width with NEUTRAL fill (zero capacity, PAD
        labels/taints/domains, no-op taint effect) so pad rows filter out
        identically on every plugin, then placed under the node-plane
        shardings. ``ec`` itself is untouched — results and the host mirror
        always see the real node count."""
        from ..parallel import mesh as M

        n_pad = self._n_pad
        pad = M.pad_node_axis
        host = T.DevCluster(
            allocatable=pad(ec.allocatable, 0, n_pad, 0.0),
            node_label_key=pad(ec.node_label_key, 0, n_pad, PAD),
            node_label_kv=pad(ec.node_label_kv, 0, n_pad, PAD),
            node_label_num=pad(ec.node_label_num, 0, n_pad, 0.0),
            taint_key=pad(ec.taint_key, 0, n_pad, PAD),
            taint_kv=pad(ec.taint_kv, 0, n_pad, PAD),
            taint_effect=pad(ec.taint_effect, 0, n_pad, 0),
            node_domain=pad(ec.node_domain, 1, n_pad, PAD),
            num_domains=np.asarray(ec.num_domains),
            expr_key=np.asarray(ec.expr_key),
            expr_op=np.asarray(ec.expr_op),
            expr_vals=np.asarray(ec.expr_vals),
            expr_num=np.asarray(ec.expr_num),
            group_topo=np.asarray(ec.group_topo),
        )
        dc_specs, _ = _node_plane_specs()
        return M.shard_node_planes(self._node_mesh, host, dc_specs)

    def _put_alloc(self, alloc: np.ndarray):
        """Device copy of an allocatable plane, re-placed under the node
        sharding when the node mesh is active (a bare jnp.asarray would
        leave the replaced DevCluster with mixed shardings and trip the
        shard_map in_specs)."""
        if self.node_shards > 1:
            from jax.sharding import PartitionSpec as P

            from ..parallel import mesh as M

            return jax.device_put(
                alloc, M.node_sharding(self._node_mesh, P(M.NODE_AXIS))
            )
        return jnp.asarray(alloc)

    def _to_dev_state_v2(self, used, mc, aa, pw, mt) -> T.DevState:
        """Device v2 (node-space) state/delta from host planes — padded to
        the shard width and placed under the node-plane shardings when node
        sharding is active, plain device arrays otherwise."""
        if self.node_shards > 1:
            from ..parallel import mesh as M

            n_pad = self._n_pad
            host = T.DevState(
                used=M.pad_node_axis(np.asarray(used, np.float32), 0, n_pad, 0.0),
                match_count=M.pad_node_axis(np.asarray(mc, np.float32), 1, n_pad, 0.0),
                anti_active=M.pad_node_axis(np.asarray(aa, np.float32), 1, n_pad, 0.0),
                pref_wsum=M.pad_node_axis(np.asarray(pw, np.float32), 1, n_pad, 0.0),
                match_total=np.asarray(mt, np.float32),
            )
            _, st_specs = _node_plane_specs()
            return M.shard_node_planes(self._node_mesh, host, st_specs)
        return T.DevState(
            used=jnp.asarray(used),
            match_count=jnp.asarray(mc),
            anti_active=jnp.asarray(aa),
            pref_wsum=jnp.asarray(pw),
            match_total=jnp.asarray(mt),
        )

    def _unshard_state_v2(self, state) -> T.DevState:
        """Host node-space copy of a (possibly node-sharded) v2 carry,
        sliced back to the real node count — checkpoint blobs and result
        planes never see the shard padding, so they are byte-identical
        across shard counts."""
        u = np.asarray(state.used)
        mc = np.asarray(state.match_count)
        aa = np.asarray(state.anti_active)
        pw = np.asarray(state.pref_wsum)
        if self.node_shards > 1:
            n = self._n_real
            u, mc, aa, pw = u[:n], mc[:, :n], aa[:, :n], pw[:, :n]
        return T.DevState(
            used=u, match_count=mc, anti_active=aa, pref_wsum=pw,
            match_total=np.asarray(state.match_total),
        )

    def _init_dev_state(self, force_v2: bool = False):
        from ..ops import tpu3 as V3
        from ..ops.cpu import _group_dom_per_node

        host = init_state(self.ec, self.pods)  # applies pre-bound pods
        gdom = _group_dom_per_node(self.ec)
        self._gdom = gdom
        self._Dhost = host.match_count.shape[1]
        if self.engine == "v3" and not force_v2:
            return V3.DevState3.from_host(
                host.used, host.match_count, host.anti_active, host.pref_wsum,
                self.ec, self.static3, ep=self.pods,
            )
        return self._to_dev_state_v2(
            host.used,
            T.domain_to_node_space(host.match_count, gdom),
            T.domain_to_node_space(host.anti_active, gdom),
            T.domain_to_node_space(host.pref_wsum, gdom),
            host.match_count.sum(axis=1).astype(np.float32),
        )

    def _open_recorder(self):
        """(recorder, owns) for this replay: a fresh stream per replay()
        from the configured spec (owns=True → this replay closes it), or
        a live shared recorder passed in by the caller (owns=False), or
        (None, False) — the default, recorder off."""
        from .flight import FlightRecorder, FlightRecorderConfig

        # Re-resolve here (not just in __init__): callers may assign a
        # raw path onto .flight_recorder between replays (bench.py turns
        # the recorder on for the timed run only).
        spec = FlightRecorderConfig.resolve(self.flight_recorder)
        if spec is None:
            return None, False
        if isinstance(spec, FlightRecorder):
            return spec, False
        meta = {
            "nodes": int(self.ec.num_nodes),
            "pods": int(self.pods.num_pods),
            "node_shards": int(self.node_shards),
            "paged": bool(self.paged),
            "engine": self.engine,
            "chunk_waves": int(self.chunk_waves),
            "resident_bytes": int(
                replicated_resident_bytes(
                    self.ec, self.pods,
                    pods_resident=(self.engine == "v3" and not self.paged),
                )
            ),
        }
        self._last_flight = FlightRecorder(spec, meta=meta)
        return self._last_flight, True

    def _make_exchange_probe(self):
        """Timed probe of the per-slot selection exchange (round 16):
        a jitted shard_map running the EXACT collective shape the sharded
        wave step compiles (ops.tpu.select_node_sharded) — legacy: one
        ``all_gather`` of a ``[2 + 2G]`` f32 row plus the static
        (max score, min id) fold; two-phase (round 19, the default): the
        ``[2]`` f32 all_gather + fold, then the owner-masked ``[2G]``
        psum. The production chunk program is untouched (the exchange
        runs inside its scan, where a host clock cannot reach without
        changing the compiled program — and the compiled program is
        exactly what bit-parity pins); the probe prices one exchange
        round at chunk cadence, and the recorder scales it by the
        chunk's slot count for the per-chunk estimate. Returns a
        zero-arg callable → seconds for one probed round."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        G = max(self.ec.num_groups, 1)
        n = self.node_shards
        axis = self._shard_ctx.axis
        two_phase = T.two_phase_exchange()

        def fold(rows):
            best = rows[0]
            for k in range(1, n):
                cand = rows[k]
                better = (cand[0] > best[0]) | (
                    (cand[0] == best[0]) & (cand[1] < best[1])
                )
                best = jnp.where(better, cand, best)
            return best

        def body(row):
            if not two_phase:
                return fold(jax.lax.all_gather(row, axis))
            best = fold(jax.lax.all_gather(row[:2], axis))
            placed = best[0] > T.NEG_INF
            owner = jnp.where(placed, best[1], 0.0).astype(jnp.int32) // (
                np.int32(max(self._shard_ctx.n_local, 1))
            )
            mine = (
                (jax.lax.axis_index(axis).astype(jnp.int32) == owner)
                & placed
            ).astype(jnp.float32)
            return best, jax.lax.psum(row[2:] * mine, axis)

        fn = jax.jit(
            shard_map(
                body, mesh=self._node_mesh, in_specs=P(), out_specs=P(),
                check_rep=False,
            )
        )
        row = jnp.zeros(2 + 2 * G, jnp.float32)
        jax.block_until_ready(fn(row))  # compile outside the timed loop

        def probe() -> float:
            t0 = time.perf_counter()
            jax.block_until_ready(fn(row))
            return time.perf_counter() - t0

        return probe

    def _save_checkpoint(self, state, cursor: int, all_choices, path: str,
                         released=None, boundary=None) -> None:
        from .checkpoint import ReplayCheckpoint, state_to_checkpoint

        if self.engine == "v3":
            used, mc, aa, pw = state.to_host(self.ec, self.static3, self._Dhost)
            ReplayCheckpoint(
                used=used, match_count=mc, anti_active=aa, pref_wsum=pw,
                chunk_cursor=cursor, outs=[np.asarray(o) for o in all_choices],
                released=released, boundary=boundary,
            ).save(path)
        else:
            ck = state_to_checkpoint(
                self._unshard_state_v2(state), self._gdom, self._Dhost,
                cursor, all_choices,
            )
            ck.released = released
            ck.boundary = boundary
            ck.save(path)

    def _preemption_walk(self, idx: np.ndarray, finals: np.ndarray,
                         ev_node: np.ndarray, ev_tier: np.ndarray):
        ep = self.pods
        assignments = np.where(ep.bound_node >= 0, ep.bound_node, PAD).astype(np.int32)
        preemption_walk(
            assignments, idx, finals, ev_node, ev_tier,
            self.static3.pod_tier, ep.group_id == PAD,
        )
        scheduled = ep.bound_node == PAD
        placed = int((assignments[scheduled] >= 0).sum())
        return assignments, placed

    def _apply_release(
        self, state, rel_idx: np.ndarray, rel_nodes: np.ndarray,
        as_v2: bool = False,
    ):
        """Subtract the completed pods' aggregate contribution (resources +
        count planes) from the carried device state — the device twin of
        models.state.unbind, applied at a chunk boundary. ``as_v2``: the
        caller is carrying a node-space DevState even though the engine is
        v3 (the instrumented telemetry program)."""
        from ..models.state import release_delta
        from ..ops import tpu3 as V3

        used_d, mc_d, aa_d, pw_d = release_delta(
            self.ec, self.pods, rel_idx, rel_nodes
        )
        if self.engine == "v3" and not as_v2:
            delta = V3.DevState3.from_host(
                used_d, mc_d, aa_d, pw_d, self.ec, self.static3
            )
            if self.preemption and len(rel_idx):
                # Tier planes drop completed pods too (pod tiers are
                # static, so releases ARE attributable — the former
                # exclusivity only held for evicted pods, which never
                # release because their assignment is PAD by walk time).
                # NON-GANG ONLY: the tier planes never accumulate gang
                # pods (gangs are not evictable — the wave step and
                # from_host both gate on group_id == PAD), so a gang
                # completion must not be subtracted from them either.
                st3 = self.static3
                ng = self.pods.group_id[rel_idx] == PAD
                ng_idx = np.asarray(rel_idx)[ng]
                ng_nodes = np.asarray(rel_nodes)[ng]
                R, N = self.ec.num_resources, self.ec.num_nodes
                ut = np.zeros((st3.Tt, R, N), np.float32)
                nt = np.zeros((st3.Tt, N), np.float32)
                if ng_idx.size:
                    t_arr = st3.pod_tier[ng_idx]
                    np.add.at(nt, (t_arr, ng_nodes), 1.0)
                    np.add.at(
                        ut,
                        (
                            t_arr[:, None],
                            np.arange(R)[None, :],
                            ng_nodes[:, None],
                        ),
                        self.pods.requests[ng_idx],
                    )
                delta = delta._replace(
                    used_tier=jnp.asarray(ut), npods_tier=jnp.asarray(nt)
                )
        else:
            gdom = self._gdom
            delta = self._to_dev_state_v2(
                used_d,
                T.domain_to_node_space(mc_d, gdom),
                T.domain_to_node_space(aa_d, gdom),
                T.domain_to_node_space(pw_d, gdom),
                mc_d.sum(axis=1),
            )
        return self._donated_subtract(state, delta)

    def _donated_subtract(self, state, delta):
        """Subtract a delta tree from the carried state with the STATE
        buffers donated (round 11 donation audit): the eager
        ``jax.tree.map(jnp.subtract, ...)`` the release/boundary paths
        used allocated a second full state copy per boundary. Cached on
        the engine — jit caches by function identity."""
        if getattr(self, "_sub_jit", None) is None:
            self._sub_jit = jax.jit(
                lambda s, d: jax.tree.map(jnp.subtract, s, d),
                donate_argnums=(0,),
            )
        return self._sub_jit(state, delta)

    def _apply_boundary_delta(self, state, sub_pairs, add_pairs):
        """Net host-layout plane delta of one boundary pass — releases and
        evictions (``sub_pairs``) minus retried/preempting binds
        (``add_pairs``), each a (pods, nodes) int-array pair — transformed
        to the device layout and subtracted from the carry. The
        generalization of :meth:`_apply_release`; the transform is linear,
        so one application carries the whole pass."""
        from ..models.state import release_delta
        from ..ops import tpu3 as V3

        s_idx, s_nodes = sub_pairs
        a_idx, a_nodes = add_pairs
        du, dmc, daa, dpw = release_delta(self.ec, self.pods, s_idx, s_nodes)
        au, amc, aaa, apw = release_delta(self.ec, self.pods, a_idx, a_nodes)
        net = (du - au, dmc - amc, daa - aaa, dpw - apw)
        if self.engine == "v3":
            delta = V3.DevState3.from_host(*net, self.ec, self.static3)
        else:
            gdom = self._gdom
            delta = self._to_dev_state_v2(
                net[0],
                T.domain_to_node_space(net[1], gdom),
                T.domain_to_node_space(net[2], gdom),
                T.domain_to_node_space(net[3], gdom),
                net[1].sum(axis=1),
            )
        return self._donated_subtract(state, delta)

    def _state_from_checkpoint(self, ck):
        """Device carry from a ReplayCheckpoint (shared by the plain and
        boundary resume paths)."""
        from ..ops import tpu3 as V3
        from .checkpoint import checkpoint_to_state

        if self.engine == "v3":
            return V3.DevState3.from_host(
                ck.used, ck.match_count, ck.anti_active, ck.pref_wsum,
                self.ec, self.static3,
            )
        if self.node_shards > 1:
            g = self._gdom
            return self._to_dev_state_v2(
                ck.used,
                T.domain_to_node_space(ck.match_count, g),
                T.domain_to_node_space(ck.anti_active, g),
                T.domain_to_node_space(ck.pref_wsum, g),
                ck.match_count.sum(axis=1).astype(np.float32),
            )
        return checkpoint_to_state(ck, self._gdom)

    def _replay_boundary(
        self, node_events=None, chunk_req: Optional[int] = None,
        retry_req: Optional[int] = None,
        checkpoint_path: Optional[str] = None, checkpoint_every: int = 0,
        resume: bool = False,
    ) -> ReplayResult:
        """Replay with the host boundary pass active (``retry_buffer`` > 0
        and/or ``preemption='kube'``; :mod:`.boundary`).

        Lazy sync (round 6, default): the boundary pass at b only needs
        the mirror current through chunk b−1 when it will actually READ
        it — i.e. when the retry queue is non-empty. Per chunk the loop
        fetches ONE device scalar (the non-gang failure count); quiet
        chunks (zero failures, empty queue) skip the blocking choices
        fetch entirely — the fold is deferred past the next chunk's
        dispatch (bookkeeping lags one chunk; the plane delta is only
        appended to the mirror's op log and applied if a later boundary
        flushes). The static-release decision at boundary b never needs
        chunk b−1 (one-chunk slack: ``bind_chunk < b-1``), so deferral is
        exact. Eager mode (``lazy_boundary=False``) folds every chunk with
        a blocking fetch — bit-identical results, kept as the reference
        path. The device chunk program is the plain one either way: retry
        placements and kube preemption decisions are host arithmetic
        (bit-identical to the CPU path by construction) landing on the
        carry as rank-1 plane deltas."""
        from dataclasses import replace as dc_replace

        from ..framework.framework import FrameworkConfig, SchedulerFramework
        from .boundary import BoundaryOps

        idx = self.waves.idx
        # (chunk_req, retry_req) arrive guard-adjusted from replay() —
        # the single guard call site.
        chunk_req = self.chunk_waves if chunk_req is None else chunk_req
        retry_req = self.retry_buffer if retry_req is None else retry_req
        C = min(chunk_req, max(idx.shape[0], 1))
        pad_to = ((idx.shape[0] + C - 1) // C) * C
        if pad_to != idx.shape[0]:
            idx = np.concatenate(
                [idx, np.full((pad_to - idx.shape[0], idx.shape[1]), PAD, np.int32)]
            )
        cfg = dc_replace(
            self._config if self._config is not None else FrameworkConfig(),
            enable_preemption=self.kube,
        )
        fw = SchedulerFramework(self.ec, self.pods, cfg)
        lazy = self.lazy_boundary
        tel = (
            TelemetryCollector(self.telemetry_cfg)
            if self.telemetry_cfg.enabled
            else None
        )
        # Flight recorder (round 16): same contract as the plain path —
        # host-side observation only, parity-pinned against recorder-off.
        rec, rec_own = self._open_recorder()
        _tick = _make_tick(tel if tel is not None else rec)
        probe = (
            self._make_exchange_probe()
            if rec is not None and self.node_shards > 1
            else None
        )
        bops = BoundaryOps(
            self.ec, self.pods, fw,
            WaveBatch(idx=idx, wave_width=self.wave_width),
            self.wave_width, C,
            retry_buffer=retry_req, kube=self.kube, lazy=lazy,
            telemetry=tel,
        )
        self._last_bops = bops  # probe for the quiet-path tests/bench
        state = self._init_dev_state()
        pending_events = sorted(node_events or [], key=lambda e: e.time)
        ev_hash = events_hash(pending_events)
        ev_applied = 0  # checkpoint event cursor
        saved_alloc = np.asarray(self.dc.allocatable).copy()
        saved_alloc_ec = self.ec.allocatable.copy()
        start_chunk = 0
        if resume and checkpoint_path:
            from .checkpoint import ReplayCheckpoint

            ck = ReplayCheckpoint.load(checkpoint_path)
            if ck.boundary is None:
                raise ValueError(
                    "checkpoint was not written by a boundary-mode "
                    "(retry/kube) replay — resume it on a plain engine"
                )
            ck_hash = ck.boundary.get("ev_hash")
            if ck_hash is not None and not np.array_equal(
                np.asarray(ck_hash, np.uint8), ev_hash
            ):
                raise ValueError(
                    "checkpoint was written under a different node_events "
                    "timeline — resuming would re-apply or skip events "
                    "(evictions are not idempotent); pass the original "
                    "event list or restart the replay from scratch"
                )
            state = self._state_from_checkpoint(ck)
            bops.restore(
                ck.boundary, ck.used, ck.match_count, ck.anti_active,
                ck.pref_wsum,
            )
            start_chunk = ck.chunk_cursor
            cur = ck.boundary.get("ev_cursor")
            if cur is not None and int(np.asarray(cur).reshape(-1)[0]):
                # Catch-up: past events re-shape allocatable (the device
                # cluster starts unperturbed) WITHOUT re-evicting — the
                # restored mirror already reflects their evictions.
                ev_applied = int(np.asarray(cur).reshape(-1)[0])
                done = pending_events[:ev_applied]
                self._apply_node_events(done, saved_alloc)
                for ev in done:
                    if ev.kind == "node_down":
                        self.ec.allocatable[ev.node] = 0.0
                    elif ev.kind == "node_up":
                        self.ec.allocatable[ev.node] = saved_alloc_ec[ev.node]
                    elif ev.kind == "capacity_scale":
                        self.ec.allocatable[ev.node] = (
                            saved_alloc_ec[ev.node] * ev.scale
                        )
                pending_events = pending_events[ev_applied:]
        wave_times = self._wave_start_times(idx)
        idx_chunks = (
            [jnp.asarray(idx[c0 : c0 + C]) for c0 in range(0, idx.shape[0], C)]
            if self.engine == "v3"
            else None
        )
        # Scalar boundary summary: count of failed NON-GANG slots (the only
        # failures that enter the retry buffer — gang failures never do).
        if not hasattr(self, "_bfail_fn"):
            self._bfail_fn = jax.jit(
                lambda ch, ix, ng: (
                    (ix >= 0)
                    & (ch.reshape(ix.shape) < 0)
                    & ng[jnp.clip(ix, 0)]
                ).sum(dtype=jnp.int32)
            )
        ng_dev = jnp.asarray(self.pods.group_id == PAD)
        # Deferred fold of the previous chunk: (ci, rows, choices_dev,
        # nfail_dev). Resolved eagerly when the boundary will read the
        # mirror planes; otherwise folded AFTER the next dispatch so the
        # D2H copy overlaps device compute.
        pending = None

        def _fold_pending():
            nonlocal pending
            if pending is not None:
                ci_p, rows_p, ch_d, _nf = pending
                t_f = time.perf_counter()
                with _tick("device_wait"):
                    ch_np = np.asarray(ch_d)
                with _tick("boundary_fold"):
                    bops.fold_chunk(ci_p, rows_p, ch_np)
                if rec is not None:
                    rec.fold(ci_p, time.perf_counter() - t_f)
                pending = None

        dbuf = self.double_buffer and lazy
        rec_valid = (
            np.add.accumulate(
                [
                    int((idx[c0 : c0 + C] >= 0).sum())
                    for c0 in range(0, idx.shape[0], C)
                ]
            )
            if rec is not None
            else None
        )
        rec_pub = None
        rec_retry = None
        if rec is not None:
            from ..parallel import dcn as _dcn

            rec_pub = _dcn.publish_stats()
            rec_retry = _dcn.retry_stats()
        t0 = time.perf_counter()
        try:
            for ci, c0 in enumerate(range(0, idx.shape[0], C)):
                if ci < start_chunk:
                    continue
                rel_staged = None
                if (
                    dbuf
                    and pending is not None
                    and not (tel is not None and tel.cfg.want_series)
                    and not (
                        pending_events
                        and pending_events[0].time <= wave_times[c0]
                    )
                ):
                    # Double-buffer (round 10): run boundary ci's RELEASE
                    # passes before blocking on chunk ci-1's failure
                    # scalar — the device is still computing, so this
                    # host bookkeeping is free. Exact: the release
                    # decision reads only chunks ≤ ci−2 (one-chunk
                    # slack), and the op-log's key sort restores eager
                    # flush order. Skipped when chaos events are due at
                    # this boundary (eviction must precede the release
                    # decision) or series telemetry samples (its depth
                    # series reads post-fold state).
                    with _tick("boundary_fold"):
                        rel_staged = bops.boundary_releases(
                            ci, wave_times[c0]
                        )
                if pending is not None and (
                    int(pending[3]) > 0
                    or bops.retry_q
                    or (tel is not None and tel.cfg.want_series)
                ):
                    # The boundary below will run the retry pass (new
                    # failures or a carried-over queue): it needs chunk
                    # ci-1 folded and the mirror planes flushed. Series
                    # telemetry also forces the fold — the boundary's
                    # utilization sample reads the mirror's committed
                    # planes, and a quiet lazy chunk would leave them one
                    # chunk stale.
                    _fold_pending()
                chaos_p: List[np.ndarray] = []
                chaos_n: List[np.ndarray] = []
                if pending_events:
                    chunk_t = wave_times[c0]
                    due = [e for e in pending_events if e.time <= chunk_t]
                    if due:
                        if any(e.kind == "node_down" for e in due):
                            # NoExecute eviction reads the mirror's bound
                            # state — it must be current through chunk
                            # ci-1 (quiet lazy chunks may not be yet).
                            _fold_pending()
                        self._apply_node_events(due, saved_alloc)
                        if tel is not None and tel.cfg.want_timeline:
                            for ev in due:
                                if ev.kind in ("node_down", "node_up"):
                                    tel.event(
                                        ev.kind, float(ev.time), -1, int(ev.node)
                                    )
                        # The host mirror's plugins read ec.allocatable
                        # live — keep it in lockstep with the device copy.
                        for ev in due:
                            if ev.kind == "node_down":
                                self.ec.allocatable[ev.node] = 0.0
                                # NoExecute: evict the node's bound pods
                                # through the mirror (they re-enter the
                                # retry buffer and are re-attempted in
                                # THIS boundary's retry pass, like the
                                # CPU engine's requeue-at-event-instant).
                                cp, cn = bops.evict_node(
                                    ev.node, ci, float(chunk_t)
                                )
                                if cp.size:
                                    chaos_p.append(cp)
                                    chaos_n.append(cn)
                            elif ev.kind == "node_up":
                                self.ec.allocatable[ev.node] = saved_alloc_ec[ev.node]
                            elif ev.kind == "capacity_scale":
                                self.ec.allocatable[ev.node] = (
                                    saved_alloc_ec[ev.node] * ev.scale
                                )
                        pending_events = pending_events[len(due):]
                        ev_applied += len(due)
                with _tick("boundary_fold"):
                    if rel_staged is not None:
                        rel = rel_staged
                        binds, evicts = bops.boundary_retry(
                            ci, wave_times[c0]
                        )
                    else:
                        rel, binds, evicts = bops.boundary(
                            ci, wave_times[c0]
                        )
                if (
                    rel[0].size or binds[0].size or evicts[0].size or chaos_p
                ):
                    with _tick("host_mirror"):
                        state = self._apply_boundary_delta(
                            state,
                            (
                                np.concatenate([rel[0], evicts[0], *chaos_p]),
                                np.concatenate([rel[1], evicts[1], *chaos_n]),
                            ),
                            binds,
                        )
                with _tick("dispatch"), _chunk_ann(ci):
                    if self.engine == "v3":
                        state, choices = self.chunk_fn(
                            self.dc, state, self._slot_src, self._extra_src,
                            idx_chunks[ci],
                        )
                    else:
                        state, choices = self.chunk_fn(
                            self.dc, state,
                            T.gather_slots(self.pods, idx[c0 : c0 + C]),
                        )
                if lazy:
                    ix_dev = (
                        idx_chunks[ci]
                        if idx_chunks is not None
                        else jnp.asarray(idx[c0 : c0 + C])
                    )
                    nf_d = self._bfail_fn(choices, ix_dev, ng_dev)
                    if hasattr(choices, "copy_to_host_async"):
                        choices.copy_to_host_async()
                    # Quiet previous chunk: fold it now — its D2H copy was
                    # launched an iteration ago and chunk ci is already in
                    # flight, so this host work overlaps device compute.
                    _fold_pending()
                    pending = (ci, idx[c0 : c0 + C], choices, nf_d)
                else:
                    # Eager fold: one blocking fetch per chunk. (The
                    # choices buffer is fully consumed here — the mirror
                    # carries the placements, so checkpoints save NO outs.)
                    t_f = time.perf_counter()
                    with _tick("device_wait"):
                        ch_np = np.asarray(choices)
                    with _tick("boundary_fold"):
                        bops.fold_chunk(ci, idx[c0 : c0 + C], ch_np)
                    if rec is not None:
                        rec.fold(ci, time.perf_counter() - t_f)
                if (
                    checkpoint_path
                    and checkpoint_every
                    and (ci + 1) % checkpoint_every == 0
                ):
                    # Blob parity with the eager path: the mirror's
                    # bookkeeping must be current through chunk ci.
                    _fold_pending()
                    blob = bops.to_blob()
                    # Applied-event cursor + timeline hash: a resume must
                    # neither re-apply past events (evictions are not
                    # idempotent) nor skip future ones, and must reject a
                    # different event list outright.
                    blob["ev_cursor"] = np.asarray([ev_applied], np.int64)
                    blob["ev_hash"] = ev_hash
                    t_ck = time.perf_counter()
                    self._save_checkpoint(
                        state, ci + 1, [], checkpoint_path,
                        released=bops.released, boundary=blob,
                    )
                    if rec is not None:
                        rec.checkpoint(
                            ci + 1, _file_bytes(checkpoint_path),
                            time.perf_counter() - t_ck,
                        )
                if rec is not None:
                    ex_s = probe() if probe is not None else None
                    if ex_s is not None and tel is not None:
                        tel.phases.add("selection_exchange", ex_s)
                    pub_now = _dcn.publish_stats()
                    ck_pub = None
                    if pub_now != rec_pub:
                        ck_pub = {
                            "count": pub_now["count"] - rec_pub["count"],
                            "wall_s": round(
                                pub_now["wall_s"] - rec_pub["wall_s"], 6
                            ),
                            "bytes": pub_now["bytes"] - rec_pub["bytes"],
                        }
                        rec_pub = pub_now
                    retry_now = _dcn.retry_stats()
                    kv_retry = None
                    if retry_now != rec_retry:
                        kv_retry = {
                            "retries": retry_now["retries"]
                            - rec_retry["retries"],
                            "giveups": retry_now["giveups"]
                            - rec_retry["giveups"],
                            "backoff_s": round(
                                retry_now["backoff_s"]
                                - rec_retry["backoff_s"], 6
                            ),
                        }
                        rec_retry = retry_now
                    rec.chunk(
                        ci,
                        t_virtual=wave_times[c0],
                        dispatched=int(rec_valid[ci]),
                        # Mirror bookkeeping lags one chunk under lazy —
                        # a liveness gauge, not the parity-bearing count.
                        placed=int(bops.placed_total),
                        phase_acc=(
                            tel.phases.acc
                            if tel is not None
                            else rec.phases.acc
                        ),
                        exchange_probe_s=ex_s,
                        exchange_slots=(
                            C * idx.shape[1] if ex_s is not None else None
                        ),
                        ckpt_publish=ck_pub,
                        kv_retry=kv_retry,
                    )
            _fold_pending()
            if self.kube:
                # Trailing boundary (greedy anchor twin): last-chunk
                # failures still get their PostFilter attempt.
                rel, binds, evicts = bops.boundary(idx.shape[0] // C, np.inf)
                if rel[0].size or binds[0].size or evicts[0].size:
                    state = self._apply_boundary_delta(
                        state,
                        (
                            np.concatenate([rel[0], evicts[0]]),
                            np.concatenate([rel[1], evicts[1]]),
                        ),
                        binds,
                    )
                    jax.block_until_ready(state)
        finally:
            if node_events:
                self.dc = self.dc._replace(allocatable=self._put_alloc(saved_alloc))
                self.ec.allocatable[:] = saved_alloc_ec
        wall = time.perf_counter() - t0

        to_schedule = int((idx >= 0).sum())
        assignments = bops.assignments
        placed = bops.placed_total
        if self.engine == "v3":
            used, mc, aa, pw = state.to_host(self.ec, self.static3, self._Dhost)
        else:
            hs = self._unshard_state_v2(state)
            used = hs.used
            mc = T.node_space_to_domain(hs.match_count, self._gdom, self._Dhost)
            aa = T.node_space_to_domain(hs.anti_active, self._gdom, self._Dhost)
            pw = T.node_space_to_domain(hs.pref_wsum, self._gdom, self._Dhost)
        util = utilization_means(used, self.ec.allocatable, self.ec.vocab._r)
        pending_m = (self.pods.bound_node == PAD) & (assignments == PAD)
        frag = fragmentation_gauges(
            self.ec.allocatable, used, self.pods.requests[pending_m],
            self.ec.vocab._r,
        )
        host_state = SchedState(
            used=used, match_count=mc, anti_active=aa, pref_wsum=pw,
            bound=assignments.copy(),
        )
        if rec is not None and rec_own:
            rec.close({"placed": int(placed)})
        return ReplayResult(
            assignments=assignments,
            placed=placed,
            unschedulable=to_schedule - placed,
            preemptions=bops.preemptions,
            attempts=to_schedule,
            wall_clock_s=wall,
            placements_per_sec=placed / wall if wall > 0 else 0.0,
            virtual_makespan=float(self.pods.arrival.max()) if self.pods.num_pods else 0.0,
            utilization=util,
            state=host_state,
            retry_dropped=bops.retry_dropped,
            evictions=bops.evictions,
            evict_rescheduled=bops.evict_rescheduled,
            evict_stranded=bops.evict_stranded,
            evict_latency_mean=bops.evict_latency_mean,
            fragmentation=frag,
            telemetry=tel.result() if tel is not None else None,
        )

    def _wave_start_times(self, idx: np.ndarray) -> np.ndarray:
        """Arrival time of each wave's first valid pod (for timed events)."""
        return wave_start_times(self.pods, idx)

    def _apply_node_events(self, events, saved_alloc: np.ndarray) -> None:
        """Mutate the device cluster's allocatable rows (failure
        injection). Capacity changes affect future placements; on the
        boundary path (``retry_buffer``/``kube``) the caller ALSO evicts
        ``node_down`` victims through the host mirror with NoExecute
        semantics (``BoundaryOps.evict_node``), matching the CPU event
        engine. The plain path keeps the capacity-only semantics — no
        mirror exists to requeue victims through."""
        alloc = np.asarray(self.dc.allocatable).copy()
        for ev in events:
            if ev.kind == "node_down":
                alloc[ev.node] = 0.0
            elif ev.kind == "node_up":
                alloc[ev.node] = saved_alloc[ev.node]
            elif ev.kind == "capacity_scale":
                alloc[ev.node] = saved_alloc[ev.node] * ev.scale
        self.dc = self.dc._replace(allocatable=self._put_alloc(alloc))

    def replay(
        self,
        checkpoint_path: Optional[str] = None,
        checkpoint_every: int = 0,
        resume: bool = False,
        node_events=None,
    ) -> ReplayResult:
        """Run the replay; optionally snapshot the carry every K chunks to
        ``checkpoint_path`` and/or resume from it (SURVEY.md §5).

        ``node_events`` (list of sim.runtime.NodeEvent) are applied at chunk
        boundaries: an event fires before the first chunk whose start wave's
        arrival time is past the event time (granularity = chunk_waves; use
        smaller chunks for finer timing). With ``retry_buffer``/``kube``
        active, ``node_down`` additionally evicts bound pods (NoExecute)
        through the boundary mirror; without a retry buffer only future
        placements are affected."""
        from .checkpoint import ReplayCheckpoint, checkpoint_to_state, state_to_checkpoint

        validate_node_events(node_events, self.ec.num_nodes)
        if self.preemption and (checkpoint_path or resume):
            raise ValueError(
                "checkpoint/resume is not supported with device preemption "
                "(tier planes are not checkpointed)"
            )
        if self.retry_buffer or self.kube:
            if self.completions is False:
                raise ValueError(
                    "completions=False is not supported with retry_buffer/"
                    "kube preemption (the boundary pass owns releases)"
                )
        # Granularity-envelope guard (round 5, VERDICT r4 #2; see
        # sim.granularity) — ONE call site for every replay path; no-op
        # for duration-free traces, shapes inside the measured-safe
        # regime, and explicit completions=False (which the boundary
        # modes reject above).
        chunk_req, retry_req = self.chunk_waves, self.retry_buffer
        if self.completions is not False:
            from .granularity import guard as _gran_guard

            chunk_req, retry_req = _gran_guard(
                self.pods, self.waves.idx, chunk_req, retry_req,
                enabled=self.granularity_guard,
                engine_name="jax replay engine",
            )
        if self.retry_buffer or self.kube:
            return self._replay_boundary(
                node_events=node_events, chunk_req=chunk_req,
                retry_req=retry_req, checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every, resume=resume,
            )
        if (
            node_events
            and self.engine == "v3"
            and (self.static3.mc_h_bf16 or self.static3.anti_h_bf16)
            and any(e.kind == "capacity_scale" for e in node_events)
        ):
            # Capacity scaling can push per-node pod counts past the bf16
            # exactness bound baked into the kernel — rebuild without it.
            from ..ops import tpu3 as V3

            self.static3 = V3.V3Static.build(
                self.ec, self.pods, self.spec, self.dmax_coarse,
                preemption=self.preemption, allow_bf16_host=False,
            )
            self.shared3 = V3.Shared3.build(self.ec, self.static3)
            self.chunk_fn = make_chunk_fn3_src(
                self.static3, self.shared3,
                rep_slots_for(self.static3, self.pods),
                self.wave_width, self.spec,
            )
            # Keep the device-resident per-pod rows in lockstep with the
            # rebuilt static tables (value-identical today, but a silent
            # desync trap if V3Static ever derives them from a rebuild
            # parameter).
            self._extra_src = V3.ExtraSource.build(
                self.static3, self.pods.num_pods
            )

        idx = self.waves.idx
        C = min(chunk_req, max(idx.shape[0], 1))
        pad_to = ((idx.shape[0] + C - 1) // C) * C
        if pad_to != idx.shape[0]:
            idx = np.concatenate(
                [idx, np.full((pad_to - idx.shape[0], idx.shape[1]), PAD, np.int32)]
            )
        from ..ops import tpu3 as V3
        from ..utils.metrics import log

        tel = (
            TelemetryCollector(self.telemetry_cfg)
            if self.telemetry_cfg.enabled
            else None
        )
        # Flight recorder (round 16): pure host-side observation — with
        # telemetry off it owns the phase timers, so recorder rows still
        # carry PHASE_NAMES deltas without a collector. Nothing below
        # changes a device program, a fold order or a checkpoint payload.
        rec, rec_own = self._open_recorder()
        _tick = _make_tick(tel if tel is not None else rec)
        probe = (
            self._make_exchange_probe()
            if rec is not None and self.node_shards > 1
            else None
        )
        # In-scan rejection attribution (series+): thread a [K] i32 reject
        # counter through the scan carry via the instrumented reference
        # chunk program — one extra fetch per REPLAY, never per pod. The
        # default "summary" granularity takes none of these branches and
        # runs the exact same device program as before.
        use_rej = tel is not None and tel.cfg.want_series
        if use_rej and self.preemption:
            log.info(
                "telemetry: rejection attribution is not available with "
                "in-scan tier preemption (the instrumented program has no "
                "tier planes) — latency/phase telemetry still collected"
            )
            use_rej = False
        if use_rej and (checkpoint_path or resume):
            log.info(
                "telemetry: rejection attribution is disabled under "
                "checkpoint/resume (the instrumented carry is not part of "
                "checkpoints) — latency/phase telemetry still collected"
            )
            use_rej = False
        if use_rej and self.node_shards > 1:
            log.info(
                "telemetry: rejection attribution is disabled under node "
                "sharding (the instrumented reference program carries "
                "replicated node planes) — latency/phase telemetry still "
                "collected"
            )
            use_rej = False
        rej_dev = None
        if use_rej:
            if self.engine == "v3":
                log.info(
                    "telemetry series: plain v3 replay uses the reference "
                    "(v2) chunk program for in-scan rejection attribution "
                    "— placements are bit-identical (parity-pinned), "
                    "throughput is the v2 envelope"
                )
            if not hasattr(self, "_chunk_fn_rej"):
                self._chunk_fn_rej = make_chunk_fn_rej(
                    self.wave_width, self.spec
                )
            rej_dev = jnp.zeros(
                len(spec_plugin_names(self.spec)), jnp.int32
            )

        state = self._init_dev_state(force_v2=use_rej)
        all_choices = []
        start_chunk = 0
        if resume and checkpoint_path:
            ck = ReplayCheckpoint.load(checkpoint_path)
            if ck.boundary is not None:
                raise ValueError(
                    "checkpoint was written by a boundary-mode (retry/"
                    "kube) replay — its placements live in the host "
                    "mirror, not the saved outs; resume it with the "
                    "same retry_buffer/preemption configuration"
                )
            state = self._state_from_checkpoint(ck)
            all_choices = [jnp.asarray(o) for o in ck.outs]
            start_chunk = ck.chunk_cursor
        pending_events = sorted(node_events or [], key=lambda e: e.time)
        rel_time = self.pods.arrival + np.where(
            np.isfinite(self.pods.duration), self.pods.duration, np.inf
        )
        completions_on = bool(
            self.completions is not False  # None (the default) = on
            and np.isfinite(rel_time).any()
        )
        wave_times = (
            self._wave_start_times(idx)
            # use_rej: series telemetry also samples utilization at chunk
            # boundaries, which needs the chunk start times. The recorder
            # stamps the chunk's virtual time on every row (host numpy
            # only — no program effect).
            if (pending_events or completions_on or use_rej or rec is not None)
            else None
        )
        pending_fold = None  # (rows, choices) of the not-yet-folded chunk
        nongang = self.pods.group_id == PAD
        if completions_on and self.preemption:
            # Completions × preemption (round 4): folds run EAGERLY (the
            # chunk's eviction events must land in the host bookkeeping
            # BEFORE the next boundary's release decisions, or a pod the
            # device evicted would "release" resources it no longer
            # holds). The one-chunk slack therefore becomes an explicit
            # bind-chunk check instead of a fold lag; the pipeline eats
            # one blocking fetch per chunk — correctness over overlap for
            # this opt-in combination.
            chunk_of_arr = bind_chunk_of(self.pods, idx, C)
        if completions_on:
            host_assign = np.where(
                self.pods.bound_node >= 0, self.pods.bound_node, PAD
            ).astype(np.int32)
            released = np.zeros(self.pods.num_pods, bool)
            if start_chunk:
                # Resume: the saved state already carries pre-resume
                # releases — seed from the persisted mask (or reconstruct
                # from the saved outs for pre-field checkpoints). The
                # one-chunk slack is restored by folding only chunks
                # ≤ start_chunk−2 and re-pending the last saved chunk.
                have_mask = getattr(ck, "released", None) is not None
                host_assign, _ = rebuild_fork_state(
                    self.pods, idx, C, all_choices, wave_times,
                    max(start_chunk - 1, 0), reconstruct_released=False,
                )
                if have_mask:
                    released = ck.released.astype(bool)
                else:
                    # released=None ⟹ a checkpoint from before the field
                    # existed ⟹ its state was built under the OLD
                    # (no-slack) release rule — reconstruct with slack=0.
                    _, released = rebuild_fork_state(
                        self.pods, idx, C, all_choices, wave_times,
                        start_chunk, slack=0,
                    )
                if start_chunk >= 1:
                    pending_fold = (
                        idx[(start_chunk - 1) * C : start_chunk * C],
                        np.asarray(all_choices[start_chunk - 1]),
                    )
        saved_alloc = np.asarray(self.dc.allocatable).copy()
        # Pre-stage the per-chunk wave indices on device (a few MB total):
        # the timed loop then issues ONE call per chunk with no H2D.
        idx_chunks = (
            [
                jnp.asarray(idx[c0 : c0 + C])
                for c0 in range(0, idx.shape[0], C)
            ]
            if self.engine == "v3" and not use_rej and not self.paged
            else None
        )
        # Paged pod waves (round 14): per-chunk pages of the slot planes
        # stream host->device with one-chunk prefetch instead of whole-trace
        # residency. v3 pages carry page-LOCAL row indices (the kernels only
        # consume pod_id as a width, never as an identity).
        pager = None
        if self.paged and not use_rej:
            if self.engine == "v3":
                def _fetch_page(pci):
                    rows = idx[pci * C : (pci + 1) * C]
                    flat = rows.reshape(-1)
                    local = np.where(
                        rows >= 0,
                        np.arange(
                            rows.size, dtype=np.int32
                        ).reshape(rows.shape),
                        PAD,
                    ).astype(np.int32)
                    return (
                        T.SlotSource.page(self.pods, flat),
                        V3.ExtraSource.page(self.static3, flat),
                        jnp.asarray(local),
                    )
            else:
                def _fetch_page(pci):
                    return T.gather_slots(
                        self.pods, idx[pci * C : (pci + 1) * C]
                    )
            pager = _PodPager(_fetch_page, threaded=_pager_thread_enabled())
        rec_valid = (
            np.add.accumulate(
                [
                    int((idx[c0 : c0 + C] >= 0).sum())
                    for c0 in range(0, idx.shape[0], C)
                ]
            )
            if rec is not None
            else None
        )
        rec_stalls_seen = 0
        rec_inval_seen = 0
        rec_pub = None
        rec_retry = None
        if rec is not None:
            from ..parallel import dcn as _dcn

            rec_pub = _dcn.publish_stats()
            rec_retry = _dcn.retry_stats()
        t0 = time.perf_counter()
        for ci, c0 in enumerate(range(0, idx.shape[0], C)):
            if ci < start_chunk:
                continue
            if pending_events:
                chunk_t = wave_times[c0]
                due = [e for e in pending_events if e.time <= chunk_t]
                if due:
                    self._apply_node_events(due, saved_alloc)
                    if tel is not None and tel.cfg.want_timeline:
                        for ev in due:
                            if ev.kind in ("node_down", "node_up"):
                                tel.event(
                                    ev.kind, float(ev.time), -1, int(ev.node)
                                )
                    pending_events = pending_events[len(due):]
            if completions_on:
                if self.preemption and pending_fold is not None:
                    # Eager eviction-aware fold of the previous chunk.
                    rows_p, out_p = pending_fold
                    preemption_walk(
                        host_assign, rows_p,
                        np.asarray(out_p[0]).reshape(rows_p.shape),
                        np.asarray(out_p[1]), np.asarray(out_p[2]),
                        self.static3.pod_tier, nongang,
                        released=released,
                    )
                    pending_fold = None
                t_chunk = wave_times[c0]
                if np.isfinite(t_chunk):
                    due_m = (
                        (host_assign != PAD)
                        & ~released
                        & np.isfinite(rel_time)
                        & (rel_time <= t_chunk)
                    )
                    if self.preemption:
                        # Folds are eager here, so the one-chunk slack
                        # is the explicit bind-chunk rule.
                        due_m &= chunk_of_arr < ci - 1
                    due_p = np.nonzero(due_m)[0]
                    if due_p.size:
                        with _tick("host_mirror"):
                            state = self._apply_release(
                                state, due_p, host_assign[due_p],
                                as_v2=use_rej,
                            )
                        released[due_p] = True
            if use_rej and wave_times is not None and np.isfinite(
                wave_times[c0]
            ):
                # Utilization economics (round 13): chunk-boundary sample
                # of the committed device state (binds through chunk ci-1
                # plus the releases applied above). The fetch blocks on
                # the in-flight chunk — a series-mode-only sync; summary
                # runs the untouched program. The instrumented-rej carry
                # guarantees node-space [N, R] state.used here.
                with _tick("host_mirror"):
                    tel.sample(
                        float(wave_times[c0]),
                        **series_gauges(
                            np.asarray(state.used),
                            np.asarray(self.dc.allocatable),
                            self.ec.vocab._r,
                        ),
                    )
            with _tick("dispatch"), _chunk_ann(ci):
                if use_rej:
                    state, rej_dev, choices = self._chunk_fn_rej(
                        self.dc, state, rej_dev,
                        T.gather_slots(self.pods, idx[c0 : c0 + C]),
                    )
                elif self.engine == "v3":
                    if pager is not None:
                        src, xsrc, lidx = pager.get(ci)
                        state, choices = self.chunk_fn(
                            self.dc, state, src, xsrc, lidx
                        )
                    else:
                        state, choices = self.chunk_fn(
                            self.dc, state, self._slot_src, self._extra_src,
                            idx_chunks[ci],
                        )
                else:
                    state, choices = self.chunk_fn(
                        self.dc, state,
                        pager.get(ci)
                        if pager is not None
                        else T.gather_slots(self.pods, idx[c0 : c0 + C]),
                    )
            if pager is not None and c0 + C < idx.shape[0]:
                # Stage the next page while this chunk is still on device.
                pager.prefetch(ci + 1)
            all_choices.append(choices)
            if completions_on and self.preemption:
                pending_fold = (idx[c0 : c0 + C], choices)
            elif completions_on:
                # Fold the PREVIOUS chunk's choices AFTER dispatching this
                # one: the blocking fetch overlaps the in-flight chunk, and
                # boundary b only ever sees chunks ≤ b−2 (the one-chunk
                # slack; the greedy anchor implements the same rule).
                if pending_fold is not None:
                    rows_p, ch_p = pending_fold
                    ch = np.asarray(ch_p).reshape(rows_p.shape)
                    v = rows_p >= 0
                    host_assign[rows_p[v]] = ch[v]
                pending_fold = (idx[c0 : c0 + C], choices)
            if checkpoint_path and checkpoint_every and (ci + 1) % checkpoint_every == 0:
                t_ck = time.perf_counter()
                self._save_checkpoint(
                    state, ci + 1, all_choices, checkpoint_path,
                    released=(
                        released
                        if completions_on
                        else np.zeros(self.pods.num_pods, bool)
                    ),
                )
                if rec is not None:
                    rec.checkpoint(
                        ci + 1, _file_bytes(checkpoint_path),
                        time.perf_counter() - t_ck,
                    )
            if rec is not None:
                if pager is not None and (
                    pager.stalls > rec_stalls_seen
                    or pager.invalidations > rec_inval_seen
                ):
                    rec.page(
                        ci, pager.last_stall_s, pager.stalls,
                        invalidations=pager.invalidations,
                    )
                    rec_stalls_seen = pager.stalls
                    rec_inval_seen = pager.invalidations
                ex_s = probe() if probe is not None else None
                if ex_s is not None and tel is not None:
                    tel.phases.add("selection_exchange", ex_s)
                pub_now = _dcn.publish_stats()
                ck_pub = None
                if pub_now != rec_pub:
                    ck_pub = {
                        "count": pub_now["count"] - rec_pub["count"],
                        "wall_s": round(
                            pub_now["wall_s"] - rec_pub["wall_s"], 6
                        ),
                        "bytes": pub_now["bytes"] - rec_pub["bytes"],
                    }
                    rec_pub = pub_now
                retry_now = _dcn.retry_stats()
                kv_retry = None
                if retry_now != rec_retry:
                    kv_retry = {
                        "retries": retry_now["retries"]
                        - rec_retry["retries"],
                        "giveups": retry_now["giveups"]
                        - rec_retry["giveups"],
                        "backoff_s": round(
                            retry_now["backoff_s"]
                            - rec_retry["backoff_s"], 6
                        ),
                    }
                    rec_retry = retry_now
                rec.chunk(
                    ci,
                    t_virtual=(
                        wave_times[c0] if wave_times is not None else None
                    ),
                    dispatched=int(rec_valid[ci]),
                    placed=(
                        int((host_assign >= 0).sum())
                        if completions_on
                        else None
                    ),
                    phase_acc=(
                        tel.phases.acc if tel is not None else rec.phases.acc
                    ),
                    pager=pager,
                    exchange_probe_s=ex_s,
                    exchange_slots=(
                        C * idx.shape[1] if ex_s is not None else None
                    ),
                    ckpt_publish=ck_pub,
                    kv_retry=kv_retry,
                )
        with _tick("device_wait"):
            jax.block_until_ready(all_choices[-1] if all_choices else state)
        wall = time.perf_counter() - t0
        if node_events:
            self.dc = self.dc._replace(allocatable=self._put_alloc(saved_alloc))

        preemptions = 0
        to_schedule = int((idx >= 0).sum())
        if self.preemption and completions_on:
            # The incremental eviction-aware folds ARE the walk; finish
            # the last pending chunk and read the result off the host
            # bookkeeping (a fresh full walk would replay evictions
            # against completed pods with the wrong interleaving).
            if pending_fold is not None:
                rows_p, out_p = pending_fold
                preemption_walk(
                    host_assign, rows_p,
                    np.asarray(out_p[0]).reshape(rows_p.shape),
                    np.asarray(out_p[1]), np.asarray(out_p[2]),
                    self.static3.pod_tier, nongang, released=released,
                )
            assignments = host_assign
            scheduled = self.pods.bound_node == PAD
            placed = int((assignments[scheduled] >= 0).sum())
            preemptions = int(
                np.concatenate(
                    [np.asarray(c[4]) for c in all_choices]
                ).sum()
            )
        elif self.preemption:
            finals = np.concatenate([np.asarray(c[0]) for c in all_choices])
            ev_node = np.concatenate([np.asarray(c[1]) for c in all_choices])
            ev_tier = np.concatenate([np.asarray(c[2]) for c in all_choices])
            ev_total = np.concatenate([np.asarray(c[4]) for c in all_choices])
            assignments, placed = self._preemption_walk(
                idx, finals, ev_node, ev_tier
            )
            preemptions = int(ev_total.sum())
        else:
            choices_np = np.asarray(jnp.concatenate(all_choices, axis=0))
            assignments = np.where(
                self.pods.bound_node >= 0, self.pods.bound_node, PAD
            ).astype(np.int32)
            flat_idx = idx.reshape(-1)
            flat_choice = choices_np.reshape(-1)
            valid = flat_idx >= 0
            assignments[flat_idx[valid]] = flat_choice[valid]
            placed = int((flat_choice[valid] >= 0).sum())

        if tel is not None:
            # Plain replay: every placement is a wave placement — bound in
            # the same chunk it arrived in, zero virtual-time latency by
            # the chunk-granular convention (SURVEY.md §5).
            tel.bind_zero(placed)
            if use_rej:
                tel.rejection_bulk(
                    spec_plugin_names(self.spec), np.asarray(rej_dev)
                )

        if self.engine == "v3" and not use_rej:
            used, mc, aa, pw = state.to_host(self.ec, self.static3, self._Dhost)
        else:
            hs = self._unshard_state_v2(state)
            used = hs.used
            mc = T.node_space_to_domain(hs.match_count, self._gdom, self._Dhost)
            aa = T.node_space_to_domain(hs.anti_active, self._gdom, self._Dhost)
            pw = T.node_space_to_domain(hs.pref_wsum, self._gdom, self._Dhost)
        util = utilization_means(used, self.ec.allocatable, self.ec.vocab._r)
        pending_m = (self.pods.bound_node == PAD) & (assignments == PAD)
        frag = fragmentation_gauges(
            self.ec.allocatable, used, self.pods.requests[pending_m],
            self.ec.vocab._r,
        )
        host_state = SchedState(
            used=used, match_count=mc, anti_active=aa, pref_wsum=pw,
            bound=assignments.copy(),
        )
        if rec is not None:
            # Pager walls join the phase accumulators (keys only present
            # when paging is on AND the recorder observed them, so the
            # canonical PHASE_NAMES-only runs are unchanged).
            # ``pager_stall`` is the EXPOSED wall; ``pager_prefetch`` the
            # fetch wall itself — hidden under the round-19 thread,
            # loop-exposed without it.
            if pager is not None and tel is not None:
                tel.phases.add("pager_stall", pager.stall_s)
                tel.phases.add("pager_prefetch", pager.prefetch_wall_s)
            if rec_own:
                rec.close({"placed": int(placed)})
        if pager is not None:
            pager.close()
        return ReplayResult(
            assignments=assignments,
            placed=placed,
            unschedulable=to_schedule - placed,
            preemptions=preemptions,
            attempts=to_schedule,
            wall_clock_s=wall,
            placements_per_sec=placed / wall if wall > 0 else 0.0,
            virtual_makespan=float(self.pods.arrival.max()) if self.pods.num_pods else 0.0,
            utilization=util,
            state=host_state,
            fragmentation=frag,
            telemetry=tel.result() if tel is not None else None,
        )


@register_strategy("jax")
def _make_jax(ec: EncodedCluster, pods: EncodedPods, config: Optional[FrameworkConfig] = None, **kw):
    return JaxReplayEngine(ec, pods, config, **kw)
