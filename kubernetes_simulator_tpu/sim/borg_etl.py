"""Real Borg-2019 schema ETL (SURVEY.md §2 trace-driver row).

Maps the Google cluster-usage trace v3 ("ClusterData2019") table exports —
``instance_events`` and optionally ``collection_events`` CSV files — into
the columnar form consumed by :func:`..sim.borg.encoded_from_cols`, which
runs the normal template-expansion Encoder path. The dataset itself is
unreachable from this environment (zero egress); the mapper is exercised
by a synthetic round-trip test that writes tiny files in the real schema
(tests/test_borg_etl.py).

Schema mapping:
- instance SUBMIT (type 0) → task arrival; the first SUBMIT per
  (collection_id, instance_index) wins.
- FINISH/KILL (types 6/7) → duration = end − arrival (missing → ∞).
- ``alloc_collection_id`` > 0 → pod-group membership (alloc set ≈ gang);
  group ids are remapped first-appearance by encoded_from_cols, and gang
  members are reordered to co-arrive at the set's first submit (the
  alloc-set semantic; pack_waves needs members adjacent).
- ``priority`` (0..450) → pod priority (the 2019 tiering).
- ``collection_id`` → app id (template class) — remapped first-appearance
  and wrapped into the template vocabulary by encoded_from_cols.
- priority < 120 (free + BEB tiers) → tolerates the ``dedicated=batch``
  taint, mirroring the generator's toleration rule.
- resource_request.cpus / .memory are normalized to the largest machine:
  scaled by ``cpu_scale`` / ``mem_scale`` into the synthetic cluster's
  units.
- timestamps are microseconds with a 600 s lead-in: converted to seconds
  from trace start, clamped at 0.

Column names accept both the BigQuery export form
(``resource_request.cpus``) and flattened variants (``cpus``/``cpu``).
Event types accept the integer enum or the upper-case name.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ..models.encode import EncodedCluster, EncodedPods
from .borg import BorgSpec, encoded_from_cols

SUBMIT, FINISH, KILL = 0, 6, 7
_TYPE_NAMES = {
    "SUBMIT": SUBMIT, "QUEUE": 1, "ENABLE": 2, "SCHEDULE": 3, "EVICT": 4,
    "FAIL": 5, "FINISH": FINISH, "KILL": KILL, "LOST": 8,
    "UPDATE_PENDING": 9, "UPDATE_RUNNING": 10,
}
_US = 1e-6
_LEAD_S = 600.0
#: free (≤99) and best-effort-batch (100..119) tiers tolerate batch taints.
_BATCH_PRIORITY_MAX = 119


def _etype(v: str) -> int:
    v = v.strip()
    if not v:
        return -1
    if v.upper() in _TYPE_NAMES:
        return _TYPE_NAMES[v.upper()]
    try:
        return int(float(v))
    except ValueError:
        return -1


def _col(row: dict, *names, default=""):
    for n in names:
        if n in row and row[n] != "":
            return row[n]
    return default


def _to_id(v: str) -> int:
    """Integer id parse without a float round-trip (ids above 2^53 must
    stay exact — the INT64 schema allows them); decimal/scientific
    notation from float-typed re-exports still parses via float."""
    try:
        return int(v)
    except ValueError:
        return int(float(v))


@dataclass
class Borg2019Etl:
    """Streaming mapper: real-schema CSVs → encoded trace columns."""

    instance_events: str
    collection_events: Optional[str] = None
    cpu_scale: float = 8.0
    mem_scale: float = 16.0 * 2**30

    def read_cols(self) -> Dict[str, np.ndarray]:
        """Columnar task table. Fast path: the native C++ event parser
        (native/borg2019.cpp) + vectorized numpy aggregation — the real
        2019 instance_events table is billions of rows, and the per-row
        csv.DictReader path below costs minutes per million rows. The
        DictReader path remains the tolerant fallback (quoted fields,
        exotic headers, no toolchain) and the parity pin
        (tests/test_borg_etl.py::test_native_ingest_matches_dictreader)."""
        from .. import native

        raw = native.read_borg2019_events(self.instance_events)
        if raw is not None:
            coll = (
                native.read_borg2019_events(self.collection_events)
                if self.collection_events
                else None
            )
            if not (self.collection_events and coll is None):
                return self._cols_from_raw(raw, coll)
        return self._cols_dictreader()

    def _cols_from_raw(self, raw, coll) -> Dict[str, np.ndarray]:
        """Vectorized twin of _cols_dictreader over the native parser's
        raw event columns — value-identical (same first-submit-in-file-
        order task rows, last-wins end times, duration rule)."""
        et = raw["etype"]
        cid = raw["cid"]
        iidx = raw["iidx"]
        t = raw["time_us"] * _US - _LEAD_S
        R = et.shape[0]
        if R == 0 or not (et == SUBMIT).any():
            raise ValueError(
                f"no instance SUBMIT events in {self.instance_events}"
            )

        def _last_wins_map(cids, vals, present):
            m = present
            c, v = cids[m], vals[m]
            if c.size == 0:
                return None
            u, ridx = np.unique(c[::-1], return_index=True)
            return u, v[len(c) - 1 - ridx]

        jp = ja = None
        if coll is not None:
            cs = coll["etype"] == SUBMIT
            jp = _last_wins_map(coll["cid"], coll["prio"], cs & (coll["prio"] >= 0))
            ja = _last_wins_map(coll["cid"], coll["alloc"], cs & (coll["alloc"] >= 0))

        def _lookup(table, q):
            if table is None:
                return np.zeros(q.shape, np.int64)
            keys_u, vals_u = table
            pos = np.clip(np.searchsorted(keys_u, q), 0, len(keys_u) - 1)
            return np.where(keys_u[pos] == q, vals_u[pos], 0).astype(np.int64)

        # Group events by (collection_id, instance_index) — lexsort is
        # stable, so file order within each group is preserved.
        order = np.lexsort((iidx, cid))
        cid_s, iidx_s = cid[order], iidx[order]
        newg = np.empty(R, bool)
        newg[0] = True
        newg[1:] = (cid_s[1:] != cid_s[:-1]) | (iidx_s[1:] != iidx_s[:-1])
        starts = np.flatnonzero(newg)
        et_s, t_s, pos_s = et[order], t[order], order

        BIG = np.iinfo(np.int64).max
        sub = et_s == SUBMIT
        first_sub = np.minimum.reduceat(np.where(sub, pos_s, BIG), starts)
        has_sub = first_sub != BIG
        # MAX submit TIME — matching the DictReader twin's
        # ``last_submit[key] = max(t, last_submit)`` exactly, so the two
        # paths stay value-identical even on traces not sorted by time
        # (end events differ: both take the LAST end in file order,
        # mirroring the dict's overwrite).
        last_sub_t = np.maximum.reduceat(
            np.where(sub, np.maximum(t_s, 0.0), -np.inf), starts
        )
        endm = (et_s == FINISH) | (et_s == KILL)
        last_end_pos = np.maximum.reduceat(np.where(endm, pos_s, -1), starts)
        has_end = last_end_pos >= 0
        end_t = np.maximum(t[np.clip(last_end_pos, 0, None)], 0.0)

        fs = first_sub[has_sub].astype(np.int64)
        # Task order = first-submit file order (the dict path's insertion
        # order) so both paths encode identically.
        o2 = np.argsort(fs, kind="stable")
        fs = fs[o2]
        arr = np.maximum(t[fs], 0.0)
        prio_raw = raw["prio"][fs].astype(np.int64)
        alloc_raw = raw["alloc"][fs].astype(np.int64)
        cidt = cid[fs]
        prio = np.where(prio_raw >= 0, prio_raw, _lookup(jp, cidt))
        alloc = np.where(alloc_raw >= 0, alloc_raw, _lookup(ja, cidt))
        cpu = raw["cpu"][fs].astype(np.float32) * np.float32(self.cpu_scale)
        mem = raw["mem"][fs].astype(np.float32) * np.float32(self.mem_scale)
        ls_t = last_sub_t[has_sub][o2]
        he = has_end[has_sub][o2]
        en = end_t[has_sub][o2]
        dur = np.where(
            ~he | (ls_t > en), np.inf, np.maximum(en - ls_t, 0.0)
        ).astype(np.float32)
        return self._finish_cols(arr, cpu, mem, prio, alloc, cidt, dur)

    def _cols_dictreader(self) -> Dict[str, np.ndarray]:
        # Optional job-level fallbacks (priority / alloc set) keyed by
        # collection_id, from collection_events.
        job_prio: Dict[int, int] = {}
        job_alloc: Dict[int, int] = {}
        if self.collection_events:
            with open(self.collection_events, newline="") as f:
                for row in csv.DictReader(f):
                    if _etype(_col(row, "type")) != SUBMIT:
                        continue
                    cid = _to_id(_col(row, "collection_id", default="0"))
                    p = _col(row, "priority")
                    if p != "":
                        job_prio[cid] = _to_id(p)
                    a = _col(row, "alloc_collection_id")
                    if a != "":
                        job_alloc[cid] = _to_id(a)

        # One streaming pass over instance_events: the FIRST SUBMIT wins
        # the task row (arrival); FINISH/KILL record the end time. A
        # re-scheduled instance (EVICT → re-SUBMIT cycles are common in
        # the real trace) anchors its duration at the LAST submit before
        # the end, so the replay holds resources for the final runtime —
        # not the whole eviction-spanning lifetime.
        tasks: Dict[Tuple[int, int], list] = {}
        ends: Dict[Tuple[int, int], float] = {}
        last_submit: Dict[Tuple[int, int], float] = {}
        with open(self.instance_events, newline="") as f:
            for row in csv.DictReader(f):
                et = _etype(_col(row, "type"))
                cid = _to_id(_col(row, "collection_id", default="0"))
                iidx = _to_id(_col(row, "instance_index", default="0"))
                key = (cid, iidx)
                t = float(_col(row, "time", default="0")) * _US - _LEAD_S
                if et == SUBMIT:
                    last_submit[key] = max(
                        max(t, 0.0), last_submit.get(key, 0.0)
                    )
                    if key in tasks:
                        continue
                    prio = _col(row, "priority")
                    prio = (
                        _to_id(prio) if prio != ""
                        else job_prio.get(cid, 0)
                    )
                    alloc = _col(row, "alloc_collection_id")
                    alloc = (
                        _to_id(alloc) if alloc != ""
                        else job_alloc.get(cid, 0)
                    )
                    cpu = float(
                        _col(row, "resource_request.cpus", "cpus", "cpu",
                             default="0")
                    )
                    mem = float(
                        _col(row, "resource_request.memory", "memory", "mem",
                             default="0")
                    )
                    tasks[key] = [max(t, 0.0), cpu, mem, prio, alloc, cid]
                elif et in (FINISH, KILL):
                    ends[key] = max(t, 0.0)

        P = len(tasks)
        if P == 0:
            raise ValueError(
                f"no instance SUBMIT events in {self.instance_events}"
            )
        keys = list(tasks.keys())
        arr = np.array([tasks[k][0] for k in keys], np.float64)
        cpu = np.array([tasks[k][1] for k in keys], np.float32) * np.float32(
            self.cpu_scale
        )
        mem = np.array([tasks[k][2] for k in keys], np.float32) * np.float32(
            self.mem_scale
        )
        prio = np.array([tasks[k][3] for k in keys], np.int64)
        alloc = np.array([tasks[k][4] for k in keys], np.int64)
        appid = np.array([tasks[k][5] for k in keys], np.int64)
        def _dur(k):
            if k not in ends:
                return np.inf
            start = last_submit.get(k, tasks[k][0])
            if start > ends[k]:
                # Re-SUBMIT after the last FINISH/KILL: the restarted
                # incarnation is still running at trace end — hold its
                # resources for the remainder (advisor round-2: clamping
                # to the stale end gave duration 0, freeing instantly).
                return np.inf
            return max(ends[k] - start, 0.0)

        dur = np.array([_dur(k) for k in keys], np.float32)
        return self._finish_cols(arr, cpu, mem, prio, alloc, appid, dur)

    def _finish_cols(self, arr, cpu, mem, prio, alloc, appid, dur):
        """Shared tail: alloc sets → gangs with co-arrival + final sort."""
        group = np.where(alloc > 0, alloc, -1)
        # Alloc-set members co-arrive at the set's first submit and must be
        # index-adjacent (pack_waves packs a gang into one wave).
        sort_t = np.asarray(arr, np.float64).copy()
        gm = group >= 0
        if gm.any():
            u, inv = np.unique(group[gm], return_inverse=True)
            mins = np.full(len(u), np.inf)
            np.minimum.at(mins, inv, arr[gm])
            sort_t[gm] = mins[inv]
        order = np.lexsort((arr, group, sort_t))
        arr2 = sort_t[order]  # gang members share the set's arrival
        return {
            "arrival": arr2,
            "cpu": cpu[order],
            "mem": mem[order],
            "priority": prio[order].astype(np.int32),
            "group_id": group[order],
            "app_id": appid[order],
            "tolerates": (prio[order] <= _BATCH_PRIORITY_MAX).astype(np.int32),
            "duration": dur[order],
        }


def load_borg2019(
    instance_events: str,
    spec: BorgSpec,
    collection_events: Optional[str] = None,
    cpu_scale: float = 8.0,
    mem_scale: float = 16.0 * 2**30,
) -> Tuple[EncodedCluster, EncodedPods, dict]:
    """Real-schema ingest → (EncodedCluster, EncodedPods, meta): the
    Borg-2019 counterpart of sim.borg.load_trace_csv. ``spec`` supplies
    the cluster shape and template vocabulary."""
    etl = Borg2019Etl(
        instance_events=instance_events,
        collection_events=collection_events,
        cpu_scale=cpu_scale,
        mem_scale=mem_scale,
    )
    return encoded_from_cols(spec, etl.read_cols())
