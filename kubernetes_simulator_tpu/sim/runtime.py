"""Simulation runtime — layers L4/L7 (SURVEY.md §1, §3.1).

Event-driven replay over a virtual clock: pod arrivals come from the trace,
bindings update the shared state used by subsequent pods, pod completions
free resources, node events perturb the cluster mid-replay (failure
injection, SURVEY.md §5). No apiserver/kubelet — the simulator IS the fake
backend (SURVEY.md §4.4).

This module is the **cpu** strategy (the [BASELINE]-mandated default path).
The `jax` strategy in :mod:`.jax_runtime` replays the same encoded trace as
a fused device program and must produce placements this engine agrees with
on parity workloads.
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..framework.framework import FrameworkConfig, SchedulerFramework, ScheduleResult
from ..framework.queue import SchedulingQueue
from ..framework.registry import register_strategy
from ..models.encode import PAD, EncodedCluster, EncodedPods
from ..models.state import SchedState, bind, init_state, unbind
from ..utils.metrics import (
    fragmentation_gauges,
    round_fragmentation,
    series_gauges,
    utilization_means,
)
from .telemetry import ReplayTelemetry, TelemetryCollector, TelemetryConfig

# Event kinds, in tie-break order at equal timestamps: node events first,
# then completions (free resources), then arrivals, then permit timeouts.
EV_NODE = 0
EV_FINISH = 1
EV_ARRIVAL = 2
EV_PERMIT_TIMEOUT = 3

DEFAULT_PERMIT_TIMEOUT = 600.0  # virtual seconds a gang may hold reservations


@dataclass
class NodeEvent:
    """Cluster perturbation at a virtual timestamp (failure injection)."""

    time: float
    kind: str  # "node_down" | "node_up" | "capacity_scale"
    node: int
    scale: float = 1.0


_EVENT_KINDS = ("node_down", "node_up", "capacity_scale")


def validate_node_events(
    events: Optional[List[NodeEvent]], num_nodes: int
) -> List[NodeEvent]:
    """Up-front validation shared by every engine (CPU, device replay,
    what-if timelines): a malformed timeline raises an actionable
    ``ValueError`` instead of silently misbehaving mid-replay. Checks:
    known kind, node index in range, finite non-negative non-decreasing
    times, ``node_up`` only after a ``node_down`` on the same node, and a
    non-negative ``capacity_scale`` factor. Returns the (unmodified)
    list for chaining."""
    events = events or []
    down: set = set()
    prev_t = -np.inf
    for i, ev in enumerate(events):
        where = f"node_events[{i}]"
        if ev.kind not in _EVENT_KINDS:
            raise ValueError(
                f"{where}: unknown kind {ev.kind!r} (expected one of "
                f"{', '.join(_EVENT_KINDS)})"
            )
        if not (0 <= int(ev.node) < num_nodes):
            raise ValueError(
                f"{where}: node {ev.node} out of range for a cluster of "
                f"{num_nodes} nodes"
            )
        t = float(ev.time)
        if not np.isfinite(t) or t < 0:
            raise ValueError(
                f"{where}: time {ev.time!r} must be a finite value >= 0"
            )
        if t < prev_t:
            raise ValueError(
                f"{where}: time {t} is before the previous event's "
                f"{prev_t} — timelines must be sorted by time (the "
                f"checkpoint event cursor and the boundary-granular "
                f"device application both assume it)"
            )
        prev_t = t
        if ev.kind == "node_down":
            down.add(int(ev.node))
        elif ev.kind == "node_up":
            if int(ev.node) not in down:
                raise ValueError(
                    f"{where}: node_up for node {ev.node} without a prior "
                    f"node_down — recovery of a node that never failed "
                    f"usually means a mis-built timeline"
                )
            down.discard(int(ev.node))
        elif ev.kind == "capacity_scale" and (
            not np.isfinite(float(ev.scale)) or float(ev.scale) < 0
        ):
            raise ValueError(
                f"{where}: capacity_scale factor {ev.scale!r} must be a "
                f"finite value >= 0"
            )
    return events


def events_hash(events: Optional[List[NodeEvent]]) -> np.ndarray:
    """Stable 32-byte digest of a timeline (uint8[32]) — stored in
    boundary-mode checkpoint blobs so a resume under a DIFFERENT event
    list is rejected instead of silently re-applying or skipping
    events."""
    import hashlib

    items = tuple(
        (float(e.time), str(e.kind), int(e.node), float(e.scale))
        for e in (events or [])
    )
    digest = hashlib.sha256(repr(items).encode()).digest()
    return np.frombuffer(digest, dtype=np.uint8).copy()


@dataclass
class ReplayResult:
    assignments: np.ndarray  # [P] i32 node per pod (PAD = never placed)
    placed: int
    unschedulable: int
    preemptions: int
    attempts: int
    wall_clock_s: float
    placements_per_sec: float
    virtual_makespan: float
    utilization: Dict[str, float]
    state: SchedState
    # Pods dropped on retry-buffer overflow (device retry/kube-preemption
    # paths; [K8S] keeps everything — a nonzero value means placements
    # were lost to buffer capacity, not infeasibility).
    retry_dropped: int = 0
    # Chaos disruption counters — node_down NoExecute evictions, kept
    # DISTINCT from scheduler-initiated `preemptions` so failure injection
    # is never conflated with PostFilter victim selection. `rescheduled`
    # counts evicted pods that later re-bound; `stranded` = evicted and
    # never re-placed by trace end; latency is mean virtual time from
    # eviction to re-bind (boundary-granular on the device path).
    evictions: int = 0
    evict_rescheduled: int = 0
    evict_stranded: int = 0
    evict_latency_mean: float = 0.0
    # Utilization economics (round 13): end-of-replay fragmentation /
    # stranded-capacity / packing gauges (utils.metrics
    # fragmentation_gauges) computed from the committed state against the
    # restored allocatable, with the still-pending pod set. Bit-identical
    # CPU engine ↔ device paths. None only on legacy callers that build
    # the result by hand.
    fragmentation: Optional[dict] = None
    # Telemetry (sim.telemetry.ReplayTelemetry) — None at granularity
    # "off". Latency histograms, rejection attribution, series, phase
    # timers; see the telemetry module docstring for cross-engine
    # parity semantics.
    telemetry: Optional["ReplayTelemetry"] = None

    def summary(self) -> dict:
        out = {
            "placed": self.placed,
            "unschedulable": self.unschedulable,
            "preemptions": self.preemptions,
            "attempts": self.attempts,
            "wall_clock_s": round(self.wall_clock_s, 4),
            "placements_per_sec": round(self.placements_per_sec, 1),
            "virtual_makespan": self.virtual_makespan,
            "utilization": {k: round(v, 4) for k, v in self.utilization.items()},
            "retry_dropped": self.retry_dropped,
            "evictions": self.evictions,
            "evict_rescheduled": self.evict_rescheduled,
            "evict_stranded": self.evict_stranded,
            "evict_latency_mean": round(self.evict_latency_mean, 4),
        }
        if self.fragmentation is not None:
            out["fragmentation"] = round_fragmentation(self.fragmentation)
        if self.telemetry is not None:
            out["telemetry"] = self.telemetry.summary()
        return out


class CpuReplayEngine:
    def __init__(
        self,
        ec: EncodedCluster,
        pods: EncodedPods,
        config: Optional[FrameworkConfig] = None,
        permit_timeout: float = DEFAULT_PERMIT_TIMEOUT,
        telemetry=None,
    ):
        self.ec = ec
        self.pods = pods
        self.fw = SchedulerFramework(ec, pods, config)
        self.permit_timeout = permit_timeout
        # Telemetry granularity (str | TelemetryConfig | None→"summary").
        # The event engine is the exact oracle: latencies are recorded at
        # the event clock, rejections at the failing attempt itself.
        self.telemetry_cfg = TelemetryConfig.resolve(telemetry)

    # -- helpers -----------------------------------------------------------

    def _affinity_dependent(self, p: int) -> bool:
        pods = self.pods
        return bool(
            pods.aff_req[p, 0] >= 0
            or pods.anti_req[p, 0] >= 0
            or pods.spread_g[p, 0] >= 0
        )

    # -- main loop ---------------------------------------------------------

    def replay(self, node_events: Optional[List[NodeEvent]] = None) -> ReplayResult:
        ec, pods = self.ec, self.pods
        validate_node_events(node_events, ec.num_nodes)
        st = init_state(ec, pods)
        q = SchedulingQueue()
        events: List[Tuple[float, int, int, int]] = []  # (time, kind, seq, payload)
        seq = 0

        def push_event(t: float, kind: int, payload: int) -> int:
            nonlocal seq
            s = seq
            heapq.heappush(events, (t, kind, s, payload))
            seq += 1
            return s

        to_schedule = np.nonzero(pods.bound_node == PAD)[0]
        for p in to_schedule:
            push_event(float(pods.arrival[p]), EV_ARRIVAL, int(p))
        node_events = node_events or []
        for i, ev in enumerate(node_events):
            push_event(ev.time, EV_NODE, i)
        # Per-pod seq of the CURRENT finish timer: an eviction + re-bind
        # re-arms the timer, and the stale event must not complete the pod
        # early (same staleness class as gang permit timeouts).
        finish_seq: Dict[int, int] = {}

        # Completions of pre-bound pods.
        for p in np.nonzero(pods.bound_node >= 0)[0]:
            if np.isfinite(pods.duration[p]):
                finish_seq[int(p)] = push_event(
                    float(pods.arrival[p] + pods.duration[p]), EV_FINISH, int(p)
                )

        # Gang bookkeeping ([K8S] coscheduling Permit; SURVEY.md §3.3).
        reserved: Dict[int, List[int]] = {}
        failed_groups: Dict[int, float] = {}  # group → virtual time of failure
        gang_timeout_seq: Dict[int, int] = {}
        failed_groups_ver: Dict[int, int] = {}  # group → progress_ver at failure

        placed = preemptions = attempts = 0
        # Chaos disruption accounting: eviction time per still-displaced
        # pod (a re-bind pops it; what remains at trace end is stranded).
        evictions = evict_rescheduled = 0
        evict_lat_sum = 0.0
        evict_time: Dict[int, float] = {}
        # Last successful placement per pod: a COMPLETED pod keeps its node
        # (it ran; it is not unschedulable), unlike st.bound which goes PAD
        # at EV_FINISH. Evictions clear it until re-placed.
        assignments = np.where(pods.bound_node >= 0, pods.bound_node, PAD).astype(
            np.int32
        )
        now = 0.0
        # Committed cluster progress (commits, completions, evictions, node
        # events) — NOT speculative gang reserves. Gates timed gang retries
        # so a gang that cannot complete doesn't spin the virtual clock.
        progress_ver = 0
        saved_alloc = ec.allocatable.copy()
        tel = (
            TelemetryCollector(self.telemetry_cfg)
            if self.telemetry_cfg.enabled
            else None
        )
        want_series = tel is not None and tel.cfg.want_series
        want_timeline = tel is not None and tel.cfg.want_timeline
        # First COMMITTED bind per pod — latency is arrival→first bind;
        # re-binds after eviction/preemption must not re-record.
        lat_seen: set = set()

        def record_bind(m: int, t: float) -> None:
            if tel is None:
                return
            tel.clear_episode(m)
            if want_timeline:
                tel.event("bind", t, int(m), int(st.bound[m]))
            if m not in lat_seen:
                lat_seen.add(m)
                lat = t - float(pods.arrival[m])
                if lat <= 0.0:
                    tel.bind_zero()
                else:
                    tel.bind_latency(m, lat)

        t0 = time.perf_counter()

        def rollback_group(g: int, park: bool):
            # ``park=False`` (permit timeout): members were placeable and the
            # gang just failed to assemble in time → backoff retry ([K8S]
            # coscheduling rejects waiting pods back through the backoff
            # queue) — but only if committed progress happened since the
            # last failure, else retrying cannot help and would spin the
            # virtual clock. ``park=True`` (a member failed): assembling
            # again needs a cluster event → everyone waits for one.
            retry = (not park) and failed_groups_ver.get(g) != progress_ver
            for m in reserved.pop(g, []):
                unbind(ec, pods, st, m)
                if retry:
                    q.requeue_backoff(m, int(pods.priority[m]), now)
                else:
                    q.mark_unschedulable(m, int(pods.priority[m]), now)
            gang_timeout_seq.pop(g, None)
            failed_groups[g] = now
            failed_groups_ver[g] = progress_ver

        def evict(p: int, requeue: bool = True):
            if tel is not None:
                # A displacement starts a fresh unschedulable episode: the
                # next fully-failed attempt re-enters the reasons counts.
                tel.clear_episode(int(p))
            unbind(ec, pods, st, int(p))
            assignments[int(p)] = PAD
            # An evicted reserved gang member returns to the queue
            # unreserved — drop it from the reservation so a later re-bind
            # cannot enter the members list twice.
            g = int(pods.group_id[p])
            if g != PAD and g in reserved and int(p) in reserved[g]:
                reserved[g].remove(int(p))
                if not reserved[g]:
                    reserved.pop(g)
                    gang_timeout_seq.pop(g, None)
            if requeue:
                q.push(int(p), int(pods.priority[p]))

        while events or len(q):
            _pt = time.perf_counter() if tel is not None else 0.0
            if events:
                # Advance to the next event OR the next backoff expiry,
                # whichever is first — a 1s backoff must not stretch to the
                # next event's timestamp.
                nb = q.next_backoff_time()
                t_next = events[0][0]
                now = max(now, min(t_next, nb) if nb is not None else t_next)
                progressed_cluster = False
                while events and events[0][0] <= now:
                    _, kind, ev_seq, payload = heapq.heappop(events)
                    if kind == EV_ARRIVAL:
                        q.push(payload, int(pods.priority[payload]))
                    elif kind == EV_FINISH:
                        if st.bound[payload] != PAD and finish_seq.get(payload) == ev_seq:
                            unbind(ec, pods, st, payload)
                            finish_seq.pop(payload, None)
                            progressed_cluster = True
                            progress_ver += 1
                    elif kind == EV_NODE:
                        ev = node_events[payload]
                        if ev.kind == "node_down":
                            ec.allocatable[ev.node] = 0.0
                            if want_timeline:
                                tel.event("node_down", now, -1, int(ev.node))
                            # NoExecute semantics: evict and requeue ([K8S]).
                            for m in np.nonzero(st.bound == ev.node)[0]:
                                if want_timeline:
                                    tel.event("evict", now, int(m), int(ev.node))
                                evict(int(m))
                                evictions += 1
                                evict_time[int(m)] = now
                        elif ev.kind == "node_up":
                            ec.allocatable[ev.node] = saved_alloc[ev.node]
                            if want_timeline:
                                tel.event("node_up", now, -1, int(ev.node))
                        elif ev.kind == "capacity_scale":
                            ec.allocatable[ev.node] = saved_alloc[ev.node] * ev.scale
                        progressed_cluster = True
                        progress_ver += 1
                    elif kind == EV_PERMIT_TIMEOUT:
                        g = payload
                        # Seq must match: stale timeouts from a rolled-back
                        # reservation cycle must not cancel a fresh one.
                        if g in reserved and gang_timeout_seq.get(g) == ev_seq:
                            rollback_group(g, park=False)
                if progressed_cluster:
                    q.flush_unschedulable(now)
            q.flush_backoff(now)
            if tel is not None:
                tel.phases.add("host_events", time.perf_counter() - _pt)
                if want_series:
                    tel.sample(
                        now,
                        active=len(q),
                        unschedulable=q.num_unschedulable,
                        backoff=q.num_backoff,
                        # Utilization economics (round 13): sampled after
                        # the instant's events, before scheduling — the
                        # device boundary samples the same committed
                        # state via the shared helper (bit-parity).
                        **series_gauges(st.used, ec.allocatable, ec.vocab._r),
                    )
                _pt = time.perf_counter()

            made_bind = False
            while True:
                p = q.pop()
                if p is None:
                    break
                g = int(pods.group_id[p])
                if g != PAD and g in failed_groups and failed_groups[g] == now:
                    # Group already failed at this instant; retry later.
                    # No ``now``: this was not a real scheduling attempt, so
                    # it must not inflate the pod's exponential backoff.
                    q.mark_unschedulable(p, int(pods.priority[p]))
                    continue
                attempts += 1
                res = self.fw.schedule_one(
                    st, p, allow_preemption=g == PAD, want_reasons=want_series
                )
                if res.node == PAD:
                    if want_series and res.reasons is not None:
                        tel.rejection(int(p), res.reasons)
                    if g != PAD and g in reserved:
                        rollback_group(g, park=True)
                    q.mark_unschedulable(p, int(pods.priority[p]), now)
                    continue
                for v in res.victims:
                    if want_timeline:
                        tel.event("preempt", now, int(v), int(st.bound[v]))
                    evict(v)
                    preemptions += 1
                    progress_ver += 1
                bind(ec, pods, st, p, res.node)
                if g != PAD:
                    members = reserved.setdefault(g, [])
                    if not members:
                        gang_timeout_seq[g] = push_event(
                            now + self.permit_timeout, EV_PERMIT_TIMEOUT, g
                        )
                    members.append(p)
                    if len(members) >= int(pods.pg_min_member[g]):
                        # Permit: whole gang reserved → commit.
                        for m in reserved.pop(g):
                            placed += 1
                            made_bind = True
                            progress_ver += 1
                            assignments[m] = st.bound[m]
                            record_bind(m, now)
                            if m in evict_time:
                                evict_rescheduled += 1
                                evict_lat_sum += now - evict_time.pop(m)
                            if np.isfinite(pods.duration[m]):
                                finish_seq[m] = push_event(
                                    now + float(pods.duration[m]), EV_FINISH, m
                                )
                        gang_timeout_seq.pop(g, None)
                        failed_groups.pop(g, None)
                        failed_groups_ver.pop(g, None)
                else:
                    placed += 1
                    made_bind = True
                    progress_ver += 1
                    assignments[p] = res.node
                    record_bind(p, now)
                    if p in evict_time:
                        evict_rescheduled += 1
                        evict_lat_sum += now - evict_time.pop(p)
                    if np.isfinite(pods.duration[p]):
                        finish_seq[p] = push_event(
                            now + float(pods.duration[p]), EV_FINISH, p
                        )
                if made_bind and q.num_unschedulable:
                    # Binding is a cluster event for affinity/spread waiters.
                    q.flush_unschedulable(now)
            if tel is not None:
                tel.phases.add("host_schedule", time.perf_counter() - _pt)
            # Idle until the next event (or backoff expiry).
            nb = q.next_backoff_time()
            if not events and len(q) == 0 and nb is not None:
                now = max(now, nb)
                q.flush_backoff(now)
                if len(q) == 0:
                    break

        # Any still-reserved gang at trace end never completed → roll back.
        for g in list(reserved):
            rollback_group(g, park=True)

        wall = time.perf_counter() - t0
        ec.allocatable[:] = saved_alloc
        util = utilization_means(st.used, ec.allocatable, ec.vocab._r)
        unsched = int((assignments[to_schedule] == PAD).sum())
        pending = to_schedule[assignments[to_schedule] == PAD]
        frag = fragmentation_gauges(
            ec.allocatable, st.used, pods.requests[pending], ec.vocab._r
        )
        return ReplayResult(
            assignments=assignments,
            placed=placed,
            unschedulable=unsched,
            preemptions=preemptions,
            attempts=attempts,
            wall_clock_s=wall,
            placements_per_sec=placed / wall if wall > 0 else 0.0,
            virtual_makespan=now,
            utilization=util,
            state=st,
            evictions=evictions,
            evict_rescheduled=evict_rescheduled,
            evict_stranded=len(evict_time),
            evict_latency_mean=(
                evict_lat_sum / evict_rescheduled if evict_rescheduled else 0.0
            ),
            fragmentation=frag,
            telemetry=tel.result() if tel is not None else None,
        )


@register_strategy("cpu")
def _make_cpu(ec: EncodedCluster, pods: EncodedPods, config: Optional[FrameworkConfig] = None, **kw):
    return CpuReplayEngine(ec, pods, config, **kw)
