"""Flight recorder (round 16): streaming in-flight observability for
long replays — one JSONL event per chunk boundary (plus per page-stall /
checkpoint / boundary-fold) so an hour-scale Borg-headline run is
watchable while it executes and attributable afterwards.

Every row carries: virtual time at the chunk boundary, placements /
slots dispatched so far, a rolling placements-per-second gauge,
PHASE_NAMES phase-timer deltas since the previous event, pager state
(prefetch depth, cumulative stall count, stall wall-time), the
selection-exchange probe wall under nodeShards, checkpoint blob bytes,
and memory residency (the ``replicated_resident_bytes`` estimate plus
the host RSS high-water from ``getrusage``).

The recorder is OFF by default and bit-parity pinned
(tests/test_flight.py): placements, deterministic JSONL and checkpoint
blobs are identical with the recorder on or off — it never changes a
device program, a fold ordering or a checkpoint payload; it only reads
clocks and counters at chunk cadence. Rows are written through
:class:`utils.metrics.JsonlWriter` (schema-stamped, process-stamped
under DCN); ``KSIM_DETERMINISTIC_JSONL=1`` zeroes every wall-clock-
derived field (``FLIGHT_WALL_FIELDS``) so a fixed-seed recorder stream
is byte-stable — the flight twin of the replay-row scrub.

Consumers: ``scripts/bottleneck_report.py`` (dominant-regime naming),
``scripts/dcn_launch.py --watch`` (live recorder lines), and bench.py's
``borg_headline`` mode.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional

from .telemetry import PhaseTimers

# Wall-clock-derived row fields zeroed under KSIM_DETERMINISTIC_JSONL
# (kept PRESENT as numbers so schema-v5 validation still sees them).
# Values inside the "phases" delta dict are zeroed too — phase timers
# are perf_counter deltas. Everything else in a flight row (chunk
# cursor, virtual time, dispatch/placement counts, pager stall/
# invalidation COUNTS, prefetch depth, checkpoint blob bytes, residency
# estimate) is deterministic for a fixed seed and stays.
# ``pager_waits`` is a COUNT but rides this list anyway: whether a
# threaded prefetch finished before ``get`` asked is a race outcome
# (round 19), unlike miss/invalidation counts which are structural.
FLIGHT_WALL_FIELDS = (
    "wall_s",
    "rolling_pps",
    "stall_s",
    # Round 21: the renewal age observed at a steal/speculate decision
    # is wall-clock evidence (the threshold it exceeded is config and
    # stays). Trace stamps (trace/span/parent/link) are handled in
    # _emit: dropped entirely in deterministic mode so streams are
    # byte-identical with KSIM_TRACE on and off.
    "renew_age_s",
    "pager_stall_s",
    "pager_prefetch_s",
    "pager_wait_s",
    "pager_waits",
    "exchange_probe_s",
    "exchange_est_s",
    "ckpt_wall_s",
    "rss_peak_mib",
    # Round 22: serving-plane query rows carry the batch's wall latency
    # (cold-vs-warm evidence). Queue depth / occupancy / warm flag are
    # structural and stay.
    "latency_s",
)

# Rolling placements/sec window: events, not seconds — chunk cadence is
# workload-dependent and the gauge should react within a few chunks.
_ROLL_WINDOW = 8


def rss_peak_mib() -> float:
    """Host RSS high-water in MiB (``getrusage`` ``ru_maxrss``; KiB on
    Linux, bytes on macOS). 0.0 where the resource module is absent —
    never raises, the recorder must not take a run down."""
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        scale = 2**20 if sys.platform == "darwin" else 2**10
        return round(peak * scale / 2**20, 1)
    except Exception:
        return 0.0


@dataclass
class FlightRecorderConfig:
    """``flightRecorder:`` YAML section / ``flight_recorder=`` engine
    kwarg. ``path`` is the JSONL sink (suffixed ``.p<pid>`` per process
    under DCN, like every other sink); ``every`` is the chunk cadence
    (1 = every chunk boundary; page/checkpoint/fold events always
    emit)."""

    path: str
    every: int = 1

    @classmethod
    def resolve(cls, v) -> Optional["FlightRecorderConfig"]:
        """None stays None (recorder off — the default); a path string
        becomes a config; a config or live recorder passes through."""
        if v is None or isinstance(v, (FlightRecorderConfig, FlightRecorder)):
            return v
        if isinstance(v, str):
            return cls(path=v)
        raise ValueError(
            f"flight_recorder: expected a path, FlightRecorderConfig or "
            f"None, got {v!r}"
        )


class FlightRecorder:
    """Streaming JSONL emitter for one replay. Construct via
    :meth:`open` (engines) or directly with a config; call
    :meth:`chunk` once per chunk boundary and :meth:`page` /
    :meth:`checkpoint` / :meth:`fold` as those events occur, then
    :meth:`close`. Owns a :class:`PhaseTimers` so a telemetry-off run
    still gets phase deltas (the engine routes its ``_tick`` here when
    no collector exists)."""

    def __init__(self, cfg: FlightRecorderConfig, meta: Optional[dict] = None):
        from ..parallel import dcn
        from ..utils.metrics import JsonlWriter

        self.cfg = cfg
        self.phases = PhaseTimers()  # used when telemetry is off
        self._meta = dict(meta or {})
        self._writer = JsonlWriter(dcn.output_path_for_process(cfg.path))
        self._t0 = time.perf_counter()
        self._last_phases: Dict[str, float] = {}
        self._roll: deque = deque(maxlen=_ROLL_WINDOW)  # (wall, progressed)
        self._events = 0
        self._emit(
            {
                "event": "start",
                "chunk": -1,
                "wall_s": 0.0,
                "rss_peak_mib": rss_peak_mib(),
                **self._meta,
            }
        )
        # Fleet-event subscription (round 18): lease/steal/speculation/
        # claim events from parallel.dcn land in this stream as "fleet"
        # rows, interleaved with the chunk rows — the straggler tests pin
        # the trail here. Unregistered on close; a raising sink is
        # dropped by dcn itself.
        self._fleet_sink = self.fleet_event
        dcn.EVENT_SINKS.append(self._fleet_sink)
        self._dcn_mod = dcn

    @classmethod
    def open(cls, spec, meta: Optional[dict] = None) -> Optional["FlightRecorder"]:
        """Engine entry point: ``spec`` is whatever the ``flight_recorder``
        kwarg carried (None / path / config / live recorder). Returns a
        live recorder or None (off). A recorder instance passes through
        so callers can share one across resume legs."""
        cfg = FlightRecorderConfig.resolve(spec)
        if cfg is None:
            return None
        if isinstance(cfg, FlightRecorder):
            return cfg
        return cls(cfg, meta=meta)

    # -- event emitters ----------------------------------------------------

    def chunk(
        self,
        ci: int,
        t_virtual: Optional[float] = None,
        dispatched: Optional[int] = None,
        placed: Optional[int] = None,
        phase_acc: Optional[Dict[str, float]] = None,
        pager=None,
        exchange_probe_s: Optional[float] = None,
        exchange_slots: Optional[int] = None,
        ckpt_publish: Optional[dict] = None,
        kv_retry: Optional[dict] = None,
    ) -> None:
        """One chunk-boundary row. ``phase_acc`` is the CUMULATIVE phase
        accumulator (the collector's or this recorder's own) — the row
        carries deltas since the previous chunk row. ``pager`` is a
        ``_PodPager`` (or anything with stalls/stall_s/prefetches/depth).
        ``exchange_probe_s`` is one timed round of the selection-exchange
        probe; ``exchange_est_s`` scales it to the chunk's slot count
        (the per-slot all_gather runs once per slot inside the scan).
        ``kv_retry`` (round 17) is the chunk's KV retry delta — retries
        burned, give-ups, backoff wall — attributing coordination-plane
        flakiness (real or faultline-injected) to the chunk it hit."""
        self._events += 1
        if self.cfg.every > 1 and (ci % self.cfg.every) != 0:
            return
        wall = time.perf_counter() - self._t0
        acc = dict(phase_acc if phase_acc is not None else self.phases.acc)
        delta = {
            k: round(v - self._last_phases.get(k, 0.0), 6)
            for k, v in sorted(acc.items())
        }
        self._last_phases = acc
        progressed = placed if placed is not None else dispatched
        rolling = 0.0
        if progressed is not None:
            self._roll.append((wall, int(progressed)))
            if len(self._roll) >= 2:
                (w0, p0), (w1, p1) = self._roll[0], self._roll[-1]
                if w1 > w0:
                    rolling = (p1 - p0) / (w1 - w0)
        row = {
            "event": "chunk",
            "chunk": int(ci),
            "wall_s": round(wall, 6),
            "rolling_pps": round(rolling, 1),
            "phases": delta,
            "rss_peak_mib": rss_peak_mib(),
        }
        if t_virtual is not None:
            import math

            row["t_virtual"] = (
                round(float(t_virtual), 6)
                if math.isfinite(float(t_virtual))
                else None
            )
        if dispatched is not None:
            row["dispatched"] = int(dispatched)
        if placed is not None:
            row["placed"] = int(placed)
        if pager is not None:
            row["pager_depth"] = int(getattr(pager, "depth", 0))
            row["pager_stalls"] = int(getattr(pager, "stalls", 0))
            row["pager_stall_s"] = round(
                float(getattr(pager, "stall_s", 0.0)), 6
            )
            # Round-19 overlap ledger: the prefetch fetches' own wall
            # (hidden when the pager thread is on, loop-exposed when
            # off), blocking waits on in-flight prefetches, and staged
            # pages invalidated by resume jumps. Always present so the
            # stream is byte-identical threaded on vs off under the
            # deterministic scrub.
            row["pager_prefetch_s"] = round(
                float(getattr(pager, "prefetch_wall_s", 0.0)), 6
            )
            row["pager_waits"] = int(getattr(pager, "waits", 0))
            row["pager_wait_s"] = round(
                float(getattr(pager, "wait_s", 0.0)), 6
            )
            row["pager_invalidations"] = int(
                getattr(pager, "invalidations", 0)
            )
        if exchange_probe_s is not None:
            row["exchange_probe_s"] = round(float(exchange_probe_s), 6)
            if exchange_slots:
                row["exchange_slots"] = int(exchange_slots)
                row["exchange_est_s"] = round(
                    float(exchange_probe_s) * int(exchange_slots), 6
                )
        if ckpt_publish:
            row["dcn_publish"] = dict(ckpt_publish)
        if kv_retry:
            row["dcn_retry"] = dict(kv_retry)
        self._emit(row)

    def page(
        self, ci: int, stall_s: float, stalls: int,
        invalidations: Optional[int] = None,
    ) -> None:
        """A pager prefetch MISS (the synchronous fetch the prefetch
        exists to hide) — emitted per stall, they are the exceptional
        case the report looks for. ``invalidations`` (round 19) rides
        along when a resume jump discarded the staged page: previously
        that surfaced as a plain stall, under-reporting what the pager
        threw away."""
        row = {
            "event": "page",
            "chunk": int(ci),
            "stall_s": round(float(stall_s), 6),
            "pager_stalls": int(stalls),
            "wall_s": round(time.perf_counter() - self._t0, 6),
        }
        if invalidations:
            row["pager_invalidations"] = int(invalidations)
        self._emit(row)

    def checkpoint(
        self, ci: int, nbytes: int, wall_s: float, sink: str = "local"
    ) -> None:
        """A checkpoint left the engine: ``sink`` is "local" (npz blob on
        disk) or "dcn" (KV publication). ``nbytes`` is the blob size —
        deterministic, so it survives the JSONL scrub."""
        self._emit(
            {
                "event": "checkpoint",
                "chunk": int(ci),
                "ckpt_bytes": int(nbytes),
                "ckpt_wall_s": round(float(wall_s), 6),
                "ckpt_sink": sink,
                "wall_s": round(time.perf_counter() - self._t0, 6),
            }
        )

    def fold(self, ci: int, wall_s: float) -> None:
        """A boundary-mode mirror fold resolved (the host-side D2H +
        bookkeeping the lazy path tries to overlap)."""
        self._emit(
            {
                "event": "boundary_fold",
                "chunk": int(ci),
                "stall_s": round(float(wall_s), 6),
                "wall_s": round(time.perf_counter() - self._t0, 6),
            }
        )

    def query(
        self,
        batch: int,
        queued: int,
        occupancy: float,
        warm: bool,
        latency_s: float,
        engines: int,
    ) -> None:
        """One serving-plane batch resolved (round 22, sim.service): how
        many queries coalesced, the scenario-axis occupancy, whether the
        pool answered warm (value swap against a resident executable) or
        cold (fresh compile), and the batch wall. Everything but
        ``latency_s`` is deterministic for a fixed query sequence."""
        self._emit(
            {
                "event": "query",
                "chunk": -1,
                "batch": int(batch),
                "queue_depth": int(queued),
                "batch_occupancy": round(float(occupancy), 4),
                "warm": bool(warm),
                "engines": int(engines),
                "latency_s": round(float(latency_s), 6),
                "wall_s": round(time.perf_counter() - self._t0, 6),
            }
        )

    def fleet_event(self, event: dict) -> None:
        """One fleet coordination event (parallel.dcn._mirror_event):
        lease / steal / speculate / block_done / spec_lost / join /
        claim / recovered, plus the round-20 durability events —
        journal_adopt (a completed block adopted from the durable
        journal without re-execution) and journal_resume (a checkpoint
        restore whose winning cursor came from the journal rather than
        the live KV store). Round 21 adds ckpt_load / ckpt_fallback and
        the faultline fault_* kinds, each stamped with its causal trace
        identity (trace/span/parent — parallel.trace) by dcn before this
        sink sees it. Flattened into the row — every field but the wall
        clocks is deterministic for a fixed schedule."""
        ev = dict(event)
        # ckpt_publish events name their kind under "kind" (pinned by
        # test_durable); pop BOTH so the payload can never shadow the
        # row's own kind="flight" stamp (round 21 fix — shadowed rows
        # were invisible to read_stream).
        kind = ev.pop("event", None) or ev.pop("kind", None) or "?"
        ev.pop("kind", None)
        self._emit(
            {
                "event": "fleet",
                "fleet_event": str(kind),
                "chunk": -1,
                "wall_s": round(time.perf_counter() - self._t0, 6),
                **ev,
            }
        )

    def close(self, summary: Optional[dict] = None) -> None:
        try:
            self._dcn_mod.EVENT_SINKS.remove(self._fleet_sink)
        except (AttributeError, ValueError):
            pass
        if self._writer is None:
            return
        row = {
            "event": "end",
            "chunk": -1,
            "wall_s": round(time.perf_counter() - self._t0, 6),
            "rss_peak_mib": rss_peak_mib(),
            "events": self._events,
        }
        if summary:
            row.update(summary)
        self._emit(row)
        self._writer.close()
        self._writer = None

    # -- plumbing ----------------------------------------------------------

    def _emit(self, row: dict) -> None:
        from ..utils.metrics import deterministic_jsonl

        if self._writer is None:
            return
        row = {"kind": "flight", **row}
        if deterministic_jsonl():
            for k in FLIGHT_WALL_FIELDS:
                if k in row:
                    row[k] = 0.0
            if isinstance(row.get("phases"), dict):
                row["phases"] = {k: 0.0 for k in row["phases"]}
            # Round 19: with the background publisher and the retrying
            # publisher thread interleaving KV traffic with the loop,
            # WHICH chunk row a publish/retry delta lands on is a race
            # outcome — every numeric in these blocks is scrubbed, not
            # just the ``_s`` walls.
            for blk in ("dcn_publish", "dcn_retry"):
                if isinstance(row.get(blk), dict):
                    row[blk] = {
                        k: (
                            (0.0 if isinstance(v, float) else 0)
                            if isinstance(v, (int, float))
                            and not isinstance(v, bool)
                            else v
                        )
                        for k, v in row[blk].items()
                    }
            # Round 21: trace identity fields are deterministic values
            # but their PRESENCE depends on KSIM_TRACE — drop them so
            # deterministic streams are byte-identical stamping-on vs
            # stamping-off (the parity bar); live streams keep them.
            for k in ("trace", "span", "parent", "link"):
                row.pop(k, None)
        try:
            self._writer.write(row)
        except OSError:
            # Telemetry must never take the replay down mid-flight; a
            # full disk degrades to a truncated stream, not a crash.
            self._writer = None


def read_stream(path: str):
    """Parsed flight rows from ``path`` (list of dicts, malformed lines
    skipped). Shared by bottleneck_report and the tests."""
    import json

    rows = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and row.get("kind") == "flight":
                    rows.append(row)
    except OSError:
        return []
    return rows
