"""kubernetes_simulator_tpu — a TPU-native Kubernetes cluster/scheduler
simulator with the capability surface of wangchen615/kubernetes-simulator
(see SURVEY.md; the reference mount was empty, so the blueprint is the
[BASELINE]+[K8S] surface documented there).

Layers (SURVEY.md §1): models/ = L0 cluster-state + encodings; framework/ =
L1 scheduling framework + L3 queue + L6 registry; plugins/ = L2 plugin set;
sim/ = L4 runtime + L5 trace/what-if drivers; ops/ = the numpy/JAX kernels
behind Filter/Score; parallel/ = TPU mesh + collectives; utils/ = config,
metrics, quantities.
"""

__version__ = "0.1.0"

from .models.core import (  # noqa: F401
    Cluster,
    Effect,
    LabelSelector,
    MatchExpression,
    Node,
    NodeAffinitySpec,
    NodeSelectorTerm,
    Operator,
    Pod,
    PodAffinitySpec,
    PodAffinityTerm,
    PodGroup,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from .models.encode import encode  # noqa: F401
