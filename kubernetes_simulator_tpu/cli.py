"""CLI (SURVEY.md §2 "CLI / API"): run replays and what-if sweeps from a
YAML config.

    python -m kubernetes_simulator_tpu run config.yaml [--strategy jax]
    python -m kubernetes_simulator_tpu what-if config.yaml
    python -m kubernetes_simulator_tpu tune config.yaml
    python -m kubernetes_simulator_tpu serve config.yaml < queries.ndjson
    python -m kubernetes_simulator_tpu validate config.yaml
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .framework.registry import get_strategy
from .parallel import dcn
from .utils.config import SimConfig, build_encoded_case
from .utils.metrics import (
    JsonlWriter,
    config_hash,
    log,
    replay_row,
    whatif_rows,
)
from .utils.profiling import device_trace


def _writer_context(cfg, config_path: str) -> dict:
    """Row-stamping context (schema v2): the seed / engine / config hash
    that produced every row in the file, so results stay attributable
    after the config moves on."""
    import yaml

    with open(config_path) as f:
        d = yaml.safe_load(f) or {}
    seed = (
        cfg.borg.seed if cfg.borg is not None
        else (cfg.workload.seed if cfg.workload is not None else 0)
    )
    return {
        "seed": int(seed),
        "engine": cfg.strategy,
        "config_hash": config_hash(d),
    }


def _chaos_timeline(cfg, ec, ep, seed):
    """Materialize one seeded chaos campaign from the ``chaos:`` section
    (horizon defaults to the workload makespan — later events could never
    fire anyway)."""
    from .sim.synthetic import make_chaos_timeline

    ch = cfg.chaos
    last_arrival = float(ep.arrival.max())
    horizon = ch.horizon if ch.horizon is not None else last_arrival
    events = make_chaos_timeline(
        ec.num_nodes,
        seed=seed,
        horizon=horizon,
        mtbf=ch.mtbf,
        mttr=ch.mttr,
        node_fraction=ch.node_fraction,
        max_events=ch.max_events,
    )
    # Envelope guard: device engines replay no chunks past the final
    # wave, so events beyond the last arrival can only fire on the CPU
    # engine — a configured horizon out there is almost always a
    # mis-set horizon, not a longer campaign.
    late = sum(1 for ev in events if ev.time > last_arrival)
    if late:
        log.warning(
            "chaos: %d event(s) beyond the trace's last arrival "
            "(t=%.1f; chaos.horizon=%.1f) — device engines stop at the "
            "final wave and will never apply them",
            late, last_arrival, horizon,
        )
    return events


def cmd_run(args) -> int:
    cfg = SimConfig.load(args.config)
    if args.strategy:
        cfg.strategy = args.strategy
    timeline_out = (
        getattr(args, "timeline_out", None) or cfg.telemetry.timeline_out
    )
    gran = cfg.telemetry.granularity
    if timeline_out and gran != "off":
        gran = "timeline"  # a timeline sink needs timeline events
    ec, ep = build_encoded_case(cfg)
    log.info("encoded %d nodes / %d pods", ec.num_nodes, ep.num_pods)
    factory = get_strategy(cfg.strategy)
    kw = {"telemetry": gran}
    if cfg.strategy == "jax":
        kw.update({"wave_width": cfg.wave_width, "chunk_waves": cfg.chunk_waves,
                   "preemption": cfg.device_preemption,
                   "retry_buffer": cfg.whatif.retry_buffer,
                   "node_shards": cfg.node_shards,
                   "paged": cfg.paged_waves})
        if cfg.flight_recorder is not None:
            from .sim.flight import FlightRecorderConfig

            kw["flight_recorder"] = FlightRecorderConfig(
                path=cfg.flight_recorder.path,
                every=cfg.flight_recorder.every,
            )
    engine = factory(ec, ep, cfg.framework, **kw)
    events = None
    if cfg.chaos is not None and cfg.chaos.enabled:
        events = _chaos_timeline(cfg, ec, ep, cfg.chaos.seed)
        log.info("chaos: injecting %d node events", len(events))
    # The writer owns the output file for the whole command: a failing
    # replay still closes (and flushes) whatever was written.
    out_path = dcn.output_path_for_process(cfg.output)
    with JsonlWriter(out_path, context=_writer_context(cfg, args.config)) as out:
        with device_trace(args.profile_dir):
            res = engine.replay(node_events=events) if events else engine.replay()
        out.write(replay_row(f"replay-{cfg.strategy}", res, {"config": args.config}))
    if timeline_out and res.telemetry is not None:
        from .sim.telemetry import write_chrome_trace

        n_ev = write_chrome_trace(
            timeline_out, res, arrival=ep.arrival, duration=ep.duration,
            requests=ep.requests, rindex=ec.vocab._r,
        )
        log.info("timeline: wrote %d trace events to %s", n_ev, timeline_out)
    log.info(
        "placed %d/%d pods in %.3fs (%.0f placements/sec)",
        res.placed,
        res.placed + res.unschedulable,
        res.wall_clock_s,
        res.placements_per_sec,
    )
    return 0


def cmd_whatif(args) -> int:
    from .parallel.mesh import make_mesh
    from .sim.whatif import WhatIfEngine, uniform_scenarios

    cfg = SimConfig.load(args.config)
    if cfg.whatif.scenarios <= 0:
        log.error("config has no whatIf.scenarios")
        return 2
    ec, ep = build_encoded_case(cfg)
    scen = uniform_scenarios(
        ec,
        cfg.whatif.scenarios,
        seed=cfg.whatif.seed,
        p_node_down=cfg.whatif.node_down_p,
        p_capacity=cfg.whatif.capacity_p,
        p_taint=cfg.whatif.taint_p,
    )
    if cfg.chaos is not None and cfg.chaos.enabled:
        # Failure-sweep campaign: scenario 0 stays the clean reference;
        # every other scenario gets its own seeded timeline so the batch
        # answers "which failure timeline hurts most" in one SPMD run.
        n_ev = 0
        for s in range(1, len(scen)):
            scen[s].events = _chaos_timeline(
                cfg, ec, ep, cfg.chaos.seed + s
            )
            n_ev += len(scen[s].events)
        log.info(
            "chaos: %d timed events across %d scenario timelines",
            n_ev, len(scen) - 1,
        )
    mesh = make_mesh() if cfg.whatif.mesh else None
    eng = WhatIfEngine(
        ec,
        ep,
        scen,
        cfg.framework,
        wave_width=cfg.wave_width,
        chunk_waves=cfg.chunk_waves,
        mesh=mesh,
        preemption=cfg.device_preemption,
        completions=cfg.whatif.completions,
        retry_buffer=cfg.whatif.retry_buffer,
        telemetry=cfg.telemetry.granularity,
    )
    # DCN: every process assembles the identical gathered result; each
    # writes its own sink (process 0 keeps the configured path, which is
    # the file the parity bar compares against a single-process run).
    out_path = dcn.output_path_for_process(cfg.output)
    with JsonlWriter(out_path, context=_writer_context(cfg, args.config)) as out:
        with device_trace(args.profile_dir):
            res = eng.run()
        for row in whatif_rows(res, {"config": args.config, "mesh": bool(mesh)}):
            out.write(row)
    log.info(
        "what-if: %d scenarios, %d placements in %.3fs (%.0f placements/sec aggregate)"
        + (f" across {res.process_count} processes" if res.process_count > 1 else ""),
        len(scen),
        res.total_placed,
        res.wall_clock_s,
        res.placements_per_sec,
    )
    return 0


def cmd_tune(args) -> int:
    from .parallel.mesh import make_mesh
    from .sim.tuner import PolicyTuner

    cfg = SimConfig.load(args.config)
    if cfg.tune is None:
        log.error("config has no tune: section")
        return 2
    errors = validate_config(cfg)
    if errors:
        for e in errors:
            log.error("config: %s", e)
        return 2
    tu = cfg.tune
    ec, ep = build_encoded_case(cfg)
    log.info("encoded %d nodes / %d pods", ec.num_nodes, ep.num_pods)
    mesh = make_mesh() if tu.mesh else None
    tuner = PolicyTuner(
        ec, ep, cfg.framework,
        algo=tu.algo, population=tu.population, rounds=tu.rounds,
        seed=tu.seed, elite_frac=tu.elite_frac, objective=tu.objective,
        constraints=tu.constraints, evaluator=tu.evaluator,
        train_scenarios=tu.train_scenarios,
        heldout_scenarios=tu.heldout_scenarios,
        scenario_seed=tu.scenario_seed,
        p_node_down=tu.node_down_p, p_capacity=tu.capacity_p,
        p_taint=tu.taint_p,
        weight_bounds=(
            tuple(tu.weight_bounds) if tu.weight_bounds else None
        ),
        tune_strategy=tu.tune_strategy,
        wave_width=8 if cfg.wave_width == "auto" else cfg.wave_width,
        chunk_waves=cfg.chunk_waves,
        completions=cfg.whatif.completions,
        mesh=mesh,
        cpu_oracle=tu.cpu_oracle, cpu_envelope=tu.cpu_envelope,
    )
    out_path = dcn.output_path_for_process(tu.output or cfg.output)
    with JsonlWriter(out_path, context=_writer_context(cfg, args.config)) as out:
        with device_trace(args.profile_dir):
            res = tuner.run(writer=out)
    log.info(
        "tune: %s over %d rounds x %d candidates (%d evaluations, "
        "%d compile%s) in %.3fs",
        tu.algo, res.rounds, res.population, res.evaluations,
        res.compile_count or 0, "" if res.compile_count == 1 else "s",
        res.wall_clock_s,
    )
    log.info(
        "tune: held-out objective %.6f vs default %.6f (%s); best policy %s",
        res.heldout_objective, res.default_heldout_objective,
        "improved" if res.improved() else "no improvement",
        res.best_policy,
    )
    if res.cpu_envelope is not None:
        log.info(
            "tune: CPU-oracle objective %.6f (envelope %.3g)",
            res.cpu_objective, res.cpu_envelope,
        )
    return 0


def cmd_serve(args) -> int:
    """Resident query service (round 22): read NDJSON what-if queries
    from ``service.input`` (a file or named pipe) or stdin, answer them
    through a pooled-engine :class:`~.sim.service.QueryService`, and
    stream schema-v7 ``query-result`` rows to the configured output."""
    from .sim.service import QueryService, serve_lines

    cfg = SimConfig.load(args.config)
    if args.strategy:
        cfg.strategy = args.strategy
    if cfg.service is None:
        log.error("config has no service: section")
        return 2
    errors = validate_config(cfg)
    if errors:
        for e in errors:
            log.error("config: %s", e)
        return 2
    sv = cfg.service
    ec, ep = build_encoded_case(cfg)
    log.info("encoded %d nodes / %d pods", ec.num_nodes, ep.num_pods)
    flight = None
    if cfg.flight_recorder is not None:
        from .sim.flight import FlightRecorder, FlightRecorderConfig

        flight = FlightRecorder(
            FlightRecorderConfig(
                path=cfg.flight_recorder.path,
                every=cfg.flight_recorder.every,
            ),
            meta={"mode": "serve"},
        )
    with JsonlWriter(
        cfg.output, context=_writer_context(cfg, args.config)
    ) as out:
        service = QueryService(
            ec, ep, cfg.framework,
            max_batch=sv.max_batch,
            batch_deadline_s=sv.batch_deadline_s,
            max_engines=sv.max_engines,
            granularity=sv.granularity,
            retry_buffer=sv.retry_buffer,
            writer=out,
            flight=flight,
            wave_width=8 if cfg.wave_width == "auto" else cfg.wave_width,
            chunk_waves=cfg.chunk_waves,
        )
        try:
            with device_trace(args.profile_dir):
                if sv.input is not None:
                    # A named pipe blocks here until a producer connects —
                    # that is the serving contract, not a hang.
                    with open(sv.input) as f:
                        stats = serve_lines(service, f, out)
                else:
                    stats = serve_lines(service, sys.stdin, out)
        finally:
            if flight is not None:
                flight.close()
    log.info(
        "serve: %d queries in %d batches (%d cold build%s, %d warm, "
        "%d error%s)",
        stats["queries"], stats["batches"],
        stats["cold_builds"], "" if stats["cold_builds"] == 1 else "s",
        stats["warm_hits"],
        stats["errors"], "" if stats["errors"] == 1 else "s",
    )
    return 0


def _recovery_errors(cfg) -> list:
    """Actionable refusals for the ``dcn.recovery`` section (round 15).
    Shared by validate_config and the pre-dispatch env export in main():
    enabling survivor recovery outside a DCN fleet, or with the liveness
    heartbeats its failure detector rides on disabled, must fail with a
    message naming the fix — not silently no-op."""
    rec = getattr(cfg, "dcn_recovery", None)
    if rec is None:
        return []
    errors = []
    if rec.checkpoint_every < 0:
        errors.append(
            "dcn.recovery.checkpointEvery: must be >= 0 (0 disables "
            "checkpoint publication; a claimed block then re-executes "
            "from chunk 0)"
        )
    if rec.max_claims < 1:
        errors.append(
            "dcn.recovery.maxClaims: must be >= 1 (each dead block "
            "needs at least one claim generation)"
        )
    if not rec.enable:
        return errors
    if int(os.environ.get("KSIM_DCN_NPROC", "1") or 1) <= 1:
        errors.append(
            "dcn.recovery.enable: survivor recovery needs a multi-process "
            "DCN fleet — launch through scripts/dcn_launch.py (--elastic N "
            "adds spare claimants); KSIM_DCN_NPROC is unset/1, so there is "
            "no sibling to claim a dead block"
        )
    if dcn.heartbeat_every() == 0:
        errors.append(
            "dcn.recovery.enable: recovery needs liveness heartbeats — "
            "remove KSIM_DCN_HEARTBEAT_EVERY=0 (stale beacons are the "
            "failure detector that opens claims)"
        )
    return errors


def _workqueue_errors(cfg) -> list:
    """Actionable refusals for the ``dcn.workQueue`` section (round 18).
    Shared by validate_config and the pre-dispatch env export in main():
    the queue outside a DCN fleet, speculation without the checkpoints
    it resumes from, or a nonsensical block size must fail with a
    message naming the fix — not silently no-op."""
    wq = getattr(cfg, "dcn_workqueue", None)
    if wq is None:
        return []
    errors = []
    if wq.block_size < 0:
        errors.append(
            "dcn.workQueue.blockSize: must be >= 0 scenarios per block "
            "(0 = auto: one block per worker, reproducing the static "
            "partition when nobody steals)"
        )
    if wq.straggler_s < 0:
        errors.append(
            "dcn.workQueue.stragglerS: must be >= 0 seconds (0 = auto: "
            "half the KSIM_DCN_STALL_S lease-expiry window)"
        )
    if not wq.enable:
        if wq.speculate or wq.block_size or wq.straggler_s:
            log.warning(
                "dcn.workQueue: speculate/blockSize/stragglerS set but "
                "enable is false — the work queue stays off"
            )
        return errors
    if int(os.environ.get("KSIM_DCN_NPROC", "1") or 1) <= 1:
        errors.append(
            "dcn.workQueue.enable: the work-stealing queue needs a "
            "multi-process DCN fleet — launch through "
            "scripts/dcn_launch.py; KSIM_DCN_NPROC is unset/1, so there "
            "is nobody to lease blocks from the queue"
        )
    if dcn.heartbeat_every() == 0:
        errors.append(
            "dcn.workQueue.enable: the queue needs liveness heartbeats — "
            "remove KSIM_DCN_HEARTBEAT_EVERY=0 (lease renewals ride the "
            "heartbeat cadence; without them every lease looks expired)"
        )
    if wq.speculate:
        rec = getattr(cfg, "dcn_recovery", None)
        if rec is None or rec.checkpoint_every < 1:
            errors.append(
                "dcn.workQueue.speculate: speculative re-execution "
                "resumes from the straggler's newest published "
                "checkpoint — set dcn.recovery.checkpointEvery >= 1 "
                "(without checkpoints a backup re-executes the whole "
                "block and rarely beats the straggler)"
            )
    return errors


def _faultline_errors(cfg) -> list:
    """Actionable refusals for the ``faultline:`` section (round 17).
    Shared by validate_config and the pre-dispatch env export in main().
    Negative rates/seeds and malformed kill schedules are refused;
    injection with recovery disabled is LEGAL but warned — every injected
    kill or retry give-up then fails the fleet attributed instead of
    recovering, which is occasionally what a drill wants."""
    fl = getattr(cfg, "faultline", None)
    if fl is None:
        return []
    errors = []
    if fl.seed < 0:
        errors.append(
            "faultline.seed: must be >= 0 (the seed derives every "
            "per-class injection stream)"
        )
    for attr, yaml_key in (
        ("kv_error_rate", "kvErrorRate"),
        ("kv_delay_rate", "kvDelayRate"),
        ("torn_write_rate", "tornWriteRate"),
        ("stale_read_rate", "staleReadRate"),
    ):
        rate = getattr(fl, attr)
        if not (0.0 <= rate <= 1.0):
            errors.append(
                f"faultline.{yaml_key}: must be in [0, 1], got {rate!r} "
                "(a per-operation injection probability)"
            )
    if fl.kv_delay_s < 0:
        errors.append("faultline.kvDelayS: must be >= 0 seconds")
    if fl.kill:
        from .parallel import faultline as _faultline

        try:
            _faultline.parse_kill_schedule(str(fl.kill))
        except ValueError as e:
            errors.append(f"faultline.kill: {e}")
    if getattr(fl, "slow", None):
        from .parallel import faultline as _faultline

        try:
            _faultline.parse_slow_schedule(str(fl.slow))
        except ValueError as e:
            errors.append(f"faultline.slow: {e}")
    if not fl.enabled:
        return errors
    rec = getattr(cfg, "dcn_recovery", None)
    if rec is None or not rec.enable:
        log.warning(
            "faultline: injection enabled with dcn.recovery disabled — "
            "injected kills and retry give-ups will fail the fleet with "
            "an attributed error instead of recovering (set "
            "dcn.recovery.enable to drill the recovery path)"
        )
    return errors


def _overlap_errors(cfg) -> list:
    """Actionable refusals for the ``overlap:`` section (round 19).
    Shared by validate_config and the pre-dispatch env export in main().
    A gate explicitly enabled on a config that lacks the machinery it
    overlaps is refused — silently accepting it would report perfect
    hidden wall for work that never existed."""
    ov = getattr(cfg, "overlap", None)
    if ov is None:
        return []
    errors = []
    if ov.pager_thread and not getattr(cfg, "paged_waves", False):
        errors.append(
            "overlap.pagerThread: true requires pagedWaves: true — "
            "without paged pod waves there is no pager (and no page "
            "fetch) to move off the chunk-loop thread"
        )
    if ov.background_publisher:
        rec = getattr(cfg, "dcn_recovery", None)
        wq = getattr(cfg, "dcn_workqueue", None)
        has_ckpt = (
            rec is not None and rec.enable and rec.checkpoint_every >= 1
        ) or (wq is not None and wq.enable)
        if not has_ckpt:
            errors.append(
                "overlap.backgroundPublisher: true requires a checkpoint "
                "cadence — enable dcn.recovery with checkpointEvery >= 1 "
                "(or dcn.workQueue) so there are publications to move "
                "off the loop thread"
            )
    return errors


def _durable_errors(cfg) -> list:
    """Actionable refusals for the ``dcn.durable`` section (round 20).
    Shared by validate_config and the pre-dispatch env export in main().
    A durability journal outside a DCN fleet, or on a config with no
    checkpoint cadence at all, is refused — the journal would sit empty
    while claiming crash-restart coverage; an unwritable journal
    directory is refused up front rather than discovered at the first
    mirrored publication."""
    du = getattr(cfg, "dcn_durable", None)
    if du is None:
        return []
    errors = []
    if not du.dir:
        if du.resume:
            errors.append(
                "dcn.durable.resume: true requires dcn.durable.dir — "
                "there is no journal to seed the fleet from"
            )
        return errors
    if int(os.environ.get("KSIM_DCN_NPROC", "1") or 1) <= 1:
        errors.append(
            "dcn.durable.dir: the durability journal mirrors a DCN "
            "fleet's checkpoint/queue publications — launch through "
            "scripts/dcn_launch.py (ideally --supervise); "
            "KSIM_DCN_NPROC is unset/1, so there is no fleet state to "
            "make durable"
        )
    rec = getattr(cfg, "dcn_recovery", None)
    wq = getattr(cfg, "dcn_workqueue", None)
    has_ckpt = (
        rec is not None and rec.enable and rec.checkpoint_every >= 1
    ) or (wq is not None and wq.enable)
    if not has_ckpt:
        errors.append(
            "dcn.durable.dir: the journal rides checkpoint/queue "
            "publication — enable dcn.recovery with checkpointEvery >= 1 "
            "(or dcn.workQueue) so there is something durable to mirror"
        )
    try:
        os.makedirs(du.dir, exist_ok=True)
        probe = os.path.join(du.dir, f".ksim_probe.{os.getpid()}")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as e:
        errors.append(
            f"dcn.durable.dir: {du.dir!r} is not writable ({e}) — the "
            "journal must outlive the fleet, so it is created eagerly"
        )
    return errors


def _service_errors(cfg) -> list:
    """Actionable refusals for the ``service:`` section (round 22). The
    resident query service swaps scenario values against ONE compiled
    executable per pool engine, so every envelope the defrag family
    rides on — the kube boundary mirror, single-process planes — must
    hold before the first query is admitted, not fail mid-batch."""
    sv = getattr(cfg, "service", None)
    if sv is None:
        return []
    errors = []
    if cfg.strategy != "jax":
        errors.append(
            "service: requires strategy: jax (the resident engine pool "
            "is the compiled what-if plane)"
        )
    if cfg.device_preemption != "kube":
        errors.append(
            "service: defrag queries drain nodes through chaos eviction, "
            "which needs devicePreemption: kube (the boundary host "
            "mirror applies per-scenario timelines)"
        )
    if not cfg.whatif.retry_buffer:
        errors.append(
            "service: requires whatIf.retryBuffer > 0 — without the "
            "boundary retry pass a drained node's pods are never "
            "rescheduled, so every defrag answer degenerates"
        )
    if cfg.node_shards > 1:
        errors.append(
            "service: nodeShards > 1 is not supported — the query batch "
            "spends the device on the scenario axis, and set_scenarios "
            "refuses sliced engines"
        )
    if cfg.whatif.mesh:
        errors.append(
            "service: whatIf.mesh is not supported (resident engines "
            "are single-process; set_scenarios refuses meshed engines)"
        )
    if sv.max_batch < 1:
        errors.append("service.maxBatch: must be >= 1")
    if sv.batch_deadline_s <= 0:
        errors.append(
            "service.batchDeadlineS: must be > 0 (the admission queue "
            "needs a flush deadline; use maxBatch: 1 for per-query "
            "dispatch)"
        )
    if sv.max_engines < 1:
        errors.append("service.maxEngines: must be >= 1")
    if sv.retry_buffer < 1:
        errors.append("service.retryBuffer: must be >= 1")
    from .sim.telemetry import _LEVELS as _tel_levels

    if sv.granularity not in _tel_levels:
        errors.append(
            f"service.granularity: must be one of "
            f"{', '.join(_tel_levels)}, got {sv.granularity!r}"
        )
    if sv.input is not None and not os.path.exists(sv.input):
        errors.append(f"service.input: file not found: {sv.input}")
    return errors


def validate_config(cfg) -> list:
    """Structural checks → list of actionable error strings (empty = ok)."""
    from .framework.registry import available_strategies
    from .plugins.builtin import PLUGIN_FACTORIES

    errors = []
    # Built-ins register lazily — make them visible before consulting the
    # registry (the L6 contract: validate agrees with get_strategy).
    from .sim import runtime as _rt  # noqa: F401
    try:
        from .sim import jax_runtime as _jrt  # noqa: F401
    except Exception:
        pass
    known_strategies = available_strategies()
    if cfg.strategy not in known_strategies:
        errors.append(
            f"strategy: unknown '{cfg.strategy}' "
            f"(registered: {', '.join(known_strategies)})"
        )
    ww = 8 if cfg.wave_width == "auto" else cfg.wave_width
    for e in cfg.framework.plugins or []:
        if not isinstance(e, dict) or "name" not in e:
            errors.append(
                f"profile.plugins: entry must be a mapping with name:, got {e!r}"
            )
            continue
        name = e.get("name")
        if name not in PLUGIN_FACTORIES:
            errors.append(
                f"profile.plugins: unknown plugin '{name}' "
                f"(known: {', '.join(sorted(PLUGIN_FACTORIES))})"
            )
    known = set(PLUGIN_FACTORIES)
    for name, w in (cfg.framework.weights or {}).items():
        if name not in known:
            errors.append(f"profile.weights: unknown plugin '{name}'")
        elif not isinstance(w, (int, float)) or w < 0:
            errors.append(f"profile.weights.{name}: must be a number >= 0")
    if cfg.borg is not None:
        if cfg.borg.nodes <= 0:
            errors.append("workload.borg.nodes: must be > 0")
        if cfg.borg.tasks <= 0:
            errors.append("workload.borg.tasks: must be > 0")
        if cfg.borg.max_gang > ww:
            errors.append(
                f"workload.borg.maxGang ({cfg.borg.max_gang}) exceeds "
                f"waveWidth ({ww}): a gang must fit in one wave"
            )
        for p_attr, key in (
            ("trace_path", "tracePath"),
            ("instance_events", "instanceEvents"),
            ("collection_events", "collectionEvents"),
        ):
            p = getattr(cfg.borg, p_attr, None)
            if p and not os.path.exists(p):
                errors.append(f"workload.borg.{key}: file not found: {p}")
        if cfg.borg.cpu_scale <= 0 or cfg.borg.mem_scale <= 0:
            errors.append("workload.borg.cpuScale/memScale: must be > 0")
    else:
        if cfg.cluster.nodes <= 0:
            errors.append("cluster.nodes: must be > 0")
        wl = cfg.workload
        if wl is not None:
            if wl.pods <= 0:
                errors.append("workload.pods: must be > 0")
            if wl.gang_fraction and wl.gang_size > ww:
                errors.append(
                    f"workload.gangSize ({wl.gang_size}) exceeds waveWidth "
                    f"({ww}): a gang must fit in one wave"
                )
    if cfg.whatif.scenarios < 0:
        errors.append("whatIf.scenarios: must be >= 0")
    if cfg.whatif.retry_buffer < 0:
        errors.append("whatIf.retryBuffer: must be >= 0")
    if cfg.device_preemption not in (True, False, "tier", "kube"):
        errors.append(
            f"devicePreemption: must be true/false/'tier'/'kube', got "
            f"{cfg.device_preemption!r}"
        )
    tier_on = cfg.device_preemption in (True, "tier")
    if cfg.whatif.retry_buffer and tier_on:
        errors.append(
            "whatIf.retryBuffer is not supported with tier devicePreemption"
        )
    if cfg.device_preemption == "kube" and not cfg.whatif.retry_buffer:
        errors.append(
            "devicePreemption: kube requires whatIf.retryBuffer > 0 "
            "(failed pods reach the PostFilter through the boundary "
            "retry pass)"
        )
    if cfg.device_preemption == "kube" and cfg.whatif.mesh:
        errors.append(
            "devicePreemption: kube requires a no-mesh what-if batch "
            "(the eager per-chunk folds would serialize the scenario "
            "axis); tier preemption runs under a mesh"
        )
    if cfg.whatif.retry_buffer and cfg.whatif.completions is False:
        errors.append(
            "whatIf.retryBuffer requires the device-release path; remove "
            "whatIf.completions: false (the retry pass runs at completion "
            "boundaries)"
        )
    if cfg.node_shards < 0:
        errors.append("nodeShards: must be >= 0 (0/1 = replicated planes)")
    if cfg.node_shards > 1:
        if cfg.strategy != "jax":
            errors.append(
                "nodeShards: intra-scenario node-plane sharding is a "
                "strategy: jax feature (the what-if batch spends the mesh "
                "on the scenario axis)"
            )
        if tier_on:
            errors.append(
                "nodeShards is not supported with tier devicePreemption "
                "(the sharded chunk program is the node-space engine; use "
                "devicePreemption: kube)"
            )
    if cfg.paged_waves:
        if cfg.strategy != "jax":
            errors.append("pagedWaves: requires strategy: jax")
        if cfg.whatif.retry_buffer or cfg.device_preemption == "kube":
            errors.append(
                "pagedWaves is not supported with whatIf.retryBuffer / "
                "devicePreemption: kube yet (the boundary mirror "
                "pre-stages the whole wave index tensor)"
            )
    ch = cfg.chaos
    if ch is not None and ch.enabled:
        if ch.mtbf <= 0:
            errors.append("chaos.mtbf: must be > 0")
        if ch.mttr < 0:
            errors.append("chaos.mttr: must be >= 0")
        if not 0.0 < ch.node_fraction <= 1.0:
            errors.append("chaos.nodeFraction: must be in (0, 1]")
        if ch.horizon is not None and ch.horizon <= 0:
            errors.append("chaos.horizon: must be > 0 (or omitted)")
        if ch.max_events is not None and ch.max_events < 0:
            errors.append("chaos.maxEvents: must be >= 0")
        if cfg.strategy == "jax" and not cfg.whatif.retry_buffer:
            errors.append(
                "chaos with strategy: jax requires whatIf.retryBuffer > 0 "
                "— without the boundary retry pass node_down only blocks "
                "future placements (no NoExecute eviction of bound pods)"
            )
        if cfg.whatif.scenarios > 0 and cfg.device_preemption != "kube":
            errors.append(
                "chaos what-if sweeps require devicePreemption: kube "
                "(per-scenario timelines apply through the kube-mode "
                "host mirrors at chunk boundaries)"
            )
    tu = cfg.tune
    if tu is not None:
        from .sim.tuner import (
            _ALWAYS_METRICS, _RESULT_METRICS, normalize_constraints,
        )

        if tu.algo not in ("cem", "random"):
            errors.append(
                f"tune.algo: must be 'cem' or 'random', got {tu.algo!r}"
            )
        if tu.population < 2:
            errors.append("tune.population: must be >= 2")
        if tu.rounds < 1:
            errors.append("tune.rounds: must be >= 1")
        if not 0.0 < tu.elite_frac <= 1.0:
            errors.append("tune.eliteFrac: must be in (0, 1]")
        if tu.train_scenarios < 1 or tu.heldout_scenarios < 1:
            errors.append(
                "tune.scenarios: train and heldout must both be >= 1 "
                "(the acceptance check runs on the held-out split)"
            )
        if tu.evaluator not in ("auto", "device", "cpu"):
            errors.append(
                f"tune.evaluator: must be 'auto', 'device' or 'cpu', "
                f"got {tu.evaluator!r}"
            )
        try:
            cons = normalize_constraints(tu.constraints)
        except ValueError as e:
            errors.append(f"tune.constraints: {e}")
            cons = []
        terms = list(tu.objective or {}) + [c["metric"] for c in cons]
        for term in terms:
            if term not in _RESULT_METRICS:
                errors.append(
                    f"tune.objective: unknown term '{term}' "
                    f"(known: {', '.join(sorted(_RESULT_METRICS))})"
                )
            elif term not in _ALWAYS_METRICS and tu.evaluator == "device":
                # auto/cpu route such terms to the CPU event engine
                # (round 13); only an EXPLICIT device evaluator is stuck
                # with the batched-sweep metric set.
                errors.append(
                    f"tune.objective: term '{term}' rides the kube host "
                    "mirrors, which the batched policy sweep does not "
                    "support — drop 'evaluator: device' or use terms "
                    f"from {', '.join(sorted(_ALWAYS_METRICS))}"
                )
        wb = tu.weight_bounds
        if wb is not None and (len(wb) != 2 or wb[0] >= wb[1]):
            errors.append(
                "tune.weightBounds: must be [lo, hi] with lo < hi"
            )
        if tu.cpu_envelope < 0:
            errors.append("tune.cpuEnvelope: must be >= 0")
    from .sim.telemetry import _LEVELS as _TEL_LEVELS

    if cfg.telemetry.granularity not in _TEL_LEVELS:
        errors.append(
            f"telemetry.granularity: must be one of "
            f"{', '.join(_TEL_LEVELS)}, got {cfg.telemetry.granularity!r}"
        )
    if cfg.telemetry.timeline_out:
        d = os.path.dirname(cfg.telemetry.timeline_out) or "."
        if not os.path.isdir(d):
            errors.append(
                f"telemetry.timelineOut: directory not found: {d}"
            )
    if cfg.chunk_waves <= 0:
        errors.append("chunkWaves: must be > 0")
    if cfg.wave_width != "auto" and cfg.wave_width <= 0:
        errors.append("waveWidth: must be > 0 (or 'auto')")
    if cfg.device_preemption and cfg.strategy == "cpu":
        errors.append(
            "devicePreemption requires strategy: jax (the cpu engine runs "
            "kube PostFilter preemption instead)"
        )
    if cfg.flight_recorder is not None:
        fr = cfg.flight_recorder
        if cfg.strategy != "jax":
            errors.append(
                "flightRecorder requires strategy: jax (the cpu engine "
                "has no chunk loop to record)"
            )
        d = os.path.dirname(fr.path) or "."
        if not os.path.isdir(d):
            errors.append(
                f"flightRecorder.path: directory not found: {d}"
            )
        elif not os.access(d, os.W_OK):
            errors.append(
                f"flightRecorder.path: directory not writable: {d}"
            )
        if fr.every <= 0:
            errors.append("flightRecorder.every: must be > 0")
        if cfg.borg is not None and cfg.node_shards <= 1:
            errors.append(
                "flightRecorder on a borg headline workload without "
                "nodeShards: the replicated planes bust one device at "
                "Borg scale — set nodeShards > 1 (and usually "
                "pagedWaves: true)"
            )
    errors.extend(_recovery_errors(cfg))
    errors.extend(_workqueue_errors(cfg))
    errors.extend(_faultline_errors(cfg))
    errors.extend(_overlap_errors(cfg))
    errors.extend(_durable_errors(cfg))
    errors.extend(_service_errors(cfg))
    return errors


def cmd_validate(args) -> int:
    try:
        cfg = SimConfig.load(args.config)
    except ValueError as e:
        # Parse-time schema errors (e.g. non-bool whatIf.completions)
        # still come out as the JSON error report, not a traceback.
        print(json.dumps({"errors": [str(e)]}, indent=2))
        return 1
    errors = validate_config(cfg)
    nodes = cfg.borg.nodes if cfg.borg else cfg.cluster.nodes
    tasks = (
        cfg.borg.tasks if cfg.borg
        else (cfg.workload.pods if cfg.workload else 1000)
    )
    print(json.dumps({"strategy": cfg.strategy, "nodes": nodes, "tasks": tasks,
                      "workload": "borg" if cfg.borg else "synthetic",
                      "devicePreemption": cfg.device_preemption,
                      "whatif_scenarios": cfg.whatif.scenarios,
                      "errors": errors}, indent=2))
    return 1 if errors else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_simulator_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("run", cmd_run), ("what-if", cmd_whatif),
                     ("tune", cmd_tune), ("serve", cmd_serve),
                     ("validate", cmd_validate)):
        p = sub.add_parser(name)
        p.add_argument("config")
        p.add_argument("--strategy", choices=["cpu", "jax"])
        p.add_argument("--profile-dir", default=None, help="jax.profiler trace output dir")
        if name == "run":
            p.add_argument(
                "--timeline-out", default=None,
                help="write the simulated cluster timeline as a Chrome "
                     "trace JSON (Perfetto-loadable); implies telemetry "
                     "granularity 'timeline'",
            )
        p.set_defaults(fn=fn)
    args = ap.parse_args(argv)
    # Config-driven recovery knobs (round 15, dcn.recovery:) must land in
    # the env BEFORE jax.distributed bring-up — the coordination-service
    # failure-detector widening reads KSIM_DCN_RECOVER at initialize.
    # setdefault: an operator's explicit env always wins over the YAML.
    if args.cmd != "validate":
        try:
            cfg_pre = SimConfig.load(args.config)
        except Exception:
            cfg_pre = None  # the command fn reports config errors itself
        rec = cfg_pre.dcn_recovery if cfg_pre is not None else None
        if rec is not None and rec.enable:
            errors = _recovery_errors(cfg_pre)
            if errors:
                for e in errors:
                    log.error("config: %s", e)
                return 2
            os.environ.setdefault("KSIM_DCN_RECOVER", "1")
            if rec.checkpoint_every:
                os.environ.setdefault(
                    "KSIM_DCN_CKPT_EVERY", str(rec.checkpoint_every)
                )
            os.environ.setdefault(
                "KSIM_DCN_MAX_CLAIMS", str(rec.max_claims)
            )
        # Work-queue knobs (round 18, dcn.workQueue:) must also land
        # before bring-up — mesh.init_distributed widens the runtime
        # failure detector when the queue is on (a straggler must not be
        # declared dead while a backup races it).
        wq = (
            getattr(cfg_pre, "dcn_workqueue", None)
            if cfg_pre is not None
            else None
        )
        if wq is not None and wq.enable:
            errors = _workqueue_errors(cfg_pre)
            if errors:
                for e in errors:
                    log.error("config: %s", e)
                return 2
            os.environ.setdefault("KSIM_DCN_WORKQUEUE", "1")
            if wq.block_size:
                os.environ.setdefault(
                    "KSIM_DCN_WQ_BLOCK", str(wq.block_size)
                )
            if wq.speculate:
                os.environ.setdefault("KSIM_DCN_SPECULATE", "1")
            if wq.straggler_s:
                os.environ.setdefault(
                    "KSIM_DCN_STRAGGLER_S", str(wq.straggler_s)
                )
        # Faultline injection knobs (round 17, faultline:) ride the same
        # pre-dispatch export — the KV-client wrapper reads KSIM_FAULTLINE_*
        # lazily, but a consistent fleet wants them pinned before any
        # worker touches the coordination plane.
        fl = cfg_pre.faultline if cfg_pre is not None else None
        if fl is not None and fl.enabled:
            errors = _faultline_errors(cfg_pre)
            if errors:
                for e in errors:
                    log.error("config: %s", e)
                return 2
            os.environ.setdefault("KSIM_FAULTLINE", "1")
            os.environ.setdefault("KSIM_FAULTLINE_SEED", str(fl.seed))
            for val, env in (
                (fl.kv_error_rate, "KSIM_FAULTLINE_KV_ERROR_RATE"),
                (fl.kv_delay_rate, "KSIM_FAULTLINE_KV_DELAY_RATE"),
                (fl.kv_delay_s, "KSIM_FAULTLINE_KV_DELAY_S"),
                (fl.torn_write_rate, "KSIM_FAULTLINE_TORN_RATE"),
                (fl.stale_read_rate, "KSIM_FAULTLINE_STALE_RATE"),
            ):
                if val:
                    os.environ.setdefault(env, str(val))
            if fl.kill:
                os.environ.setdefault("KSIM_FAULTLINE_KILL", str(fl.kill))
            if getattr(fl, "slow", None):
                os.environ.setdefault("KSIM_FAULTLINE_SLOW", str(fl.slow))
        # Overlap gates (round 19, overlap:) ride the same pre-dispatch
        # export. Engines default every gate ON, so only explicit values
        # are exported — a None field stays the engine default, and an
        # operator's explicit env still wins (setdefault).
        ov = getattr(cfg_pre, "overlap", None) if cfg_pre is not None else None
        if ov is not None:
            errors = _overlap_errors(cfg_pre)
            if errors:
                for e in errors:
                    log.error("config: %s", e)
                return 2
            for val, env in (
                (ov.pager_thread, "KSIM_PAGER_THREAD"),
                (ov.background_publisher, "KSIM_DCN_CKPT_ASYNC"),
                (ov.two_phase_exchange, "KSIM_TWO_PHASE_EXCHANGE"),
            ):
                if val is not None:
                    os.environ.setdefault(env, "1" if val else "0")
        # Durable-ground knobs (round 20, dcn.durable:) ride the same
        # pre-dispatch export — resume seeding happens during the first
        # replay's bring-up, so the journal path must be pinned before
        # any engine touches the coordination plane.
        du = (
            getattr(cfg_pre, "dcn_durable", None)
            if cfg_pre is not None
            else None
        )
        if du is not None and (du.dir or du.resume):
            errors = _durable_errors(cfg_pre)
            if errors:
                for e in errors:
                    log.error("config: %s", e)
                return 2
            os.environ.setdefault("KSIM_DCN_DURABLE_DIR", str(du.dir))
            if du.resume:
                os.environ.setdefault("KSIM_DCN_RESUME", "1")
    # Multi-host DCN bring-up (round 11): a no-op without the
    # KSIM_DCN_* env set by scripts/dcn_launch.py. Enables the compile
    # cache BEFORE jax.distributed.initialize (documented ordering).
    if dcn.maybe_init_from_env():
        nproc, pid = dcn.process_info()
        log.info("DCN: process %d/%d up", pid, nproc)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
