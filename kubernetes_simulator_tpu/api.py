"""High-level facade — the one-stop API a reference user reaches for.

    sim = Simulator(cluster, pods, strategy="jax")
    result = sim.run()
    whatif = sim.what_if(scenarios=256, mesh=True)
    tuned = sim.tune(rounds=6, population=16)

    svc = SimulatorService(cluster, pods)            # round 22
    svc.submit({"op": "defrag", "tenant": "a", "id": "q1",
                "nodes": [3, 4], "drainAt": 5.0, "recoverAt": 12.0})
    rows = svc.poll("a")
    svc.close()
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .framework.framework import FrameworkConfig
from .framework.registry import available_strategies, get_strategy
from .models.core import Cluster, Pod
from .models.encode import encode


class Simulator:
    def __init__(
        self,
        cluster: Cluster,
        pods: Sequence[Pod],
        strategy: str = "cpu",
        plugins: Optional[List[dict]] = None,
        weights: Optional[dict] = None,
        enable_preemption: bool = True,
        **engine_kw,
    ):
        self.cluster = cluster
        self.pods = list(pods)
        self.strategy = strategy
        self.config = FrameworkConfig(
            plugins=plugins, weights=weights, enable_preemption=enable_preemption
        )
        self.engine_kw = engine_kw
        from .plugins.builtin import inject_default_spread, resolved_default_constraints

        if resolved_default_constraints(self.config):
            # Shallow-copy each pod with a fresh topology_spread list (the
            # only field the injector appends to) so the caller's Pod
            # objects are never mutated — a second Simulator built from
            # the same pods must not inherit these constraints.
            import dataclasses

            self.pods = [
                dataclasses.replace(p, topology_spread=list(p.topology_spread))
                for p in self.pods
            ]
            inject_default_spread(self.pods, self.config)
        self.ec, self.ep = encode(cluster, self.pods)

    def run(self, timeline_out: Optional[str] = None, **replay_kw):
        """One replay with the configured strategy. ``timeline_out`` writes
        the simulated cluster timeline as a Chrome trace JSON (Perfetto-
        loadable) — it forces ``telemetry='timeline'`` on the engine
        unless the caller already picked a granularity."""
        engine_kw = dict(self.engine_kw)
        if timeline_out and "telemetry" not in engine_kw:
            engine_kw["telemetry"] = "timeline"
        engine = get_strategy(self.strategy)(self.ec, self.ep, self.config, **engine_kw)
        res = engine.replay(**replay_kw)
        if timeline_out and getattr(res, "telemetry", None) is not None:
            from .sim.telemetry import write_chrome_trace

            write_chrome_trace(
                timeline_out, res,
                arrival=self.ep.arrival, duration=self.ep.duration,
            )
        return res

    def what_if(
        self,
        scenarios=None,
        num_scenarios: int = 0,
        seed: int = 0,
        mesh: bool = False,
        collect_assignments: bool = False,
        fork_checkpoint: Optional[str] = None,
        **kw,
    ):
        """Batched what-if over cluster-state perturbations. Pass explicit
        ``scenarios`` (list of sim.whatif.Scenario) or ``num_scenarios``
        for the uniform random sampler.

        Round 22: repeated same-shape calls reuse ONE resident engine —
        the scenario stacks swap as traced values against the compiled
        executable (:meth:`WhatIfEngine.set_scenarios`), closing the
        compile-per-query hole (compile count stays 1 for N queries,
        pinned in tests/test_service.py). A batch the resident engine
        refuses (shape/envelope drift) transparently rebuilds."""
        from .parallel.mesh import make_mesh
        from .sim.whatif import WhatIfEngine, uniform_scenarios

        if scenarios is None:
            scenarios = uniform_scenarios(self.ec, num_scenarios, seed=seed)
        scenarios = list(scenarios)
        key = (
            len(scenarios), bool(mesh), bool(collect_assignments),
            fork_checkpoint, repr(sorted(kw.items())),
        )
        cached = getattr(self, "_whatif_cache", None)
        if cached is not None and cached[0] == key:
            try:
                cached[1].set_scenarios(scenarios)
                return cached[1].run()
            except ValueError:
                self._whatif_cache = None
        eng = WhatIfEngine(
            self.ec,
            self.ep,
            scenarios,
            self.config,
            mesh=make_mesh() if mesh else None,
            collect_assignments=collect_assignments,
            fork_checkpoint=fork_checkpoint,
            **kw,
        )
        self._whatif_cache = (key, eng)
        return eng.run()

    def tune(
        self,
        algo: str = "cem",
        population: int = 16,
        rounds: int = 6,
        seed: int = 0,
        objective: Optional[dict] = None,
        mesh: bool = False,
        output: Optional[str] = None,
        **kw,
    ):
        """Policy tuning (round 9): seeded search over this simulator's
        Score-plugin policy surface — weights plus the NodeResourcesFit
        strategy — evaluating each round's whole candidate population in
        ONE batched what-if sweep (the policy vector is a traced
        per-scenario input, so only values change between rounds).
        Returns a :class:`~.sim.tuner.TuneResult`; ``output`` streams the
        search trajectory as schema-v3 JSONL. Extra ``kw`` forwards to
        :class:`~.sim.tuner.PolicyTuner` (scenario split sizes, bounds,
        CPU-oracle knobs, ...)."""
        from .parallel.mesh import make_mesh
        from .sim.tuner import PolicyTuner
        from .utils.metrics import JsonlWriter

        tuner = PolicyTuner(
            self.ec, self.ep, self.config,
            algo=algo, population=population, rounds=rounds, seed=seed,
            objective=objective, mesh=make_mesh() if mesh else None, **kw,
        )
        if output is None:
            return tuner.run()
        with JsonlWriter(output) as out:
            return tuner.run(writer=out)

    def chaos_timeline(
        self,
        seed: int = 0,
        mtbf: float = 200.0,
        mttr: float = 20.0,
        node_fraction: float = 0.2,
        horizon: Optional[float] = None,
        max_events: Optional[int] = None,
    ):
        """Seeded MTBF/MTTR failure/recovery timeline for this cluster —
        pass it as ``run(node_events=...)`` or per-scenario via
        ``sim.whatif.Scenario(events=...)``. Horizon defaults to the
        workload makespan."""
        from .sim.synthetic import make_chaos_timeline

        if horizon is None:
            horizon = float(self.ep.arrival.max())
        return make_chaos_timeline(
            self.ec.num_nodes, seed=seed, horizon=horizon, mtbf=mtbf,
            mttr=mttr, node_fraction=node_fraction, max_events=max_events,
        )

    @staticmethod
    def strategies() -> List[str]:
        # Force-register the builtins, then report.
        for name in ("cpu", "jax"):
            try:
                get_strategy(name)
            except Exception:
                pass
        return available_strategies()


class SimulatorService:
    """Resident what-if query service (round 22) — the facade over
    :class:`~.sim.service.QueryService`. Encodes the cluster/trace once
    and keeps compiled engines hot between queries: submit what-if
    queries from many tenants, poll per-tenant results, apply
    bind/release/evict deltas to the live base state, close when done.

        svc = SimulatorService(cluster, pods, max_batch=3)
        svc.submit({"op": "defrag", "tenant": "a", "id": "q1",
                    "nodes": [3], "drainAt": 5.0})
        rows = svc.poll("a")          # [] until batch-full or deadline
        rows = svc.flush() and svc.poll("a")   # force the batch now
        svc.close()

    Every engine/service knob (``max_batch``, ``batch_deadline_s``,
    ``max_engines``, ``granularity``, ``retry_buffer``, ``wave_width``,
    ``chunk_waves``, ``writer``, ``flight``) forwards to
    :class:`QueryService`."""

    def __init__(
        self,
        cluster: Cluster,
        pods: Sequence[Pod],
        plugins: Optional[List[dict]] = None,
        weights: Optional[dict] = None,
        **service_kw,
    ):
        from .sim.service import QueryService

        config = FrameworkConfig(plugins=plugins, weights=weights)
        ec, ep = encode(cluster, list(pods))
        self._svc = QueryService(ec, ep, config, **service_kw)

    def submit(self, query: dict):
        """Admit one query dict; returns ``(tenant, id)``."""
        return self._svc.submit(query)

    def poll(self, tenant: Optional[str] = None) -> List[dict]:
        """Drain finished results (one tenant, or all)."""
        return self._svc.poll(tenant)

    def flush(self) -> int:
        """Answer every pending query now (ignore the deadline)."""
        return self._svc.flush()

    def stats(self) -> dict:
        return self._svc.stats()

    def apply_bind(self, bind_id: str, node, requests) -> None:
        self._svc.apply_bind(bind_id, node, requests)

    def apply_release(self, bind_id: str) -> None:
        self._svc.apply_release(bind_id)

    def apply_evict(self, node) -> List[str]:
        return self._svc.apply_evict(node)

    def close(self) -> List[dict]:
        """Flush, drop the engine pool, return undelivered results."""
        return self._svc.close()

    def __enter__(self) -> "SimulatorService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
