"""Faultline (round 17): seeded, deterministic fault injection for the
DCN fleet plane.

The round-15 recovery machinery (parallel.dcn heartbeats, claims,
checkpoints, survivor rebalance) had only ever been exercised by one
clean SIGKILL.  Faultline wraps the jax.distributed KV client and the
heartbeat file mirrors with a *seeded* injector so tests and the fuzz
harness (scripts/faultline_fuzz.py) can drive adversarial schedules
deterministically:

- transient KV set/get errors (``FaultlineInjected``, raised *before*
  the real call so the KV state stays well-defined),
- added KV latency,
- torn / truncated / corrupted checkpoint blob writes (only keys under
  ``ksim/ckpt/`` — gather and coordination keys are never mangled),
- stale reads (a get/dir-get occasionally returns the previous snapshot
  observed for that key),
- SIGKILL schedules keyed on the heartbeat cursor
  (``KSIM_FAULTLINE_KILL="1@run:0,*@recover:-1"`` — ``*`` entries use a
  KV CAS so exactly one process dies per entry, whichever heartbeats
  first; process 0 hosts the jax.distributed coordination service and
  its death can never be survived, so ``*`` only matches pids > 0 —
  name ``0@...`` explicitly to drill the unsurvivable case).

Everything is off by default and config-gated (``faultline:`` YAML via
cli.py, or ``KSIM_FAULTLINE_*`` env directly).  The injector never
touches the compiled chunk program — only the coordination plane — so a
surviving fleet must still produce an end gather byte-identical to a
no-failure run; that is the property the fuzzer pins.

Determinism contract: each fault class draws from its own
``random.Random`` stream derived from ``(seed, pid, class)``, so the
k-th decision of a class is a pure function of the seed — same seed ⇒
same schedule (pinned by tests/test_faultline.py).  The *interleaving*
of classes across wall time may differ between runs (gather polling is
timing-dependent); byte-parity of results is guaranteed by the retry /
CRC / recovery semantics in parallel.dcn, not by identical interleaving.
"""

from __future__ import annotations

import logging
import os
import signal
import zlib
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)

# Keys whose *values* may be torn/corrupted on write.  Everything else
# (heartbeats, claims, gather payloads, coordination keys) is left
# intact — torn writes model a checkpoint publisher dying mid-blob.
_TEAR_PREFIX = "ksim/ckpt/"

# Coordination keys used by faultline itself (the ``*`` kill CAS); never
# injected, always through the raw client.
_SELF_PREFIX = "ksim/faultline/"

_TRUTHY = {"1", "true", "yes", "on"}


class FaultlineInjected(RuntimeError):
    """A fault injected by faultline (not a real infrastructure error)."""


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in _TRUTHY


def active() -> bool:
    """Whether fault injection is enabled for this process."""
    return _env_flag("KSIM_FAULTLINE")


def parse_kill_schedule(spec: str) -> List[Tuple[str, str, int]]:
    """Parse ``KSIM_FAULTLINE_KILL`` into ``(pid, state, chunk)`` entries.

    Grammar: comma-separated ``<pid>@<state>:<chunk>`` tokens where
    ``pid`` is a process index, ``*`` (any process — resolved to
    exactly one via a KV CAS) or ``all`` (round 20: EVERY process,
    coordinator included, no CAS — the whole-fleet-death drill for the
    supervised-restart path), ``state`` is a heartbeat state (``run``,
    ``recover``, ``gather``; defaults to ``run`` when omitted), and
    ``chunk`` is the heartbeat cursor at or after which the kill fires
    (``-1`` fires on the first matching beat).  Raises ``ValueError``
    on malformed tokens so validate_config can refuse bad schedules.
    """
    entries: List[Tuple[str, str, int]] = []
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        head, sep, chunk_s = tok.rpartition(":")
        if not sep:
            raise ValueError(f"faultline kill entry {tok!r} is missing ':<chunk>'")
        if "@" in head:
            pid_s, state = head.split("@", 1)
        else:
            pid_s, state = head, "run"
        pid_s = pid_s.strip()
        state = state.strip()
        if pid_s not in ("*", "all"):
            if not pid_s.lstrip("-").isdigit() or int(pid_s) < 0:
                raise ValueError(
                    f"faultline kill entry {tok!r}: pid must be a non-negative "
                    f"process index or '*'"
                )
        if not state:
            raise ValueError(f"faultline kill entry {tok!r}: empty state")
        try:
            chunk = int(chunk_s)
        except ValueError:
            raise ValueError(
                f"faultline kill entry {tok!r}: chunk {chunk_s!r} is not an integer"
            ) from None
        entries.append((pid_s, state, chunk))
    return entries


def parse_slow_schedule(spec: str) -> List[Tuple[int, int, float]]:
    """Parse ``KSIM_FAULTLINE_SLOW`` into ``(pid, chunk, factor)`` entries.

    Grammar: comma-separated ``<pid>@<chunk>:<factor>`` tokens — from
    heartbeat cursor ``chunk`` onward, process ``pid`` sleeps ``factor``
    seconds per heartbeat while in the ``run`` state.  Unlike the kill
    grammar there is no ``*``: a straggler must be named so the slow
    schedule is a pure function of the config (no CAS race deciding who
    straggles).  Raises ``ValueError`` on malformed tokens.
    """
    entries: List[Tuple[int, int, float]] = []
    for tok in (spec or "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        head, sep, factor_s = tok.rpartition(":")
        if not sep or "@" not in head:
            raise ValueError(
                f"faultline slow entry {tok!r} must be '<pid>@<chunk>:<factor>'"
            )
        pid_s, chunk_s = head.split("@", 1)
        if not pid_s.strip().isdigit():
            raise ValueError(
                f"faultline slow entry {tok!r}: pid must be a named "
                f"non-negative process index ('*' is not allowed — "
                f"stragglers are deterministic by construction)"
            )
        try:
            chunk = int(chunk_s)
        except ValueError:
            raise ValueError(
                f"faultline slow entry {tok!r}: chunk {chunk_s!r} is not an integer"
            ) from None
        try:
            factor = float(factor_s)
        except ValueError:
            raise ValueError(
                f"faultline slow entry {tok!r}: factor {factor_s!r} is not a number"
            ) from None
        if factor < 0:
            raise ValueError(
                f"faultline slow entry {tok!r}: factor must be >= 0 seconds"
            )
        entries.append((int(pid_s), chunk, factor))
    return entries


class Injector:
    """Seeded, per-process fault decider.

    One ``random.Random`` stream per fault class, derived from
    ``(seed, pid, class)`` — drawing from one class never shifts
    another, and the k-th decision of a class depends only on the seed.
    """

    CLASSES = ("kv_error", "kv_delay", "torn", "stale", "file")

    def __init__(
        self,
        seed: int = 0,
        pid: int = 0,
        kv_error_rate: float = 0.0,
        kv_delay_rate: float = 0.0,
        kv_delay_s: float = 0.02,
        torn_write_rate: float = 0.0,
        stale_read_rate: float = 0.0,
        kill: str = "",
        slow: str = "",
    ):
        self.seed = int(seed)
        self.pid = int(pid)
        self.kv_delay_s = max(float(kv_delay_s), 0.0)
        self.rates = {
            "kv_error": float(kv_error_rate),
            "kv_delay": float(kv_delay_rate),
            "torn": float(torn_write_rate),
            "stale": float(stale_read_rate),
            "file": float(torn_write_rate),
        }
        self.kill_entries = parse_kill_schedule(kill)
        # Slow (straggler) schedule — kept out of CLASSES/counts: it is
        # not a rate-driven class and pinned stats stay five-keyed.
        self.slow_entries = parse_slow_schedule(slow)
        self.slow_count = 0
        self.counts = {c: 0 for c in self.CLASSES}
        self._rng: dict = {}

    def _stream(self, name: str):
        import random

        r = self._rng.get(name)
        if r is None:
            # Distinct 64-bit-ish seeds per (seed, pid, class); crc32 of
            # the class name keeps streams independent without hashing
            # tuples (random.Random only seeds on int/str/bytes).
            r = random.Random(
                (self.seed * 1_000_003 + self.pid * 8191) ^ zlib.crc32(name.encode())
            )
            self._rng[name] = r
        return r

    def hit(self, cls: str) -> bool:
        """Draw the next decision for ``cls``; True means inject."""
        rate = self.rates.get(cls, 0.0)
        if rate <= 0.0:
            return False
        if self._stream(cls).random() < rate:
            self.counts[cls] += 1
            return True
        return False

    def tear(self, value: str) -> str:
        """Mangle a blob: truncate (torn write) or flip one character."""
        if not value:
            return value
        r = self._stream("tear")
        if r.random() < 0.5 and len(value) > 1:
            return value[: 1 + int(r.random() * (len(value) - 1))]
        i = int(r.random() * len(value))
        return value[:i] + chr((ord(value[i]) ^ 0x1) & 0x7F) + value[i + 1 :]

    def stats(self) -> dict:
        return dict(self.counts)


def from_env() -> Injector:
    """Build an :class:`Injector` from ``KSIM_FAULTLINE_*``."""
    pid = int(os.environ.get("KSIM_DCN_PID", "0") or 0)
    return Injector(
        seed=int(os.environ.get("KSIM_FAULTLINE_SEED", "0") or 0),
        pid=pid,
        kv_error_rate=float(os.environ.get("KSIM_FAULTLINE_KV_ERROR_RATE", "0") or 0),
        kv_delay_rate=float(os.environ.get("KSIM_FAULTLINE_KV_DELAY_RATE", "0") or 0),
        kv_delay_s=float(os.environ.get("KSIM_FAULTLINE_KV_DELAY_S", "0.02") or 0),
        torn_write_rate=float(os.environ.get("KSIM_FAULTLINE_TORN_RATE", "0") or 0),
        stale_read_rate=float(os.environ.get("KSIM_FAULTLINE_STALE_RATE", "0") or 0),
        kill=os.environ.get("KSIM_FAULTLINE_KILL", ""),
        slow=os.environ.get("KSIM_FAULTLINE_SLOW", ""),
    )


_INJECTOR: Optional[Injector] = None
_PROXY = None
_KILLED_CAS: set = set()


def _emit_fault(event: dict) -> None:
    """Mirror one injection as a fleet event (round 21 black box): the
    post-mortem links an injected fault to the retry/fallback/steal it
    provoked through the trace id derived from the injected key (see
    ``parallel.trace.trace_for_key``). Lazy dcn import — faultline is
    imported BY dcn — and best-effort: telemetry never alters the
    injection schedule or takes the run down."""
    try:
        from . import dcn

        dcn._mirror_event(event)
    except Exception:
        pass


def injector() -> Injector:
    """The process-wide injector singleton (lazily built from env)."""
    global _INJECTOR
    if _INJECTOR is None:
        _INJECTOR = from_env()
    return _INJECTOR


def reset() -> None:
    """Drop the singleton + proxy (tests re-read env on next use)."""
    global _INJECTOR, _PROXY
    _INJECTOR = None
    _PROXY = None
    _KILLED_CAS.clear()


class _KvProxy:
    """KV-client wrapper injecting faults ahead of the real calls.

    ``raw`` exposes the unwrapped client for coordination ops that must
    not be injected (the ``*`` kill CAS).  Errors are raised *before*
    the real call so the KV store never holds a half-applied op; torn
    writes are the one deliberate exception — the mangled value IS
    written, modelling a publisher dying mid-blob, and only ever for
    checkpoint chunk keys.
    """

    def __init__(self, client, inj: Injector):
        self.raw = client
        self._inj = inj
        # key -> previously observed value, for stale-read injection.
        self._seen: dict = {}

    def _fault(self, cls: str, key, op: str) -> None:
        _emit_fault(
            {"event": "fault_inject", "pid": int(self._inj.pid),
             "class": cls, "key": str(key), "op": op,
             "n": int(self._inj.counts.get(cls, 0))}
        )

    def _delay(self, key, op: str):
        if self._inj.hit("kv_delay"):
            import time

            self._fault("kv_delay", key, op)
            time.sleep(self._inj.kv_delay_s)

    def key_value_set(self, key, value, *args, **kwargs):
        if key.startswith(_SELF_PREFIX):
            return self.raw.key_value_set(key, value, *args, **kwargs)
        self._delay(key, "set")
        if self._inj.hit("kv_error"):
            self._fault("kv_error", key, "set")
            raise FaultlineInjected(f"injected KV set error for {key!r}")
        if key.startswith(_TEAR_PREFIX) and self._inj.hit("torn"):
            log.debug("faultline: tearing write of %s", key)
            self._fault("torn", key, "set")
            value = self._inj.tear(value)
        return self.raw.key_value_set(key, value, *args, **kwargs)

    def blocking_key_value_get(self, key, *args, **kwargs):
        self._delay(key, "get")
        if self._inj.hit("kv_error"):
            self._fault("kv_error", key, "get")
            raise FaultlineInjected(f"injected KV get error for {key!r}")
        prev = self._seen.get(key)
        val = self.raw.blocking_key_value_get(key, *args, **kwargs)
        self._seen[key] = val
        if prev is not None and self._inj.hit("stale"):
            self._fault("stale", key, "get")
            return prev
        return val

    def key_value_dir_get(self, prefix, *args, **kwargs):
        self._delay(prefix, "dir_get")
        if self._inj.hit("kv_error"):
            self._fault("kv_error", prefix, "dir_get")
            raise FaultlineInjected(f"injected KV dir-get error for {prefix!r}")
        skey = ("dir", prefix)
        prev = self._seen.get(skey)
        val = self.raw.key_value_dir_get(prefix, *args, **kwargs)
        self._seen[skey] = val
        if prev is not None and self._inj.hit("stale"):
            self._fault("stale", prefix, "dir_get")
            return prev
        return val

    def __getattr__(self, name):
        return getattr(self.raw, name)


def wrap_kv(client):
    """Wrap the jax.distributed KV client when faultline is active.

    Identity when off — ``dcn._client()`` calls this on every KV touch,
    and the off-by-default contract (bit-identical behaviour with
    ``KSIM_FAULTLINE`` unset) is pinned by tests.
    """
    if client is None or not active():
        return client
    global _PROXY
    if _PROXY is None or _PROXY.raw is not client:
        _PROXY = _KvProxy(client, injector())
    return _PROXY


def file_blob(blob: str) -> str:
    """Maybe-mangle a heartbeat file-mirror payload (torn mirror write)."""
    if not active():
        return blob
    inj = injector()
    if inj.hit("file"):
        _emit_fault(
            {"event": "fault_inject", "pid": int(inj.pid),
             "class": "file", "op": "mirror",
             "n": int(inj.counts.get("file", 0))}
        )
        return inj.tear(blob)
    return blob


def maybe_slow(chunk: int, state: str) -> float:
    """Sleep per the straggler schedule; returns seconds slept.

    Called by ``dcn.heartbeat`` at the TOP of the beat — *before* the
    beacon/lease-renewal publish — so the sleep ages the PREVIOUS beacon
    and renewal on the wire (the signal straggler detection reads) while
    the beat published after waking carries a fresh timestamp.  Only the
    ``run`` state is slowed: slowing ``gather``/``recover`` would stall
    coordination itself rather than manufacture a compute straggler.
    """
    if not active() or state != "run":
        return 0.0
    inj = injector()
    slept = 0.0
    for pid_s, thr, factor in inj.slow_entries:
        if pid_s != inj.pid or int(chunk) < thr or factor <= 0:
            continue
        if slept == 0.0:
            log.warning(
                "faultline: slowing process %d by %.3gs (schedule entry "
                "%r at chunk=%d)",
                inj.pid, factor, f"{pid_s}@{thr}:{factor:g}", int(chunk),
            )
            # Round 21: the injected straggle is the causal root of the
            # speculation it provokes — linked via trace.CTX (the block
            # this process is executing while it sleeps).
            _emit_fault(
                {"event": "fault_slow", "pid": int(inj.pid),
                 "class": "slow", "chunk": int(chunk),
                 "factor": float(factor), "n": int(inj.slow_count)}
            )
        import time

        time.sleep(factor)
        inj.slow_count += 1
        slept += factor
    return slept


def maybe_kill(chunk: int, state: str) -> None:
    """Fire any matching SIGKILL schedule entry for this heartbeat.

    Called by ``dcn.heartbeat`` after the beacon publish.  Named-pid
    entries fire unconditionally once ``chunk`` reaches the threshold in
    the named state; ``*`` entries race a CAS on
    ``ksim/faultline/kill/<idx>`` through the *raw* client so exactly
    one process per entry dies, whichever heartbeats first — byte-parity
    of the surviving fleet must hold regardless of which one.  ``*``
    never matches process 0: it hosts the jax.distributed coordination
    service, whose death aborts every healthy task — killing the
    coordinator must be asked for by name (``0@run:N``) or via ``all``
    (every process, no CAS), the round-20 drills for the supervised
    durable-journal restart.

    Round 20: kill entries fire only in the ORIGINAL fleet
    (``KSIM_DCN_RESTART_COUNT`` unset or 0).  A supervised relaunch
    exports the attempt number, so a resumed fleet replays the same
    schedule config without re-dying at the same chunk; the rate-driven
    classes (torn/kv_error/...) stay active — the CRC stack absorbs
    them either way.
    """
    if not active():
        return
    try:
        if int(os.environ.get("KSIM_DCN_RESTART_COUNT", "0") or 0) > 0:
            return
    except ValueError:
        pass
    inj = injector()
    if not inj.kill_entries:
        return
    for idx, (pid_s, st, thr) in enumerate(inj.kill_entries):
        if st != state or int(chunk) < thr:
            continue
        if pid_s == "all":
            pass  # every process dies — no CAS, no pid filter
        elif pid_s == "*":
            if inj.pid == 0 or idx in _KILLED_CAS:
                continue
            try:
                from . import dcn

                c = dcn._client()
                raw = getattr(c, "raw", c)
                # CAS: first writer wins the right to die.
                raw.key_value_set(f"{_SELF_PREFIX}kill/{idx}", str(inj.pid))
            except Exception:
                _KILLED_CAS.add(idx)  # lost (or unreachable): never ours
                continue
        elif int(pid_s) != inj.pid:
            continue
        log.warning(
            "faultline: killing process %d (schedule entry %r at state=%s chunk=%d)",
            inj.pid,
            f"{pid_s}@{st}:{thr}",
            state,
            int(chunk),
        )
        # Round 21 black box: one last event line BEFORE the SIGKILL —
        # the post-mortem ties the death to the block/recovery this
        # process was executing (trace.CTX) and to the steal/claim a
        # survivor raises against it.
        _emit_fault(
            {"event": "fault_kill", "pid": int(inj.pid),
             "class": "kill", "state": str(state), "chunk": int(chunk),
             "n": int(idx)}
        )
        os.kill(os.getpid(), signal.SIGKILL)
