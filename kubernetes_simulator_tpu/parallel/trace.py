"""Fleet black box (round 21): causal trace identity for every
scenario-block lifecycle and checkpoint cursor.

Every fleet coordination event the DCN layer mirrors (``dcn.
_mirror_event`` → ``events.jsonl`` + flight-recorder fleet rows) is
stamped with three read-only telemetry fields:

``trace``
    Stable identity of the THING the event is about:

    - ``blk:<bid>``       work-queue scenario block ``bid``
    - ``blk:s<pid>``      static-slice block owned by (dead) ``pid``
    - ``ckpt:<pid>:<cur>`` ``pid``'s checkpoint blob at chunk ``cur``

``span``
    The hop itself: ``<trace>/<hop>.g<gen>.p<pid>`` for block hops
    (exec / spec / done / dup / spec_lost / adopt / claim / recover) and
    ``<trace>/<hop>.p<pid>`` for checkpoint hops (publish / load /
    journal_resume / fallback).

``parent``
    The span that causally produced this one (absent for roots): a
    steal's parent is the expired holder's exec span, a dup-discard's
    parent is the loser's own exec span, a checkpoint load's parent is
    the publish span that wrote the blob, and so on.

Some events additionally carry ``link`` — a second trace id bridging
two lifecycles (e.g. a ``ckpt_load`` during a steal links the loaded
``ckpt:`` trace to the block being resumed), so the post-mortem's flow
arrows can follow a block across a process death.

Every value is a pure function of protocol state (pid / gen / bid /
cursor) — no wall clocks, no randomness — so stamped telemetry streams
stay deterministic for a fixed schedule, and stamping changes NOTHING
outside telemetry: placements, result JSONL and checkpoint blobs are
byte-identical with ``KSIM_TRACE=0`` (the off switch; default on).

``scripts/fleet_postmortem.py`` consumes these fields to rebuild one
causally-ordered fleet timeline and audit the protocol invariants.
"""

from __future__ import annotations

import os
from typing import Optional

# Execution context for cross-lifecycle links: the block trace this
# process is currently executing/recovering (set by dcn's work-queue
# runner and recovery claim path around the execute callback). Read by
# checkpoint-side stamping so a load/publish during a resume carries a
# ``link`` back to the block that caused it. Single-slot on purpose —
# one block executes at a time per process; the background publisher
# thread reads whatever block is current, which is the block whose
# state it is publishing.
CTX = [None]


def enabled() -> bool:
    """Trace stamping gate (``KSIM_TRACE``; default ON). Off mode
    exists for the byte-identity parity bar, not for production."""
    return os.environ.get("KSIM_TRACE", "1") not in ("", "0")


def block_trace(bid) -> str:
    """Trace id of work-queue scenario block ``bid``."""
    return f"blk:{int(bid)}"


def static_trace(dead_pid) -> str:
    """Trace id of the static-slice block owned by ``dead_pid``."""
    return f"blk:s{int(dead_pid)}"


def ckpt_trace(pid, cursor) -> str:
    """Trace id of ``pid``'s checkpoint blob at chunk ``cursor``."""
    return f"ckpt:{int(pid)}:{int(cursor)}"


def exec_span(bid, gen, pid) -> str:
    """Span of one execution attempt of block ``bid`` at generation
    ``gen`` by ``pid`` — created by a lease (g0) or a steal (g>0)."""
    return f"{block_trace(bid)}/exec.g{int(gen)}.p{int(pid)}"


def spec_span(bid, gen, pid) -> str:
    """Span of a one-shot speculative re-execution (same generation as
    the straggling holder — speculation burns no lease generation)."""
    return f"{block_trace(bid)}/spec.g{int(gen)}.p{int(pid)}"


def publish_span(pid, cursor) -> str:
    """Span of the publication that wrote ``ckpt:<pid>:<cursor>``."""
    return f"{ckpt_trace(pid, cursor)}/publish.p{int(pid)}"


def trace_for_key(key: str) -> Optional[str]:
    """Derive the trace id a coordination-plane KV key belongs to, or
    None for keys outside any traced lifecycle (heartbeats, gather
    payload slots, exit rendezvous). Used by faultline to stamp an
    injected fault with the lifecycle it perturbs."""
    parts = str(key).strip("/").split("/")
    if len(parts) < 3 or parts[0] != "ksim":
        return None
    try:
        if parts[1] == "ckpt" and len(parts) >= 6:
            # ksim/ckpt/<epoch>/<pid>/<lo>-<hi>/<cursor>[/<leaf>]
            return ckpt_trace(int(parts[3]), int(parts[5]))
        if parts[1] == "claim" and len(parts) >= 5:
            # ksim/claim/<seq>/<name>/<dead_pid>/<gen>
            return static_trace(int(parts[4]))
        if parts[1] == "wq" and len(parts) >= 6:
            # ksim/wq/<seq>/<name>/<sub>/<bid>[/...]
            if parts[4] in ("lease", "renew", "done", "spec", "result"):
                return block_trace(int(parts[5]))
    except (ValueError, IndexError):
        return None
    return None


def stamp(event: dict) -> dict:
    """Add ``trace``/``span``/``parent`` (and ``link`` where a second
    lifecycle is bridged) to one fleet event dict, in place. The single
    choke point — ``dcn._mirror_event`` calls it before fan-out, so the
    events.jsonl mirror, the flight-recorder fleet rows and any other
    sink all carry identical stamps. Unknown kinds and missing fields
    degrade to no stamp, never an error; a no-op with the gate off or
    when the event already carries a ``trace`` (pre-stamped)."""
    if not enabled() or "trace" in event:
        return event
    try:
        kind = event.get("event", event.get("kind"))
        pid = event.get("pid")
        bid = event.get("block")
        gen = event.get("gen", 0)
        if kind == "lease":
            event["trace"] = block_trace(bid)
            event["span"] = exec_span(bid, gen, pid)
        elif kind == "steal":
            event["trace"] = block_trace(bid)
            event["span"] = exec_span(bid, gen, pid)
            if int(event.get("from", -1)) >= 0:
                event["parent"] = exec_span(
                    bid, int(gen) - 1, event["from"]
                )
        elif kind == "speculate":
            event["trace"] = block_trace(bid)
            event["span"] = spec_span(bid, gen, pid)
            if int(event.get("from", -1)) >= 0:
                event["parent"] = exec_span(bid, gen, event["from"])
        elif kind == "block_done":
            event["trace"] = block_trace(bid)
            event["span"] = (
                f"{block_trace(bid)}/done.g{int(gen)}.p{int(pid)}"
            )
            event["parent"] = (
                spec_span(bid, gen, pid)
                if event.get("spec")
                else exec_span(bid, gen, pid)
            )
        elif kind == "spec_lost":
            event["trace"] = block_trace(bid)
            event["span"] = (
                f"{block_trace(bid)}/spec_lost.g{int(gen)}.p{int(pid)}"
            )
            event["parent"] = spec_span(bid, gen, pid)
        elif kind == "dup_discard":
            event["trace"] = block_trace(bid)
            event["span"] = (
                f"{block_trace(bid)}/dup.g{int(gen)}.p{int(pid)}"
            )
            event["parent"] = exec_span(bid, gen, pid)
        elif kind == "journal_adopt":
            event["trace"] = block_trace(bid)
            event["span"] = f"{block_trace(bid)}/adopt.p{int(pid)}"
            if "gen" in event and int(event.get("from", -1)) >= 0:
                # The dead fleet's done span — adoption is causally the
                # continuation of the completion the journal preserved.
                event["parent"] = (
                    f"{block_trace(bid)}/done.g{int(event['gen'])}"
                    f".p{int(event['from'])}"
                )
        elif kind == "claim":
            tr = static_trace(event["for"])
            event["trace"] = tr
            event["span"] = (
                f"{tr}/claim.g{int(gen)}.p{int(event['claimant'])}"
            )
            if int(gen) > 0:
                # The fenced hand-off: gen>0 means an earlier claimant
                # died mid-recovery. Its claimant pid is not in this
                # event; the post-mortem resolves the prefix.
                event["parent"] = f"{tr}/claim.g{int(gen) - 1}"
        elif kind == "recovered":
            tr = static_trace(event["for"])
            event["trace"] = tr
            event["span"] = (
                f"{tr}/recover.g{int(gen)}.p{int(event['claimant'])}"
            )
            event["parent"] = (
                f"{tr}/claim.g{int(gen)}.p{int(event['claimant'])}"
            )
        elif kind == "ckpt_publish":
            cur = event["cursor"]
            event["trace"] = ckpt_trace(pid, cur)
            event["span"] = publish_span(pid, cur)
            if CTX[0]:
                event["link"] = CTX[0]
        elif kind in ("journal_resume", "ckpt_load", "ckpt_fallback"):
            cur = event["cursor"]
            hop = {
                "journal_resume": "journal_resume",
                "ckpt_load": "load",
                "ckpt_fallback": "fallback",
            }[kind]
            by = event.get("by", pid)
            event["trace"] = ckpt_trace(pid, cur)
            event["span"] = (
                f"{ckpt_trace(pid, cur)}/{hop}.p{int(by)}"
            )
            event["parent"] = publish_span(pid, cur)
            if CTX[0]:
                event["link"] = CTX[0]
        elif kind in ("fault_inject", "fault_kill", "fault_slow"):
            tr = event.get("key") and trace_for_key(event["key"])
            if not tr and CTX[0]:
                tr = CTX[0]
            if not tr and kind == "fault_kill":
                # A kill outside any block context is the causal HEAD
                # of the dead pid's static-recovery lifecycle: the
                # survivor's claim/recovered events share this trace,
                # so the post-mortem flow arrow runs dead → claimant.
                tr = static_trace(pid)
            base = tr if tr else "fault"
            tag = event.get("class", kind)
            seq = event.get("n", 0)
            event["span"] = f"{base}/{kind}.{tag}.n{int(seq)}.p{int(pid)}"
            if tr:
                event["trace"] = tr
        # join and unknown kinds: no trace identity.
    except (KeyError, TypeError, ValueError):
        pass
    return event
