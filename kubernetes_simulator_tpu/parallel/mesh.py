"""TPU mesh + scenario sharding (SURVEY.md §2 parallelism mapping).

The what-if scenario axis is the framework's data-parallel axis: S perturbed
cluster states shard over a ``jax.sharding.Mesh`` of TPU devices
(`scenarios` axis), each device scanning the same pod stream against its
local scenarios. Collectives (the XLA-compiled equivalents of the
reference-world's NCCL) appear only at metric-gather time — one ``psum`` /
``all_gather`` over ICI per replay, exactly as SURVEY.md §5 prescribes.

Multi-host (DCN) scaling (round 11, parallel.dcn) localizes rather than
spans: ``init_distributed()`` brings up ``jax.distributed``, the engine
slices the scenario axis into contiguous per-process blocks and runs the
chunk loop over a process-LOCAL mesh (``dcn.localize_mesh``), and the
processes combine results exactly once per replay via a host-side gather
over the coordination service — still one collective per replay, now with
zero DCN traffic inside the chunk loop.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SCENARIO_AXIS = "scenarios"

#: Intra-scenario node-plane axis (round 14). Where SCENARIO_AXIS shards
#: *which* cluster each device sees, NODE_AXIS shards *the nodes of one
#: cluster*: each device holds a contiguous block of the node planes
#: ([N, R] resources, [G, N] count planes) and evaluates its block's
#: Filter+Score; selection is a two-stage argmax (local per-shard reduce,
#: then one tiny cross-device (score, global-node-id) exchange — see
#: ops.tpu.select_node_sharded). Composes with the scenario/DCN axes by
#: nesting: processes × scenarios × node shards.
NODE_AXIS = "nodes"


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up over DCN ([K8S]-world has no equivalent; this is
    the TPU-native answer to a distributed communication backend). No-op for
    single-process runs.

    With survivor recovery on (``KSIM_DCN_RECOVER``, round 15) the
    coordination service's OWN failure detector is widened past the
    gather deadline: its default ~100s tolerance would propagate a fatal
    error that aborts every healthy task while a survivor is still
    rebalancing the dead process's block. parallel.dcn's liveness
    beacons (KSIM_DCN_STALL_S) stay the fast detector. The round-18
    work-stealing queue widens it for the same reason: a straggling or
    deferred-join process must not be declared dead by the runtime while
    the queue is still racing a speculative re-execution against it."""
    if not (num_processes and num_processes > 1):
        return
    from . import dcn

    if not (dcn.recover_enabled() or dcn.wq_enabled()):
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return
    import os

    from jax._src import distributed as _dist
    from jax._src import xla_bridge as _xb

    if _xb.backends_are_initialized():
        raise RuntimeError(
            "init_distributed() must be called before any JAX "
            "computations are executed."
        )
    timeout_s = float(os.environ.get("KSIM_DCN_TIMEOUT_S", "300"))
    _dist.global_state.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        service_heartbeat_interval_seconds=10,
        service_max_missing_heartbeats=max(int(timeout_s / 5), 10),
    )


def make_mesh(num_devices: Optional[int] = None, axis: str = SCENARIO_AXIS) -> Mesh:
    """1-D device mesh over the scenario axis. ``num_devices`` defaults to
    all visible devices (TPU slice, or the CPU virtual devices in tests)."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis,))


def make_node_mesh(num_shards: int) -> Mesh:
    """1-D device mesh over the NODE axis — ``num_shards`` devices each
    carrying 1/num_shards of a single scenario's node planes. Raises when
    the host does not expose that many devices (node sharding never spans
    processes; compose with parallel.dcn for that). LOCAL devices only:
    inside a DCN fleet ``jax.devices()`` leads with process 0's devices,
    which are unaddressable from every other process — a node-sharded
    source replay feeding a fleet (the round-18 work-queue fork leg)
    must shard over the devices this process owns."""
    devs = jax.local_devices()
    if num_shards > len(devs):
        raise ValueError(
            f"node_shards={num_shards} exceeds the {len(devs)} visible "
            f"devices; use node_shards <= {len(devs)} (or shard scenarios "
            "across processes with parallel.dcn instead)"
        )
    return Mesh(np.array(devs[:num_shards]), (NODE_AXIS,))


def spans_processes(mesh: Optional[Mesh]) -> bool:
    """True when ``mesh`` contains devices this process cannot address —
    i.e. it is a cross-process (DCN) mesh. The engine localizes such
    meshes (parallel.dcn.localize_mesh) before the chunk loop; result
    paths branch on this instead of the blunt ``process_count() > 1``
    (a local mesh inside a multi-process run is the common round-11
    case and needs no global-array plumbing)."""
    if mesh is None:
        return False
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def scenario_sharding(mesh: Mesh, axis: str = SCENARIO_AXIS) -> NamedSharding:
    """Shard the leading (scenario) dimension; replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def node_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    """NamedSharding for one node-plane tensor from its PartitionSpec
    (``P(NODE_AXIS)`` for [N, ...] planes, ``P(None, NODE_AXIS)`` for
    [G, N] / [T, N] planes, ``P()`` for replicated scalars/tables)."""
    return NamedSharding(mesh, spec)


def pad_node_axis(a: np.ndarray, axis: int, n_pad: int, fill) -> np.ndarray:
    """Host copy of ``a`` with its node ``axis`` padded to ``n_pad`` rows
    of ``fill``. Padding is host-side only — encoded inputs and results
    always keep the real node count; sharded device planes carry the pad
    so every shard is the same width (see shard_node_planes)."""
    n = a.shape[axis]
    if n == n_pad:
        return np.asarray(a)
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, n_pad - n)
    return np.pad(np.asarray(a), pad, constant_values=fill)


def shard_node_planes(mesh: Mesh, tree, specs):
    """device_put every leaf of ``tree`` under the matching PartitionSpec
    in ``specs`` (same structure). Leaves must already be padded so the
    node axis divides ``mesh`` evenly (pad_node_axis); a leaf with spec
    P() is replicated across the node shards."""
    return jax.tree.map(
        lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)), tree, specs
    )


def _global_put(a, sh: NamedSharding):
    """Host copy → global array for a multi-process mesh. Built from each
    process's local data via make_array_from_callback: device_put's
    cross-process consistency check compares values with ``==``, which NaN
    entries (numeric-label slots) always fail even though every process
    holds identical bytes."""
    a = np.asarray(a)
    return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])


def shard_scenario_tree(mesh: Mesh, tree, axis: str = SCENARIO_AXIS):
    """device_put every leaf with its leading dim sharded over the mesh.

    Multi-process (DCN): leaves are pulled back to host and re-emitted as
    global arrays — device_put from a single-device array to a sharding
    spanning non-addressable devices is not defined."""
    sh = scenario_sharding(mesh, axis)
    if spans_processes(mesh):
        return jax.tree.map(lambda a: _global_put(a, sh), tree)
    return jax.tree.map(lambda a: jax.device_put(a, sh), tree)


def replicate_tree(mesh: Mesh, tree):
    sh = replicated(mesh)
    if spans_processes(mesh):
        return jax.tree.map(lambda a: _global_put(a, sh), tree)
    return jax.tree.map(lambda a: jax.device_put(a, sh), tree)


def fit_population(population: int, per_candidate: int, mesh: Optional[Mesh]) -> int:
    """Smallest population ≥ ``population`` whose FLAT sweep axis
    (population × per_candidate scenarios) divides over the mesh devices.

    The policy tuner (round 9, sim.tuner) evaluates its whole candidate
    population in one sweep by flattening (candidate, train-scenario)
    pairs onto the scenario axis — the same data-parallel axis the
    perturbation sweeps shard. A mesh requires that flat axis to divide
    evenly over devices (WhatIfEngine raises otherwise), so the tuner
    rounds the population UP here and fills the extra rows with fresh
    samples rather than failing or silently truncating — and LOGS the
    padding (no silent caps): callers surface the requested vs. fitted
    sizes in their result metadata (TuneResult.population_requested,
    WhatIfResult.n_devices).

    DCN case (round 11): the flat axis must divide
    ``process_count × local_devices`` — each process takes a contiguous
    1/process_count block of the flat axis, and its LOCAL slice must in
    turn divide its local mesh devices. A mesh that already spans
    processes counts its devices once; a process-local mesh in a
    multi-process run is scaled by ``process_count``; even a mesh-less
    DCN sweep must divide ``process_count`` for the slicing to be even.
    The padding log names the DCN factorization so operators see why the
    population grew."""
    requested = population = max(int(population), 1)
    nproc = jax.process_count()
    if mesh is None:
        if nproc <= 1:
            return population
        ndev, label = nproc, f"{nproc} processes (no mesh)"
    elif spans_processes(mesh):
        ndev = int(mesh.devices.size)
        label = f"{ndev} mesh devices across {nproc} processes"
    elif nproc > 1:
        local = int(mesh.devices.size)
        ndev = local * nproc
        label = f"{nproc} processes x {local} local mesh devices = {ndev}"
    else:
        ndev = int(mesh.devices.size)
        label = f"{ndev} mesh devices"
    while (population * per_candidate) % ndev:
        population += 1
    if population != requested:
        from ..utils.metrics import log

        log.info(
            "fit_population: padded population %d -> %d (+%d rows) so the "
            "flat axis (%d x %d) divides over %s",
            requested, population, population - requested,
            population, per_candidate, label,
        )
    return population
