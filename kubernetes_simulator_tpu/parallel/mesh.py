"""TPU mesh + scenario sharding (SURVEY.md §2 parallelism mapping).

The what-if scenario axis is the framework's data-parallel axis: S perturbed
cluster states shard over a ``jax.sharding.Mesh`` of TPU devices
(`scenarios` axis), each device scanning the same pod stream against its
local scenarios. Collectives (the XLA-compiled equivalents of the
reference-world's NCCL) appear only at metric-gather time — one ``psum`` /
``all_gather`` over ICI per replay, exactly as SURVEY.md §5 prescribes.

Multi-host (DCN) scaling uses the same code path: ``init_distributed()``
brings up ``jax.distributed`` and the mesh simply spans all processes'
devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SCENARIO_AXIS = "scenarios"


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up over DCN ([K8S]-world has no equivalent; this is
    the TPU-native answer to a distributed communication backend). No-op for
    single-process runs."""
    if num_processes and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )


def make_mesh(num_devices: Optional[int] = None, axis: str = SCENARIO_AXIS) -> Mesh:
    """1-D device mesh over the scenario axis. ``num_devices`` defaults to
    all visible devices (TPU slice, or the CPU virtual devices in tests)."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis,))


def scenario_sharding(mesh: Mesh, axis: str = SCENARIO_AXIS) -> NamedSharding:
    """Shard the leading (scenario) dimension; replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _global_put(a, sh: NamedSharding):
    """Host copy → global array for a multi-process mesh. Built from each
    process's local data via make_array_from_callback: device_put's
    cross-process consistency check compares values with ``==``, which NaN
    entries (numeric-label slots) always fail even though every process
    holds identical bytes."""
    a = np.asarray(a)
    return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])


def shard_scenario_tree(mesh: Mesh, tree, axis: str = SCENARIO_AXIS):
    """device_put every leaf with its leading dim sharded over the mesh.

    Multi-process (DCN): leaves are pulled back to host and re-emitted as
    global arrays — device_put from a single-device array to a sharding
    spanning non-addressable devices is not defined."""
    sh = scenario_sharding(mesh, axis)
    if jax.process_count() > 1:
        return jax.tree.map(lambda a: _global_put(a, sh), tree)
    return jax.tree.map(lambda a: jax.device_put(a, sh), tree)


def replicate_tree(mesh: Mesh, tree):
    sh = replicated(mesh)
    if jax.process_count() > 1:
        return jax.tree.map(lambda a: _global_put(a, sh), tree)
    return jax.tree.map(lambda a: jax.device_put(a, sh), tree)


def fit_population(population: int, per_candidate: int, mesh: Optional[Mesh]) -> int:
    """Smallest population ≥ ``population`` whose FLAT sweep axis
    (population × per_candidate scenarios) divides over the mesh devices.

    The policy tuner (round 9, sim.tuner) evaluates its whole candidate
    population in one sweep by flattening (candidate, train-scenario)
    pairs onto the scenario axis — the same data-parallel axis the
    perturbation sweeps shard. A mesh requires that flat axis to divide
    evenly over devices (WhatIfEngine raises otherwise), so the tuner
    rounds the population UP here and fills the extra rows with fresh
    samples rather than failing or silently truncating — and LOGS the
    padding (no silent caps): callers surface the requested vs. fitted
    sizes in their result metadata (TuneResult.population_requested,
    WhatIfResult.n_devices)."""
    requested = population = max(int(population), 1)
    if mesh is None:
        return population
    ndev = int(mesh.devices.size)
    while (population * per_candidate) % ndev:
        population += 1
    if population != requested:
        from ..utils.metrics import log

        log.info(
            "fit_population: padded population %d -> %d (+%d rows) so the "
            "flat axis (%d x %d) divides over %d mesh devices",
            requested, population, population - requested,
            population, per_candidate, ndev,
        )
    return population
