"""Multi-host DCN replay (round 11): process-local execution, ONE
end-of-replay gather.

The scenario axis is the framework's data-parallel axis (parallel.mesh);
across hosts it splits the same way: each process owns the CONTIGUOUS
``jax.process_index()`` block of the scenario list and runs the entire
chunk loop on it **locally** — a mesh is restricted to the process's own
devices (:func:`localize_mesh`), the boundary-mode host mirrors exist
only for local scenarios, and ``WhatIfEngine._fetch``/``_fold`` touch
only addressable shards. By construction there are ZERO cross-process
collectives inside the chunk loop; the processes meet exactly once per
replay, at result assembly, through :func:`gather` — a host-side gather
over the ``jax.distributed`` coordination (KV-store) service, the SURVEY
§5 "one collective per replay" contract realized over DCN.

Why host-side rather than psum/all_gather: the result tensors are tiny
([S] counters and quantiles), and routing them through the coordination
service keeps the compiled chunk programs bit-identical to the
single-process mesh programs — which is what makes the 2-process parity
bar (byte-identical placements, JSONL, checkpoint blobs) attainable. It
also runs on jaxlib CPU builds whose runtime rejects cross-process XLA
computations outright, so the path is exercised in CI without TPU hosts
(scripts/dcn_launch.py spawns the coordinator + workers on one machine).

``GATHER_COUNT`` is module-global so tests can pin the "exactly one
gather per replay" contract. Gathers are SPMD-disciplined: every process
must call :func:`gather` the same number of times with the same ``name``
(the per-call sequence number is part of the KV key).
"""

from __future__ import annotations

import base64
import json
import os
import pickle
import random
import time
import zlib
from typing import Dict, Optional

import numpy as np

# Cross-process gathers performed by this process since import. Tests
# diff it around a replay to pin "one gather per replay, zero per chunk".
GATHER_COUNT = 0
_seq = 0

# The coordination service speaks gRPC with a 4 MiB default message cap —
# payloads are chunked well below it.
_KV_CHUNK = 2 * 1024 * 1024

# ---------------------------------------------------------------------------
# Gather payload compression (round 14). Assignment tensors dominate the
# gather bytes at Borg scale ([S_local, P] int32 — 100k+ pods per row), and
# they are extremely delta-compressible (node ids of consecutive pods
# cluster; PAD runs are constant). Large integer ndarrays are re-encoded as
# zlib(delta int32) before the KV put and decoded transparently on gather;
# decode is byte-exact (values, dtype, shape — pinned by
# tests/test_dcn_units.py). Float/object leaves and small arrays pass
# through untouched — the codec must never cost more than it saves.

_COMPRESS_MIN_ELEMS = 1024
# (raw, compressed) byte totals for this process's gather puts since
# import — tests and operators read the reduction off these.
COMPRESS_BYTES = [0, 0]

_I32_MIN = np.iinfo(np.int32).min
_I32_MAX = np.iinfo(np.int32).max


class _PackedArray:
    """Wire wrapper for one compressed ndarray leaf. ``codec``:
    "delta-zlib" (zlib over consecutive int32 deltas of the flattened
    array — first element is delta-from-zero) or "zlib" (zlib over the
    raw bytes; the fallback when deltas overflow int32)."""

    __slots__ = ("codec", "dtype", "shape", "data")

    def __init__(self, codec: str, dtype: str, shape, data: bytes):
        self.codec = codec
        self.dtype = dtype
        self.shape = shape
        self.data = data


def _pack_leaf(a):
    import zlib

    if not (
        isinstance(a, np.ndarray)
        and a.size >= _COMPRESS_MIN_ELEMS
        and np.issubdtype(a.dtype, np.integer)
    ):
        return a
    flat = a.reshape(-1).astype(np.int64)
    deltas = np.diff(flat, prepend=np.int64(0))
    if deltas.min() >= _I32_MIN and deltas.max() <= _I32_MAX:
        codec, raw = "delta-zlib", deltas.astype("<i4").tobytes()
    else:
        codec, raw = "zlib", np.ascontiguousarray(a).tobytes()
    comp = zlib.compress(raw, 6)
    if len(comp) >= a.nbytes:
        return a  # incompressible — ship raw
    COMPRESS_BYTES[0] += a.nbytes
    COMPRESS_BYTES[1] += len(comp)
    return _PackedArray(codec, a.dtype.str, a.shape, comp)


def _unpack_leaf(p):
    import zlib

    if not isinstance(p, _PackedArray):
        return p
    raw = zlib.decompress(p.data)
    if p.codec == "delta-zlib":
        flat = np.cumsum(np.frombuffer(raw, dtype="<i4").astype(np.int64))
        return flat.astype(np.dtype(p.dtype)).reshape(p.shape)
    return (
        np.frombuffer(raw, dtype=np.dtype(p.dtype)).reshape(p.shape).copy()
    )


def _walk_payload(obj, leaf):
    """Structure-preserving map over the gather payload containers (dict /
    list / tuple); everything else is a leaf. Symmetric for pack and
    unpack, so round-tripping preserves the payload's exact shape."""
    if isinstance(obj, dict):
        return {k: _walk_payload(v, leaf) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_walk_payload(v, leaf) for v in obj)
    return leaf(obj)


def maybe_init_from_env() -> bool:
    """Join the ``jax.distributed`` coordinator described by
    ``KSIM_DCN_COORD`` / ``KSIM_DCN_NPROC`` / ``KSIM_DCN_PID`` (set by
    scripts/dcn_launch.py; the bare ``DCN_*`` spellings of the test
    harness are honored too). Returns True when a multi-process setup was
    initialized.

    Ordering contract: the persistent compile cache is configured FIRST —
    ``compile_cache.enable()`` must precede ``jax.distributed.initialize``
    (it reads config/env only, never initializes the backend; pinned by
    tests/test_dcn_units.py)."""
    coord = os.environ.get("KSIM_DCN_COORD") or os.environ.get("DCN_COORD")
    nproc = int(
        os.environ.get("KSIM_DCN_NPROC") or os.environ.get("DCN_NPROC") or 0
    )
    if not coord or nproc <= 1:
        return False
    from ..utils.compile_cache import enable as _cc

    _cc()  # BEFORE initialize — see docstring
    pid = int(
        os.environ.get("KSIM_DCN_PID") or os.environ.get("DCN_PID") or 0
    )
    from .mesh import init_distributed

    init_distributed(
        coordinator_address=coord, num_processes=nproc, process_id=pid
    )
    return True


def active() -> bool:
    """True in a multi-process (DCN) run."""
    import jax

    return jax.process_count() > 1


def process_info() -> tuple:
    import jax

    return jax.process_count(), jax.process_index()


def spare_count() -> int:
    """Processes at the TAIL of the pid range that own no scenario block
    (``KSIM_DCN_SPARES``, round 15). Spares skip the chunk loop, sit in
    the gather, and exist only to claim dead/straggling workers' blocks
    — the ``--elastic`` late-joiner capacity of scripts/dcn_launch.py."""
    try:
        return max(int(os.environ.get("KSIM_DCN_SPARES", "0")), 0)
    except ValueError:
        return 0


def worker_count() -> int:
    """Processes that own a scenario block (process_count - spares)."""
    nproc, _ = process_info()
    return max(nproc - spare_count(), 1)


def is_spare() -> bool:
    _, pid = process_info()
    return pid >= worker_count()


def local_slice(n_global: int) -> slice:
    """This process's contiguous block of a length-``n_global`` leading
    axis (requires ``n_global % worker_count == 0``). The block order
    matches a global ``make_mesh()`` scenario sharding: ``jax.devices()``
    orders devices by process, so process p's local shards hold exactly
    rows ``[p*n/np, (p+1)*n/np)`` — which is what makes the sliced run's
    concatenated results bit-identical to the single-process mesh run.

    Spare processes (round 15) own nothing; they are handed the LAST
    worker's block purely so engine construction sees valid shapes —
    ``WhatIfEngine`` marks them ``_dcn_spare`` and never runs the chunks."""
    workers = worker_count()
    _, pid = process_info()
    per = n_global // workers
    p = min(pid, workers - 1)
    return slice(p * per, (p + 1) * per)


def localize_mesh(mesh):
    """Restrict a (possibly cross-process) mesh to THIS process's devices,
    preserving axis names. Identity for None / already-local meshes.

    This is the heart of the round-11 DCN design: the engine slices the
    scenario axis per process and runs the same shard_map chunk programs
    over a LOCAL mesh — every shard addressable, per-chunk device→host
    traffic process-local, no cross-process XLA computation anywhere."""
    from .mesh import spans_processes

    if mesh is None or not spans_processes(mesh):
        return mesh
    import jax
    from jax.sharding import Mesh

    me = jax.process_index()
    mine = [d for d in mesh.devices.flat if d.process_index == me]
    if not mine:
        raise ValueError(
            "mesh has no devices addressable from process "
            f"{me} — every process must contribute devices to a DCN mesh"
        )
    return Mesh(np.array(mine), mesh.axis_names)


def _client():
    from jax._src import distributed

    c = distributed.global_state.client
    if c is None:
        raise RuntimeError(
            "jax.distributed is not initialized — call "
            "parallel.mesh.init_distributed (or run under "
            "scripts/dcn_launch.py) before gathering"
        )
    # Round 17: every KV touch flows through here, so this is the single
    # injection point for faultline's deterministic fault schedules.
    # Identity when KSIM_FAULTLINE is off.
    from . import faultline

    return faultline.wrap_kv(c)


def _timeout_ms() -> int:
    return int(float(os.environ.get("KSIM_DCN_TIMEOUT_S", "300")) * 1000)


# -- bounded KV retries (round 17) -------------------------------------------
#
# Before faultline, every coordination-plane KV call was a single
# unretried gRPC round trip — one transient error could fail a heartbeat,
# lose a claim, or abort the end gather. kv_retry is THE retry policy:
# bounded attempts, exponential backoff with jitter, and an attributed
# DcnRetryError on give-up. Applied to heartbeats, claims, checkpoint
# publication and the gather publication; the gather's GETs keep their
# own poll loop (_get_attributed), which already retries by construction.

RETRY_STATS = {"attempts": 0, "retries": 0, "giveups": 0, "backoff_s": 0.0}


def retry_stats() -> dict:
    """Snapshot of :data:`RETRY_STATS` (copy — callers diff it)."""
    return dict(RETRY_STATS)


class DcnRetryError(RuntimeError):
    """A bounded KV retry gave up. Carries the operation, key, attempt
    count and last error so a fleet failure is attributed to the exact
    coordination op that exhausted its budget."""

    def __init__(self, op: str, key: str, attempts: int, elapsed_s: float, last):
        super().__init__(
            f"dcn: {op} on {key!r} gave up after {attempts} attempts over "
            f"{elapsed_s:.2f}s of bounded backoff "
            f"(KSIM_DCN_RETRIES/KSIM_DCN_RETRY_BASE_S); last error: {last!r}"
        )
        self.op = op
        self.key = key
        self.attempts = attempts
        self.last = last


def _retry_attempts() -> int:
    try:
        return max(int(os.environ.get("KSIM_DCN_RETRIES", "4")), 1)
    except ValueError:
        return 4


def _retry_base_s() -> float:
    return float(os.environ.get("KSIM_DCN_RETRY_BASE_S", "0.05"))


def _retry_cap_s() -> float:
    return float(os.environ.get("KSIM_DCN_RETRY_CAP_S", "2.0"))


def kv_retry(
    fn,
    *,
    op: str,
    key: str = "",
    attempts: Optional[int] = None,
    base_s: Optional[float] = None,
    cap_s: Optional[float] = None,
    sleep=time.sleep,
    jitter=None,
):
    """Run ``fn()`` with bounded exponential backoff + jitter.

    Delay before retry k (0-based) is ``min(cap_s, base_s * 2**k) * u``
    with ``u`` uniform in [0.5, 1.0] — full-jitter-lite, bounded both
    sides so tests can pin the envelope. ``sleep``/``jitter`` are
    injectable for the timing-bound unit tests. Raises
    :class:`DcnRetryError` after the last attempt fails."""
    n = _retry_attempts() if attempts is None else max(int(attempts), 1)
    base = _retry_base_s() if base_s is None else float(base_s)
    cap = _retry_cap_s() if cap_s is None else float(cap_s)
    rnd = random.random if jitter is None else jitter
    t0 = time.monotonic()
    last = None
    for k in range(n):
        try:
            out = fn()
        except Exception as e:
            RETRY_STATS["attempts"] += 1
            last = e
            if k + 1 >= n:
                break
            RETRY_STATS["retries"] += 1
            d = min(cap, base * (2.0 ** k)) * (0.5 + 0.5 * rnd())
            RETRY_STATS["backoff_s"] += d
            sleep(d)
        else:
            RETRY_STATS["attempts"] += 1
            return out
    RETRY_STATS["giveups"] += 1
    raise DcnRetryError(op, key, n, time.monotonic() - t0, last)


# -- liveness heartbeats (round 12) -----------------------------------------
#
# Each process overwrites ONE key (``ksim/hb/<pid>``) with a small JSON
# progress beacon on a chunk cadence. Plain KV puts — no barrier, no
# blocking read, never counted by GATHER_COUNT — so the "one gather per
# replay" contract is untouched. Readers (the attributed gather timeout
# below, and out-of-fleet monitors via the KSIM_DCN_HB_DIR file mirror)
# see at most one stale beacon per process, never a backlog.

HB_PREFIX = "ksim/hb"


def heartbeat_every() -> int:
    """Chunk cadence for :func:`heartbeat` publication
    (``KSIM_DCN_HEARTBEAT_EVERY``, default every chunk; 0 disables)."""
    return int(os.environ.get("KSIM_DCN_HEARTBEAT_EVERY", "1"))


def _stall_s() -> float:
    """Beacon age beyond which a silent sibling is presumed dead
    (``KSIM_DCN_STALL_S``). The default is generous relative to the
    per-chunk cadence: a chunk that takes a minute of wall clock without
    a beat means the process is gone, not slow."""
    return float(os.environ.get("KSIM_DCN_STALL_S", "60"))


def _poll_s() -> float:
    """Inner poll interval of the attributed gather wait
    (``KSIM_DCN_POLL_S``)."""
    return float(os.environ.get("KSIM_DCN_POLL_S", "2"))


def heartbeat(
    chunk: int,
    total: Optional[int] = None,
    block: Optional[tuple] = None,
    wall_s: Optional[float] = None,
    phases: Optional[Dict[str, float]] = None,
    state: str = "run",
    extra: Optional[dict] = None,
) -> bool:
    """Publish this process's progress beacon: last completed ``chunk``
    (−1 before the first), global scenario ``block`` ``(lo, hi)``,
    wall-clock seconds, a phase-timer snapshot, and a live-buffer gauge.
    Defensive by design — a heartbeat failure must never kill a replay —
    and a no-op outside multi-process runs. Returns True when published."""
    try:
        nproc, pid = process_info()
    except Exception:
        return False
    if nproc <= 1:
        return False
    beat: dict = {
        "pid": int(pid),
        "chunk": int(chunk),
        "state": str(state),
        "t": time.time(),
    }
    if total is not None:
        beat["total_chunks"] = int(total)
    if block is not None:
        beat["block"] = [int(block[0]), int(block[1])]
    if wall_s is not None:
        beat["wall_s"] = round(float(wall_s), 3)
    if phases:
        beat["phases"] = {k: round(float(v), 6) for k, v in phases.items()}
    try:  # live-buffer gauge (cheap count; bytes are the bench's job)
        import jax

        beat["live_buffers"] = len(jax.live_arrays())
    except Exception:
        pass
    if extra:
        beat.update(extra)
    blob = json.dumps(beat, sort_keys=True)
    from . import faultline

    hb_dir = os.environ.get("KSIM_DCN_HB_DIR")
    if hb_dir:
        # File mirror for monitors OUTSIDE the fleet (dcn_launch --watch):
        # the launcher parent never joins the coordination service, so it
        # tails these instead. Atomic replace — readers never see a torn
        # write (faultline may still tear the PAYLOAD to exercise reader
        # tolerance; monitors must treat unparseable beacons as absent).
        try:
            os.makedirs(hb_dir, exist_ok=True)
            tmp = os.path.join(hb_dir, f".p{pid}.tmp")
            with open(tmp, "w") as f:
                f.write(faultline.file_blob(blob))
            os.replace(tmp, os.path.join(hb_dir, f"p{pid}.json"))
        except OSError:
            pass
    key = f"{HB_PREFIX}/{pid}"
    ok = True
    try:
        # Beacons are frequent and best-effort: a short retry budget
        # absorbs a transient blip, a give-up just means one stale beat.
        kv_retry(
            lambda: _client().key_value_set(key, blob, allow_overwrite=True),
            op="heartbeat",
            key=key,
            attempts=2,
        )
    except Exception:
        ok = False
    # Kill schedules fire on the heartbeat cursor whether or not the
    # publish landed — a deterministic schedule must not drift because a
    # transient KV error ate one beat.
    faultline.maybe_kill(int(chunk), str(state))
    return ok


def maybe_heartbeat(chunk_done: int, every: Optional[int] = None, **kw) -> bool:
    """Cadence gate for :func:`heartbeat`: publish when ``chunk_done + 1``
    is a multiple of ``every`` (so the ``chunk_done=-1`` start-of-replay
    beacon always publishes, and every=1 beats on every chunk)."""
    if every is None:
        every = heartbeat_every()
    if every <= 0:
        return False
    if (int(chunk_done) + 1) % every:
        return False
    return heartbeat(chunk_done, **kw)


def read_heartbeats() -> Dict[int, dict]:
    """All published beacons, ``{pid: beat}``. Empty on any failure —
    callers treat a missing beacon as \"no evidence\", not as death."""
    try:
        entries = kv_retry(
            lambda: _client().key_value_dir_get(HB_PREFIX),
            op="read_heartbeats",
            key=HB_PREFIX,
            attempts=2,
        )
    except Exception:
        return {}
    out: Dict[int, dict] = {}
    for key, val in entries:
        tail = str(key).rsplit("/", 1)[-1]
        try:
            out[int(tail)] = json.loads(val)
        except (ValueError, TypeError):
            continue
    return out


# -- recoverable work-queue (round 15) ---------------------------------------
#
# The static "process p owns block p forever" slicing becomes recoverable:
# workers periodically publish compressed checkpoint blobs of their block
# state to the KV store (riding the round-14 delta+zlib codec), and a
# survivor that detects a stale sibling beacon while sitting in the gather
# CLAIMS the dead process's block (compare-and-set on a write-once key —
# single-claimant), re-executes it from the newest checkpoint, and
# publishes the dead pid's gather payload in its stead. Everything is
# deterministic, so the gathered result is byte-identical to a no-failure
# run. All of it is opt-in: with KSIM_DCN_RECOVER unset the round-12
# attributed DcnGatherTimeout behavior is unchanged.

CKPT_PREFIX = "ksim/ckpt"
CLAIM_PREFIX = "ksim/claim"


def recover_enabled() -> bool:
    """Survivor rebalance on a stale beacon (``KSIM_DCN_RECOVER``;
    default off — the round-12 attributed fail-fast stays the default)."""
    return str(
        os.environ.get("KSIM_DCN_RECOVER", "0")
    ).strip().lower() in ("1", "true", "yes", "on")


def ckpt_every() -> int:
    """Chunk cadence for :func:`publish_checkpoint` (``KSIM_DCN_CKPT_EVERY``,
    default 0 = no checkpoint publication; recovery then re-executes a
    claimed block from chunk 0 — still byte-identical, just slower)."""
    try:
        return max(int(os.environ.get("KSIM_DCN_CKPT_EVERY", "0")), 0)
    except ValueError:
        return 0


def max_claims() -> int:
    """Claim generations per dead block (``KSIM_DCN_MAX_CLAIMS``): if the
    claimant of generation g itself goes stale mid-recovery, survivors
    open generation g+1, up to this cap (then the attributed timeout)."""
    try:
        return max(int(os.environ.get("KSIM_DCN_MAX_CLAIMS", "2")), 1)
    except ValueError:
        return 2


def _encode_payload(payload) -> list:
    """pack → pickle → base64 → gRPC-cap-sized chunks (shared by the
    gather publication and the checkpoint blobs)."""
    packed = _walk_payload(payload, _pack_leaf)
    blob = base64.b64encode(
        pickle.dumps(packed, protocol=pickle.HIGHEST_PROTOCOL)
    ).decode("ascii")
    return [
        blob[i : i + _KV_CHUNK] for i in range(0, len(blob), _KV_CHUNK)
    ] or [""]


def _decode_payload(chunks) -> object:
    return _walk_payload(
        pickle.loads(base64.b64decode("".join(chunks))), _unpack_leaf
    )


# -- checkpoint blob integrity (round 17) ------------------------------------
#
# Checkpoint chunks carried no integrity check: a torn or corrupted KV
# value (publisher dying mid-blob, a flipped byte anywhere in transit or
# storage) either crashed the unpickle or — worse — silently resumed bad
# state. Every chunk is now framed ``kf1:<crc32>:<len>:<data>`` and the
# manifest (written LAST) is JSON carrying the chunk count plus the
# crc32/length of the whole reassembled blob. load_checkpoint validates
# both layers and on ANY mismatch falls back to the newest PRIOR complete
# cursor (counted in CRC_STATS["fallbacks"]) instead of crashing.

_FRAME_MAGIC = "kf1"

# frames_ok/frames_bad: per-chunk validation outcomes; fallbacks: cursors
# skipped (torn/corrupt/undecodable) on the way to a usable checkpoint.
CRC_STATS = {"frames_ok": 0, "frames_bad": 0, "fallbacks": 0}


def crc_stats() -> dict:
    """Snapshot of :data:`CRC_STATS` (copy — callers diff it)."""
    return dict(CRC_STATS)


def _frame_chunk(data: str) -> str:
    """Wrap one checkpoint chunk in the CRC32+length frame."""
    crc = zlib.crc32(data.encode("ascii")) & 0xFFFFFFFF
    return f"{_FRAME_MAGIC}:{crc:08x}:{len(data)}:{data}"


def _unframe_chunk(framed: str) -> str:
    """Validate + strip one frame; ValueError on torn/truncated/corrupt."""
    magic, _, rest = framed.partition(":")
    if magic != _FRAME_MAGIC or not rest:
        raise ValueError("checkpoint chunk is not framed (torn header?)")
    crc_s, _, rest = rest.partition(":")
    len_s, sep, data = rest.partition(":")
    if not sep:
        raise ValueError("checkpoint chunk frame is truncated")
    if len(data) != int(len_s):
        raise ValueError(
            f"checkpoint chunk length mismatch: framed {int(len_s)}, "
            f"got {len(data)} (torn write)"
        )
    if (zlib.crc32(data.encode("ascii")) & 0xFFFFFFFF) != int(crc_s, 16):
        raise ValueError("checkpoint chunk CRC32 mismatch (corrupt blob)")
    return data


def _mirror_event(event: dict) -> None:
    """Append one claim/recovery event line to ``$KSIM_DCN_HB_DIR/
    events.jsonl`` so out-of-fleet monitors (dcn_launch --watch) can
    surface a rebalance live. Best-effort; single ``write`` of one line
    keeps concurrent appenders from tearing each other."""
    hb_dir = os.environ.get("KSIM_DCN_HB_DIR")
    if not hb_dir:
        return
    try:
        os.makedirs(hb_dir, exist_ok=True)
        line = json.dumps(dict(event, t=time.time()), sort_keys=True)
        with open(os.path.join(hb_dir, "events.jsonl"), "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


# Pids this process observed dead past the stall window with recovery on
# (claimed by us or by a sibling). Non-empty ⇒ the fleet is DEGRADED: the
# collective jax.distributed shutdown can never complete (a dead task
# never joins the shutdown barrier) and must be skipped at exit.
DEGRADED: set = set()
_EXIT_CODE = [0]
_degraded_exit_armed = [False]


def _arm_degraded_exit() -> None:
    """A fleet that lost a process must never reach the jax.distributed
    client teardown: the dead task cannot join the shutdown barrier, and
    the coordination service's propagated error ABORTS every healthy
    task (xla's client.h "Terminating process ... fatal errors" —
    SIGABRT after the survivor already printed its byte-identical
    result). Armed the moment a stale sibling is detected with recovery
    on: an atexit hook — registered after jax's machinery, so it runs
    FIRST — flushes stdio and hard-exits. An uncaught exception still
    exits nonzero (sys.excepthook runs before atexit and records it)."""
    if _degraded_exit_armed[0]:
        return
    _degraded_exit_armed[0] = True
    import atexit
    import sys

    prev_hook = sys.excepthook

    def _failing_hook(tp, val, tb):
        _EXIT_CODE[0] = 1
        prev_hook(tp, val, tb)

    sys.excepthook = _failing_hook

    def _hard_exit():
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        os._exit(_EXIT_CODE[0])

    atexit.register(_hard_exit)


def checkpoint_epoch() -> int:
    """Namespace for this replay's checkpoints: the sequence number the
    end-of-replay gather WILL use (``_seq + 1``). Keeps a resumed claim
    from ever reading a previous replay's blobs."""
    return _seq + 1


def gather_seq() -> int:
    """Sequence number of the gather currently in flight — equal to the
    epoch under which this replay's checkpoints were published. Valid
    while inside :func:`gather` (recovery callbacks capture it so the
    resume path reads THIS replay's blobs, not a previous one's)."""
    return _seq


# Cumulative checkpoint-publication accounting for THIS process (flight
# recorder round 16): number of publications, wall spent encoding +
# pushing KV chunks, and encoded bytes on the wire. Read via
# :func:`publish_stats`; the flight recorder diffs it per chunk.
PUBLISH_STATS = {"count": 0, "wall_s": 0.0, "bytes": 0}


def publish_stats() -> dict:
    """Snapshot of :data:`PUBLISH_STATS` (copy — callers diff it)."""
    return dict(PUBLISH_STATS)


def publish_checkpoint(
    cursor: int, payload, block: tuple, epoch: Optional[int] = None
) -> bool:
    """Publish this process's block-state checkpoint at chunk ``cursor``
    under ``ksim/ckpt/<epoch>/<pid>/<lo>-<hi>/<cursor>``. Round 17: every
    chunk is CRC32+length framed and the manifest key (``/n``, written
    LAST so a reader that finds one never sees an in-flight blob) is JSON
    carrying the chunk count plus whole-blob crc/length — a torn or
    corrupted chunk is detected on load, not resumed. Defensive like
    :func:`heartbeat`: returns False (never raises) outside DCN or when
    the bounded KV retries give up.

    Each successful publication is clocked into :data:`PUBLISH_STATS`
    (encode + KV push wall, encoded bytes) and mirrored as a
    ``ckpt_publish`` event for ``dcn_launch --watch``."""
    try:
        nproc, pid = process_info()
        if nproc <= 1:
            return False
        t0 = time.perf_counter()
        c = _client()
        raw_chunks = _encode_payload(payload)
        blob_len = sum(len(ch) for ch in raw_chunks)
        blob_crc = 0
        for ch in raw_chunks:
            blob_crc = zlib.crc32(ch.encode("ascii"), blob_crc)
        chunks = [_frame_chunk(ch) for ch in raw_chunks]
        manifest = json.dumps(
            {"n": len(chunks), "crc": f"{blob_crc & 0xFFFFFFFF:08x}",
             "len": blob_len},
            sort_keys=True,
        )
        lo, hi = int(block[0]), int(block[1])
        ep = checkpoint_epoch() if epoch is None else int(epoch)
        prefix = f"{CKPT_PREFIX}/{ep}/{pid}/{lo}-{hi}/{int(cursor)}"
        for j, ch in enumerate(chunks):
            kv_retry(
                lambda k=f"{prefix}/{j}", v=ch: c.key_value_set(
                    k, v, allow_overwrite=True
                ),
                op="publish_checkpoint",
                key=f"{prefix}/{j}",
            )
        kv_retry(
            lambda: c.key_value_set(
                f"{prefix}/n", manifest, allow_overwrite=True
            ),
            op="publish_checkpoint",
            key=f"{prefix}/n",
        )
        wall = time.perf_counter() - t0
        nbytes = sum(len(ch) for ch in chunks)
        PUBLISH_STATS["count"] += 1
        PUBLISH_STATS["wall_s"] += wall
        PUBLISH_STATS["bytes"] += nbytes
        _mirror_event(
            {
                "kind": "ckpt_publish",
                "pid": pid,
                "cursor": int(cursor),
                "bytes": nbytes,
                "wall_s": round(wall, 6),
            }
        )
        return True
    except Exception:
        return False


def load_checkpoint(
    pid: int, epoch: Optional[int] = None, before_cursor: Optional[int] = None
):
    """Newest VALID checkpoint published by ``pid`` this replay:
    ``{"cursor", "block": (lo, hi), "payload"}``, or None when nothing
    usable exists (the claimant then re-executes from chunk 0). One
    directory read, no blocking waits — the publisher is dead.

    Round 17: candidates are walked newest-cursor-first and each must
    pass the full integrity stack — JSON manifest (chunk count + whole-
    blob crc32/length), per-chunk CRC32+length frames, and payload
    decode. Any failure logs, bumps ``CRC_STATS["fallbacks"]`` and moves
    on to the next older cursor, so a torn/corrupt newest blob degrades
    to the prior complete checkpoint instead of crashing or silently
    resuming bad state. ``before_cursor`` restricts to strictly older
    cursors — the resume path in sim/whatif.py uses it to retry with an
    older blob when a decoded payload turns out unusable (signature or
    carrier-shape mismatch)."""
    try:
        c = _client()
        ep = checkpoint_epoch() if epoch is None else int(epoch)
        entries = kv_retry(
            lambda: c.key_value_dir_get(f"{CKPT_PREFIX}/{ep}/{int(pid)}"),
            op="load_checkpoint",
            key=f"{CKPT_PREFIX}/{ep}/{int(pid)}",
            attempts=2,
        )
    except Exception:
        return None
    from ..utils.metrics import log

    table: Dict[tuple, Dict[str, str]] = {}
    for key, val in entries:
        parts = str(key).strip("/").split("/")
        if len(parts) < 3:
            continue
        blk, cur, leaf = parts[-3], parts[-2], parts[-1]
        table.setdefault((blk, cur), {})[leaf] = val
    candidates = []
    for (blk, cur), kv in table.items():
        if "n" not in kv:
            continue  # manifest not yet written — in-flight blob
        try:
            cursor = int(cur)
            lo, hi = (int(x) for x in blk.split("-"))
        except ValueError:
            continue
        if before_cursor is not None and cursor >= int(before_cursor):
            continue
        candidates.append((cursor, (lo, hi), kv))
    for cursor, block, kv in sorted(candidates, reverse=True):
        try:
            man = json.loads(kv["n"])
            if isinstance(man, dict):
                n = int(man["n"])
                want_crc, want_len = man.get("crc"), man.get("len")
            else:  # legacy bare-int manifest (pre-round-17 blobs)
                n, want_crc, want_len = int(man), None, None
            chunks = []
            for j in range(n):
                ch = kv[str(j)]
                if want_crc is not None:
                    ch = _unframe_chunk(ch)
                chunks.append(ch)
            CRC_STATS["frames_ok"] += len(chunks) if want_crc is not None else 0
            if want_crc is not None:
                crc = 0
                for ch in chunks:
                    crc = zlib.crc32(ch.encode("ascii"), crc)
                if (
                    f"{crc & 0xFFFFFFFF:08x}" != want_crc
                    or sum(len(ch) for ch in chunks) != int(want_len)
                ):
                    raise ValueError(
                        "manifest crc/length mismatch over reassembled blob"
                    )
            payload = _decode_payload(chunks)
        except Exception as e:
            CRC_STATS["frames_bad"] += 1
            CRC_STATS["fallbacks"] += 1
            log.warning(
                "dcn: process %d's checkpoint at cursor %d failed "
                "validation (%s) — falling back to the prior complete "
                "checkpoint", int(pid), cursor, e,
            )
            continue
        return {"cursor": cursor, "block": block, "payload": payload}
    return None


def try_claim(dead_pid: int, gen: int, name: str = "whatif") -> bool:
    """Compare-and-set claim on ``dead_pid``'s block for the CURRENT
    gather: ``key_value_set`` without ``allow_overwrite`` fails when the
    key exists, so exactly one process wins generation ``gen``. Claim
    metadata (claimant pid, block owner, generation, wall time) is the
    value, for attribution of a second failure during recovery.

    Round 17: the CAS runs under :func:`kv_retry`, and a failure no
    longer short-circuits to "lost" — a transient error is ambiguous
    (the set may have landed before the error surfaced), so the claim
    key is read back and the VALUE decides. Only a readable claim naming
    another pid is a genuine loss; an unreadable key reads as lost too
    (the poll loop re-enters the claim protocol and settles it)."""
    nproc, pid = process_info()
    meta = {
        "claimant": int(pid),
        "for": int(dead_pid),
        "gen": int(gen),
        "t": time.time(),
    }
    key = f"{CLAIM_PREFIX}/{_seq}/{name}/{int(dead_pid)}/{int(gen)}"
    try:
        kv_retry(
            lambda: _client().key_value_set(
                key, json.dumps(meta, sort_keys=True)
            ),
            op="claim",
            key=key,
        )
        return True
    except Exception:
        pass
    claim = read_claim(dead_pid, gen, name=name)
    return claim is not None and int(claim.get("claimant", -1)) == int(pid)


def read_claim(dead_pid: int, gen: int, name: str = "whatif"):
    """Metadata of an existing claim (None when absent/unreadable)."""
    try:
        val = _client().blocking_key_value_get(
            f"{CLAIM_PREFIX}/{_seq}/{name}/{int(dead_pid)}/{int(gen)}",
            2000,
        )
        return json.loads(val)
    except Exception:
        return None


class DcnGatherTimeout(RuntimeError):
    """gather() abandoned: a sibling never published its payload. Carries
    the missing pids and the heartbeat table for programmatic use."""

    def __init__(self, msg, missing=None, heartbeats=None):
        super().__init__(msg)
        self.missing = list(missing or [])
        self.heartbeats = dict(heartbeats or {})


def _describe_process(p: int, hb: Dict[int, dict], now: float) -> str:
    b = hb.get(p)
    if b is None:
        return f"process {p}: no heartbeat ever received"
    age = max(0.0, now - float(b.get("t", now)))
    parts = [f"process {p}: last heartbeat {age:.1f}s ago"]
    chunk = b.get("chunk", "?")
    total = b.get("total_chunks")
    parts.append(
        f"last completed chunk {chunk}"
        + (f"/{total}" if total is not None else "")
    )
    parts.append(f"state={b.get('state', '?')}")
    if "block" in b:
        lo, hi = b["block"]
        parts.append(f"scenario block [{lo}, {hi})")
    return ", ".join(parts)


def _publish_for(c, prefix: str, pid: int, payload) -> None:
    """Publish a gather payload under ``pid``'s keys (used by a claimant
    standing in for a dead sibling, and by :func:`gather` itself). When
    recovery is enabled an already-existing key is tolerated: a presumed-
    dead straggler that publishes after its block was absorbed collides
    with the claimant's byte-identical publication — first writer wins."""
    chunks = _encode_payload(payload)
    tolerant = recover_enabled()
    try:
        for j, ch in enumerate(chunks):
            kv_retry(
                lambda k=f"{prefix}/{pid}/{j}", v=ch: c.key_value_set(k, v),
                op="gather_publish",
                key=f"{prefix}/{pid}/{j}",
            )
        kv_retry(
            lambda: c.key_value_set(f"{prefix}/{pid}/n", str(len(chunks))),
            op="gather_publish",
            key=f"{prefix}/{pid}/n",
        )
    except DcnRetryError:
        if not tolerant:
            raise  # attributed give-up — op/key/attempts in the message
        from ..utils.metrics import log

        log.warning(
            "dcn: gather keys for process %d already exist — block was "
            "published by another claimant (or the straggler itself); "
            "keeping the first write",
            pid,
        )


def _maybe_recover(c, prefix: str, p: int, name: str, recover) -> bool:
    """Survivor rebalance (round 15): ``p``'s beacon is stale and recovery
    is on. Claim generations 0..max_claims-1 of ``p``'s block; on a CAS
    win, rebuild the block via ``recover(p, gen)`` (checkpoint resume
    inside)
    and publish it under ``p``'s gather keys. On a CAS loss, defer to a
    LIVE claimant (keep polling for its publication); a claimant that is
    itself stale opens the next generation — the second-failure-during-
    recovery path. Returns False when generations are exhausted (caller
    raises the attributed timeout)."""
    from ..utils.metrics import log

    _, me = process_info()
    stall = _stall_s()
    for gen in range(max_claims()):
        # Coordinator claims LAST (round 17): process 0 hosts the
        # jax.distributed coordination service — the one process whose
        # death the fleet can never survive. Re-executing a dead block
        # is exactly the work most likely to die again under fault
        # pressure, so while any OTHER live worker could absorb it,
        # give them one stall window to claim first. With no live
        # sibling left (or the window expired unclaimed) process 0
        # claims as before — liveness is unchanged.
        if me == 0 and read_claim(p, gen, name=name) is None:
            deadline = time.monotonic() + stall
            while time.monotonic() < deadline:
                now = time.time()
                others = [
                    q for q, b in read_heartbeats().items()
                    if q not in (me, p) and q not in DEGRADED
                    and now - float(b.get("t", 0.0)) <= stall
                ]
                if not others:
                    break
                time.sleep(_poll_s())
                if read_claim(p, gen, name=name) is not None:
                    break
        if try_claim(p, gen, name=name):
            log.warning(
                "dcn: process %d claims dead process %d's block "
                "(gen %d) — resuming from its newest checkpoint",
                me, p, gen,
            )
            _mirror_event(
                {"event": "claim", "claimant": int(me), "for": int(p),
                 "gen": int(gen)}
            )
            t0 = time.monotonic()
            # Claim-generation fencing (round 17): the generation rides
            # into the recovery engine so telemetry can attribute which
            # claim attempt produced the block — gen > 0 means an earlier
            # claimant died mid-recovery and this is the hand-off.
            payload = recover(p, gen)
            _publish_for(c, prefix, p, payload)
            log.warning(
                "dcn: process %d resumed and republished process %d's "
                "block in %.1fs", me, p, time.monotonic() - t0,
            )
            _mirror_event(
                {"event": "recovered", "claimant": int(me), "for": int(p),
                 "gen": int(gen),
                 "wall_s": round(time.monotonic() - t0, 3)}
            )
            return True
        claim = read_claim(p, gen, name=name)
        claimant = None if claim is None else int(claim.get("claimant", -1))
        if claimant is None or claimant == me:
            return True  # our own (or unreadable) claim — poll for keys
        # A claim younger than the stall window gets the benefit of the
        # doubt even without a fresh beacon — the claimant may still be
        # building its recovery engine (compile warm-up beats nothing).
        claim_age = time.time() - float(claim.get("t", 0.0))
        b = read_heartbeats().get(claimant)
        beat_age = (
            None if b is None else time.time() - float(b.get("t", 0.0))
        )
        if claim_age <= stall or beat_age is None or beat_age <= stall:
            return True  # live claimant is recovering — wait for it
        # Claimant died mid-recovery too: open the next generation.
        log.warning(
            "dcn: claimant %d of process %d's block (gen %d) went stale "
            "itself — opening generation %d", claimant, p, gen, gen + 1,
        )
    return False


def _get_attributed(c, key: str, p: int, name: str, recover=None):
    """``blocking_key_value_get`` as a short poll loop: each expiry
    inspects sibling heartbeats. A sibling whose beacon has gone stale
    past KSIM_DCN_STALL_S while we sit in the gather is presumed dead.
    With recovery off (default) the wait is abandoned IMMEDIATELY with an
    attributed :class:`DcnGatherTimeout` — instead of the anonymous hang
    to the full KSIM_DCN_TIMEOUT_S. With KSIM_DCN_RECOVER on and a
    ``recover`` callback, the dead block is claimed and re-executed
    (:func:`_maybe_recover`) and the wait continues. A sibling with a
    fresh beacon (or none at all — heartbeats may be disabled) keeps the
    round-11 semantics: wait to the full deadline, then raise with
    whatever attribution exists."""
    deadline = time.monotonic() + _timeout_ms() / 1000.0
    poll_ms = max(int(_poll_s() * 1000), 50)
    stall = _stall_s()
    prefix = key.rsplit("/", 2)[0]
    while True:
        remaining_ms = int((deadline - time.monotonic()) * 1000)
        if remaining_ms <= 0:
            hb = read_heartbeats()
            raise DcnGatherTimeout(
                f"gather({name!r}): timed out after "
                f"KSIM_DCN_TIMEOUT_S={_timeout_ms() / 1000:g}s waiting for "
                f"{_describe_process(p, hb, time.time())}. The fleet must "
                "be restarted together (scripts/dcn_launch.py).",
                missing=[p],
                heartbeats=hb,
            )
        try:
            return c.blocking_key_value_get(key, min(poll_ms, remaining_ms))
        except Exception:
            hb = read_heartbeats()
            b = hb.get(p)
            if b is not None and (
                time.time() - float(b.get("t", 0.0))
            ) > stall:
                if recover is not None and recover_enabled():
                    DEGRADED.add(p)
                    _arm_degraded_exit()
                    if _maybe_recover(c, prefix, p, name, recover):
                        continue  # claimed/claimant publishing — poll again
                raise DcnGatherTimeout(
                    f"gather({name!r}): process {p} looks DEAD — "
                    f"{_describe_process(p, hb, time.time())}; its beacon "
                    "stopped advancing for more than "
                    f"KSIM_DCN_STALL_S={stall:g}s while this process is "
                    "already in the end-of-replay gather. The scenario "
                    "axis has a hole; restart the fleet together "
                    "(scripts/dcn_launch.py).",
                    missing=[p],
                    heartbeats=hb,
                )
            # Fresh beacon (sibling alive but slower) or no beacon at all
            # (heartbeats disabled) — keep waiting toward the deadline.


def gather(name: str, payload, recover=None) -> list:
    """THE cross-process gather: publish this process's ``payload`` and
    return every process's, in process order. Called at most once per
    replay (result assembly); the chunk loop never reaches it.

    Payloads are pickled (numpy arrays, dataclasses — trusted sibling
    processes of the same program), base64-encoded and chunked under the
    coordination service's gRPC message cap. Keys carry a monotonically
    increasing sequence number, so repeated replays in one process
    lifetime never collide — provided every process gathers in the same
    order (SPMD discipline, same as collectives).

    ``recover`` (round 15): ``recover(dead_pid, gen) -> payload`` rebuilds
    a dead sibling's block deterministically (``gen`` is the claim
    generation, round 17). With KSIM_DCN_RECOVER on, a
    stale beacon routes through the claim protocol (:func:`_maybe_recover`)
    instead of raising, and the gather still completes in full."""
    global GATHER_COUNT, _seq
    nproc, pid = process_info()
    _seq += 1
    GATHER_COUNT += 1
    c = _client()
    # Round 14: delta+zlib the large integer tensors before the KV put —
    # remote payloads decode through _unpack_leaf in _decode_payload; the
    # LOCAL payload is returned as-is (it never crosses the wire), so
    # compression is invisible to callers either way.
    raw0, comp0 = COMPRESS_BYTES
    prefix = f"ksim/gather/{_seq}/{name}"
    _publish_for(c, prefix, pid, payload)
    if COMPRESS_BYTES[0] > raw0:
        from ..utils.metrics import log

        log.info(
            "gather(%s): compressed %.1f KiB of int tensors to %.1f KiB "
            "(%.1fx) before the KV put",
            name,
            (COMPRESS_BYTES[0] - raw0) / 1024,
            (COMPRESS_BYTES[1] - comp0) / 1024,
            (COMPRESS_BYTES[0] - raw0) / max(COMPRESS_BYTES[1] - comp0, 1),
        )
    out = []
    for p in range(nproc):
        if p == pid:
            out.append(payload)
            continue
        n = int(
            _get_attributed(c, f"{prefix}/{p}/n", p, name, recover=recover)
        )
        out.append(
            _decode_payload(
                _get_attributed(
                    c, f"{prefix}/{p}/{j}", p, name, recover=recover
                )
                for j in range(n)
            )
        )
    return out


def output_path_for_process(path: Optional[str]) -> Optional[str]:
    """Per-process JSONL/checkpoint sink: process 0 keeps the configured
    path (its file is the one the parity bar compares byte-for-byte
    against a single-process run); siblings write ``<path>.p<pid>`` so
    concurrent workers on one machine never interleave writes."""
    if path is None:
        return None
    _, pid = process_info()
    return path if pid == 0 else f"{path}.p{pid}"
