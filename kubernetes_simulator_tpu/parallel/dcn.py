"""Multi-host DCN replay (round 11): process-local execution, ONE
end-of-replay gather.

The scenario axis is the framework's data-parallel axis (parallel.mesh);
across hosts it splits the same way: each process owns the CONTIGUOUS
``jax.process_index()`` block of the scenario list and runs the entire
chunk loop on it **locally** — a mesh is restricted to the process's own
devices (:func:`localize_mesh`), the boundary-mode host mirrors exist
only for local scenarios, and ``WhatIfEngine._fetch``/``_fold`` touch
only addressable shards. By construction there are ZERO cross-process
collectives inside the chunk loop; the processes meet exactly once per
replay, at result assembly, through :func:`gather` — a host-side gather
over the ``jax.distributed`` coordination (KV-store) service, the SURVEY
§5 "one collective per replay" contract realized over DCN.

Why host-side rather than psum/all_gather: the result tensors are tiny
([S] counters and quantiles), and routing them through the coordination
service keeps the compiled chunk programs bit-identical to the
single-process mesh programs — which is what makes the 2-process parity
bar (byte-identical placements, JSONL, checkpoint blobs) attainable. It
also runs on jaxlib CPU builds whose runtime rejects cross-process XLA
computations outright, so the path is exercised in CI without TPU hosts
(scripts/dcn_launch.py spawns the coordinator + workers on one machine).

``GATHER_COUNT`` is module-global so tests can pin the "exactly one
gather per replay" contract. Gathers are SPMD-disciplined: every process
must call :func:`gather` the same number of times with the same ``name``
(the per-call sequence number is part of the KV key).
"""

from __future__ import annotations

import base64
import os
import pickle
from typing import Optional

import numpy as np

# Cross-process gathers performed by this process since import. Tests
# diff it around a replay to pin "one gather per replay, zero per chunk".
GATHER_COUNT = 0
_seq = 0

# The coordination service speaks gRPC with a 4 MiB default message cap —
# payloads are chunked well below it.
_KV_CHUNK = 2 * 1024 * 1024


def maybe_init_from_env() -> bool:
    """Join the ``jax.distributed`` coordinator described by
    ``KSIM_DCN_COORD`` / ``KSIM_DCN_NPROC`` / ``KSIM_DCN_PID`` (set by
    scripts/dcn_launch.py; the bare ``DCN_*`` spellings of the test
    harness are honored too). Returns True when a multi-process setup was
    initialized.

    Ordering contract: the persistent compile cache is configured FIRST —
    ``compile_cache.enable()`` must precede ``jax.distributed.initialize``
    (it reads config/env only, never initializes the backend; pinned by
    tests/test_dcn_units.py)."""
    coord = os.environ.get("KSIM_DCN_COORD") or os.environ.get("DCN_COORD")
    nproc = int(
        os.environ.get("KSIM_DCN_NPROC") or os.environ.get("DCN_NPROC") or 0
    )
    if not coord or nproc <= 1:
        return False
    from ..utils.compile_cache import enable as _cc

    _cc()  # BEFORE initialize — see docstring
    pid = int(
        os.environ.get("KSIM_DCN_PID") or os.environ.get("DCN_PID") or 0
    )
    from .mesh import init_distributed

    init_distributed(
        coordinator_address=coord, num_processes=nproc, process_id=pid
    )
    return True


def active() -> bool:
    """True in a multi-process (DCN) run."""
    import jax

    return jax.process_count() > 1


def process_info() -> tuple:
    import jax

    return jax.process_count(), jax.process_index()


def local_slice(n_global: int) -> slice:
    """This process's contiguous block of a length-``n_global`` leading
    axis (requires ``n_global % process_count == 0``). The block order
    matches a global ``make_mesh()`` scenario sharding: ``jax.devices()``
    orders devices by process, so process p's local shards hold exactly
    rows ``[p*n/np, (p+1)*n/np)`` — which is what makes the sliced run's
    concatenated results bit-identical to the single-process mesh run."""
    nproc, pid = process_info()
    per = n_global // nproc
    return slice(pid * per, (pid + 1) * per)


def localize_mesh(mesh):
    """Restrict a (possibly cross-process) mesh to THIS process's devices,
    preserving axis names. Identity for None / already-local meshes.

    This is the heart of the round-11 DCN design: the engine slices the
    scenario axis per process and runs the same shard_map chunk programs
    over a LOCAL mesh — every shard addressable, per-chunk device→host
    traffic process-local, no cross-process XLA computation anywhere."""
    from .mesh import spans_processes

    if mesh is None or not spans_processes(mesh):
        return mesh
    import jax
    from jax.sharding import Mesh

    me = jax.process_index()
    mine = [d for d in mesh.devices.flat if d.process_index == me]
    if not mine:
        raise ValueError(
            "mesh has no devices addressable from process "
            f"{me} — every process must contribute devices to a DCN mesh"
        )
    return Mesh(np.array(mine), mesh.axis_names)


def _client():
    from jax._src import distributed

    c = distributed.global_state.client
    if c is None:
        raise RuntimeError(
            "jax.distributed is not initialized — call "
            "parallel.mesh.init_distributed (or run under "
            "scripts/dcn_launch.py) before gathering"
        )
    return c


def _timeout_ms() -> int:
    return int(float(os.environ.get("KSIM_DCN_TIMEOUT_S", "300")) * 1000)


def gather(name: str, payload) -> list:
    """THE cross-process gather: publish this process's ``payload`` and
    return every process's, in process order. Called at most once per
    replay (result assembly); the chunk loop never reaches it.

    Payloads are pickled (numpy arrays, dataclasses — trusted sibling
    processes of the same program), base64-encoded and chunked under the
    coordination service's gRPC message cap. Keys carry a monotonically
    increasing sequence number, so repeated replays in one process
    lifetime never collide — provided every process gathers in the same
    order (SPMD discipline, same as collectives)."""
    global GATHER_COUNT, _seq
    nproc, pid = process_info()
    _seq += 1
    GATHER_COUNT += 1
    c = _client()
    tmo = _timeout_ms()
    blob = base64.b64encode(
        pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    ).decode("ascii")
    chunks = [
        blob[i : i + _KV_CHUNK] for i in range(0, len(blob), _KV_CHUNK)
    ] or [""]
    prefix = f"ksim/gather/{_seq}/{name}"
    for j, ch in enumerate(chunks):
        c.key_value_set(f"{prefix}/{pid}/{j}", ch)
    c.key_value_set(f"{prefix}/{pid}/n", str(len(chunks)))
    out = []
    for p in range(nproc):
        if p == pid:
            out.append(payload)
            continue
        n = int(c.blocking_key_value_get(f"{prefix}/{p}/n", tmo))
        out.append(
            pickle.loads(
                base64.b64decode(
                    "".join(
                        c.blocking_key_value_get(f"{prefix}/{p}/{j}", tmo)
                        for j in range(n)
                    )
                )
            )
        )
    return out


def output_path_for_process(path: Optional[str]) -> Optional[str]:
    """Per-process JSONL/checkpoint sink: process 0 keeps the configured
    path (its file is the one the parity bar compares byte-for-byte
    against a single-process run); siblings write ``<path>.p<pid>`` so
    concurrent workers on one machine never interleave writes."""
    if path is None:
        return None
    _, pid = process_info()
    return path if pid == 0 else f"{path}.p{pid}"
