"""Multi-host DCN replay (round 11): process-local execution, ONE
end-of-replay gather.

The scenario axis is the framework's data-parallel axis (parallel.mesh);
across hosts it splits the same way: each process owns the CONTIGUOUS
``jax.process_index()`` block of the scenario list and runs the entire
chunk loop on it **locally** — a mesh is restricted to the process's own
devices (:func:`localize_mesh`), the boundary-mode host mirrors exist
only for local scenarios, and ``WhatIfEngine._fetch``/``_fold`` touch
only addressable shards. By construction there are ZERO cross-process
collectives inside the chunk loop; the processes meet exactly once per
replay, at result assembly, through :func:`gather` — a host-side gather
over the ``jax.distributed`` coordination (KV-store) service, the SURVEY
§5 "one collective per replay" contract realized over DCN.

Why host-side rather than psum/all_gather: the result tensors are tiny
([S] counters and quantiles), and routing them through the coordination
service keeps the compiled chunk programs bit-identical to the
single-process mesh programs — which is what makes the 2-process parity
bar (byte-identical placements, JSONL, checkpoint blobs) attainable. It
also runs on jaxlib CPU builds whose runtime rejects cross-process XLA
computations outright, so the path is exercised in CI without TPU hosts
(scripts/dcn_launch.py spawns the coordinator + workers on one machine).

``GATHER_COUNT`` is module-global so tests can pin the "exactly one
gather per replay" contract. Gathers are SPMD-disciplined: every process
must call :func:`gather` the same number of times with the same ``name``
(the per-call sequence number is part of the KV key).
"""

from __future__ import annotations

import base64
import json
import os
import pickle
import random
import time
import zlib
from typing import Dict, Optional

import numpy as np

from . import trace as _trace

# Cross-process gathers performed by this process since import. Tests
# diff it around a replay to pin "one gather per replay, zero per chunk".
GATHER_COUNT = 0
_seq = 0

# The coordination service speaks gRPC with a 4 MiB default message cap —
# payloads are chunked well below it.
_KV_CHUNK = 2 * 1024 * 1024

# ---------------------------------------------------------------------------
# Gather payload compression (round 14). Assignment tensors dominate the
# gather bytes at Borg scale ([S_local, P] int32 — 100k+ pods per row), and
# they are extremely delta-compressible (node ids of consecutive pods
# cluster; PAD runs are constant). Large integer ndarrays are re-encoded as
# zlib(delta int32) before the KV put and decoded transparently on gather;
# decode is byte-exact (values, dtype, shape — pinned by
# tests/test_dcn_units.py). Float/object leaves and small arrays pass
# through untouched — the codec must never cost more than it saves.

_COMPRESS_MIN_ELEMS = 1024
# (raw, compressed) byte totals for this process's gather puts since
# import — tests and operators read the reduction off these.
COMPRESS_BYTES = [0, 0]

_I32_MIN = np.iinfo(np.int32).min
_I32_MAX = np.iinfo(np.int32).max


class _PackedArray:
    """Wire wrapper for one compressed ndarray leaf. ``codec``:
    "delta-zlib" (zlib over consecutive int32 deltas of the flattened
    array — first element is delta-from-zero) or "zlib" (zlib over the
    raw bytes; the fallback when deltas overflow int32)."""

    __slots__ = ("codec", "dtype", "shape", "data")

    def __init__(self, codec: str, dtype: str, shape, data: bytes):
        self.codec = codec
        self.dtype = dtype
        self.shape = shape
        self.data = data


def _pack_leaf(a):
    import zlib

    if not (
        isinstance(a, np.ndarray)
        and a.size >= _COMPRESS_MIN_ELEMS
        and np.issubdtype(a.dtype, np.integer)
    ):
        return a
    flat = a.reshape(-1).astype(np.int64)
    deltas = np.diff(flat, prepend=np.int64(0))
    if deltas.min() >= _I32_MIN and deltas.max() <= _I32_MAX:
        codec, raw = "delta-zlib", deltas.astype("<i4").tobytes()
    else:
        codec, raw = "zlib", np.ascontiguousarray(a).tobytes()
    comp = zlib.compress(raw, 6)
    if len(comp) >= a.nbytes:
        return a  # incompressible — ship raw
    COMPRESS_BYTES[0] += a.nbytes
    COMPRESS_BYTES[1] += len(comp)
    return _PackedArray(codec, a.dtype.str, a.shape, comp)


def _unpack_leaf(p):
    import zlib

    if not isinstance(p, _PackedArray):
        return p
    raw = zlib.decompress(p.data)
    if p.codec == "delta-zlib":
        flat = np.cumsum(np.frombuffer(raw, dtype="<i4").astype(np.int64))
        return flat.astype(np.dtype(p.dtype)).reshape(p.shape)
    return (
        np.frombuffer(raw, dtype=np.dtype(p.dtype)).reshape(p.shape).copy()
    )


def _walk_payload(obj, leaf):
    """Structure-preserving map over the gather payload containers (dict /
    list / tuple); everything else is a leaf. Symmetric for pack and
    unpack, so round-tripping preserves the payload's exact shape."""
    if isinstance(obj, dict):
        return {k: _walk_payload(v, leaf) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_walk_payload(v, leaf) for v in obj)
    return leaf(obj)


def maybe_init_from_env() -> bool:
    """Join the ``jax.distributed`` coordinator described by
    ``KSIM_DCN_COORD`` / ``KSIM_DCN_NPROC`` / ``KSIM_DCN_PID`` (set by
    scripts/dcn_launch.py; the bare ``DCN_*`` spellings of the test
    harness are honored too). Returns True when a multi-process setup was
    initialized.

    Ordering contract: the persistent compile cache is configured FIRST —
    ``compile_cache.enable()`` must precede ``jax.distributed.initialize``
    (it reads config/env only, never initializes the backend; pinned by
    tests/test_dcn_units.py)."""
    coord = os.environ.get("KSIM_DCN_COORD") or os.environ.get("DCN_COORD")
    nproc = int(
        os.environ.get("KSIM_DCN_NPROC") or os.environ.get("DCN_NPROC") or 0
    )
    if not coord or nproc <= 1:
        return False
    from ..utils.compile_cache import enable as _cc

    _cc()  # BEFORE initialize — see docstring
    pid = int(
        os.environ.get("KSIM_DCN_PID") or os.environ.get("DCN_PID") or 0
    )
    from .mesh import init_distributed

    init_distributed(
        coordinator_address=coord, num_processes=nproc, process_id=pid
    )
    return True


def active() -> bool:
    """True in a multi-process (DCN) run."""
    import jax

    return jax.process_count() > 1


def process_info() -> tuple:
    import jax

    return jax.process_count(), jax.process_index()


def spare_count() -> int:
    """Processes at the TAIL of the pid range that own no scenario block
    (``KSIM_DCN_SPARES``, round 15). Spares skip the chunk loop, sit in
    the gather, and exist only to claim dead/straggling workers' blocks
    — the ``--elastic`` late-joiner capacity of scripts/dcn_launch.py."""
    try:
        return max(int(os.environ.get("KSIM_DCN_SPARES", "0")), 0)
    except ValueError:
        return 0


def worker_count() -> int:
    """Processes that own a scenario block (process_count - spares)."""
    nproc, _ = process_info()
    return max(nproc - spare_count(), 1)


def is_spare() -> bool:
    _, pid = process_info()
    return pid >= worker_count()


def local_slice(n_global: int) -> slice:
    """This process's contiguous block of a length-``n_global`` leading
    axis (requires ``n_global % worker_count == 0``). The block order
    matches a global ``make_mesh()`` scenario sharding: ``jax.devices()``
    orders devices by process, so process p's local shards hold exactly
    rows ``[p*n/np, (p+1)*n/np)`` — which is what makes the sliced run's
    concatenated results bit-identical to the single-process mesh run.

    Spare processes (round 15) own nothing; they are handed the LAST
    worker's block purely so engine construction sees valid shapes —
    ``WhatIfEngine`` marks them ``_dcn_spare`` and never runs the chunks."""
    workers = worker_count()
    _, pid = process_info()
    per = n_global // workers
    p = min(pid, workers - 1)
    return slice(p * per, (p + 1) * per)


def localize_mesh(mesh):
    """Restrict a (possibly cross-process) mesh to THIS process's devices,
    preserving axis names. Identity for None / already-local meshes.

    This is the heart of the round-11 DCN design: the engine slices the
    scenario axis per process and runs the same shard_map chunk programs
    over a LOCAL mesh — every shard addressable, per-chunk device→host
    traffic process-local, no cross-process XLA computation anywhere."""
    from .mesh import spans_processes

    if mesh is None or not spans_processes(mesh):
        return mesh
    import jax
    from jax.sharding import Mesh

    me = jax.process_index()
    mine = [d for d in mesh.devices.flat if d.process_index == me]
    if not mine:
        raise ValueError(
            "mesh has no devices addressable from process "
            f"{me} — every process must contribute devices to a DCN mesh"
        )
    return Mesh(np.array(mine), mesh.axis_names)


def _client():
    from jax._src import distributed

    c = distributed.global_state.client
    if c is None:
        raise RuntimeError(
            "jax.distributed is not initialized — call "
            "parallel.mesh.init_distributed (or run under "
            "scripts/dcn_launch.py) before gathering"
        )
    # Round 17: every KV touch flows through here, so this is the single
    # injection point for faultline's deterministic fault schedules.
    # Identity when KSIM_FAULTLINE is off.
    from . import faultline

    return faultline.wrap_kv(c)


def _timeout_ms() -> int:
    return int(float(os.environ.get("KSIM_DCN_TIMEOUT_S", "300")) * 1000)


# -- bounded KV retries (round 17) -------------------------------------------
#
# Before faultline, every coordination-plane KV call was a single
# unretried gRPC round trip — one transient error could fail a heartbeat,
# lose a claim, or abort the end gather. kv_retry is THE retry policy:
# bounded attempts, exponential backoff with jitter, and an attributed
# DcnRetryError on give-up. Applied to heartbeats, claims, checkpoint
# publication and the gather publication; the gather's GETs keep their
# own poll loop (_get_attributed), which already retries by construction.

RETRY_STATS = {"attempts": 0, "retries": 0, "giveups": 0, "backoff_s": 0.0}


def retry_stats() -> dict:
    """Snapshot of :data:`RETRY_STATS` (copy — callers diff it)."""
    return dict(RETRY_STATS)


class DcnRetryError(RuntimeError):
    """A bounded KV retry gave up. Carries the operation, key, attempt
    count and last error so a fleet failure is attributed to the exact
    coordination op that exhausted its budget."""

    def __init__(self, op: str, key: str, attempts: int, elapsed_s: float, last):
        super().__init__(
            f"dcn: {op} on {key!r} gave up after {attempts} attempts over "
            f"{elapsed_s:.2f}s of bounded backoff "
            f"(KSIM_DCN_RETRIES/KSIM_DCN_RETRY_BASE_S); last error: {last!r}"
        )
        self.op = op
        self.key = key
        self.attempts = attempts
        self.last = last


def _retry_attempts() -> int:
    try:
        return max(int(os.environ.get("KSIM_DCN_RETRIES", "4")), 1)
    except ValueError:
        return 4


def _retry_base_s() -> float:
    return float(os.environ.get("KSIM_DCN_RETRY_BASE_S", "0.05"))


def _retry_cap_s() -> float:
    return float(os.environ.get("KSIM_DCN_RETRY_CAP_S", "2.0"))


def kv_retry(
    fn,
    *,
    op: str,
    key: str = "",
    attempts: Optional[int] = None,
    base_s: Optional[float] = None,
    cap_s: Optional[float] = None,
    sleep=time.sleep,
    jitter=None,
):
    """Run ``fn()`` with bounded exponential backoff + jitter.

    Delay before retry k (0-based) is ``min(cap_s, base_s * 2**k) * u``
    with ``u`` uniform in [0.5, 1.0] — full-jitter-lite, bounded both
    sides so tests can pin the envelope. ``sleep``/``jitter`` are
    injectable for the timing-bound unit tests. Raises
    :class:`DcnRetryError` after the last attempt fails."""
    n = _retry_attempts() if attempts is None else max(int(attempts), 1)
    base = _retry_base_s() if base_s is None else float(base_s)
    cap = _retry_cap_s() if cap_s is None else float(cap_s)
    rnd = random.random if jitter is None else jitter
    t0 = time.monotonic()
    last = None
    for k in range(n):
        try:
            out = fn()
        except Exception as e:
            RETRY_STATS["attempts"] += 1
            last = e
            if k + 1 >= n:
                break
            RETRY_STATS["retries"] += 1
            d = min(cap, base * (2.0 ** k)) * (0.5 + 0.5 * rnd())
            RETRY_STATS["backoff_s"] += d
            sleep(d)
        else:
            RETRY_STATS["attempts"] += 1
            return out
    RETRY_STATS["giveups"] += 1
    raise DcnRetryError(op, key, n, time.monotonic() - t0, last)


# -- liveness heartbeats (round 12) -----------------------------------------
#
# Each process overwrites ONE key (``ksim/hb/<pid>``) with a small JSON
# progress beacon on a chunk cadence. Plain KV puts — no barrier, no
# blocking read, never counted by GATHER_COUNT — so the "one gather per
# replay" contract is untouched. Readers (the attributed gather timeout
# below, and out-of-fleet monitors via the KSIM_DCN_HB_DIR file mirror)
# see at most one stale beacon per process, never a backlog.

HB_PREFIX = "ksim/hb"


def heartbeat_every() -> int:
    """Chunk cadence for :func:`heartbeat` publication
    (``KSIM_DCN_HEARTBEAT_EVERY``, default every chunk; 0 disables)."""
    return int(os.environ.get("KSIM_DCN_HEARTBEAT_EVERY", "1"))


def _stall_s() -> float:
    """Beacon age beyond which a silent sibling is presumed dead
    (``KSIM_DCN_STALL_S``). The default is generous relative to the
    per-chunk cadence: a chunk that takes a minute of wall clock without
    a beat means the process is gone, not slow."""
    return float(os.environ.get("KSIM_DCN_STALL_S", "60"))


def _poll_s() -> float:
    """Inner poll interval of the attributed gather wait
    (``KSIM_DCN_POLL_S``)."""
    return float(os.environ.get("KSIM_DCN_POLL_S", "2"))


def heartbeat(
    chunk: int,
    total: Optional[int] = None,
    block: Optional[tuple] = None,
    wall_s: Optional[float] = None,
    phases: Optional[Dict[str, float]] = None,
    state: str = "run",
    extra: Optional[dict] = None,
) -> bool:
    """Publish this process's progress beacon: last completed ``chunk``
    (−1 before the first), global scenario ``block`` ``(lo, hi)``,
    wall-clock seconds, a phase-timer snapshot, and a live-buffer gauge.
    Defensive by design — a heartbeat failure must never kill a replay —
    and a no-op outside multi-process runs. Returns True when published."""
    try:
        nproc, pid = process_info()
    except Exception:
        return False
    if nproc <= 1:
        return False
    from . import faultline

    # Deterministic straggler injection (round 18): a KSIM_FAULTLINE_SLOW
    # entry for this pid sleeps BEFORE the beacon/renewal publish, so the
    # previous beat (and the work-queue lease renewal) ages on the wire
    # exactly as a genuinely slow chunk would make it — straggler tests
    # need no wall-clock races.
    faultline.maybe_slow(int(chunk), str(state))
    beat: dict = {
        "pid": int(pid),
        "chunk": int(chunk),
        "state": str(state),
        "t": time.time(),
    }
    if total is not None:
        beat["total_chunks"] = int(total)
    if block is not None:
        beat["block"] = [int(block[0]), int(block[1])]
    if wall_s is not None:
        beat["wall_s"] = round(float(wall_s), 3)
    if phases:
        beat["phases"] = {k: round(float(v), 6) for k, v in phases.items()}
    try:  # live-buffer gauge (cheap count; bytes are the bench's job)
        import jax

        beat["live_buffers"] = len(jax.live_arrays())
    except Exception:
        pass
    if extra:
        beat.update(extra)
    if _ACTIVE_LEASE[0] is not None:
        beat.setdefault("leased_blocks", 1)
        beat.setdefault("wq_block", int(_ACTIVE_LEASE[0].get("bid", -1)))
        # Round 21: the lease generation and block trace id ride the
        # beacon so dcn_launch --watch names the generation live and the
        # post-mortem can tie a beacon to the block's causal chain.
        beat.setdefault("wq_gen", int(_ACTIVE_LEASE[0].get("gen", 0)))
        if _trace.enabled():
            beat.setdefault(
                "trace",
                _trace.block_trace(_ACTIVE_LEASE[0].get("bid", -1)),
            )
    restarts = os.environ.get("KSIM_DCN_RESTART_COUNT")
    if restarts:
        # Supervised-relaunch life (round 20 supervisor; surfaced round
        # 21): lets the watcher tell attempt N's fleet from attempt 0's.
        try:
            beat["restart"] = int(restarts)
        except ValueError:
            pass
    blob = json.dumps(beat, sort_keys=True)
    hb_dir = os.environ.get("KSIM_DCN_HB_DIR")
    if hb_dir:
        # File mirror for monitors OUTSIDE the fleet (dcn_launch --watch):
        # the launcher parent never joins the coordination service, so it
        # tails these instead. Atomic replace — readers never see a torn
        # write (faultline may still tear the PAYLOAD to exercise reader
        # tolerance; monitors must treat unparseable beacons as absent).
        try:
            os.makedirs(hb_dir, exist_ok=True)
            tmp = os.path.join(hb_dir, f".p{pid}.tmp")
            with open(tmp, "w") as f:
                f.write(faultline.file_blob(blob))
            os.replace(tmp, os.path.join(hb_dir, f"p{pid}.json"))
        except OSError:
            pass
    key = f"{HB_PREFIX}/{pid}"
    ok = True
    try:
        # Beacons are frequent and best-effort: a short retry budget
        # absorbs a transient blip, a give-up just means one stale beat.
        kv_retry(
            lambda: _client().key_value_set(key, blob, allow_overwrite=True),
            op="heartbeat",
            key=key,
            attempts=2,
        )
    except Exception:
        ok = False
    # Work-queue lease renewal (round 18): while this process executes a
    # leased scenario block, every beat also overwrites the block's renew
    # key — generation-stamped, so the queue driver measures the LEASE's
    # freshness (distinct from the beacon: an idle process beats without
    # holding anything). Best-effort like the beacon itself.
    if _ACTIVE_LEASE[0] is not None:
        lease = _ACTIVE_LEASE[0]
        t0 = time.perf_counter()
        renew_rec = {
            "pid": int(pid),
            "gen": int(lease.get("gen", 0)),
            "block": int(lease.get("bid", -1)),
            "chunk": int(chunk),
            "t": time.time(),
        }
        if _trace.enabled():
            renew_rec["trace"] = _trace.block_trace(
                lease.get("bid", -1)
            )
        renew = json.dumps(renew_rec, sort_keys=True)
        try:
            kv_retry(
                lambda: _client().key_value_set(
                    lease["key"], renew, allow_overwrite=True
                ),
                op="wq_renew",
                key=lease["key"],
                attempts=2,
            )
            WQ_STATS["renewals"] += 1
        except Exception:
            pass
        WQ_STATS["renew_wall_s"] += time.perf_counter() - t0
    # Kill schedules fire on the heartbeat cursor whether or not the
    # publish landed — a deterministic schedule must not drift because a
    # transient KV error ate one beat.
    faultline.maybe_kill(int(chunk), str(state))
    return ok


def maybe_heartbeat(chunk_done: int, every: Optional[int] = None, **kw) -> bool:
    """Cadence gate for :func:`heartbeat`: publish when ``chunk_done + 1``
    is a multiple of ``every`` (so the ``chunk_done=-1`` start-of-replay
    beacon always publishes, and every=1 beats on every chunk)."""
    if every is None:
        every = heartbeat_every()
    if every <= 0:
        return False
    if (int(chunk_done) + 1) % every:
        return False
    return heartbeat(chunk_done, **kw)


def read_heartbeats() -> Dict[int, dict]:
    """All published beacons, ``{pid: beat}``. Empty on any failure —
    callers treat a missing beacon as \"no evidence\", not as death."""
    try:
        entries = kv_retry(
            lambda: _client().key_value_dir_get(HB_PREFIX),
            op="read_heartbeats",
            key=HB_PREFIX,
            attempts=2,
        )
    except Exception:
        return {}
    out: Dict[int, dict] = {}
    for key, val in entries:
        tail = str(key).rsplit("/", 1)[-1]
        try:
            out[int(tail)] = json.loads(val)
        except (ValueError, TypeError):
            continue
    return out


# -- recoverable work-queue (round 15) ---------------------------------------
#
# The static "process p owns block p forever" slicing becomes recoverable:
# workers periodically publish compressed checkpoint blobs of their block
# state to the KV store (riding the round-14 delta+zlib codec), and a
# survivor that detects a stale sibling beacon while sitting in the gather
# CLAIMS the dead process's block (compare-and-set on a write-once key —
# single-claimant), re-executes it from the newest checkpoint, and
# publishes the dead pid's gather payload in its stead. Everything is
# deterministic, so the gathered result is byte-identical to a no-failure
# run. All of it is opt-in: with KSIM_DCN_RECOVER unset the round-12
# attributed DcnGatherTimeout behavior is unchanged.

CKPT_PREFIX = "ksim/ckpt"
CLAIM_PREFIX = "ksim/claim"


def recover_enabled() -> bool:
    """Survivor rebalance on a stale beacon (``KSIM_DCN_RECOVER``;
    default off — the round-12 attributed fail-fast stays the default)."""
    return str(
        os.environ.get("KSIM_DCN_RECOVER", "0")
    ).strip().lower() in ("1", "true", "yes", "on")


def ckpt_every() -> int:
    """Chunk cadence for :func:`publish_checkpoint` (``KSIM_DCN_CKPT_EVERY``,
    default 0 = no checkpoint publication; recovery then re-executes a
    claimed block from chunk 0 — still byte-identical, just slower)."""
    try:
        return max(int(os.environ.get("KSIM_DCN_CKPT_EVERY", "0")), 0)
    except ValueError:
        return 0


def max_claims() -> int:
    """Claim generations per dead block (``KSIM_DCN_MAX_CLAIMS``): if the
    claimant of generation g itself goes stale mid-recovery, survivors
    open generation g+1, up to this cap (then the attributed timeout)."""
    try:
        return max(int(os.environ.get("KSIM_DCN_MAX_CLAIMS", "2")), 1)
    except ValueError:
        return 2


def _encode_payload(payload) -> list:
    """pack → pickle → base64 → gRPC-cap-sized chunks (shared by the
    gather publication and the checkpoint blobs)."""
    packed = _walk_payload(payload, _pack_leaf)
    blob = base64.b64encode(
        pickle.dumps(packed, protocol=pickle.HIGHEST_PROTOCOL)
    ).decode("ascii")
    return [
        blob[i : i + _KV_CHUNK] for i in range(0, len(blob), _KV_CHUNK)
    ] or [""]


def _decode_payload(chunks) -> object:
    return _walk_payload(
        pickle.loads(base64.b64decode("".join(chunks))), _unpack_leaf
    )


# -- checkpoint blob integrity (round 17) ------------------------------------
#
# Checkpoint chunks carried no integrity check: a torn or corrupted KV
# value (publisher dying mid-blob, a flipped byte anywhere in transit or
# storage) either crashed the unpickle or — worse — silently resumed bad
# state. Every chunk is now framed ``kf1:<crc32>:<len>:<data>`` and the
# manifest (written LAST) is JSON carrying the chunk count plus the
# crc32/length of the whole reassembled blob. load_checkpoint validates
# both layers and on ANY mismatch falls back to the newest PRIOR complete
# cursor (counted in CRC_STATS["fallbacks"]) instead of crashing.

_FRAME_MAGIC = "kf1"

# frames_ok/frames_bad: per-chunk validation outcomes; fallbacks: cursors
# skipped (torn/corrupt/undecodable) on the way to a usable checkpoint.
CRC_STATS = {"frames_ok": 0, "frames_bad": 0, "fallbacks": 0}


def crc_stats() -> dict:
    """Snapshot of :data:`CRC_STATS` (copy — callers diff it)."""
    return dict(CRC_STATS)


def _frame_chunk(data: str) -> str:
    """Wrap one checkpoint chunk in the CRC32+length frame."""
    crc = zlib.crc32(data.encode("ascii")) & 0xFFFFFFFF
    return f"{_FRAME_MAGIC}:{crc:08x}:{len(data)}:{data}"


def _unframe_chunk(framed: str) -> str:
    """Validate + strip one frame; ValueError on torn/truncated/corrupt."""
    magic, _, rest = framed.partition(":")
    if magic != _FRAME_MAGIC or not rest:
        raise ValueError("checkpoint chunk is not framed (torn header?)")
    crc_s, _, rest = rest.partition(":")
    len_s, sep, data = rest.partition(":")
    if not sep:
        raise ValueError("checkpoint chunk frame is truncated")
    if len(data) != int(len_s):
        raise ValueError(
            f"checkpoint chunk length mismatch: framed {int(len_s)}, "
            f"got {len(data)} (torn write)"
        )
    if (zlib.crc32(data.encode("ascii")) & 0xFFFFFFFF) != int(crc_s, 16):
        raise ValueError("checkpoint chunk CRC32 mismatch (corrupt blob)")
    return data


# -- durable ground (round 20) -----------------------------------------------
#
# Everything the recovery stack stores — checkpoint blobs, work-queue
# results, the done/lease ledger — lives in the jax.distributed KV
# store, which dies with process 0. The durability journal mirrors the
# same framed bytes to a filesystem directory (``KSIM_DCN_DURABLE_DIR``
# / ``dcn.durable:`` YAML) on the existing publication paths, so a
# WHOLE-FLEET crash — coordinator included — becomes restartable: a
# fresh fleet brought up with ``KSIM_DCN_RESUME=1`` (dcn_launch
# --resume, set automatically by --supervise relaunches) seeds its new
# KV plane from the journal. Completed work-queue blocks are adopted
# without re-execution; in-flight blocks resume from their newest
# complete durable cursor. The layout mirrors the KV namespace:
#
#   <dir>/ckpt/<epoch>/<pid>/<lo>-<hi>/<cursor>/{0..n-1, manifest.json}
#   <dir>/wq/<seq>/<name>/result/<bid>/{0..n-1, manifest.json}
#   <dir>/wq/<seq>/<name>/done/<bid>     one JSON done meta per block
#   <dir>/wq/<seq>/<name>/lease/<bid>    newest durable lease holder
#
# Chunk files carry the SAME kf1 CRC32+length frames as the KV values,
# and manifest.json is the SAME JSON manifest — written temp-then-
# ``os.replace`` and LAST, so a reader that finds a manifest never sees
# an in-flight blob, and a blob torn by a crash (or by the faultline
# torn-write injector, which every journal file is routed through)
# fails frame validation on resume and the reader falls back to the
# prior complete cursor, exactly like the KV path. The namespaces line
# up across restarts because the gather sequence is deterministic: a
# resumed fleet replays the same ``_seq``, so epochs and wq prefixes
# match the dead fleet's byte-for-byte. Writers are best-effort (never
# raise — durability must not take a healthy run down) and the
# checkpoint mirror runs inside :func:`publish_checkpoint`, i.e. on the
# round-19 background publisher thread, so the sync loop gains no new
# stall. With the directory unset every hook below is a no-op and the
# round-19 byte-identity bars are untouched.

# writes/write_wall_s/bytes: journal mirror traffic this process;
# adopted: work-queue blocks adopted from the journal without
# re-execution; resumes: checkpoint loads satisfied from the journal.
JOURNAL_STATS = {
    "writes": 0,
    "write_wall_s": 0.0,
    "bytes": 0,
    "adopted": 0,
    "resumes": 0,
}


def journal_stats() -> dict:
    """Snapshot of :data:`JOURNAL_STATS` (copy — callers diff it)."""
    return dict(JOURNAL_STATS)


def durable_dir() -> Optional[str]:
    """Root of the durability journal (``KSIM_DCN_DURABLE_DIR``), or
    None — the default — for no journal at all."""
    d = str(os.environ.get("KSIM_DCN_DURABLE_DIR", "")).strip()
    return d or None


def resume_enabled() -> bool:
    """Seed this fleet from the durability journal (``KSIM_DCN_RESUME``;
    set by ``dcn_launch --resume`` and by every supervised relaunch).
    Only meaningful with :func:`durable_dir` set."""
    return str(
        os.environ.get("KSIM_DCN_RESUME", "0")
    ).strip().lower() in ("1", "true", "yes", "on")


def _journal_write_file(path: str, data: str) -> None:
    """One torn-write-proof journal file: write a same-directory temp,
    then ``os.replace`` (atomic on POSIX). The payload is routed through
    ``faultline.file_blob`` so the torn-write injector tears journal
    files exactly like KV blobs — the CRC frames catch it on resume."""
    from . import faultline

    data = faultline.file_blob(data)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


def _journal_write_blob(subdir: str, chunks, manifest: str) -> bool:
    """Mirror one framed blob (checkpoint or work-queue result) to
    ``<durable_dir>/<subdir>/``: chunk files ``0..n-1`` first,
    ``manifest.json`` LAST. Best-effort: returns False instead of
    raising — a full disk degrades durability, never the run."""
    root = durable_dir()
    if not root:
        return False
    t0 = time.perf_counter()
    try:
        d = os.path.join(root, subdir)
        os.makedirs(d, exist_ok=True)
        nbytes = 0
        for j, ch in enumerate(chunks):
            _journal_write_file(os.path.join(d, str(j)), ch)
            nbytes += len(ch)
        _journal_write_file(os.path.join(d, "manifest.json"), manifest)
    except OSError:
        return False
    JOURNAL_STATS["writes"] += 1
    JOURNAL_STATS["write_wall_s"] += time.perf_counter() - t0
    JOURNAL_STATS["bytes"] += nbytes + len(manifest)
    return True


def _journal_write_json(rel: str, obj: dict) -> bool:
    """One atomic JSON ledger record at ``<durable_dir>/<rel>`` (the
    work-queue done/lease entries). Best-effort like the blob writer."""
    root = durable_dir()
    if not root:
        return False
    try:
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _journal_write_file(path, json.dumps(obj, sort_keys=True))
    except OSError:
        return False
    JOURNAL_STATS["writes"] += 1
    return True


def _journal_read_json(rel: str):
    """Parsed JSON at ``<durable_dir>/<rel>`` or None (absent, torn by a
    crash mid-replace — impossible on POSIX but cheap to tolerate — or
    not JSON)."""
    root = durable_dir()
    if not root:
        return None
    try:
        with open(os.path.join(root, rel)) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


def _journal_ckpt_entries(pid: int, ep: int) -> Dict[tuple, Dict[str, str]]:
    """The journal mirror of one process's checkpoint namespace, in
    ``load_checkpoint``'s table shape: ``{(blk, cur): {leaf: value}}``
    with the manifest under leaf ``"n"``. Cursors missing
    ``manifest.json`` were in flight when the fleet died and are skipped
    (the exact KV in-flight rule); frame validation happens in the
    caller's newest-first candidate walk, so a torn journal chunk falls
    back to the prior complete cursor there."""
    out: Dict[tuple, Dict[str, str]] = {}
    root = durable_dir()
    if not root:
        return out
    base = os.path.join(root, "ckpt", str(int(ep)), str(int(pid)))
    try:
        blks = os.listdir(base)
    except OSError:
        return out
    for blk in blks:
        bdir = os.path.join(base, blk)
        try:
            curs = os.listdir(bdir)
        except OSError:
            continue
        for cur in curs:
            cdir = os.path.join(bdir, cur)
            try:
                names = os.listdir(cdir)
            except OSError:
                continue
            if "manifest.json" not in names:
                continue  # in flight when the fleet died
            kv: Dict[str, str] = {}
            try:
                for name in names:
                    if name.endswith(".tmp"):
                        continue
                    with open(os.path.join(cdir, name)) as f:
                        kv["n" if name == "manifest.json" else name] = (
                            f.read()
                        )
            except OSError:
                continue
            out[(blk, cur)] = kv
    return out


def _journal_read_blob(subdir: str):
    """Decode one journaled blob directory through the full integrity
    stack (manifest chunk count, per-chunk kf1 frames, whole-blob
    crc/length) — the work-queue result reader. Returns the decoded
    payload or raises (``ValueError``/``OSError``/decode errors) so the
    caller can count the fallback and re-execute."""
    root = durable_dir()
    if not root:
        raise OSError("no durable journal configured")
    d = os.path.join(root, subdir)
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.loads(f.read())
    chunks = []
    for j in range(int(man["n"])):
        with open(os.path.join(d, str(j))) as f:
            chunks.append(_unframe_chunk(f.read()))
    crc = 0
    for ch in chunks:
        crc = zlib.crc32(ch.encode("ascii"), crc)
    if (
        f"{crc & 0xFFFFFFFF:08x}" != man.get("crc")
        or sum(len(ch) for ch in chunks) != int(man.get("len", -1))
    ):
        raise ValueError("manifest crc/length mismatch over journal blob")
    return _decode_payload(chunks)


def _journal_wq_scan(seq: int, name: str, nb: int):
    """Resume scan of the work-queue journal for gather ``seq``:
    ``(adopted, resume_hint)``. ``adopted`` maps bid -> (done meta,
    decoded payload) for blocks whose durable done record AND result
    blob both validate — the fresh fleet adopts those without
    re-execution. A done record whose result blob is missing or torn is
    dropped (the block re-executes; counted as a CRC fallback).
    ``resume_hint`` maps each unfinished bid to the pid holding its
    newest durable lease — the execute path then resumes from that
    pid's durable block checkpoint."""
    adopted: Dict[int, tuple] = {}
    hint: Dict[int, int] = {}
    if not durable_dir():
        return adopted, hint
    from ..utils.metrics import log

    base = os.path.join("wq", str(int(seq)), str(name))
    for bid in range(int(nb)):
        meta = _journal_read_json(os.path.join(base, "done", str(bid)))
        if isinstance(meta, dict):
            try:
                payload = _journal_read_blob(
                    os.path.join(base, "result", str(bid))
                )
            except Exception as e:
                CRC_STATS["frames_bad"] += 1
                CRC_STATS["fallbacks"] += 1
                log.warning(
                    "dcn journal: block %d's durable result failed "
                    "validation (%s) — re-executing it", bid, e,
                )
            else:
                adopted[bid] = (meta, payload)
                continue
        lease = _journal_read_json(os.path.join(base, "lease", str(bid)))
        if isinstance(lease, dict) and int(lease.get("pid", -1)) >= 0:
            hint[bid] = int(lease["pid"])
    return adopted, hint


def _journal_wq_result(jbase: str, bid: int, payload) -> bool:
    """Mirror one work-queue block result to the journal (framed chunks
    + manifest, the checkpoint blob treatment). Called BEFORE the
    first-complete-wins done-CAS, so a durable done record never names
    a result the journal doesn't hold."""
    if not durable_dir():
        return False
    raw = _encode_payload(payload)
    crc, blob_len = 0, 0
    for ch in raw:
        crc = zlib.crc32(ch.encode("ascii"), crc)
        blob_len += len(ch)
    manifest = json.dumps(
        {"n": len(raw), "crc": f"{crc & 0xFFFFFFFF:08x}", "len": blob_len},
        sort_keys=True,
    )
    return _journal_write_blob(
        os.path.join(jbase, "result", str(int(bid))),
        [_frame_chunk(ch) for ch in raw],
        manifest,
    )


# In-process subscribers to fleet events (round 18): the flight recorder
# registers a callback here so lease/steal/speculation/claim events land
# in its JSONL stream alongside the chunk rows. Callbacks receive the
# event dict WITHOUT the wall-clock stamp (the recorder scrubs time
# itself); a raising sink is dropped — events must never kill a replay.
EVENT_SINKS: list = []


def _mirror_event(event: dict) -> None:
    """Append one claim/recovery/work-queue event line to
    ``$KSIM_DCN_HB_DIR/events.jsonl`` so out-of-fleet monitors
    (dcn_launch --watch) can surface a rebalance live, and forward it to
    the in-process :data:`EVENT_SINKS` (flight recorder). Best-effort;
    single ``write`` of one line keeps concurrent appenders from tearing
    each other.

    Round 21: every event is stamped with its causal trace identity
    (``trace``/``span``/``parent`` — see :mod:`parallel.trace`) before
    fan-out, so the events.jsonl mirror and every in-process sink carry
    identical stamps."""
    _trace.stamp(event)
    for sink in list(EVENT_SINKS):
        try:
            sink(dict(event))
        except Exception:
            try:
                EVENT_SINKS.remove(sink)
            except ValueError:
                pass
    hb_dir = os.environ.get("KSIM_DCN_HB_DIR")
    if not hb_dir:
        return
    try:
        os.makedirs(hb_dir, exist_ok=True)
        line = json.dumps(dict(event, t=time.time()), sort_keys=True)
        with open(os.path.join(hb_dir, "events.jsonl"), "a") as f:
            f.write(line + "\n")
    except OSError:
        pass


# Pids this process observed dead past the stall window with recovery on
# (claimed by us or by a sibling). Non-empty ⇒ the fleet is DEGRADED: the
# collective jax.distributed shutdown can never complete (a dead task
# never joins the shutdown barrier) and must be skipped at exit.
DEGRADED: set = set()
_EXIT_CODE = [0]
_degraded_exit_armed = [False]


def _arm_degraded_exit() -> None:
    """A fleet that lost a process must never reach the jax.distributed
    client teardown: the dead task cannot join the shutdown barrier, and
    the coordination service's propagated error ABORTS every healthy
    task (xla's client.h "Terminating process ... fatal errors" —
    SIGABRT after the survivor already printed its byte-identical
    result). Armed the moment a stale sibling is detected with recovery
    on: an atexit hook — registered after jax's machinery, so it runs
    FIRST — flushes stdio and hard-exits. An uncaught exception still
    exits nonzero (sys.excepthook runs before atexit and records it)."""
    if _degraded_exit_armed[0]:
        return
    _degraded_exit_armed[0] = True
    import atexit
    import sys

    prev_hook = sys.excepthook

    def _failing_hook(tp, val, tb):
        _EXIT_CODE[0] = 1
        prev_hook(tp, val, tb)

    sys.excepthook = _failing_hook

    def _hard_exit():
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        os._exit(_EXIT_CODE[0])

    atexit.register(_hard_exit)


def checkpoint_epoch() -> int:
    """Namespace for this replay's checkpoints: the sequence number the
    end-of-replay gather WILL use (``_seq + 1``). Keeps a resumed claim
    from ever reading a previous replay's blobs."""
    return _seq + 1


def gather_seq() -> int:
    """Sequence number of the gather currently in flight — equal to the
    epoch under which this replay's checkpoints were published. Valid
    while inside :func:`gather` (recovery callbacks capture it so the
    resume path reads THIS replay's blobs, not a previous one's)."""
    return _seq


# Cumulative checkpoint-publication accounting for THIS process (flight
# recorder round 16): number of publications, wall spent encoding +
# pushing KV chunks, and encoded bytes on the wire. Read via
# :func:`publish_stats`; the flight recorder diffs it per chunk.
PUBLISH_STATS = {"count": 0, "wall_s": 0.0, "bytes": 0}


def publish_stats() -> dict:
    """Snapshot of :data:`PUBLISH_STATS` (copy — callers diff it)."""
    return dict(PUBLISH_STATS)


def publish_checkpoint(
    cursor: int, payload, block: tuple, epoch: Optional[int] = None
) -> bool:
    """Publish this process's block-state checkpoint at chunk ``cursor``
    under ``ksim/ckpt/<epoch>/<pid>/<lo>-<hi>/<cursor>``. Round 17: every
    chunk is CRC32+length framed and the manifest key (``/n``, written
    LAST so a reader that finds one never sees an in-flight blob) is JSON
    carrying the chunk count plus whole-blob crc/length — a torn or
    corrupted chunk is detected on load, not resumed. Defensive like
    :func:`heartbeat`: returns False (never raises) outside DCN or when
    the bounded KV retries give up.

    Each successful publication is clocked into :data:`PUBLISH_STATS`
    (encode + KV push wall, encoded bytes) and mirrored as a
    ``ckpt_publish`` event for ``dcn_launch --watch``."""
    try:
        nproc, pid = process_info()
        if nproc <= 1:
            return False
        t0 = time.perf_counter()
        c = _client()
        raw_chunks = _encode_payload(payload)
        blob_len = sum(len(ch) for ch in raw_chunks)
        blob_crc = 0
        for ch in raw_chunks:
            blob_crc = zlib.crc32(ch.encode("ascii"), blob_crc)
        chunks = [_frame_chunk(ch) for ch in raw_chunks]
        man = {
            "n": len(chunks),
            "crc": f"{blob_crc & 0xFFFFFFFF:08x}",
            "len": blob_len,
        }
        if _trace.enabled():
            # Round 21: the cursor's trace id rides BOTH the KV manifest
            # and the journal mirror (same string — the mirror-equality
            # pin holds); chunk payload bytes are untouched either way.
            man["trace"] = _trace.ckpt_trace(pid, int(cursor))
        manifest = json.dumps(man, sort_keys=True)
        lo, hi = int(block[0]), int(block[1])
        ep = checkpoint_epoch() if epoch is None else int(epoch)
        prefix = f"{CKPT_PREFIX}/{ep}/{pid}/{lo}-{hi}/{int(cursor)}"
        for j, ch in enumerate(chunks):
            kv_retry(
                lambda k=f"{prefix}/{j}", v=ch: c.key_value_set(
                    k, v, allow_overwrite=True
                ),
                op="publish_checkpoint",
                key=f"{prefix}/{j}",
            )
        kv_retry(
            lambda: c.key_value_set(
                f"{prefix}/n", manifest, allow_overwrite=True
            ),
            op="publish_checkpoint",
            key=f"{prefix}/n",
        )
        # Durable ground (round 20): mirror the SAME framed chunks and
        # manifest to the journal. Already on the publisher thread when
        # the round-19 async gate is on, so the loop gains no stall;
        # best-effort, and a no-op with the journal unset.
        journaled = durable_dir() is not None and _journal_write_blob(
            os.path.join(
                "ckpt", str(ep), str(pid), f"{lo}-{hi}", str(int(cursor))
            ),
            chunks,
            manifest,
        )
        wall = time.perf_counter() - t0
        nbytes = sum(len(ch) for ch in chunks)
        PUBLISH_STATS["count"] += 1
        PUBLISH_STATS["wall_s"] += wall
        PUBLISH_STATS["bytes"] += nbytes
        ev = {
            "kind": "ckpt_publish",
            "pid": pid,
            "cursor": int(cursor),
            "bytes": nbytes,
            "wall_s": round(wall, 6),
        }
        if journaled:
            # Key present only with the journal on — round-19 event
            # streams stay byte-unchanged with dcn.durable off.
            ev["journal"] = 1
        _mirror_event(ev)
        return True
    except Exception:
        return False


# -- background checkpoint publication (round 19) ---------------------------
#
# ``publish_checkpoint`` serializes encode (pack→pickle→zlib→base64),
# CRC framing and the retried KV sets on the caller's thread — on the
# chunk loop that is exposed wall at every checkpoint cadence. The
# publisher below moves everything AFTER the device→host snapshot onto
# one daemon thread with single-flight, newest-wins coalescing: at most
# one publication runs at a time, at most one waits, and a newer
# snapshot submitted while one is waiting replaces it (the KV plane
# only ever needs the newest durable cursor; recovery from an older
# cursor is always correct, just re-executes more chunks). Boundaries
# that need a DURABLE cursor — replay end before the final gather, a
# work-queue block completion — call :func:`drain_publisher`.
#
# Failure semantics match the synchronous path exactly: the worker runs
# the same defensive :func:`publish_checkpoint` (KV give-ups are
# swallowed, faultline's transient-KV drills keep passing), while a
# genuinely unexpected error is stored and re-raised attributed at the
# next loop touch (submit or drain). A SIGKILL mid-publication leaves
# the prior cursor loadable because the manifest key is written LAST —
# the same torn-blob story the CRC stack already covers.

BG_PUBLISH_STATS = {
    "submitted": 0,
    "coalesced": 0,
    "drains": 0,
    "drain_wait_s": 0.0,
}


def bg_publish_stats() -> dict:
    """Snapshot of :data:`BG_PUBLISH_STATS` (copy — callers diff it)."""
    return dict(BG_PUBLISH_STATS)


def ckpt_async_enabled() -> bool:
    """Round-19 A/B gate for the background publisher (default ON).
    ``KSIM_DCN_CKPT_ASYNC=0`` keeps every publication synchronous on
    the loop thread, exactly as rounds 17–18 ran it."""
    return os.environ.get("KSIM_DCN_CKPT_ASYNC", "1") not in ("", "0")


class _CheckpointPublisher:
    """Single-flight newest-wins publisher thread. Lazy: the daemon
    thread starts at the first submit, so single-process and overlap-off
    runs never spawn it."""

    def __init__(self):
        import threading

        self._cv = threading.Condition()
        self._pending = None  # (cursor, payload, block, epoch)
        self._busy = False
        self._error = None  # (cursor, exception) — re-raised on touch
        self._thread = None
        self._threading = threading

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = self._threading.Thread(
                target=self._run, name="ksim-ckpt-publisher", daemon=True
            )
            self._thread.start()

    def _raise_stored(self) -> None:
        err = self._error
        if err is not None:
            self._error = None
            cursor, exc = err
            raise RuntimeError(
                f"dcn: background checkpoint publication failed at "
                f"cursor {cursor}"
            ) from exc

    def submit(self, cursor, payload, block, epoch) -> None:
        self._raise_stored()
        with self._cv:
            if self._pending is not None:
                BG_PUBLISH_STATS["coalesced"] += 1
            self._pending = (cursor, payload, block, epoch)
            BG_PUBLISH_STATS["submitted"] += 1
            self._ensure_thread()
            self._cv.notify_all()

    def drain(self) -> None:
        """Block until nothing is pending or in flight — the durable-
        cursor boundary. Re-raises a stored worker error."""
        t0 = time.perf_counter()
        with self._cv:
            while self._busy or self._pending is not None:
                self._cv.wait(timeout=0.5)
        BG_PUBLISH_STATS["drains"] += 1
        BG_PUBLISH_STATS["drain_wait_s"] += time.perf_counter() - t0
        self._raise_stored()

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending is None:
                    self._cv.wait()
                job, self._pending = self._pending, None
                self._busy = True
            try:
                publish_checkpoint(job[0], job[1], job[2], epoch=job[3])
            except BaseException as e:  # publish_checkpoint is defensive
                self._error = (job[0], e)  # pragma: no cover
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()


_PUBLISHER = _CheckpointPublisher()


def publish_checkpoint_async(
    cursor: int, payload, block: tuple, epoch: Optional[int] = None
) -> bool:
    """Round-19 entry point for chunk-cadence publications: hand the
    (already host-resident) payload to the single-flight publisher
    thread and return immediately. Falls back to the synchronous
    :func:`publish_checkpoint` when the gate is off; no-ops outside DCN
    like every coordination call. Returns True when the publication was
    queued or synchronously pushed."""
    nproc, _pid = process_info()
    if nproc <= 1:
        return False
    if not ckpt_async_enabled():
        return publish_checkpoint(cursor, payload, block, epoch=epoch)
    _PUBLISHER.submit(cursor, payload, block, epoch)
    return True


def drain_publisher() -> None:
    """Wait for every queued background publication to finish (or be
    coalesced away) — call wherever a durable cursor is required:
    replay end before the final heartbeat/gather, work-queue block
    completion. Cheap when nothing is queued; re-raises an unexpected
    publisher error attributed to this loop touch."""
    if ckpt_async_enabled():
        _PUBLISHER.drain()


def load_checkpoint(
    pid: int, epoch: Optional[int] = None, before_cursor: Optional[int] = None
):
    """Newest VALID checkpoint published by ``pid`` this replay:
    ``{"cursor", "block": (lo, hi), "payload"}``, or None when nothing
    usable exists (the claimant then re-executes from chunk 0). One
    directory read, no blocking waits — the publisher is dead.

    Round 17: candidates are walked newest-cursor-first and each must
    pass the full integrity stack — JSON manifest (chunk count + whole-
    blob crc32/length), per-chunk CRC32+length frames, and payload
    decode. Any failure logs, bumps ``CRC_STATS["fallbacks"]`` and moves
    on to the next older cursor, so a torn/corrupt newest blob degrades
    to the prior complete checkpoint instead of crashing or silently
    resuming bad state. ``before_cursor`` restricts to strictly older
    cursors — the resume path in sim/whatif.py uses it to retry with an
    older blob when a decoded payload turns out unusable (signature or
    carrier-shape mismatch)."""
    try:
        c = _client()
        ep = checkpoint_epoch() if epoch is None else int(epoch)
        entries = kv_retry(
            lambda: c.key_value_dir_get(f"{CKPT_PREFIX}/{ep}/{int(pid)}"),
            op="load_checkpoint",
            key=f"{CKPT_PREFIX}/{ep}/{int(pid)}",
            attempts=2,
        )
    except Exception:
        return None
    from ..utils.metrics import log

    try:
        _, me = process_info()
    except Exception:
        me = -1
    table: Dict[tuple, Dict[str, str]] = {}
    for key, val in entries:
        parts = str(key).strip("/").split("/")
        if len(parts) < 3:
            continue
        blk, cur, leaf = parts[-3], parts[-2], parts[-1]
        table.setdefault((blk, cur), {})[leaf] = val
    # Durable ground (round 20): merge the journal mirror into the
    # candidate table — this is how a resumed fleet's empty KV plane
    # gets seeded with the dead fleet's checkpoints (epochs align
    # because the gather sequence replays deterministically). KV wins
    # on a per-leaf collision (same bytes by construction); journal-
    # sourced candidates ride the exact same newest-first walk, CRC
    # validation and prior-cursor fallback below.
    journal_keys: set = set()
    if durable_dir() is not None:
        for bc, jkv in _journal_ckpt_entries(int(pid), ep).items():
            dst = table.setdefault(bc, {})
            for leaf, val in jkv.items():
                if leaf not in dst:
                    dst[leaf] = val
                    journal_keys.add(bc)
    candidates = []
    for (blk, cur), kv in table.items():
        if "n" not in kv:
            continue  # manifest not yet written — in-flight blob
        try:
            cursor = int(cur)
            lo, hi = (int(x) for x in blk.split("-"))
        except ValueError:
            continue
        if before_cursor is not None and cursor >= int(before_cursor):
            continue
        candidates.append((cursor, (lo, hi), kv, (blk, cur)))
    for cursor, block, kv, raw_key in sorted(
        candidates, key=lambda t: (t[0], t[1]), reverse=True
    ):
        try:
            man = json.loads(kv["n"])
            if isinstance(man, dict):
                n = int(man["n"])
                want_crc, want_len = man.get("crc"), man.get("len")
            else:  # legacy bare-int manifest (pre-round-17 blobs)
                n, want_crc, want_len = int(man), None, None
            chunks = []
            for j in range(n):
                ch = kv[str(j)]
                if want_crc is not None:
                    ch = _unframe_chunk(ch)
                chunks.append(ch)
            CRC_STATS["frames_ok"] += len(chunks) if want_crc is not None else 0
            if want_crc is not None:
                crc = 0
                for ch in chunks:
                    crc = zlib.crc32(ch.encode("ascii"), crc)
                if (
                    f"{crc & 0xFFFFFFFF:08x}" != want_crc
                    or sum(len(ch) for ch in chunks) != int(want_len)
                ):
                    raise ValueError(
                        "manifest crc/length mismatch over reassembled blob"
                    )
            payload = _decode_payload(chunks)
        except Exception as e:
            CRC_STATS["frames_bad"] += 1
            CRC_STATS["fallbacks"] += 1
            log.warning(
                "dcn: process %d's checkpoint at cursor %d failed "
                "validation (%s) — falling back to the prior complete "
                "checkpoint", int(pid), cursor, e,
            )
            # Round 21: the fallback is a causal hop — the post-mortem
            # links an injected torn write to the fallback it provoked
            # through the shared ckpt trace id.
            _mirror_event(
                {"event": "ckpt_fallback", "pid": int(pid),
                 "cursor": int(cursor), "by": int(me),
                 "reason": str(e)[:80]}
            )
            continue
        # Round 21: every successful load is an event — it carries the
        # RESUMED cursor the invariant audit compares against the newest
        # complete durable cursor, and (via trace.CTX) a link back to
        # the block whose resume asked for it.
        _mirror_event(
            {
                "event": "ckpt_load",
                "pid": int(pid),
                "cursor": int(cursor),
                "block": [int(block[0]), int(block[1])],
                "by": int(me),
            }
        )
        if raw_key in journal_keys:
            # The winning candidate came (at least partly) from the
            # durable journal — the resume-seeding event the flight
            # recorder and dcn_launch --watch surface.
            JOURNAL_STATS["resumes"] += 1
            _mirror_event(
                {
                    "event": "journal_resume",
                    "pid": int(pid),
                    "cursor": int(cursor),
                    "block": [int(block[0]), int(block[1])],
                    "by": int(me),
                }
            )
        return {"cursor": cursor, "block": block, "payload": payload}
    return None


def try_claim(dead_pid: int, gen: int, name: str = "whatif") -> bool:
    """Compare-and-set claim on ``dead_pid``'s block for the CURRENT
    gather: ``key_value_set`` without ``allow_overwrite`` fails when the
    key exists, so exactly one process wins generation ``gen``. Claim
    metadata (claimant pid, block owner, generation, wall time) is the
    value, for attribution of a second failure during recovery.

    Round 17: the CAS runs under :func:`kv_retry`, and a failure no
    longer short-circuits to "lost" — a transient error is ambiguous
    (the set may have landed before the error surfaced), so the claim
    key is read back and the VALUE decides. Only a readable claim naming
    another pid is a genuine loss; an unreadable key reads as lost too
    (the poll loop re-enters the claim protocol and settles it)."""
    nproc, pid = process_info()
    meta = {
        "claimant": int(pid),
        "for": int(dead_pid),
        "gen": int(gen),
        "t": time.time(),
    }
    key = f"{CLAIM_PREFIX}/{_seq}/{name}/{int(dead_pid)}/{int(gen)}"
    try:
        kv_retry(
            lambda: _client().key_value_set(
                key, json.dumps(meta, sort_keys=True)
            ),
            op="claim",
            key=key,
        )
        return True
    except Exception:
        pass
    claim = read_claim(dead_pid, gen, name=name)
    return claim is not None and int(claim.get("claimant", -1)) == int(pid)


def read_claim(dead_pid: int, gen: int, name: str = "whatif"):
    """Metadata of an existing claim (None when absent/unreadable)."""
    try:
        val = _client().blocking_key_value_get(
            f"{CLAIM_PREFIX}/{_seq}/{name}/{int(dead_pid)}/{int(gen)}",
            2000,
        )
        return json.loads(val)
    except Exception:
        return None


class DcnGatherTimeout(RuntimeError):
    """gather() abandoned: a sibling never published its payload. Carries
    the missing pids and the heartbeat table for programmatic use."""

    def __init__(self, msg, missing=None, heartbeats=None):
        super().__init__(msg)
        self.missing = list(missing or [])
        self.heartbeats = dict(heartbeats or {})


def _describe_process(p: int, hb: Dict[int, dict], now: float) -> str:
    b = hb.get(p)
    if b is None:
        return f"process {p}: no heartbeat ever received"
    age = max(0.0, now - float(b.get("t", now)))
    parts = [f"process {p}: last heartbeat {age:.1f}s ago"]
    chunk = b.get("chunk", "?")
    total = b.get("total_chunks")
    parts.append(
        f"last completed chunk {chunk}"
        + (f"/{total}" if total is not None else "")
    )
    parts.append(f"state={b.get('state', '?')}")
    if "block" in b:
        lo, hi = b["block"]
        parts.append(f"scenario block [{lo}, {hi})")
    return ", ".join(parts)


def _publish_for(c, prefix: str, pid: int, payload, tolerant=None) -> None:
    """Publish a gather payload under ``pid``'s keys (used by a claimant
    standing in for a dead sibling, and by :func:`gather` itself). When
    recovery is enabled an already-existing key is tolerated: a presumed-
    dead straggler that publishes after its block was absorbed collides
    with the claimant's byte-identical publication — first writer wins.
    The work-queue result publication (round 18) forces ``tolerant=True``:
    a transient error on a write-once key is ambiguous (the set may have
    landed), and duplicate block payloads are byte-identical anyway."""
    chunks = _encode_payload(payload)
    if tolerant is None:
        tolerant = recover_enabled()
    try:
        for j, ch in enumerate(chunks):
            kv_retry(
                lambda k=f"{prefix}/{pid}/{j}", v=ch: c.key_value_set(k, v),
                op="gather_publish",
                key=f"{prefix}/{pid}/{j}",
            )
        kv_retry(
            lambda: c.key_value_set(f"{prefix}/{pid}/n", str(len(chunks))),
            op="gather_publish",
            key=f"{prefix}/{pid}/n",
        )
    except DcnRetryError:
        if not tolerant:
            raise  # attributed give-up — op/key/attempts in the message
        from ..utils.metrics import log

        log.warning(
            "dcn: gather keys for process %d already exist — block was "
            "published by another claimant (or the straggler itself); "
            "keeping the first write",
            pid,
        )


def _maybe_recover(c, prefix: str, p: int, name: str, recover) -> bool:
    """Survivor rebalance (round 15): ``p``'s beacon is stale and recovery
    is on. Claim generations 0..max_claims-1 of ``p``'s block; on a CAS
    win, rebuild the block via ``recover(p, gen)`` (checkpoint resume
    inside)
    and publish it under ``p``'s gather keys. On a CAS loss, defer to a
    LIVE claimant (keep polling for its publication); a claimant that is
    itself stale opens the next generation — the second-failure-during-
    recovery path. Returns False when generations are exhausted (caller
    raises the attributed timeout)."""
    from ..utils.metrics import log

    _, me = process_info()
    stall = _stall_s()
    for gen in range(max_claims()):
        # Coordinator claims LAST (round 17): process 0 hosts the
        # jax.distributed coordination service — the one process whose
        # death the fleet can never survive. Re-executing a dead block
        # is exactly the work most likely to die again under fault
        # pressure, so while any OTHER live worker could absorb it,
        # give them one stall window to claim first. With no live
        # sibling left (or the window expired unclaimed) process 0
        # claims as before — liveness is unchanged.
        if me == 0 and read_claim(p, gen, name=name) is None:
            deadline = time.monotonic() + stall
            while time.monotonic() < deadline:
                now = time.time()
                others = [
                    q for q, b in read_heartbeats().items()
                    if q not in (me, p) and q not in DEGRADED
                    and now - float(b.get("t", 0.0)) <= stall
                ]
                if not others:
                    break
                time.sleep(_poll_s())
                if read_claim(p, gen, name=name) is not None:
                    break
        if try_claim(p, gen, name=name):
            log.warning(
                "dcn: process %d claims dead process %d's block "
                "(gen %d) — resuming from its newest checkpoint",
                me, p, gen,
            )
            _mirror_event(
                {"event": "claim", "claimant": int(me), "for": int(p),
                 "gen": int(gen)}
            )
            t0 = time.monotonic()
            # Claim-generation fencing (round 17): the generation rides
            # into the recovery engine so telemetry can attribute which
            # claim attempt produced the block — gen > 0 means an earlier
            # claimant died mid-recovery and this is the hand-off.
            _trace.CTX[0] = _trace.static_trace(p)
            try:
                payload = recover(p, gen)
            finally:
                _trace.CTX[0] = None
            _publish_for(c, prefix, p, payload)
            log.warning(
                "dcn: process %d resumed and republished process %d's "
                "block in %.1fs", me, p, time.monotonic() - t0,
            )
            _mirror_event(
                {"event": "recovered", "claimant": int(me), "for": int(p),
                 "gen": int(gen),
                 "wall_s": round(time.monotonic() - t0, 3)}
            )
            return True
        claim = read_claim(p, gen, name=name)
        claimant = None if claim is None else int(claim.get("claimant", -1))
        if claimant is None or claimant == me:
            return True  # our own (or unreadable) claim — poll for keys
        # A claim younger than the stall window gets the benefit of the
        # doubt even without a fresh beacon — the claimant may still be
        # building its recovery engine (compile warm-up beats nothing).
        claim_age = time.time() - float(claim.get("t", 0.0))
        b = read_heartbeats().get(claimant)
        beat_age = (
            None if b is None else time.time() - float(b.get("t", 0.0))
        )
        if claim_age <= stall or beat_age is None or beat_age <= stall:
            return True  # live claimant is recovering — wait for it
        # Claimant died mid-recovery too: open the next generation.
        log.warning(
            "dcn: claimant %d of process %d's block (gen %d) went stale "
            "itself — opening generation %d", claimant, p, gen, gen + 1,
        )
    return False


def _get_attributed(c, key: str, p: int, name: str, recover=None):
    """``blocking_key_value_get`` as a short poll loop: each expiry
    inspects sibling heartbeats. A sibling whose beacon has gone stale
    past KSIM_DCN_STALL_S while we sit in the gather is presumed dead.
    With recovery off (default) the wait is abandoned IMMEDIATELY with an
    attributed :class:`DcnGatherTimeout` — instead of the anonymous hang
    to the full KSIM_DCN_TIMEOUT_S. With KSIM_DCN_RECOVER on and a
    ``recover`` callback, the dead block is claimed and re-executed
    (:func:`_maybe_recover`) and the wait continues. A sibling with a
    fresh beacon (or none at all — heartbeats may be disabled) keeps the
    round-11 semantics: wait to the full deadline, then raise with
    whatever attribution exists."""
    deadline = time.monotonic() + _timeout_ms() / 1000.0
    poll_ms = max(int(_poll_s() * 1000), 50)
    stall = _stall_s()
    prefix = key.rsplit("/", 2)[0]
    while True:
        remaining_ms = int((deadline - time.monotonic()) * 1000)
        if remaining_ms <= 0:
            hb = read_heartbeats()
            raise DcnGatherTimeout(
                f"gather({name!r}): timed out after "
                f"KSIM_DCN_TIMEOUT_S={_timeout_ms() / 1000:g}s waiting for "
                f"{_describe_process(p, hb, time.time())}. The fleet must "
                "be restarted together (scripts/dcn_launch.py).",
                missing=[p],
                heartbeats=hb,
            )
        try:
            return c.blocking_key_value_get(key, min(poll_ms, remaining_ms))
        except Exception:
            hb = read_heartbeats()
            b = hb.get(p)
            if b is not None and (
                time.time() - float(b.get("t", 0.0))
            ) > stall:
                if recover is not None and recover_enabled():
                    DEGRADED.add(p)
                    _arm_degraded_exit()
                    if _maybe_recover(c, prefix, p, name, recover):
                        continue  # claimed/claimant publishing — poll again
                raise DcnGatherTimeout(
                    f"gather({name!r}): process {p} looks DEAD — "
                    f"{_describe_process(p, hb, time.time())}; its beacon "
                    "stopped advancing for more than "
                    f"KSIM_DCN_STALL_S={stall:g}s while this process is "
                    "already in the end-of-replay gather. The scenario "
                    "axis has a hole; restart the fleet together "
                    "(scripts/dcn_launch.py).",
                    missing=[p],
                    heartbeats=hb,
                )
            # Fresh beacon (sibling alive but slower) or no beacon at all
            # (heartbeats disabled) — keep waiting toward the deadline.


def gather(name: str, payload, recover=None) -> list:
    """THE cross-process gather: publish this process's ``payload`` and
    return every process's, in process order. Called at most once per
    replay (result assembly); the chunk loop never reaches it.

    Payloads are pickled (numpy arrays, dataclasses — trusted sibling
    processes of the same program), base64-encoded and chunked under the
    coordination service's gRPC message cap. Keys carry a monotonically
    increasing sequence number, so repeated replays in one process
    lifetime never collide — provided every process gathers in the same
    order (SPMD discipline, same as collectives).

    ``recover`` (round 15): ``recover(dead_pid, gen) -> payload`` rebuilds
    a dead sibling's block deterministically (``gen`` is the claim
    generation, round 17). With KSIM_DCN_RECOVER on, a
    stale beacon routes through the claim protocol (:func:`_maybe_recover`)
    instead of raising, and the gather still completes in full."""
    global GATHER_COUNT, _seq
    nproc, pid = process_info()
    _seq += 1
    GATHER_COUNT += 1
    c = _client()
    # Round 14: delta+zlib the large integer tensors before the KV put —
    # remote payloads decode through _unpack_leaf in _decode_payload; the
    # LOCAL payload is returned as-is (it never crosses the wire), so
    # compression is invisible to callers either way.
    raw0, comp0 = COMPRESS_BYTES
    prefix = f"ksim/gather/{_seq}/{name}"
    _publish_for(c, prefix, pid, payload)
    if COMPRESS_BYTES[0] > raw0:
        from ..utils.metrics import log

        log.info(
            "gather(%s): compressed %.1f KiB of int tensors to %.1f KiB "
            "(%.1fx) before the KV put",
            name,
            (COMPRESS_BYTES[0] - raw0) / 1024,
            (COMPRESS_BYTES[1] - comp0) / 1024,
            (COMPRESS_BYTES[0] - raw0) / max(COMPRESS_BYTES[1] - comp0, 1),
        )
    out = []
    for p in range(nproc):
        if p == pid:
            out.append(payload)
            continue
        n = int(
            _get_attributed(c, f"{prefix}/{p}/n", p, name, recover=recover)
        )
        out.append(
            _decode_payload(
                _get_attributed(
                    c, f"{prefix}/{p}/{j}", p, name, recover=recover
                )
                for j in range(n)
            )
        )
    return out


# -- work-stealing scenario-block queue (round 18) ---------------------------
#
# The static "process p owns block p forever" slicing becomes a KV-backed
# WORK QUEUE over contiguous scenario blocks: processes lease blocks via
# the claim-CAS idiom (generation-stamped, renewed on the heartbeat
# cadence), publish per-block results keyed by BLOCK id instead of pid,
# and every process assembles the end result from whichever process
# completed each block — byte-identical to the static-slicing oracle for
# any interleaving, because block execution is deterministic given the
# block bounds and the full-list engine gates.
#
# On top of the queue:
#   * straggler mitigation — when a lease's renewal goes stale past
#     KSIM_DCN_STRAGGLER_S (or the holder falls under the fleet's
#     progress-rate watermark), an idle process wins a one-shot
#     speculator election and re-executes the block from the holder's
#     newest published checkpoint; first-complete-wins via CAS on the
#     block's done key, duplicates discarded deterministically.
#   * lease expiry — past KSIM_DCN_STALL_S the holder is presumed dead
#     and the lease is STOLEN (next generation), same stall window as
#     the round-15 claim protocol. Lease expiry implies a process may
#     never reach the collective shutdown barrier, so any steal or
#     speculative win arms the degraded exit fleet-wide.
#   * true elastic join — a process whose contribution starts mid-replay
#     (KSIM_DCN_JOIN_DELAY_S, set by scripts/dcn_launch.py --join) leases
#     whatever blocks are still pending instead of being restricted to
#     claiming dead siblings' work. (The jax.distributed runtime barriers
#     until every process CONNECTS, so joiners connect at launch and
#     defer their contribution — see scripts/dcn_launch.py.)
#
# Everything is off by default (KSIM_DCN_WORKQUEUE / dcn.workQueue YAML);
# wq_run bumps the gather sequence exactly once per replay, so the
# "one gather per replay" GATHER_COUNT contract is unchanged.

WQ_PREFIX = "ksim/wq"

# Cumulative work-queue accounting for THIS process. leases/steals/
# spec_* count protocol outcomes; dup_discards are executions that lost
# the done-CAS (byte-identical duplicates, dropped); renew_wall_s is the
# lease-renewal overhead riding the heartbeat cadence;
# straggler_wall_saved_s is a lower-bound estimate per speculative win
# (the residual wait before lease expiry would even have fired).
WQ_STATS = {
    "leases": 0,
    "steals": 0,
    "spec_attempts": 0,
    "spec_wins": 0,
    "spec_losses": 0,
    "blocks_executed": 0,
    "dup_discards": 0,
    "renewals": 0,
    "renew_wall_s": 0.0,
    "spec_wasted_chunks": 0,
    "straggler_wall_saved_s": 0.0,
}

# The lease this process is currently executing (set by wq_run around the
# execute callback): {"key": renew key, "bid", "gen"}. heartbeat() renews
# it on every beat and stamps the beacon with leased_blocks/wq_block.
_ACTIVE_LEASE: list = [None]

# Chunks executed by the most recent block engine (set via
# note_block_chunks by sim.whatif) — the driver charges them to
# spec_wasted_chunks when a speculative execution loses the done-CAS.
_LAST_EXEC_CHUNKS = [0]


def wq_stats() -> dict:
    """Snapshot of :data:`WQ_STATS` (copy — callers diff it)."""
    return dict(WQ_STATS)


def note_block_chunks(n: int) -> None:
    """Record how many chunks the last block execution actually ran
    (resumed executions count only the chunks after the checkpoint)."""
    _LAST_EXEC_CHUNKS[0] = max(int(n), 0)


def wq_enabled() -> bool:
    """Work-stealing scenario-block queue (``KSIM_DCN_WORKQUEUE``;
    default off — static per-process slicing stays the default)."""
    return str(
        os.environ.get("KSIM_DCN_WORKQUEUE", "0")
    ).strip().lower() in ("1", "true", "yes", "on")


def wq_block_size() -> int:
    """Scenarios per queue block (``KSIM_DCN_WQ_BLOCK``; 0 = auto:
    ``n_global // worker_count()`` — one block per worker, reproducing
    the static partition exactly when nobody steals)."""
    try:
        return max(int(os.environ.get("KSIM_DCN_WQ_BLOCK", "0")), 0)
    except ValueError:
        return 0


def speculate_enabled() -> bool:
    """Speculative re-execution of straggling blocks
    (``KSIM_DCN_SPECULATE``; default off). Requires checkpoint
    publication (``KSIM_DCN_CKPT_EVERY``) to be useful — the speculator
    resumes from the holder's newest published checkpoint."""
    return str(
        os.environ.get("KSIM_DCN_SPECULATE", "0")
    ).strip().lower() in ("1", "true", "yes", "on")


def straggler_s() -> float:
    """Lease-renewal age past which a LIVE holder counts as a straggler
    and becomes speculation-eligible (``KSIM_DCN_STRAGGLER_S``; default
    half the stall window). Distinct from lease EXPIRY at
    ``KSIM_DCN_STALL_S`` — expiry presumes death and steals the lease;
    straggling only races a backup execution against the holder."""
    try:
        v = float(os.environ.get("KSIM_DCN_STRAGGLER_S", "0") or 0.0)
    except ValueError:
        v = 0.0
    return v if v > 0 else _stall_s() / 2.0


def join_delay_s() -> float:
    """Seconds this process defers its work-queue contribution
    (``KSIM_DCN_JOIN_DELAY_S``, set per-joiner by scripts/dcn_launch.py
    --join). The coordination CONNECT happened at launch (the runtime
    barriers on it); the queue entry is what joins mid-replay."""
    try:
        return max(float(os.environ.get("KSIM_DCN_JOIN_DELAY_S", "0") or 0), 0.0)
    except ValueError:
        return 0.0


def wq_blocks(n_global: int) -> list:
    """Partition a length-``n_global`` scenario axis into contiguous
    ``(lo, hi)`` queue blocks of :func:`wq_block_size` scenarios (the
    last block may be smaller — uneven sizes are legal; concatenating
    block results in block order always reproduces global order)."""
    n = int(n_global)
    per = wq_block_size() or max(n // worker_count(), 1)
    return [(lo, min(lo + per, n)) for lo in range(0, n, per)]


def wq_ckpt_epoch(seq: int, bid: int) -> int:
    """Checkpoint namespace for work-queue block ``bid`` of gather
    ``seq``: always negative, so block checkpoints never collide with the
    static path's positive epochs — and distinct per block, so a
    speculator resuming block b never picks up the holder's checkpoint
    for a DIFFERENT block at a higher cursor."""
    return -(int(seq) * 100_000 + int(bid) + 1)


def _wq_read_json(c, key: str, timeout_ms: int = 2000):
    """Non-fatal JSON read of one queue key (None when absent/bad)."""
    try:
        return json.loads(c.blocking_key_value_get(key, int(timeout_ms)))
    except Exception:
        return None


def _wq_cas(c, key: str, meta: dict):
    """Write-once CAS with transient-ambiguity read-back (the try_claim
    pattern): returns the WINNING value — ``meta`` itself when our set
    landed, the existing value on a loss, None when the key is
    unreadable (callers treat that as a loss and re-poll)."""
    blob = json.dumps(meta, sort_keys=True)
    try:
        kv_retry(lambda: c.key_value_set(key, blob), op="wq_cas", key=key)
        return meta
    except Exception:
        pass
    return _wq_read_json(c, key)


def wq_run(name: str, blocks: list, execute) -> list:
    """THE work-queue driver: lease, execute and publish scenario blocks
    until every block has a winner, then assemble the per-block payloads
    in block order. Every process runs this (workers, spares, joiners)
    and every process returns the same list. Counts as this replay's ONE
    gather (bumps the sequence and GATHER_COUNT exactly once).

    ``execute(bid, lo, hi, resume_pid, gen, speculative, queue_depth)``
    runs block ``bid`` deterministically and returns its payload;
    ``resume_pid >= 0`` asks it to resume from that pid's newest
    published checkpoint for this block's epoch (steals and speculative
    re-executions), ``-1`` executes from chunk 0."""
    global GATHER_COUNT, _seq
    nproc, pid = process_info()
    _seq += 1
    GATHER_COUNT += 1
    c = _client()
    nb = len(blocks)
    prefix = f"{WQ_PREFIX}/{_seq}/{name}"
    hb_on = heartbeat_every() > 0
    spec_on = speculate_enabled()
    stall = _stall_s()
    strag = straggler_s()
    poll = _poll_s()
    gen_cap = max_claims()
    deadline = time.monotonic() + _timeout_ms() / 1000.0
    local: Dict[int, object] = {}  # bid -> payload computed HERE
    done: Dict[int, dict] = {}  # bid -> winning done meta
    spec_tried: set = set()  # (bid, gen) speculator elections entered
    spec_deferred: set = set()  # leader's one-sweep election deferrals
    jbase = os.path.join("wq", str(_seq), str(name))  # journal namespace

    # Durable ground (round 20): a fleet restarted over the dead one's
    # journal adopts every block whose durable done record AND result
    # blob validate — no re-execution, and the adopted payloads are the
    # dead fleet's bytes, so the assembled gather is byte-identical to
    # an uninterrupted run. Adoption goes straight into `done`/`local`
    # (NOT through _note_done: the old fleet's steal/speculation flags
    # must not arm the degraded exit in this healthy fleet). Unfinished
    # blocks keep the newest durable lease holder as a resume hint —
    # the execute path loads that pid's durable block checkpoint.
    resume_hint: Dict[int, int] = {}
    if resume_enabled() and durable_dir():
        adopted, resume_hint = _journal_wq_scan(_seq, name, nb)
        for bid, (meta, payload) in sorted(adopted.items()):
            done[bid] = meta
            local[bid] = payload
            JOURNAL_STATS["adopted"] += 1
            _mirror_event(
                {"event": "journal_adopt", "pid": int(pid),
                 "block": int(bid), "from": int(meta.get("pid", -1)),
                 "gen": int(meta.get("gen", 0) or 0)}
            )

    def _lease_key(bid: int, gen: int) -> str:
        return f"{prefix}/lease/{int(bid)}/{int(gen)}"

    def _renew_key(bid: int) -> str:
        return f"{prefix}/renew/{int(bid)}"

    def _done_key(bid: int) -> str:
        return f"{prefix}/done/{int(bid)}"

    def _read_dir(sub: str) -> dict:
        """All keys under ``<prefix>/<sub>`` as {tail-path: parsed JSON}.
        One non-blocking dir RPC — a blocking get on an ABSENT key would
        wait out its whole timeout, which the poll sweeps can't afford."""
        try:
            entries = kv_retry(
                lambda: c.key_value_dir_get(f"{prefix}/{sub}"),
                op="wq_dir",
                key=f"{prefix}/{sub}",
                attempts=2,
            )
        except Exception:
            return {}
        out = {}
        for key, val in entries:
            tail = str(key).split(f"/{sub}/", 1)[-1]
            try:
                out[tail] = json.loads(val)
            except (ValueError, TypeError):
                continue
        return out

    def _note_done(bid: int, meta: dict) -> None:
        done[bid] = meta
        # Durable done ledger (round 20): every learner mirrors the
        # winning meta — the same KV bytes from every process, so the
        # atomic-replace writes are idempotent, and the record survives
        # the winner dying right after its CAS landed.
        _journal_write_json(os.path.join(jbase, "done", str(bid)), meta)
        # A stolen or speculated block means some process may never reach
        # the collective shutdown barrier (a dead holder can't; a live
        # straggler may be unboundedly late) — EVERY process that learns
        # of it skips the barrier at exit, so nobody hangs on it.
        if meta.get("spec") or int(meta.get("gen", 0) or 0) > 0:
            _arm_degraded_exit()

    def _run_block(
        bid, gen, resume_pid, speculative, renew_age=0.0, threshold=0.0
    ):
        from ..utils.metrics import log

        lo, hi = blocks[bid]
        qd = nb - len(done)
        kind = (
            "speculate" if speculative else ("steal" if gen else "lease")
        )
        verb = {
            "lease": "leases", "steal": "steals", "speculate": "speculates",
        }[kind]
        log.info(
            "dcn wq: process %d %s block %d [%d, %d) gen %d%s",
            pid, verb, bid, lo, hi, gen,
            f" (resuming from pid {resume_pid})" if resume_pid >= 0 else "",
        )
        ev = {"event": kind, "pid": int(pid), "block": int(bid),
              "gen": int(gen), "from": int(resume_pid)}
        if kind in ("steal", "speculate"):
            # Evidence for the post-mortem's "every steal is preceded by
            # a stale renewal" invariant: the renewal age observed at
            # the decision, and the threshold it had to exceed.
            ev["renew_age_s"] = round(float(renew_age), 3)
            ev["threshold_s"] = round(float(threshold), 3)
        _mirror_event(ev)
        _ACTIVE_LEASE[0] = {
            "key": _renew_key(bid), "bid": int(bid), "gen": int(gen),
        }
        # Durable lease ledger (round 20): the newest holder of each
        # block, so a restarted fleet knows WHOSE durable checkpoint to
        # resume an in-flight block from.
        _journal_write_json(
            os.path.join(jbase, "lease", str(bid)),
            {"pid": int(pid), "gen": int(gen), "t": time.time()},
        )
        t0 = time.monotonic()
        _trace.CTX[0] = _trace.block_trace(bid)
        try:
            payload = execute(bid, lo, hi, resume_pid, gen, speculative, qd)
        finally:
            _ACTIVE_LEASE[0] = None
            _trace.CTX[0] = None
        local[bid] = payload
        _publish_for(
            c, f"{prefix}/result/{bid}", pid, payload, tolerant=True
        )
        _journal_wq_result(jbase, bid, payload)
        win = _wq_cas(
            c, _done_key(bid),
            {"pid": int(pid), "gen": int(gen), "spec": bool(speculative),
             "t": time.time()},
        )
        won = win is not None and int(win.get("pid", -1)) == int(pid)
        if won:
            WQ_STATS["blocks_executed"] += 1
            if speculative:
                WQ_STATS["spec_wins"] += 1
                # Lower-bound wall saved: the residual wait before lease
                # EXPIRY would even have let anyone steal the block.
                WQ_STATS["straggler_wall_saved_s"] += max(
                    stall - float(renew_age), 0.0
                )
            _mirror_event(
                {"event": "block_done", "pid": int(pid), "block": int(bid),
                 "gen": int(gen), "spec": bool(speculative),
                 "wall_s": round(time.monotonic() - t0, 3)}
            )
        else:
            WQ_STATS["dup_discards"] += 1
            if speculative:
                WQ_STATS["spec_losses"] += 1
                WQ_STATS["spec_wasted_chunks"] += _LAST_EXEC_CHUNKS[0]
            log.info(
                "dcn wq: process %d's %s of block %d lost the "
                "first-complete-wins CAS to process %s — duplicate "
                "discarded (byte-identical by construction)",
                pid, kind, bid, None if win is None else win.get("pid"),
            )
            _mirror_event(
                {"event": "spec_lost" if speculative else "dup_discard",
                 "pid": int(pid), "block": int(bid), "gen": int(gen)}
            )
        if win is not None:
            _note_done(bid, win)

    def _try_lease(bid: int, gen: int) -> bool:
        win = _wq_cas(
            c, _lease_key(bid, gen),
            {"pid": int(pid), "gen": int(gen), "t": time.time()},
        )
        return win is not None and int(win.get("pid", -1)) == int(pid)

    # Mid-replay joiner (dcn_launch --join): the coordination connect
    # happened at process start; the CONTRIBUTION is deferred here. While
    # asleep the fleet sees a live "join" beacon, never a stale one.
    delay = join_delay_s()
    if delay > 0:
        if hb_on:
            heartbeat(
                -1, state="join",
                extra={"leased_blocks": 0, "queue_depth": nb,
                       "join_delay_s": delay},
            )
        time.sleep(delay)
        _mirror_event({"event": "join", "pid": int(pid)})

    # Phase A — primary drain: generation-0 leases, iteration order
    # rotated so process p starts at block p (mod nb). With the auto
    # block size (one block per worker) and no contention this
    # reproduces the static partition exactly.
    for k in range(nb):
        bid = (pid + k) % nb
        if bid in done or time.monotonic() > deadline:
            continue
        dones = _read_dir("done")
        if str(bid) in dones:
            _note_done(bid, dones[str(bid)])
            continue
        if _try_lease(bid, 0):
            WQ_STATS["leases"] += 1
            _run_block(bid, 0, resume_hint.get(bid, -1), False)

    # Phase B — wait for the remaining blocks; steal expired leases, lease
    # late-appearing pending blocks, and speculate on stragglers.
    while len(done) < nb:
        if time.monotonic() > deadline:
            hb = read_heartbeats()
            missing = sorted(b for b in range(nb) if b not in done)
            raise DcnGatherTimeout(
                f"wq_run({name!r}): timed out after "
                f"KSIM_DCN_TIMEOUT_S={_timeout_ms() / 1000:g}s with blocks "
                f"{missing} still unfinished. "
                + "; ".join(
                    _describe_process(q, hb, time.time())
                    for q in sorted(hb)
                ),
                missing=missing,
                heartbeats=hb,
            )
        progressed = False
        beats = read_heartbeats()
        now = time.time()
        # Fleet progress-rate watermark (the round-8 live-buffer gauge's
        # companion): the fastest chunk rate any lease-holder reports.
        rates = [
            float(b.get("wq_rate", 0.0))
            for b in beats.values()
            if b.get("wq_rate") and now - float(b.get("t", 0.0)) <= stall
        ]
        watermark = max(rates) if rates else 0.0
        dones = _read_dir("done")
        for bid, meta in (
            (int(k), v) for k, v in dones.items() if k.isdigit()
        ):
            if bid not in done:
                _note_done(bid, meta)
                progressed = True
        lease_dir = _read_dir("lease")  # "<bid>/<gen>" -> meta
        renews = _read_dir("renew")  # "<bid>" -> meta
        newest: Dict[int, tuple] = {}
        for tail, meta in lease_dir.items():
            parts_k = tail.split("/")
            if len(parts_k) != 2:
                continue
            try:
                b, g = int(parts_k[0]), int(parts_k[1])
            except ValueError:
                continue
            if b not in newest or g > newest[b][0]:
                newest[b] = (g, meta)
        for bid in range(nb):
            if bid in done:
                continue
            gen, lease = newest.get(bid, (-1, None))
            if lease is None:
                # Never leased — pending work (the elastic-join case, or
                # a fleet with more blocks than processes racing here).
                if _try_lease(bid, 0):
                    WQ_STATS["leases"] += 1
                    _run_block(bid, 0, resume_hint.get(bid, -1), False)
                    progressed = True
                continue
            holder = int(lease.get("pid", -1))
            if holder == pid:
                continue  # ambiguity artifact: our own lease, re-poll
            renew = renews.get(str(bid))
            if renew is not None and int(renew.get("gen", -1)) == gen:
                age = now - float(renew.get("t", now))
                holder_chunk = int(renew.get("chunk", -1))
            else:
                age = now - float(lease.get("t", now))
                holder_chunk = -1
            hb_holder = beats.get(holder)
            holder_rate = (
                float(hb_holder.get("wq_rate", 0.0)) if hb_holder else 0.0
            )
            lagging = (
                watermark > 0.0
                and holder_rate > 0.0
                and holder_rate < 0.25 * watermark
            )
            if (
                spec_on
                and (bid, gen) not in spec_tried
                and holder_chunk >= 0  # first-chunk compile is exempt
                and (age > strag or lagging)
            ):
                if pid == 0 and (bid, gen) not in spec_deferred:
                    # The leader hosts the coordination service — its
                    # death is unsurvivable by construction, so give
                    # sibling idle processes one poll's head start at
                    # the election and take the risky role only when
                    # nobody else picked it up.
                    spec_deferred.add((bid, gen))
                    continue
                # Straggler: one-shot speculator election per (block,
                # generation) — exactly one idle process re-executes.
                # Checked BEFORE lease expiry: a speculative win
                # completes the block without burning one of the
                # gen_cap-bounded lease generations, so an untried
                # election always gets the first shot — steal is the
                # fallback once it is spent (or the holder never
                # renewed at this generation).
                spec_tried.add((bid, gen))
                win = _wq_cas(
                    c, f"{prefix}/spec/{bid}/{gen}",
                    {"pid": int(pid), "t": now},
                )
                if win is not None and int(win.get("pid", -1)) == pid:
                    WQ_STATS["spec_attempts"] += 1
                    _run_block(
                        bid, gen, holder, True,
                        renew_age=age, threshold=strag,
                    )
                    progressed = True
                continue
            if age > stall and gen < gen_cap:
                # Lease EXPIRED — the holder is presumed dead (same stall
                # window as the round-15 claim protocol). Steal it: open
                # the next generation and resume from the holder's newest
                # published checkpoint for this block.
                if _try_lease(bid, gen + 1):
                    WQ_STATS["steals"] += 1
                    DEGRADED.add(holder)
                    _arm_degraded_exit()
                    _run_block(
                        bid, gen + 1, holder, False,
                        renew_age=age, threshold=stall,
                    )
                    progressed = True
                continue
        if not progressed:
            if hb_on:
                # Idle, queue not empty: the beacon says so explicitly —
                # "waiting with zero leases" is not "stalled holding one".
                heartbeat(
                    -1, state="wq_wait",
                    extra={
                        "leased_blocks": 0,
                        "queue_depth": int(nb - len(done)),
                    },
                )
            time.sleep(poll)

    # Phase C — assembly: fetch each block from its WINNER (local reuse
    # when we won it), in block order. Results were published BEFORE the
    # done-CAS, so the keys exist by construction.
    parts = []
    for bid in range(nb):
        win = done[bid]
        wpid = int(win.get("pid", -1))
        if bid in local:
            # Ours (winner or byte-identical duplicate) or adopted from
            # the durable journal — the journal-adopted case is the one
            # where `wpid` names a DEAD fleet's process whose result
            # keys don't exist in this fleet's KV plane at all.
            parts.append(local[bid])
            continue
        rp = f"{prefix}/result/{bid}/{wpid}"
        n = int(_get_attributed(c, f"{rp}/n", wpid, name, recover=None))
        parts.append(
            _decode_payload(
                _get_attributed(c, f"{rp}/{j}", wpid, name, recover=None)
                for j in range(n)
            )
        )

    # Phase D — exit rendezvous. A degraded exit skips the collective
    # shutdown barrier, but process 0 hosts the coordination service:
    # its teardown ABORTS every process still touching the KV — and a
    # live straggler may be mid-execution (it will lose the done-CAS,
    # then fetch the winners for ITS assembly) arbitrarily long after
    # the fleet finished. Each process marks its assembly complete; the
    # leader lingers until every peer has either marked done or stopped
    # advancing its beacon for a grace window (it is dead — waiting
    # longer helps nobody).
    try:
        kv_retry(
            lambda: c.key_value_set(
                f"{prefix}/exit/{pid}", json.dumps({"t": time.time()})
            ),
            op="wq_exit",
            key=f"{prefix}/exit/{pid}",
            attempts=2,
        )
    except Exception:
        pass
    if pid == 0 and _degraded_exit_armed[0]:
        grace = max(stall, 10.0)
        last_t: Dict[int, float] = {}
        last_adv: Dict[int, float] = {}
        while time.monotonic() < deadline:
            exited = _read_dir("exit")
            waiting = [
                q for q in range(nproc)
                if q != pid and str(q) not in exited
            ]
            if not waiting:
                break
            beats = read_heartbeats()
            mono = time.monotonic()
            any_alive = False
            for q in waiting:
                t_q = float(beats.get(q, {}).get("t", 0.0))
                if q not in last_adv or t_q > last_t.get(q, 0.0):
                    last_t[q] = t_q
                    last_adv[q] = mono
                if mono - last_adv[q] <= grace:
                    any_alive = True
            if not any_alive:
                break
            time.sleep(poll)
    return parts


def output_path_for_process(path: Optional[str]) -> Optional[str]:
    """Per-process JSONL/checkpoint sink: process 0 keeps the configured
    path (its file is the one the parity bar compares byte-for-byte
    against a single-process run); siblings write ``<path>.p<pid>`` so
    concurrent workers on one machine never interleave writes."""
    if path is None:
        return None
    _, pid = process_info()
    return path if pid == 0 else f"{path}.p{pid}"
