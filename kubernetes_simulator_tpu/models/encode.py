"""String→integer SoA encodings of the cluster object model (SURVEY.md §3.4).

Everything the scheduling hot loop touches is encoded here ONCE, on host,
into rectangular numpy arrays (padded + masked — SURVEY.md §7 hard part #4).
Nothing inside the CPU-vectorized or JAX device loop touches strings.

Key encoding decisions:

- **kv ids**: every (label key, label value) pair gets one integer id, so
  set-membership tests (``In``/``NotIn``) are integer equality — equal kv id
  implies equal key AND value.
- **Selector-expression dedup**: node-selector match expressions are
  interned into one table (``expr_*``); pods reference expressions by id.
  Node-side match matrices ``[N, E]`` are then computed *on device* from
  node label tensors, so what-if label perturbations flow through without
  re-encoding (SURVEY.md §2 "what-if scenario engine").
- **Count groups**: every unique (label selector, resolved namespace set,
  topology key) used by inter-pod (anti-)affinity or topology-spread terms
  becomes one "count group" g. The mutable scheduling state carries
  ``match_count[g, domain]`` (plus symmetric-anti and preferred-weight
  tensors) updated by scatter-add at bind time — SURVEY.md §7 hard part #2.
  Pod labels are static, so ``pod_matches_group[p, g]`` is precomputed host
  side.

Provenance: [K8S] semantics + [BASELINE] surface; reference mount empty
(SURVEY.md §0) — no reference file:line citations are possible.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .core import (
    CPU,
    MEMORY,
    PODS,
    Cluster,
    Effect,
    LabelSelector,
    MatchExpression,
    NodeSelectorTerm,
    Operator,
    Pod,
    PodAffinityTerm,
)

# Default allocatable "pods" slots when a node spec omits it ([K8S] kubelet
# default --max-pods).
DEFAULT_MAX_PODS = 110.0

# Pad values. PAD = empty slot; WILDCARD is used by toleration keys
# (key=None + Exists → tolerate everything).
PAD = -1
TOL_PAD = -2
TOL_WILDCARD = -1


def _try_float(s: str) -> float:
    try:
        return float(s)
    except (TypeError, ValueError):
        return np.nan


@dataclass
class Vocab:
    """Interning tables shared by every encoded tensor."""

    resources: List[str] = field(default_factory=list)
    keys: List[str] = field(default_factory=list)
    kvs: List[Tuple[str, str]] = field(default_factory=list)
    namespaces: List[str] = field(default_factory=list)
    topo_keys: List[str] = field(default_factory=list)

    def __post_init__(self):
        self._r = {v: i for i, v in enumerate(self.resources)}
        self._k = {v: i for i, v in enumerate(self.keys)}
        self._kv = {v: i for i, v in enumerate(self.kvs)}
        self._ns = {v: i for i, v in enumerate(self.namespaces)}
        self._t = {v: i for i, v in enumerate(self.topo_keys)}

    def _intern(self, table: list, index: dict, item) -> int:
        i = index.get(item)
        if i is None:
            i = len(table)
            table.append(item)
            index[item] = i
        return i

    def resource(self, name: str) -> int:
        return self._intern(self.resources, self._r, name)

    def key(self, k: str) -> int:
        return self._intern(self.keys, self._k, k)

    def kv(self, k: str, v: str) -> int:
        return self._intern(self.kvs, self._kv, (k, str(v)))

    def ns(self, n: str) -> int:
        return self._intern(self.namespaces, self._ns, n)

    def topo(self, k: str) -> int:
        return self._intern(self.topo_keys, self._t, k)


@dataclass(frozen=True)
class CountGroupKey:
    """Dedup key for a count group (see module docstring)."""

    selector: LabelSelector
    namespaces: Tuple[str, ...]  # sorted, resolved
    topology_key: str


def _pad2(rows: Sequence[Sequence[int]], width: int, pad=PAD, dtype=np.int32) -> np.ndarray:
    out = np.full((len(rows), max(width, 1)), pad, dtype=dtype)
    for i, r in enumerate(rows):
        if r:
            out[i, : len(r)] = r
    return out


def _pad3(rows: Sequence[Sequence[Sequence[int]]], w1: int, w2: int, pad=PAD) -> np.ndarray:
    out = np.full((len(rows), max(w1, 1), max(w2, 1)), pad, dtype=np.int32)
    for i, terms in enumerate(rows):
        for j, term in enumerate(terms):
            if term:
                out[i, j, : len(term)] = term
    return out


@dataclass
class EncodedCluster:
    """Static (per-scenario) node-side tensors. Shapes use N nodes, R
    resources, L label slots, TT taint slots, T topology keys, E exprs,
    G count groups, D domains (padded to Dmax)."""

    vocab: Vocab
    node_names: List[str]
    num_nodes: int
    allocatable: np.ndarray  # [N, R] f32
    node_label_key: np.ndarray  # [N, L] i32 (PAD)
    node_label_kv: np.ndarray  # [N, L] i32 (PAD)
    node_label_num: np.ndarray  # [N, L] f32 (NaN when not numeric)
    taint_key: np.ndarray  # [N, TT] i32 (PAD)
    taint_kv: np.ndarray  # [N, TT] i32 (PAD)
    taint_effect: np.ndarray  # [N, TT] i32 (0 = pad)
    node_domain: np.ndarray  # [T, N] i32 domain id per topology key (PAD = key absent)
    num_domains: np.ndarray  # [T] i32
    max_domains: int
    # Interned node-selector expression table.
    expr_key: np.ndarray  # [E] i32
    expr_op: np.ndarray  # [E] i32
    expr_vals: np.ndarray  # [E, V] i32 (PAD)
    expr_num: np.ndarray  # [E] f32
    # Count groups.
    group_topo: np.ndarray  # [G] i32 → topology-key index
    group_keys: List[CountGroupKey]

    @property
    def num_resources(self) -> int:
        return self.allocatable.shape[1]

    @property
    def num_groups(self) -> int:
        return len(self.group_keys)


@dataclass
class EncodedPods:
    """Workload-side tensors. Index order = arrival order; the first
    ``num_prebound`` entries may carry ``bound_node >= 0`` (initial state)."""

    num_pods: int
    names: List[str]
    requests: np.ndarray  # [P, R] f32
    priority: np.ndarray  # [P] i32
    arrival: np.ndarray  # [P] f64
    duration: np.ndarray  # [P] f32 (inf = runs forever)
    ns: np.ndarray  # [P] i32
    bound_node: np.ndarray  # [P] i32 (PAD = needs scheduling)
    # Tolerations.
    tol_key: np.ndarray  # [P, TO] i32 (TOL_PAD / TOL_WILDCARD)
    tol_kv: np.ndarray  # [P, TO] i32 (PAD = Exists operator: any value)
    tol_effect: np.ndarray  # [P, TO] i32 (0 = all effects)
    # Node affinity (expression ids into EncodedCluster.expr_*).
    na_req: np.ndarray  # [P, TR, TE] i32 (PAD); a term is valid iff slot 0 >= 0
    na_has_req: np.ndarray  # [P] bool
    na_pref: np.ndarray  # [P, TP, TE] i32
    na_pref_w: np.ndarray  # [P, TP] f32 (0 = pad)
    # Inter-pod affinity (count-group ids).
    aff_req: np.ndarray  # [P, AR] i32 (PAD)
    anti_req: np.ndarray  # [P, AA] i32 (PAD)
    pref_aff: np.ndarray  # [P, PA] i32 (PAD)
    pref_aff_w: np.ndarray  # [P, PA] f32 (negative = preferred anti-affinity)
    # Topology spread.
    spread_g: np.ndarray  # [P, SP] i32 (PAD)
    spread_skew: np.ndarray  # [P, SP] i32
    spread_dns: np.ndarray  # [P, SP] bool (True = DoNotSchedule)
    # Static selector matches.
    pod_matches_group: np.ndarray  # [P, G] bool
    # Gang / coscheduling.
    group_id: np.ndarray  # [P] i32 (PAD = not in a pod group)
    pg_min_member: np.ndarray  # [NG] i32
    pg_names: List[str]


class Encoder:
    """Builds :class:`EncodedCluster` + :class:`EncodedPods` from the object
    model. One encoder instance = one shared vocab."""

    def __init__(self):
        self.vocab = Vocab()
        # Seed well-known resources so indices are stable across traces.
        for r in (CPU, MEMORY, PODS):
            self.vocab.resource(r)
        self._exprs: List[Tuple[int, int, Tuple[int, ...], float]] = []
        self._expr_index: Dict = {}
        self._groups: List[CountGroupKey] = []
        self._group_index: Dict[CountGroupKey, int] = {}

    # -- interning ---------------------------------------------------------

    def _intern_expr(self, e: MatchExpression) -> int:
        kid = self.vocab.key(e.key)
        vals = tuple(sorted(self.vocab.kv(e.key, v) for v in e.values))
        num = _try_float(e.values[0]) if e.values else np.nan
        item = (kid, int(e.operator), vals, num)
        idx = self._expr_index.get(item)
        if idx is None:
            idx = len(self._exprs)
            self._exprs.append(item)
            self._expr_index[item] = idx
        return idx

    def _intern_group(self, selector: LabelSelector, namespaces: Tuple[str, ...], topology_key: str) -> int:
        key = CountGroupKey(selector, tuple(sorted(namespaces)), topology_key)
        idx = self._group_index.get(key)
        if idx is None:
            idx = len(self._groups)
            self._groups.append(key)
            self._group_index[key] = idx
            self.vocab.topo(topology_key)
            for n in namespaces:
                self.vocab.ns(n)
        return idx

    def _term_group(self, term: PodAffinityTerm, pod_ns: str) -> int:
        ns = term.namespaces or (pod_ns,)
        return self._intern_group(term.label_selector, tuple(ns), term.topology_key)

    # -- main entry --------------------------------------------------------

    def encode(self, cluster: Cluster, workload: Sequence[Pod]) -> Tuple[EncodedCluster, EncodedPods]:
        pods: List[Pod] = list(cluster.pods) + list(workload)

        # Resource vocabulary: union over nodes and pods (extended resources
        # become extra rows — [BASELINE] "device-plugin extended resources").
        for n in cluster.nodes:
            for r in n.allocatable:
                self.vocab.resource(r)
        for p in pods:
            for r in p.requests:
                self.vocab.resource(r)

        enc_pods = self._encode_pods(cluster, pods)
        enc_cluster = self._encode_cluster(cluster)
        # pod_matches_group needs the final group table → fill here.
        G = len(self._groups)
        pmg = np.zeros((len(pods), max(G, 1)), dtype=bool)
        for gi, gk in enumerate(self._groups):
            ns_set = set(gk.namespaces)
            for pi, p in enumerate(pods):
                if p.namespace in ns_set and gk.selector.matches(p.labels):
                    pmg[pi, gi] = True
        enc_pods.pod_matches_group = pmg
        return enc_cluster, enc_pods

    # -- pods --------------------------------------------------------------

    def _encode_pods(self, cluster: Cluster, pods: List[Pod]) -> EncodedPods:
        P = len(pods)
        node_index = {n.name: i for i, n in enumerate(cluster.nodes)}

        tol_rows_k, tol_rows_v, tol_rows_e = [], [], []
        na_req_rows, na_pref_rows, na_pref_w_rows = [], [], []
        aff_rows, anti_rows, pref_rows, pref_w_rows = [], [], [], []
        spr_rows, spr_skew_rows, spr_dns_rows = [], [], []

        for p in pods:
            tk, tv, te = [], [], []
            for t in p.tolerations:
                tk.append(TOL_WILDCARD if t.key is None else self.vocab.key(t.key))
                tv.append(PAD if t.operator == "Exists" else self.vocab.kv(t.key or "", t.value))
                te.append(0 if t.effect is None else int(t.effect))
            tol_rows_k.append(tk)
            tol_rows_v.append(tv)
            tol_rows_e.append(te)

            na_req_rows.append(
                [[self._intern_expr(e) for e in term.match_expressions] for term in p.node_affinity.required]
            )
            na_pref_rows.append(
                [[self._intern_expr(e) for e in pt.term.match_expressions] for pt in p.node_affinity.preferred]
            )
            na_pref_w_rows.append([float(pt.weight) for pt in p.node_affinity.preferred])

            aff_rows.append([self._term_group(t, p.namespace) for t in p.pod_affinity.required])
            anti_rows.append([self._term_group(t, p.namespace) for t in p.pod_anti_affinity.required])
            pg, pw = [], []
            for wt in p.pod_affinity.preferred:
                pg.append(self._term_group(wt.term, p.namespace))
                pw.append(float(wt.weight))
            for wt in p.pod_anti_affinity.preferred:
                pg.append(self._term_group(wt.term, p.namespace))
                pw.append(-float(wt.weight))
            pref_rows.append(pg)
            pref_w_rows.append(pw)

            sg, sk, sd = [], [], []
            for c in p.topology_spread:
                sg.append(self._intern_group(c.label_selector, (p.namespace,), c.topology_key))
                sk.append(int(c.max_skew))
                sd.append(c.when_unsatisfiable == "DoNotSchedule")
            spr_rows.append(sg)
            spr_skew_rows.append(sk)
            spr_dns_rows.append(sd)

        R = len(self.vocab.resources)
        requests = np.zeros((P, R), dtype=np.float32)
        for i, p in enumerate(pods):
            for r, q in p.requests.items():
                requests[i, self.vocab.resource(r)] = q

        # Gang groups.
        pg_index: Dict[str, int] = {}
        pg_names: List[str] = []
        group_id = np.full(P, PAD, dtype=np.int32)
        explicit_sizes: Dict[str, int] = {}
        member_counts: Dict[str, int] = {}
        for i, p in enumerate(pods):
            if p.pod_group is not None:
                if p.pod_group not in pg_index:
                    pg_index[p.pod_group] = len(pg_names)
                    pg_names.append(p.pod_group)
                group_id[i] = pg_index[p.pod_group]
                member_counts[p.pod_group] = member_counts.get(p.pod_group, 0) + 1
        for name, g in cluster.pod_groups.items():
            explicit_sizes[name] = g.min_member
        pg_min = np.array(
            [explicit_sizes.get(n, member_counts.get(n, 1)) for n in pg_names],
            dtype=np.int32,
        ).reshape(-1)

        w = lambda rows: max((len(r) for r in rows), default=0)
        na_req_w1 = w(na_req_rows)
        na_req_w2 = max((len(t) for r in na_req_rows for t in r), default=0)
        na_pref_w1 = w(na_pref_rows)
        na_pref_w2 = max((len(t) for r in na_pref_rows for t in r), default=0)

        pref_w_arr = np.zeros((P, max(w(pref_rows), 1)), dtype=np.float32)
        for i, r in enumerate(pref_w_rows):
            if r:
                pref_w_arr[i, : len(r)] = r
        na_pref_w_arr = np.zeros((P, max(na_pref_w1, 1)), dtype=np.float32)
        for i, r in enumerate(na_pref_w_rows):
            if r:
                na_pref_w_arr[i, : len(r)] = r
        spr_skew = _pad2(spr_skew_rows, w(spr_rows), pad=0)
        spr_dns = np.zeros((P, max(w(spr_rows), 1)), dtype=bool)
        for i, r in enumerate(spr_dns_rows):
            if r:
                spr_dns[i, : len(r)] = r

        return EncodedPods(
            num_pods=P,
            names=[p.name for p in pods],
            requests=requests,
            priority=np.array([p.priority for p in pods], dtype=np.int32).reshape(-1),
            arrival=np.array([p.arrival_time for p in pods], dtype=np.float64).reshape(-1),
            duration=np.array(
                [np.inf if p.duration is None else p.duration for p in pods], dtype=np.float32
            ).reshape(-1),
            ns=np.array([self.vocab.ns(p.namespace) for p in pods], dtype=np.int32).reshape(-1),
            bound_node=np.array(
                [node_index.get(p.node_name, PAD) if p.node_name else PAD for p in pods],
                dtype=np.int32,
            ).reshape(-1),
            tol_key=_pad2(tol_rows_k, w(tol_rows_k), pad=TOL_PAD),
            tol_kv=_pad2(tol_rows_v, w(tol_rows_v)),
            tol_effect=_pad2(tol_rows_e, w(tol_rows_e), pad=0),
            na_req=_pad3(na_req_rows, na_req_w1, na_req_w2),
            na_has_req=np.array([len(p.node_affinity.required) > 0 for p in pods], dtype=bool),
            na_pref=_pad3(na_pref_rows, na_pref_w1, na_pref_w2),
            na_pref_w=na_pref_w_arr,
            aff_req=_pad2(aff_rows, w(aff_rows)),
            anti_req=_pad2(anti_rows, w(anti_rows)),
            pref_aff=_pad2(pref_rows, w(pref_rows)),
            pref_aff_w=pref_w_arr,
            spread_g=_pad2(spr_rows, w(spr_rows)),
            spread_skew=spr_skew,
            spread_dns=spr_dns,
            pod_matches_group=np.zeros((P, 1), dtype=bool),  # filled in encode()
            group_id=group_id,
            pg_min_member=pg_min,
            pg_names=pg_names,
        )

    # -- cluster -----------------------------------------------------------

    def _encode_cluster(self, cluster: Cluster) -> EncodedCluster:
        N = len(cluster.nodes)
        R = len(self.vocab.resources)
        alloc = np.zeros((N, R), dtype=np.float32)
        pods_ri = self.vocab.resource(PODS)
        for i, n in enumerate(cluster.nodes):
            for r, q in n.allocatable.items():
                alloc[i, self.vocab.resource(r)] = q
            if PODS not in n.allocatable:
                alloc[i, pods_ri] = DEFAULT_MAX_PODS

        lab_k, lab_v, lab_n = [], [], []
        tn_k, tn_v, tn_e = [], [], []
        for n in cluster.nodes:
            lk, lv, ln = [], [], []
            for k, v in n.labels.items():
                lk.append(self.vocab.key(k))
                lv.append(self.vocab.kv(k, v))
                ln.append(_try_float(v))
            lab_k.append(lk)
            lab_v.append(lv)
            lab_n.append(ln)
            tk, tv, te = [], [], []
            for t in n.taints:
                tk.append(self.vocab.key(t.key))
                tv.append(self.vocab.kv(t.key, t.value))
                te.append(int(t.effect))
            tn_k.append(tk)
            tn_v.append(tv)
            tn_e.append(te)

        L = max((len(r) for r in lab_k), default=0)
        label_num = np.full((N, max(L, 1)), np.nan, dtype=np.float32)
        for i, r in enumerate(lab_n):
            if r:
                label_num[i, : len(r)] = r

        # Topology domains per topo key (sorted label values → deterministic
        # domain ids; SURVEY.md §7 hard part #6 determinism).
        T = len(self.vocab.topo_keys)
        node_domain = np.full((max(T, 1), N), PAD, dtype=np.int32)
        num_domains = np.zeros(max(T, 1), dtype=np.int32)
        for ti, tkey in enumerate(self.vocab.topo_keys):
            vals = sorted({n.labels[tkey] for n in cluster.nodes if tkey in n.labels})
            vi = {v: j for j, v in enumerate(vals)}
            num_domains[ti] = len(vals)
            for ni, n in enumerate(cluster.nodes):
                if tkey in n.labels:
                    node_domain[ti, ni] = vi[n.labels[tkey]]

        E = len(self._exprs)
        V = max((len(e[2]) for e in self._exprs), default=0)
        expr_key = np.array([e[0] for e in self._exprs] or [PAD], dtype=np.int32).reshape(-1)
        expr_op = np.array([e[1] for e in self._exprs] or [0], dtype=np.int32).reshape(-1)
        expr_vals = _pad2([list(e[2]) for e in self._exprs] or [[]], V)
        expr_num = np.array(
            [e[3] for e in self._exprs] or [np.nan], dtype=np.float32
        ).reshape(-1)

        group_topo = np.array(
            [self.vocab.topo(g.topology_key) for g in self._groups] or [PAD], dtype=np.int32
        ).reshape(-1)

        return EncodedCluster(
            vocab=self.vocab,
            node_names=[n.name for n in cluster.nodes],
            num_nodes=N,
            allocatable=alloc,
            node_label_key=_pad2(lab_k, L),
            node_label_kv=_pad2(lab_v, L),
            node_label_num=label_num,
            taint_key=_pad2(tn_k, max((len(r) for r in tn_k), default=0)),
            taint_kv=_pad2(tn_v, max((len(r) for r in tn_v), default=0)),
            taint_effect=_pad2(tn_e, max((len(r) for r in tn_e), default=0), pad=0),
            node_domain=node_domain,
            num_domains=num_domains,
            max_domains=int(num_domains.max()) if T else 1,
            expr_key=expr_key,
            expr_op=expr_op,
            expr_vals=expr_vals,
            expr_num=expr_num,
            group_topo=group_topo,
            group_keys=list(self._groups),
        )


def encode(cluster: Cluster, workload: Sequence[Pod]) -> Tuple[EncodedCluster, EncodedPods]:
    """Convenience one-shot encode with a fresh vocab."""
    return Encoder().encode(cluster, workload)
