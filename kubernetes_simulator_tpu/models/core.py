"""Cluster object model (layer L0 of SURVEY.md §1).

Typed, user-facing descriptions of nodes, pods, taints, tolerations,
affinity terms, topology-spread constraints, and pod groups. These mirror
the upstream Kubernetes API types that the reference simulator schedules
over ([K8S] semantics; [BASELINE] capability surface — the reference mount
was empty, see SURVEY.md §0, so citations are to upstream semantics, not
reference file:line).

Everything here is plain Python; the SoA tensor encodings that the CPU and
JAX scheduling paths consume live in :mod:`kubernetes_simulator_tpu.models.encode`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.quantity import parse_quantity

# Well-known resource names (upstream v1 core). Extended resources (e.g.
# "google.com/tpu", "nvidia.com/gpu") are arbitrary additional keys.
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
DEFAULT_RESOURCES = (CPU, MEMORY, PODS, EPHEMERAL_STORAGE)


class Effect(enum.IntEnum):
    """Taint effects. Integer values are the on-tensor encoding (0 = pad)."""

    NO_SCHEDULE = 1
    PREFER_NO_SCHEDULE = 2
    NO_EXECUTE = 3

    @classmethod
    def parse(cls, s: str) -> "Effect":
        return {
            "NoSchedule": cls.NO_SCHEDULE,
            "PreferNoSchedule": cls.PREFER_NO_SCHEDULE,
            "NoExecute": cls.NO_EXECUTE,
        }[s]


class Operator(enum.IntEnum):
    """Selector-expression operators ([K8S] NodeSelectorOperator /
    LabelSelectorOperator). Integer values are the on-tensor encoding."""

    IN = 1
    NOT_IN = 2
    EXISTS = 3
    DOES_NOT_EXIST = 4
    GT = 5
    LT = 6

    @classmethod
    def parse(cls, s: str) -> "Operator":
        return {
            "In": cls.IN,
            "NotIn": cls.NOT_IN,
            "Exists": cls.EXISTS,
            "DoesNotExist": cls.DOES_NOT_EXIST,
            "Gt": cls.GT,
            "Lt": cls.LT,
        }[s]


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: Effect = Effect.NO_SCHEDULE

    def __post_init__(self):
        if isinstance(self.effect, str):
            object.__setattr__(self, "effect", Effect.parse(self.effect))
        object.__setattr__(self, "key", str(self.key))
        object.__setattr__(self, "value", str(self.value))

    @classmethod
    def from_dict(cls, d: dict) -> "Taint":
        eff = d.get("effect", "NoSchedule")
        return cls(
            key=d["key"],
            value=str(d.get("value", "")),
            effect=eff if isinstance(eff, Effect) else Effect.parse(eff),
        )


@dataclass(frozen=True)
class Toleration:
    """[K8S] v1.Toleration. ``key=None`` with ``operator="Exists"`` tolerates
    everything; ``effect=None`` matches all effects."""

    key: Optional[str] = None
    operator: str = "Equal"  # "Equal" | "Exists"
    value: str = ""
    effect: Optional[Effect] = None

    def __post_init__(self):
        if isinstance(self.effect, str):
            object.__setattr__(self, "effect", Effect.parse(self.effect))
        if self.key is not None:
            object.__setattr__(self, "key", str(self.key))
        object.__setattr__(self, "operator", str(self.operator))
        object.__setattr__(self, "value", str(self.value))

    def tolerates(self, taint: Taint) -> bool:
        if self.effect is not None and self.effect != taint.effect:
            return False
        if self.key is None:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value

    @classmethod
    def from_dict(cls, d: dict) -> "Toleration":
        eff = d.get("effect")
        if isinstance(eff, str):
            eff = Effect.parse(eff)
        return cls(
            key=d.get("key"),
            operator=d.get("operator", "Equal"),
            value=str(d.get("value", "")),
            effect=eff,
        )


@dataclass(frozen=True)
class MatchExpression:
    """One requirement inside a selector term ([K8S] NodeSelectorRequirement
    / LabelSelectorRequirement)."""

    key: str
    operator: Operator
    values: Tuple[str, ...] = ()

    @classmethod
    def make(cls, key: str, operator, values: Sequence[str] = ()) -> "MatchExpression":
        op = operator if isinstance(operator, Operator) else Operator.parse(operator)
        return cls(key=key, operator=op, values=tuple(str(v) for v in values))

    def matches(self, labels: Dict[str, str]) -> bool:
        """Evaluate against a label map. [K8S] nodeaffinity semantics:
        In/Gt/Lt require the key to be present; NotIn/DoesNotExist match
        when the key is absent."""
        present = self.key in labels
        if self.operator == Operator.EXISTS:
            return present
        if self.operator == Operator.DOES_NOT_EXIST:
            return not present
        if self.operator == Operator.IN:
            return present and labels[self.key] in self.values
        if self.operator == Operator.NOT_IN:
            return not (present and labels[self.key] in self.values)
        # Gt / Lt: single integer value, key must be present and numeric.
        if not present:
            return False
        try:
            node_v = float(labels[self.key])
            want = float(self.values[0])
        except (ValueError, IndexError):
            return False
        return node_v > want if self.operator == Operator.GT else node_v < want


@dataclass(frozen=True)
class NodeSelectorTerm:
    """AND of match expressions ([K8S] NodeSelectorTerm)."""

    match_expressions: Tuple[MatchExpression, ...] = ()

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(e.matches(labels) for e in self.match_expressions)


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    term: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinitySpec:
    """[K8S] v1.NodeAffinity: required = OR of terms; preferred = weighted."""

    required: Tuple[NodeSelectorTerm, ...] = ()  # empty → no requirement
    preferred: Tuple[PreferredSchedulingTerm, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """[K8S] metav1.LabelSelector: match_labels AND match_expressions."""

    match_labels: Tuple[Tuple[str, str], ...] = ()
    match_expressions: Tuple[MatchExpression, ...] = ()

    @classmethod
    def make(cls, match_labels: Dict[str, str] = None, match_expressions=()) -> "LabelSelector":
        return cls(
            match_labels=tuple(sorted((match_labels or {}).items())),
            match_expressions=tuple(match_expressions),
        )

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        return all(e.matches(labels) for e in self.match_expressions)

    @property
    def empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


@dataclass(frozen=True)
class PodAffinityTerm:
    """[K8S] v1.PodAffinityTerm: select existing pods by label selector in
    ``namespaces`` (empty → the incoming pod's own namespace), co-located by
    ``topology_key``."""

    label_selector: LabelSelector
    topology_key: str
    namespaces: Tuple[str, ...] = ()


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinitySpec:
    required: Tuple[PodAffinityTerm, ...] = ()
    preferred: Tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class TopologySpreadConstraint:
    """[K8S] v1.TopologySpreadConstraint."""

    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # "DoNotSchedule" | "ScheduleAnyway"
    label_selector: LabelSelector


@dataclass
class Node:
    name: str
    capacity: Dict[str, float] = field(default_factory=dict)
    allocatable: Optional[Dict[str, float]] = None  # defaults to capacity
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)

    def __post_init__(self):
        self.capacity = {k: parse_quantity(v) for k, v in self.capacity.items()}
        if self.allocatable is None:
            self.allocatable = dict(self.capacity)
        else:
            self.allocatable = {k: parse_quantity(v) for k, v in self.allocatable.items()}
        # Every node implicitly has the hostname topology label.
        self.labels.setdefault("kubernetes.io/hostname", self.name)


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    requests: Dict[str, float] = field(default_factory=dict)
    priority: int = 0
    arrival_time: float = 0.0
    duration: Optional[float] = None  # virtual seconds until completion; None = forever
    tolerations: List[Toleration] = field(default_factory=list)
    node_affinity: NodeAffinitySpec = field(default_factory=NodeAffinitySpec)
    pod_affinity: PodAffinitySpec = field(default_factory=PodAffinitySpec)
    pod_anti_affinity: PodAffinitySpec = field(default_factory=PodAffinitySpec)
    topology_spread: List[TopologySpreadConstraint] = field(default_factory=list)
    pod_group: Optional[str] = None  # gang / coscheduling group name
    node_name: Optional[str] = None  # pre-bound pods in the initial cluster state

    def __post_init__(self):
        self.requests = {k: parse_quantity(v) for k, v in self.requests.items()}
        # Every pod consumes one "pods" slot ([K8S] node allocatable.pods).
        self.requests.setdefault(PODS, 1.0)


@dataclass(frozen=True)
class PodGroup:
    """[K8S] scheduler-plugins coscheduling PodGroup: all-or-nothing gang of
    at least ``min_member`` pods."""

    name: str
    min_member: int


@dataclass
class Cluster:
    nodes: List[Node]
    pods: List[Pod] = field(default_factory=list)  # pre-existing (possibly bound) pods
    pod_groups: Dict[str, PodGroup] = field(default_factory=dict)

    def node_by_name(self, name: str) -> Node:
        for n in self.nodes:
            if n.name == name:
                return n
        raise KeyError(name)
