"""Mutable scheduling state (numpy host version).

The entire effect of a binding on future scheduling decisions is captured by
four dense tensors (SURVEY.md §3.5 ``apply_bindings``):

- ``used[N, R]``          — per-node resource usage (includes the "pods" row)
- ``match_count[G, D]``   — placed pods matching count-group g per domain
- ``anti_active[G, D]``   — placed pods *having* required anti-affinity term g
                            per domain (the symmetric anti-affinity check)
- ``pref_wsum[G, D]``     — summed preferred-(anti)affinity weights of placed
                            pods per (group, domain) (symmetric scoring)

``bind``/``unbind`` are exact inverses — gang rollback and pod completion
depend on that (SURVEY.md §7 hard part #3). The JAX backend carries the same
tensors as a pytree and updates them with scatter-adds inside ``lax.scan``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .encode import PAD, EncodedCluster, EncodedPods


@dataclass
class SchedState:
    used: np.ndarray  # [N, R] f32
    match_count: np.ndarray  # [G, D] f32
    anti_active: np.ndarray  # [G, D] f32
    pref_wsum: np.ndarray  # [G, D] f32
    bound: np.ndarray  # [P] i32 (PAD = unbound)

    def copy(self) -> "SchedState":
        return SchedState(
            self.used.copy(),
            self.match_count.copy(),
            self.anti_active.copy(),
            self.pref_wsum.copy(),
            self.bound.copy(),
        )


def init_state(ec: EncodedCluster, pods: EncodedPods, apply_prebound: bool = True) -> SchedState:
    G = max(ec.num_groups, 1)
    D = max(ec.max_domains, 1)
    st = SchedState(
        used=np.zeros((ec.num_nodes, ec.num_resources), dtype=np.float32),
        match_count=np.zeros((G, D), dtype=np.float32),
        anti_active=np.zeros((G, D), dtype=np.float32),
        pref_wsum=np.zeros((G, D), dtype=np.float32),
        bound=np.full(pods.num_pods, PAD, dtype=np.int32),
    )
    if apply_prebound:
        for p in np.nonzero(pods.bound_node >= 0)[0]:
            bind(ec, pods, st, int(p), int(pods.bound_node[p]))
    return st


def _group_domains(ec: EncodedCluster, node: int) -> np.ndarray:
    """Domain id of ``node`` for each count group's topology key ([G] i32,
    PAD where the node lacks the key or the group row is padding)."""
    gt = ec.group_topo
    dom = np.where(gt >= 0, ec.node_domain[np.clip(gt, 0, None), node], PAD)
    return dom


def _apply(ec: EncodedCluster, pods: EncodedPods, st: SchedState, p: int, n: int, sign: float) -> None:
    st.used[n] += sign * pods.requests[p]
    dom = _group_domains(ec, n)  # [G]
    ok = dom >= 0
    sel = ok & pods.pod_matches_group[p]
    if sel.any():
        np.add.at(st.match_count, (np.nonzero(sel)[0], dom[sel]), sign)
    for g in pods.anti_req[p]:
        if g >= 0 and dom[g] >= 0:
            st.anti_active[g, dom[g]] += sign
    for g, w in zip(pods.pref_aff[p], pods.pref_aff_w[p]):
        if g >= 0 and dom[g] >= 0:
            st.pref_wsum[g, dom[g]] += sign * w


def bind(ec: EncodedCluster, pods: EncodedPods, st: SchedState, p: int, n: int) -> None:
    _apply(ec, pods, st, p, n, 1.0)
    st.bound[p] = n


def unbind(ec: EncodedCluster, pods: EncodedPods, st: SchedState, p: int) -> None:
    n = int(st.bound[p])
    if n == PAD:
        return
    _apply(ec, pods, st, p, n, -1.0)
    st.bound[p] = PAD


def release_delta(
    ec: EncodedCluster, pods: EncodedPods, idx: np.ndarray, nodes: np.ndarray
):
    """Aggregate state contribution of pods ``idx`` bound at ``nodes`` —
    the vectorized sum of per-pod ``_apply(sign=+1)`` effects, in the host
    state layout. The device engines subtract it from the carried planes
    when completed pods free their resources at chunk boundaries
    (SURVEY.md §2 L4: completions are the other half of the binding
    contract). Returns (used [N,R], match_count [G,D], anti_active [G,D],
    pref_wsum [G,D])."""
    N, R = ec.num_nodes, ec.num_resources
    G = max(ec.num_groups, 1)
    D = max(ec.max_domains, 1)
    used = np.zeros((N, R), np.float32)
    mc = np.zeros((G, D), np.float32)
    aa = np.zeros((G, D), np.float32)
    pw = np.zeros((G, D), np.float32)
    if len(idx) == 0:
        return used, mc, aa, pw
    idx = np.asarray(idx)
    nodes = np.asarray(nodes)
    np.add.at(used, nodes, pods.requests[idx])
    gt = ec.group_topo[:G]
    # dom[g, k] = domain of pod k's node under group g's topology.
    dom = np.where(
        (gt >= 0)[:, None], ec.node_domain[np.clip(gt, 0, None)][:, nodes], PAD
    )  # [G, K]
    sel = (dom >= 0) & pods.pod_matches_group[idx].T[:G]
    gg, kk = np.nonzero(sel)
    np.add.at(mc, (gg, dom[gg, kk]), 1.0)
    for col in range(pods.anti_req.shape[1]):
        g = pods.anti_req[idx, col]
        ok = (g >= 0) & (dom[np.clip(g, 0, None), np.arange(len(idx))] >= 0)
        if ok.any():
            np.add.at(
                aa,
                (g[ok], dom[g[ok], np.nonzero(ok)[0]]),
                1.0,
            )
    for col in range(pods.pref_aff.shape[1]):
        g = pods.pref_aff[idx, col]
        w = pods.pref_aff_w[idx, col]
        ok = (g >= 0) & (dom[np.clip(g, 0, None), np.arange(len(idx))] >= 0)
        if ok.any():
            np.add.at(
                pw,
                (g[ok], dom[g[ok], np.nonzero(ok)[0]]),
                w[ok].astype(np.float32),
            )
    return used, mc, aa, pw
