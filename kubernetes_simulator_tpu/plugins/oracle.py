"""Pure-Python per-(pod, node) scheduling oracle.

A third, independent implementation of the [K8S] plugin semantics that works
directly on the object model (strings, dicts, dataclasses) with no encoding
and no vectorization. It is deliberately slow and simple — it exists so the
unit/parity tests can anchor the numpy and JAX paths against something whose
correctness is auditable by eye (SURVEY.md §4 test strategy, tiers 1–2).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..models.core import (
    Cluster,
    Effect,
    Node,
    Pod,
    PodAffinityTerm,
)

MAX_NODE_SCORE = 100.0


class OracleState:
    """Placements as plain python: pod name → node name."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self.placed: Dict[str, Tuple[Pod, str]] = {}
        for p in cluster.pods:
            if p.node_name:
                self.placed[p.name] = (p, p.node_name)

    def bind(self, pod: Pod, node_name: str) -> None:
        self.placed[pod.name] = (pod, node_name)

    def unbind(self, pod: Pod) -> None:
        self.placed.pop(pod.name, None)

    def used(self, node: Node) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for p, nn in self.placed.values():
            if nn == node.name:
                for r, q in p.requests.items():
                    out[r] = out.get(r, 0.0) + q
        return out

    def pods_on_domain(self, topology_key: str, domain_value: str) -> List[Pod]:
        out = []
        for p, nn in self.placed.values():
            node = self.cluster.node_by_name(nn)
            if node.labels.get(topology_key) == domain_value:
                out.append(p)
        return out


def _term_matches_pod(term: PodAffinityTerm, owner_ns: str, pod: Pod) -> bool:
    namespaces = term.namespaces or (owner_ns,)
    return pod.namespace in namespaces and term.label_selector.matches(pod.labels)


# -- filters ----------------------------------------------------------------

def fits_resources(st: OracleState, pod: Pod, node: Node) -> bool:
    used = st.used(node)
    for r, q in pod.requests.items():
        if used.get(r, 0.0) + q > node.allocatable.get(r, 110.0 if r == "pods" else 0.0) + 1e-6:
            return False
    return True


def tolerates_taints(pod: Pod, node: Node) -> bool:
    for t in node.taints:
        if t.effect in (Effect.NO_SCHEDULE, Effect.NO_EXECUTE):
            if not any(tol.tolerates(t) for tol in pod.tolerations):
                return False
    return True


def prefer_no_schedule_count(pod: Pod, node: Node) -> int:
    c = 0
    for t in node.taints:
        if t.effect == Effect.PREFER_NO_SCHEDULE:
            if not any(tol.tolerates(t) for tol in pod.tolerations):
                c += 1
    return c


def node_affinity_ok(pod: Pod, node: Node) -> bool:
    req = pod.node_affinity.required
    if not req:
        return True
    return any(term.matches(node.labels) for term in req)


def interpod_ok(st: OracleState, pod: Pod, node: Node) -> bool:
    # Required affinity (with the first-pod bootstrap exception).
    for term in pod.pod_affinity.required:
        dom = node.labels.get(term.topology_key)
        anywhere = any(
            _term_matches_pod(term, pod.namespace, q) for q, _ in st.placed.values()
        )
        if not anywhere and _term_matches_pod(term, pod.namespace, pod):
            continue
        if dom is None:
            return False
        if not any(
            _term_matches_pod(term, pod.namespace, q)
            for q in st.pods_on_domain(term.topology_key, dom)
        ):
            return False
    # Incoming pod's required anti-affinity.
    for term in pod.pod_anti_affinity.required:
        dom = node.labels.get(term.topology_key)
        if dom is None:
            continue
        if any(
            _term_matches_pod(term, pod.namespace, q)
            for q in st.pods_on_domain(term.topology_key, dom)
        ):
            return False
    # Symmetric: placed pods' required anti-affinity vs this pod.
    for q, nn in st.placed.values():
        for term in q.pod_anti_affinity.required:
            qnode = st.cluster.node_by_name(nn)
            qdom = qnode.labels.get(term.topology_key)
            dom = node.labels.get(term.topology_key)
            if qdom is not None and dom == qdom and _term_matches_pod(term, q.namespace, pod):
                return False
    return True


def spread_ok(st: OracleState, pod: Pod, node: Node) -> bool:
    for c in pod.topology_spread:
        if c.when_unsatisfiable != "DoNotSchedule":
            continue
        dom = node.labels.get(c.topology_key)
        if dom is None:
            return False
        domains = sorted({n.labels[c.topology_key] for n in st.cluster.nodes if c.topology_key in n.labels})
        if not domains:
            return False
        counts = {
            d: sum(
                1
                for q in st.pods_on_domain(c.topology_key, d)
                if q.namespace == pod.namespace and c.label_selector.matches(q.labels)
            )
            for d in domains
        }
        self_match = 1 if c.label_selector.matches(pod.labels) else 0
        if counts[dom] + self_match - min(counts.values()) > c.max_skew:
            return False
    return True


# -- scores -----------------------------------------------------------------

def least_allocated(st: OracleState, pod: Pod, node: Node, weights: Dict[str, float]) -> float:
    """Integer node scores, as upstream ([K8S] computes with int64 division):
    floor(Σ w_r·floor(100·frac_r) / Σw). Zero-alloc resources score 0."""
    import math

    used = st.used(node)
    total, wsum = 0.0, 0.0
    for r, w in weights.items():
        wsum += w
        alloc = node.allocatable.get(r, 0.0)
        if alloc <= 0:
            continue
        frac = (alloc - used.get(r, 0.0) - pod.requests.get(r, 0.0)) / alloc
        total += w * math.floor(min(max(frac, 0.0), 1.0) * MAX_NODE_SCORE)
    return math.floor(total / wsum) if wsum else 0.0


def node_affinity_score(pod: Pod, node: Node) -> float:
    return float(
        sum(pt.weight for pt in pod.node_affinity.preferred if pt.term.matches(node.labels))
    )


def interpod_score(st: OracleState, pod: Pod, node: Node) -> float:
    raw = 0.0
    for wt in pod.pod_affinity.preferred:
        dom = node.labels.get(wt.term.topology_key)
        if dom is not None:
            raw += wt.weight * sum(
                1
                for q in st.pods_on_domain(wt.term.topology_key, dom)
                if _term_matches_pod(wt.term, pod.namespace, q)
            )
    for wt in pod.pod_anti_affinity.preferred:
        dom = node.labels.get(wt.term.topology_key)
        if dom is not None:
            raw -= wt.weight * sum(
                1
                for q in st.pods_on_domain(wt.term.topology_key, dom)
                if _term_matches_pod(wt.term, pod.namespace, q)
            )
    # Symmetric: placed pods' preferred terms toward the incoming pod.
    for q, nn in st.placed.values():
        qnode = st.cluster.node_by_name(nn)
        for wt in q.pod_affinity.preferred:
            if node.labels.get(wt.term.topology_key) == qnode.labels.get(wt.term.topology_key) \
               and qnode.labels.get(wt.term.topology_key) is not None \
               and _term_matches_pod(wt.term, q.namespace, pod):
                raw += wt.weight
        for wt in q.pod_anti_affinity.preferred:
            if node.labels.get(wt.term.topology_key) == qnode.labels.get(wt.term.topology_key) \
               and qnode.labels.get(wt.term.topology_key) is not None \
               and _term_matches_pod(wt.term, q.namespace, pod):
                raw -= wt.weight
    return raw


def spread_score(st: OracleState, pod: Pod, node: Node) -> Optional[float]:
    """Upstream podtopologyspread scoring ([K8S] scoring.go): for each
    ScheduleAnyway constraint, ``cnt·log(size+2) + (maxSkew−1)`` over
    existing matching pods in the node's domain (no self term), truncated
    to an integer. A node missing any scored key is ignored → −1; no
    ScheduleAnyway constraints → None (PreScore Skip). f32 arithmetic in
    constraint order, matching ops.cpu.spread_score bit-for-bit."""
    import numpy as np

    raw = np.float32(0.0)
    any_scored = False
    ignored = False
    for c in pod.topology_spread:
        if c.when_unsatisfiable == "DoNotSchedule":
            continue
        any_scored = True
        domains = {
            n.labels[c.topology_key]
            for n in st.cluster.nodes
            if c.topology_key in n.labels
        }
        dom = node.labels.get(c.topology_key)
        if dom is None:
            ignored = True
            continue
        w = np.float32(np.log(np.float64(len(domains)) + 2.0))
        cnt = sum(
            1
            for q in st.pods_on_domain(c.topology_key, dom)
            if q.namespace == pod.namespace and c.label_selector.matches(q.labels)
        )
        raw = np.float32(raw + (np.float32(cnt) * w + np.float32(c.max_skew - 1)))
    if not any_scored:
        return None
    if ignored:
        return -1.0
    # Upstream int64(math.Round(score)): floor(x+0.5) for non-negative x.
    return float(np.floor(raw + np.float32(0.5)))
