"""Builtin scheduler plugins — CPU (numpy) default path.

Mirrors the upstream kube-scheduler default plugin set named by [BASELINE]:
NodeResourcesFit (LeastAllocated/MostAllocated/RequestedToCapacityRatio),
TaintToleration, NodeAffinity, InterPodAffinity, PodTopologySpread, plus
device-plugin extended resources (extra rows in the resource tensors) and
Coscheduling (gang Permit — enforced by the runtime, see
:mod:`..framework.framework`).

Each plugin exposes vectorized-over-nodes ``filter``/``score``/``normalize``
against the encoded state. The per-(pod, node) object-model oracle used by
the unit tests lives in :mod:`.oracle`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..models.encode import EncodedCluster, EncodedPods
from ..models.state import SchedState
from ..ops import cpu as K


@dataclass
class SchedulingContext:
    """Per-replay immutable context handed to every plugin call."""

    ec: EncodedCluster
    pods: EncodedPods
    expr_match: np.ndarray  # [N, E] — cached expr_match_matrix(ec)

    @classmethod
    def build(cls, ec: EncodedCluster, pods: EncodedPods) -> "SchedulingContext":
        return cls(ec=ec, pods=pods, expr_match=K.expr_match_matrix(ec))


class Plugin:
    """Extension-point interface ([K8S] framework.Plugin). ``filter`` returns
    a feasibility mask over all nodes (None = no opinion); ``score`` returns
    raw per-node scores which ``normalize`` maps to [0, 100]."""

    name: str = "Plugin"

    def filter(self, ctx: SchedulingContext, st: SchedState, p: int) -> Optional[np.ndarray]:
        return None

    def score(self, ctx: SchedulingContext, st: SchedState, p: int) -> Optional[np.ndarray]:
        return None

    def normalize(self, raw: np.ndarray, feasible: np.ndarray) -> np.ndarray:
        return raw


class NodeResourcesFit(Plugin):
    """[K8S] noderesources/fit. ``strategy`` ∈ {LeastAllocated, MostAllocated,
    RequestedToCapacityRatio}; ``resources`` maps resource name → weight
    (default cpu=1, memory=1). Extended resources participate in the Filter
    unconditionally (they are rows of the tensors)."""

    name = "NodeResourcesFit"

    def __init__(
        self,
        ctx: SchedulingContext,
        strategy: str = "LeastAllocated",
        resources: Optional[Dict[str, float]] = None,
        shape: Optional[List[dict]] = None,
    ):
        self.strategy = strategy
        res = resources or {"cpu": 1.0, "memory": 1.0}
        R = ctx.ec.num_resources
        self.weights = np.zeros(R, dtype=np.float32)
        for rname, w in res.items():
            ri = ctx.ec.vocab._r.get(rname)
            if ri is not None:
                self.weights[ri] = w
        pts = shape or [{"utilization": 0, "score": 0}, {"utilization": 100, "score": 10}]
        self.shape_x = np.array([pt["utilization"] for pt in pts], dtype=np.float32)
        self.shape_y = np.array([pt["score"] * 10.0 for pt in pts], dtype=np.float32)

    def filter(self, ctx, st, p):
        return K.fit_mask(ctx.ec, st, ctx.pods, p)

    def score(self, ctx, st, p):
        if self.strategy == "LeastAllocated":
            return K.least_allocated_score(ctx.ec, st, ctx.pods, p, self.weights)
        if self.strategy == "MostAllocated":
            return K.most_allocated_score(ctx.ec, st, ctx.pods, p, self.weights)
        return K.requested_to_capacity_ratio_score(
            ctx.ec, st, ctx.pods, p, self.weights, self.shape_x, self.shape_y
        )


class TaintToleration(Plugin):
    """[K8S] tainttoleration: Filter on untolerated NoSchedule/NoExecute;
    Score prefers fewer untolerated PreferNoSchedule taints."""

    name = "TaintToleration"

    def __init__(self, ctx: SchedulingContext):
        pass

    def filter(self, ctx, st, p):
        return K.taint_mask(ctx.ec, ctx.pods, p)

    def score(self, ctx, st, p):
        return K.taint_prefer_count(ctx.ec, ctx.pods, p)

    def normalize(self, raw, feasible):
        return K.normalize_max(raw, feasible, reverse=True)


class NodeAffinity(Plugin):
    """[K8S] nodeaffinity: required terms filter; preferred terms score."""

    name = "NodeAffinity"

    def __init__(self, ctx: SchedulingContext):
        pass

    def filter(self, ctx, st, p):
        return K.node_affinity_mask(ctx.expr_match, ctx.pods, p)

    def score(self, ctx, st, p):
        return K.node_affinity_score(ctx.expr_match, ctx.pods, p)

    def normalize(self, raw, feasible):
        return K.normalize_max(raw, feasible)


class InterPodAffinity(Plugin):
    """[K8S] interpodaffinity over the count-group tensors (SURVEY.md §7
    hard part #2): required (anti-)affinity filter incl. the symmetric
    existing-pods'-anti-affinity check; preferred terms score both ways."""

    name = "InterPodAffinity"

    def __init__(self, ctx: SchedulingContext):
        pass

    def filter(self, ctx, st, p):
        return K.interpod_filter_mask(ctx.ec, st, ctx.pods, p)

    def score(self, ctx, st, p):
        return K.interpod_score(ctx.ec, st, ctx.pods, p)

    def normalize(self, raw, feasible):
        return K.normalize_min_max(raw, feasible)


class PodTopologySpread(Plugin):
    """[K8S] podtopologyspread: DoNotSchedule constraints filter on maxSkew;
    scoring prefers domains with fewer matching pods."""

    name = "PodTopologySpread"

    def __init__(self, ctx: SchedulingContext, defaultingType=None, defaultConstraints=None):
        # Defaulting args are consumed pre-encode by inject_default_spread;
        # accepted here so the KubeSchedulerConfiguration vocabulary parses.
        pass

    def filter(self, ctx, st, p):
        return K.spread_filter_mask(ctx.ec, st, ctx.pods, p)

    def score(self, ctx, st, p):
        # None when the pod has no ScheduleAnyway constraints ([K8S]
        # PreScore Skip) — the framework then contributes nothing.
        return K.spread_score(ctx.ec, st, ctx.pods, p)

    def normalize(self, raw, feasible):
        return K.spread_normalize(raw, feasible)


PLUGIN_FACTORIES = {
    "NodeResourcesFit": NodeResourcesFit,
    "TaintToleration": TaintToleration,
    "NodeAffinity": NodeAffinity,
    "InterPodAffinity": InterPodAffinity,
    "PodTopologySpread": PodTopologySpread,
}

#: Plugin name → default Score weight ([K8S] default profile weights).
DEFAULT_WEIGHTS = {
    "NodeResourcesFit": 1.0,
    "TaintToleration": 3.0,
    "NodeAffinity": 2.0,
    "InterPodAffinity": 2.0,
    "PodTopologySpread": 2.0,
}

#: Policy-tuner surface (round 9, sim.tuner): the default Score-weight
#: search range. Upstream accepts weights in [0, 100]; the useful dynamic
#: range is far smaller — only weight RATIOS matter to the argmax.
TUNABLE_WEIGHT_RANGE = (0.0, 10.0)

#: NodeResourcesFit scoring strategies with a cheap traced selector in the
#: device score fold (ops.tpu POLICY_COLS "fit_least" column; index order
#: matters: fit_least > 0.5 selects LeastAllocated).
TUNABLE_FIT_STRATEGIES = ("MostAllocated", "LeastAllocated")


def tunable_parameters(config=None) -> List[dict]:
    """The tunable-parameter surface for the policy tuner: one ``weight``
    entry per Score plugin (canonical PLUGIN_FACTORIES order — the same
    order as ops.tpu.POLICY_WEIGHT_COLS) plus the NodeResourcesFit
    strategy ``choice``. ``enabled`` marks parameters whose plugin is in
    the config's plugin list (disabled plugins' score rows are statically
    absent from the device program, so their columns are inert — the
    search pins them to their defaults). ``default`` reflects the config's
    own weights/args, so the unmodified policy vector reproduces the
    configured scheduler exactly."""
    weights = dict(DEFAULT_WEIGHTS)
    enabled = set(PLUGIN_FACTORIES)
    strategy = "LeastAllocated"
    if config is not None:
        weights.update(config.weights or {})
        if config.plugins is not None:
            enabled = {e["name"] for e in config.plugins}
            for e in config.plugins:
                if e.get("name") == "NodeResourcesFit":
                    strategy = e.get("args", {}).get("strategy", strategy)
    lo, hi = TUNABLE_WEIGHT_RANGE
    out = [
        {
            "name": name, "kind": "weight", "lo": lo, "hi": hi,
            "default": float(weights[name]), "enabled": name in enabled,
        }
        for name in PLUGIN_FACTORIES
    ]
    out.append({
        "name": "NodeResourcesFit.strategy", "kind": "choice",
        "choices": TUNABLE_FIT_STRATEGIES, "default": strategy,
        # A RequestedToCapacityRatio base strategy has no traced selector
        # (its shape table is static) — the column is inert then.
        "enabled": (
            "NodeResourcesFit" in enabled
            and strategy in TUNABLE_FIT_STRATEGIES
        ),
    })
    return out


def make_plugins(
    ctx: SchedulingContext, plugin_config: Optional[List[dict]] = None
) -> List[Plugin]:
    """Instantiate a plugin list from config entries
    ``[{"name": ..., "args": {...}}, ...]`` (default: full default set)."""
    if plugin_config is None:
        plugin_config = [{"name": n} for n in PLUGIN_FACTORIES]
    out = []
    for entry in plugin_config:
        factory = PLUGIN_FACTORIES[entry["name"]]
        out.append(factory(ctx, **entry.get("args", {})))
    return out


#: kube-scheduler "System" default spreading (KubeSchedulerConfiguration
#: PodTopologySpreadArgs when defaultingType=System).
SYSTEM_DEFAULT_SPREAD = [
    {"maxSkew": 3, "topologyKey": "kubernetes.io/hostname",
     "whenUnsatisfiable": "ScheduleAnyway"},
    {"maxSkew": 5, "topologyKey": "topology.kubernetes.io/zone",
     "whenUnsatisfiable": "ScheduleAnyway"},
]


def resolved_default_constraints(config):
    """The PodTopologySpread defaulting constraint list from config, or
    None when not configured — the single source for both the predicate
    and the injector."""
    constraints = None
    for e in (config.plugins if config and config.plugins is not None else []):
        if e.get("name") != "PodTopologySpread":
            continue
        args = e.get("args", {})
        if args.get("defaultingType") == "System":
            constraints = SYSTEM_DEFAULT_SPREAD
        elif args.get("defaultConstraints"):
            constraints = args["defaultConstraints"]
    return constraints


def inject_default_spread(pods, config) -> None:
    """Apply PodTopologySpread cluster-default constraints: pods WITHOUT
    explicit constraints get the plugin-args defaults, selecting on the
    pod's own labels (the simulator's stand-in for upstream's
    controller-selector lookup — pods of one controller share labels).

    Config vocabulary mirrors KubeSchedulerConfiguration:
        plugins:
        - name: PodTopologySpread
          args: {defaultingType: System}             # built-in pair
        # or explicit: args: {defaultConstraints: [{maxSkew: ..., ...}]}
    No-op unless the plugin entry asks for defaulting (upstream's List
    defaulting with an empty list)."""
    from ..models.core import LabelSelector, TopologySpreadConstraint

    constraints = resolved_default_constraints(config)
    if not constraints:
        return
    for p in pods:
        if p.topology_spread or not p.labels:
            continue
        for c in constraints:
            p.topology_spread.append(
                TopologySpreadConstraint(
                    max_skew=int(c["maxSkew"]),
                    topology_key=c["topologyKey"],
                    when_unsatisfiable=c.get("whenUnsatisfiable", "ScheduleAnyway"),
                    label_selector=LabelSelector.make(dict(p.labels)),
                )
            )
