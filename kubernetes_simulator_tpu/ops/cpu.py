"""Vectorized scheduling kernels, numpy host edition.

Each function evaluates one extension-point predicate/score for ONE pending
pod against ALL nodes at once — the ``(nodes × pending_pods)`` tensorization
[BASELINE] asks for, here in its host form. :mod:`..ops.tpu` implements the
same math in jax.numpy for the device path; the two must agree exactly
(SURVEY.md §4 parity suite).

Semantics are upstream kube-scheduler plugin semantics ([K8S]); the pure
Python oracle in :mod:`..plugins` unit tests anchors them a third time at
the object-model level.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..models.encode import PAD, TOL_PAD, TOL_WILDCARD, EncodedCluster, EncodedPods
from ..models.core import Effect, Operator
from ..models.state import SchedState

MAX_NODE_SCORE = 100.0


# ---------------------------------------------------------------------------
# Node-selector expression matching
# ---------------------------------------------------------------------------

def expr_match_matrix(ec: EncodedCluster) -> np.ndarray:
    """``M[n, e]`` — does node n satisfy interned expression e.

    Computed once per (scenario) cluster state from the label tensors, so
    label perturbations re-flow without re-encoding. [K8S] semantics:
    In/Gt/Lt require the key present; NotIn/DoesNotExist also match when the
    key is absent.
    """
    nk = ec.node_label_key[:, :, None]  # [N, L, 1]
    nv = ec.node_label_kv[:, :, None]
    ek = ec.expr_key[None, None, :]  # [1, 1, E]
    key_present = np.any((nk == ek) & (nk != PAD), axis=1)  # [N, E]
    # In: node kv ∈ expr value set (kv ids embed the key, so one equality).
    in_set = np.any(
        (nv[:, :, :, None] == ec.expr_vals[None, None, :, :]) & (nv[:, :, :, None] != PAD),
        axis=(1, 3),
    )  # [N, E]
    num = ec.node_label_num[:, :, None]  # [N, L, 1]
    with np.errstate(invalid="ignore"):
        gt = np.any((nk == ek) & (num > ec.expr_num[None, None, :]), axis=1)
        lt = np.any((nk == ek) & (num < ec.expr_num[None, None, :]), axis=1)
    op = ec.expr_op[None, :]
    return (
        ((op == Operator.IN) & key_present & in_set)
        | ((op == Operator.NOT_IN) & ~(key_present & in_set))
        | ((op == Operator.EXISTS) & key_present)
        | ((op == Operator.DOES_NOT_EXIST) & ~key_present)
        | ((op == Operator.GT) & gt)
        | ((op == Operator.LT) & lt)
    )


def selector_terms_match(M: np.ndarray, terms: np.ndarray) -> np.ndarray:
    """OR over terms of AND over expressions. ``terms``: [T, E_slots] expr
    ids (PAD-padded); a term is valid iff its first slot is a real expr.
    Returns [N] bool."""
    valid_term = terms[:, 0] >= 0  # [T]
    safe = np.clip(terms, 0, None)
    per_expr = M[:, safe] | (terms[None, :, :] < 0)  # padding exprs auto-true
    per_term = np.all(per_expr, axis=2) & valid_term[None, :]
    if not valid_term.any():
        return np.zeros(M.shape[0], dtype=bool)
    return np.any(per_term, axis=1)


# ---------------------------------------------------------------------------
# NodeResourcesFit ([K8S] noderesources; [BASELINE] LeastAllocated)
# ---------------------------------------------------------------------------

def fit_mask(ec: EncodedCluster, st: SchedState, pods: EncodedPods, p: int) -> np.ndarray:
    req = pods.requests[p]  # [R]
    return np.all(st.used + req[None, :] <= ec.allocatable + 1e-6, axis=1)


def pending_fit_mask(
    used: np.ndarray, allocatable: np.ndarray, request: np.ndarray
) -> np.ndarray:
    """[N] — which nodes could fit ONE request right now, in the
    scheduler's own fit arithmetic (identical eps form to ``fit_mask``
    above, on raw arrays instead of the encoded wrappers). The round-13
    stranded-capacity gauge (utils.metrics.fragmentation_gauges) charges
    a node's free capacity as stranded only when THIS test fails — so
    "cannot fit" means exactly what the Filter pass would decide, on
    every engine."""
    return np.all(used + request[None, :] <= allocatable + 1e-6, axis=1)


# Scores are INTEGER-valued f32 ([K8S] computes int64 node scores; we floor
# through single-op chains — sub/div/mul/floor, nothing XLA can FMA-fuse —
# so the CPU and device paths are bit-identical and argmax ties break the
# same way; SURVEY.md §7 hard part #6).


def _int_resource_score(frac: np.ndarray, weights: np.ndarray) -> np.ndarray:
    """floor(frac_r·100) per resource, exact weighted mean, floored."""
    s = np.floor(frac * np.float32(MAX_NODE_SCORE))  # [N, R], integral
    acc = np.zeros(frac.shape[0], dtype=np.float32)
    wsum = 0.0
    for r in range(frac.shape[1]):
        w = float(weights[r])
        if w != 0:
            acc = acc + s[:, r] * np.float32(w)  # exact: small ints
            wsum += w
    if wsum == 0:
        return acc
    return np.floor(acc / np.float32(wsum))


def least_allocated_score(
    ec: EncodedCluster, st: SchedState, pods: EncodedPods, p: int, weights: np.ndarray
) -> np.ndarray:
    """``floor(Σ_r w_r·floor(100·(alloc_r−used_r−req_r)/alloc_r) / Σw)``;
    rows with alloc==0 contribute 0 ([K8S] leastAllocatedScorer, integer
    node scores)."""
    req = pods.requests[p][None, :]
    alloc = ec.allocatable
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = np.where(alloc > 0, (alloc - st.used - req) / np.where(alloc > 0, alloc, 1.0), 0.0)
    frac = np.clip(frac, 0.0, 1.0)
    return _int_resource_score(frac, weights)


def most_allocated_score(
    ec: EncodedCluster, st: SchedState, pods: EncodedPods, p: int, weights: np.ndarray
) -> np.ndarray:
    req = pods.requests[p][None, :]
    alloc = ec.allocatable
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = np.where(alloc > 0, (st.used + req) / np.where(alloc > 0, alloc, 1.0), 0.0)
    frac = np.clip(frac, 0.0, 1.0)
    return _int_resource_score(frac, weights)


def piecewise_interp_int(util: np.ndarray, xs, ys) -> np.ndarray:
    """Integer-valued piecewise-linear eval: seg = y0 + floor(t·Δy). Shared
    formula with ops.tpu (single-op chains; no np.interp)."""
    out = np.full_like(util, np.float32(ys[-1]), dtype=np.float32)
    for i in range(len(xs) - 2, -1, -1):
        x0, x1 = np.float32(xs[i]), np.float32(xs[i + 1])
        y0, y1 = np.float32(ys[i]), np.float32(ys[i + 1])
        t = (util.astype(np.float32) - x0) * (np.float32(1.0) / (x1 - x0))
        seg = y0 + np.floor(t * (y1 - y0))
        out = np.where(util <= x1, seg, out)
    return np.where(util <= np.float32(xs[0]), np.float32(ys[0]), out).astype(np.float32)


def requested_to_capacity_ratio_score(
    ec: EncodedCluster,
    st: SchedState,
    pods: EncodedPods,
    p: int,
    weights: np.ndarray,
    shape_x: np.ndarray,
    shape_y: np.ndarray,
) -> np.ndarray:
    """Piecewise-linear function of utilization ([K8S]
    RequestedToCapacityRatio shape points; x in [0,100] utilization, y in
    [0,100] score)."""
    req = pods.requests[p][None, :]
    alloc = ec.allocatable
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = np.where(alloc > 0, (st.used + req) / np.where(alloc > 0, alloc, 1.0), 0.0)
    util = np.floor(np.clip(frac, 0.0, 1.0) * np.float32(100.0))
    score_r = piecewise_interp_int(util, list(shape_x), list(shape_y))  # [N, R]
    acc = np.zeros(ec.num_nodes, dtype=np.float32)
    wsum = 0.0
    for r in range(score_r.shape[1]):
        w = float(weights[r])
        if w != 0:
            acc = acc + score_r[:, r] * np.float32(w)
            wsum += w
    if wsum == 0:
        return acc
    return np.floor(acc / np.float32(wsum))


# ---------------------------------------------------------------------------
# TaintToleration ([K8S] tainttoleration)
# ---------------------------------------------------------------------------

def _untolerated(ec: EncodedCluster, pods: EncodedPods, p: int, effects: np.ndarray) -> np.ndarray:
    """[N, TT] bool — taint slot active with effect ∈ ``effects`` and not
    tolerated by any of pod p's tolerations."""
    t_eff = ec.taint_effect  # [N, TT]
    active = np.isin(t_eff, effects) & (ec.taint_key != PAD)
    tk = pods.tol_key[p]  # [TO]
    tv = pods.tol_kv[p]
    te = pods.tol_effect[p]
    valid_tol = tk != TOL_PAD  # [TO]
    key_ok = (tk[None, None, :] == TOL_WILDCARD) | (tk[None, None, :] == ec.taint_key[:, :, None])
    val_ok = (tv[None, None, :] == PAD) | (tv[None, None, :] == ec.taint_kv[:, :, None])
    eff_ok = (te[None, None, :] == 0) | (te[None, None, :] == t_eff[:, :, None])
    tolerated = np.any(key_ok & val_ok & eff_ok & valid_tol[None, None, :], axis=2)
    return active & ~tolerated


def taint_mask(ec: EncodedCluster, pods: EncodedPods, p: int) -> np.ndarray:
    """Feasible iff no untolerated NoSchedule/NoExecute taint."""
    bad = _untolerated(
        ec, pods, p, np.array([int(Effect.NO_SCHEDULE), int(Effect.NO_EXECUTE)])
    )
    return ~np.any(bad, axis=1)


def taint_prefer_count(ec: EncodedCluster, pods: EncodedPods, p: int) -> np.ndarray:
    """Count of untolerated PreferNoSchedule taints per node (score input)."""
    bad = _untolerated(ec, pods, p, np.array([int(Effect.PREFER_NO_SCHEDULE)]))
    return bad.sum(axis=1).astype(np.float32)


# ---------------------------------------------------------------------------
# NodeAffinity ([K8S] nodeaffinity)
# ---------------------------------------------------------------------------

def node_affinity_mask(M: np.ndarray, pods: EncodedPods, p: int) -> np.ndarray:
    if not pods.na_has_req[p]:
        return np.ones(M.shape[0], dtype=bool)
    return selector_terms_match(M, pods.na_req[p])


def node_affinity_score(M: np.ndarray, pods: EncodedPods, p: int) -> np.ndarray:
    """Σ weight over matched preferred terms (raw; normalized by caller)."""
    terms = pods.na_pref[p]  # [TP, TE]
    w = pods.na_pref_w[p]  # [TP]
    valid_term = terms[:, 0] >= 0
    safe = np.clip(terms, 0, None)
    per_expr = M[:, safe] | (terms[None, :, :] < 0)
    per_term = np.all(per_expr, axis=2) & valid_term[None, :]
    return (per_term * w[None, :]).sum(axis=1).astype(np.float32)


# ---------------------------------------------------------------------------
# InterPodAffinity ([K8S] interpodaffinity) — reads the count tensors
# ---------------------------------------------------------------------------

def _group_dom_per_node(ec: EncodedCluster) -> np.ndarray:
    """[G, N] domain id of each node under each group's topology key."""
    gt = np.clip(ec.group_topo, 0, None)
    dom = ec.node_domain[gt]  # [G, N]
    return np.where(ec.group_topo[:, None] >= 0, dom, PAD)


def _counts_at_nodes(counts: np.ndarray, gdom: np.ndarray) -> np.ndarray:
    """Gather ``counts[g, dom(g, n)]`` → [G, N]; 0 where the node lacks the key."""
    safe = np.clip(gdom, 0, None)
    vals = np.take_along_axis(counts, safe, axis=1)
    return np.where(gdom >= 0, vals, 0.0)


def interpod_filter_mask(
    ec: EncodedCluster, st: SchedState, pods: EncodedPods, p: int
) -> np.ndarray:
    N = ec.num_nodes
    gdom = _group_dom_per_node(ec)  # [G, N]
    cnt = _counts_at_nodes(st.match_count, gdom)  # [G, N]
    total = st.match_count.sum(axis=1)  # [G]
    ok = np.ones(N, dtype=bool)
    # Required affinity: ≥1 matching placed pod in the node's domain; the
    # bootstrap exception ([K8S]): if nothing matches anywhere and the pod
    # matches its own term, the term is satisfied.
    for g in pods.aff_req[p]:
        if g < 0:
            continue
        boot = (total[g] == 0) and bool(pods.pod_matches_group[p, g])
        term_ok = (cnt[g] >= 1) & (gdom[g] >= 0)
        ok &= term_ok | boot
    # Required anti-affinity (incoming pod's own terms): no matching placed
    # pod in the domain. Nodes without the topology key cannot conflict.
    for g in pods.anti_req[p]:
        if g < 0:
            continue
        ok &= ~((cnt[g] >= 1) & (gdom[g] >= 0))
    # Symmetric: placed pods' required anti-affinity terms reject this pod.
    anti_here = _counts_at_nodes(st.anti_active, gdom)  # [G, N]
    blocked = np.any((anti_here > 0) & pods.pod_matches_group[p][:, None], axis=0)
    return ok & ~blocked


def interpod_score(ec: EncodedCluster, st: SchedState, pods: EncodedPods, p: int) -> np.ndarray:
    """Raw preferred-affinity score: incoming pod's weighted terms counted
    over placed pods, plus the symmetric sum of placed pods' preferred
    weights toward pods matching group g."""
    gdom = _group_dom_per_node(ec)
    cnt = _counts_at_nodes(st.match_count, gdom)  # [G, N]
    raw = np.zeros(ec.num_nodes, dtype=np.float32)
    for g, w in zip(pods.pref_aff[p], pods.pref_aff_w[p]):
        if g >= 0:
            raw += w * cnt[g]
    wsum = _counts_at_nodes(st.pref_wsum, gdom)  # [G, N]
    raw += (wsum * pods.pod_matches_group[p][:, None]).sum(axis=0)
    return raw


# ---------------------------------------------------------------------------
# PodTopologySpread ([K8S] podtopologyspread)
# ---------------------------------------------------------------------------

def spread_filter_mask(
    ec: EncodedCluster, st: SchedState, pods: EncodedPods, p: int
) -> np.ndarray:
    N = ec.num_nodes
    gdom = _group_dom_per_node(ec)
    cnt = _counts_at_nodes(st.match_count, gdom)
    ok = np.ones(N, dtype=bool)
    for g, skew, dns in zip(pods.spread_g[p], pods.spread_skew[p], pods.spread_dns[p]):
        if g < 0 or not dns:
            continue
        ti = ec.group_topo[g]
        nd = int(ec.num_domains[ti])
        if nd == 0:
            ok &= False
            continue
        min_cnt = st.match_count[g, :nd].min()
        self_match = float(pods.pod_matches_group[p, g])
        new = cnt[g] + self_match
        # Nodes missing the topology key fail DoNotSchedule constraints.
        ok &= (gdom[g] >= 0) & (new - min_cnt <= skew)
    return ok


def spread_weight(ec: EncodedCluster, g: int) -> np.float32:
    """topologyNormalizingWeight for match-group ``g``'s topology:
    ``log(size + 2)`` ([K8S] podtopologyspread/scoring.go).

    DOCUMENTED DEVIATION from upstream: ``size`` here is the STATIC
    cluster-wide distinct-domain count of the key, computed once at encode.
    Upstream counts distinct domains among the pod's *filtered* nodes per
    scheduling cycle, and special-cases kubernetes.io/hostname as
    ``len(filteredNodes) − 2``. Scores deviate from upstream whenever
    filtering excludes whole domains. MEASURED (round 5, vs an
    upstream-faithful dynamic-weight oracle,
    tests/test_spread_weight_deviation.py): a pod with ONE spread
    constraint diverges 0.00% in placements even with half its domains
    filtered out (NormalizeScore is scale-invariant up to rounding); a
    pod spreading over MULTIPLE topologies at once (zone + hostname, half
    the zones filtered) flips 5.4% of decisions (cascade-inclusive
    assignment divergence 14.1%, placed counts equal). The static form
    keeps the weight a trace-time constant — a per-pod dynamic count
    would force a per-pod [N]-wide
    domain census into the device hot loop. Cross-backend parity is exact:
    all three backends consume this same value (f64 log cast once to
    f32)."""
    ti = ec.group_topo[g]
    nd = int(ec.num_domains[ti]) if ti >= 0 else 0
    return np.float32(np.log(np.float64(nd) + 2.0))


def spread_score(
    ec: EncodedCluster, st: SchedState, pods: EncodedPods, p: int
) -> Optional[np.ndarray]:
    """Upstream podtopologyspread scoring ([K8S] scoring.go): per
    ScheduleAnyway constraint, ``score += cnt·log(size+2) + (maxSkew−1)``
    over existing matching pods in the node's domain (no self term),
    truncated to an integer per node. Nodes missing any scored topology key
    are ignored — sentinel −1 (they normalize to 0). Returns None when the
    pod has no ScheduleAnyway constraints (PreScore Skip)."""
    gdom = _group_dom_per_node(ec)
    cnt = _counts_at_nodes(st.match_count, gdom)
    raw = np.zeros(ec.num_nodes, dtype=np.float32)
    ignored = np.zeros(ec.num_nodes, dtype=bool)
    any_scored = False
    for g, skew, dns in zip(pods.spread_g[p], pods.spread_skew[p], pods.spread_dns[p]):
        if g < 0 or dns:
            continue
        any_scored = True
        raw = raw + (cnt[g] * spread_weight(ec, g) + np.float32(int(skew) - 1))
        ignored |= gdom[g] < 0
    if not any_scored:
        return None
    # int64(math.Round(score)) upstream — half away from zero; scores are
    # non-negative so floor(x + 0.5), in f32 on every backend.
    raw = np.floor(raw + np.float32(0.5))
    return np.where(ignored, np.float32(-1.0), raw)


def spread_normalize(raw: np.ndarray, feasible: np.ndarray) -> np.ndarray:
    """Upstream two-pass NormalizeScore ([K8S] podtopologyspread):
    ``100·(max+min−s) // max`` with min/max over non-ignored feasible
    nodes; ignored nodes (sentinel −1) → 0; max == 0 → 100. Integer (int32)
    arithmetic, exact while ``100·(max+min) < 2³¹`` — mirrored bit-for-bit
    on the device paths."""
    out = np.zeros_like(raw, dtype=np.float32)
    scored = feasible & (raw >= 0)
    if not scored.any():
        return out
    hi = np.int32(raw[scored].max())
    lo = np.int32(raw[scored].min())
    nz = raw >= 0
    if hi <= 0:
        out[nz] = np.float32(MAX_NODE_SCORE)
        return out
    vals = (np.int32(MAX_NODE_SCORE) * (hi + lo - raw.astype(np.int32))) // hi
    out[nz] = vals[nz].astype(np.float32)
    return out


# ---------------------------------------------------------------------------
# Normalization ([K8S] defaultNormalizeScore) and selection
# ---------------------------------------------------------------------------

def normalize_max(raw: np.ndarray, feasible: np.ndarray, reverse: bool = False) -> np.ndarray:
    """``floor(raw·100/max)`` over feasible nodes ([K8S] defaultNormalizeScore,
    integer scores); reverse flips. Raw inputs are small non-negative
    integers (counts / summed int weights), so the arithmetic is exact."""
    vals = np.where(feasible, raw, 0.0)
    mx = vals.max() if feasible.any() else 0.0
    if mx <= 0:
        out = np.zeros_like(raw, dtype=np.float32)
        return np.full_like(out, MAX_NODE_SCORE) if reverse else out
    out = np.floor((raw.astype(np.float32) * np.float32(MAX_NODE_SCORE)) / np.float32(mx))
    return np.float32(MAX_NODE_SCORE) - out if reverse else out


def normalize_min_max(raw: np.ndarray, feasible: np.ndarray, reverse: bool = False) -> np.ndarray:
    """``floor((raw−lo)·(100/span))`` over feasible nodes (handles negatives —
    [K8S] interpodaffinity normalization). Constant raw → all zeros. The
    single multiply keeps both backends bit-identical."""
    if not feasible.any():
        return np.zeros_like(raw, dtype=np.float32)
    vals = raw[feasible]
    lo, hi = np.float32(vals.min()), np.float32(vals.max())
    if hi == lo:
        return np.zeros_like(raw, dtype=np.float32)
    out = np.floor((raw.astype(np.float32) - lo) * (np.float32(MAX_NODE_SCORE) / (hi - lo)))
    return np.float32(MAX_NODE_SCORE) - out if reverse else out


def select_node(scores: np.ndarray, feasible: np.ndarray) -> int:
    """Deterministic argmax with lowest-index tie-break (SURVEY.md §7 hard
    part #6: CPU and device paths must break ties identically)."""
    if not feasible.any():
        return PAD
    masked = np.where(feasible, scores, -np.inf)
    return int(np.argmax(masked))


def first_reject_update(mask: np.ndarray, m: np.ndarray):
    """One Filter step of the kube "0/N nodes available" attribution:
    charge every node the running ``mask`` still allowed but ``m`` rejects
    to the current plugin, and advance the mask. Returns
    ``(newly_rejected_count, mask & m)``. :mod:`..ops.tpu` carries the
    whole-chain device form (``first_reject_counts``)."""
    return int((mask & ~m).sum()), mask & m
